"""Rescue-claim contention: the O_EXCL claim file's steal/heartbeat/
release protocol (easydl_tpu/ps/__main__.py) under direct unit pressure —
claimant-crashed-mid-rescue steal, a steal race between many rescuers,
heartbeat protection of an ACTIVE claimant, and claim release on a clean
handoff. Plus the probe_alive tunables satellite."""

from __future__ import annotations

import os
import threading
import time

from easydl_tpu.ps.__main__ import (
    claim_heartbeat,
    claim_orphan_shard,
    claim_owner,
    probe_alive,
    release_claim,
)
from easydl_tpu.ps import registry


def _claim_path(workdir, shard):
    return os.path.join(workdir, registry.REG_DIR,
                        f"claim-shard-{shard}.json")


def _age_claim(path, seconds):
    registry.locked_mutate(
        path, lambda doc: dict(doc, t=time.time() - seconds))


def test_fresh_claim_is_exclusive(tmp_path):
    w = str(tmp_path)
    s, path = claim_orphan_shard(w, "pod-a", [0])
    assert (s, claim_owner(path)) == (0, "pod-a")
    # a concurrent rescuer cannot take a FRESH claim
    s2, path2 = claim_orphan_shard(w, "pod-b", [0])
    assert (s2, path2) == (None, None)
    assert claim_owner(path) == "pod-a"


def test_crashed_claimant_is_stolen(tmp_path):
    """Claimant crashed mid-rescue: its claim ages past stale_s with the
    shard still unserved, and the next rescuer steals it. The original,
    if it ever resumes, loses at its publish-time ownership re-check."""
    w = str(tmp_path)
    _, path = claim_orphan_shard(w, "crashed", [0])
    _age_claim(path, 120.0)
    s, path2 = claim_orphan_shard(w, "rescuer", [0], stale_s=30.0)
    assert s == 0 and path2 == path
    assert claim_owner(path) == "rescuer"
    # the resumed original observes the loss exactly where main() checks
    assert claim_owner(path) != "crashed"


def test_steal_race_has_exactly_one_winner(tmp_path):
    """Many rescuers hit a stale claim concurrently: the age-re-check and
    the overwrite are one atomic mutation under the flock, so exactly one
    steals — the rest see a now-fresh claim and stand down."""
    w = str(tmp_path)
    _, path = claim_orphan_shard(w, "crashed", [0])
    _age_claim(path, 120.0)
    results = []
    barrier = threading.Barrier(8)

    def rescuer(i):
        barrier.wait()
        s, _p = claim_orphan_shard(w, f"rescuer-{i}", [0], stale_s=30.0)
        results.append((i, s))

    threads = [threading.Thread(target=rescuer, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    winners = [i for i, s in results if s == 0]
    assert len(winners) == 1, results
    assert claim_owner(path) == f"rescuer-{winners[0]}"


def test_heartbeat_protects_active_claimant(tmp_path):
    """An ACTIVE claimant (heartbeat refreshing the timestamp) can never
    look stale, so a would-be stealer with an aggressive stale_s loses."""
    w = str(tmp_path)
    _, path = claim_orphan_shard(w, "worker", [0])
    stop = threading.Event()
    hb = threading.Thread(target=claim_heartbeat,
                          args=(path, "worker", stop, 0.05), daemon=True)
    hb.start()
    try:
        time.sleep(0.2)
        s, _ = claim_orphan_shard(w, "thief", [0], stale_s=0.15)
        assert s is None
        assert claim_owner(path) == "worker"
    finally:
        stop.set()
        hb.join(timeout=2.0)


def test_heartbeat_stands_down_after_steal(tmp_path):
    """A claimant that resumes from a wedge AFTER losing its claim must
    not resurrect its ownership over the legitimate steal: the heartbeat
    observes the loss inside the lock and exits."""
    w = str(tmp_path)
    _, path = claim_orphan_shard(w, "wedged", [0])
    _age_claim(path, 120.0)
    s, _ = claim_orphan_shard(w, "thief", [0], stale_s=30.0)
    assert s == 0
    stop = threading.Event()
    hb = threading.Thread(target=claim_heartbeat,
                          args=(path, "wedged", stop, 0.02), daemon=True)
    hb.start()
    hb.join(timeout=5.0)  # exits on its own: the claim is not ours
    assert not hb.is_alive()
    assert claim_owner(path) == "thief"
    stop.set()


def test_release_on_clean_handoff(tmp_path):
    """A published claimant releases its claim: the file is gone, and the
    shard's NEXT rescue claims fresh via O_EXCL — no staleness wait."""
    w = str(tmp_path)
    s, path = claim_orphan_shard(w, "pod-a", [0])
    assert s == 0
    assert release_claim(path, "pod-a") is True
    assert not os.path.exists(path)
    # immediately claimable by the next rescuer, no steal path involved
    s2, path2 = claim_orphan_shard(w, "pod-b", [0])
    assert s2 == 0 and claim_owner(path2) == "pod-b"


def test_release_is_owner_checked(tmp_path):
    w = str(tmp_path)
    _, path = claim_orphan_shard(w, "pod-a", [0])
    assert release_claim(path, "impostor") is False
    assert os.path.exists(path)
    assert claim_owner(path) == "pod-a"
    # releasing an already-gone claim is a quiet no-op
    assert release_claim(path, "pod-a") is True
    assert release_claim(path, "pod-a") is False


def test_probe_alive_tunables(monkeypatch):
    """EASYDL_PS_PROBE_TIMEOUT_S / EASYDL_PS_PROBE_RETRIES bound the probe
    budget: one 0.2s attempt against a dead port verdicts DEAD fast."""
    monkeypatch.setenv("EASYDL_PS_PROBE_TIMEOUT_S", "0.2")
    monkeypatch.setenv("EASYDL_PS_PROBE_RETRIES", "1")
    t0 = time.monotonic()
    assert probe_alive("localhost:1") is False
    single = time.monotonic() - t0
    assert single < 3.0
    # more retries = a bigger budget (each attempt + the 0.5s inter-try
    # sleep), proving the knob actually drives the loop
    monkeypatch.setenv("EASYDL_PS_PROBE_RETRIES", "3")
    t0 = time.monotonic()
    assert probe_alive("localhost:1") is False
    assert time.monotonic() - t0 > single + 0.5
