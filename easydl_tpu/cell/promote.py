"""Fenced promotion: turn a shipped standby workdir into the primary.

The protocol is three moves, all riding machinery that already exists:

1. **Fence** (:func:`fence_standby`): raise every shard's standby epoch
   counter (``ps/registry.py`` — the same flock-serialized file
   ``bump_epoch`` advances) to a floor at or above the highest epoch the
   primary lineage ever served at. The floor is derived from what was
   SHIPPED — the epoch-named WAL dirs plus the replicated counter file —
   so the next ``bump_epoch`` on the standby returns an epoch strictly
   greater than any epoch a partitioned old primary could stamp. Its
   late pushes then answer ``stale-epoch`` forever: refused, never
   applied, structurally — no timeout, no quorum, just monotonicity.
2. **Mark** (:func:`write_promoted_marker`): persist the one-way switch
   before any standby pod serves. A shipper that wakes up late refuses
   to pump the dead primary's bytes into the new lineage
   (:class:`easydl_tpu.cell.ship.ShipFenced`).
3. **Boot**: start ordinary ``python -m easydl_tpu.ps`` pods on the
   standby workdir WITHOUT ``--shard-index``. The existing rescue path
   does the rest — ``resolve_fresh_shard`` sees the shipped WAL/
   snapshots as prior state, claims the shard, bumps the (pre-floored)
   epoch, restores the newest complete shipped snapshot and replays the
   shipped WAL tail through the same store math the primary applied —
   bit-exact against the acked-push ledger, up to the measured
   replication lag.

:func:`promote_standby` sequences the three and measures the wall clock
(the RTO's first half); :func:`probe_fenced_push` is the negative
control — a push stamped with the OLD primary epoch against the promoted
tier, which must be refused.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional

from easydl_tpu.ps import registry as ps_registry
from easydl_tpu.ps import wal as ps_wal
from easydl_tpu.utils.env import knob_float
from easydl_tpu.utils.logging import get_logger

log = get_logger("cell", "promote")

ENV_RTO_BUDGET_S = "EASYDL_CELL_RTO_BUDGET_S"
DEFAULT_RTO_BUDGET_S = 60.0

_SHIP_DIR = "cell-ship"
_PROMOTED = "PROMOTED.json"


def _metrics():
    global _METRICS
    if _METRICS is None:
        from easydl_tpu.obs.registry import get_registry

        reg = get_registry()
        _METRICS = {
            "fenced": reg.counter(
                "easydl_cell_fenced_pushes_total",
                "late pushes stamped with a fenced (pre-promotion) epoch "
                "that the promoted tier refused",
                labelnames=("cell",)),
            "promotion": reg.histogram(
                "easydl_cell_promotion_seconds",
                "fence → every standby shard serving (the RTO's PS half)",
                labelnames=("cell",)),
        }
    return _METRICS


_METRICS = None


def ensure_epoch_floor(workdir: str, shard: int, floor: int) -> bool:
    """Raise (never lower) a shard's epoch counter to at least ``floor``.
    Returns True when the counter moved. Same file, same flock discipline
    as ``registry.bump_epoch`` — a concurrent bump composes (both are
    monotonic raises)."""
    d = os.path.join(workdir, ps_registry.REG_DIR)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"epoch-shard-{int(shard)}.json")
    try:
        os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except FileExistsError:
        pass
    moved = {"raised": False}

    def mutate(doc: dict) -> Optional[dict]:
        cur = int(doc.get("epoch", 0))
        if cur >= int(floor):
            return None
        moved["raised"] = True
        return {"epoch": int(floor)}

    ps_registry.locked_mutate(path, mutate)
    return moved["raised"]


def shipped_epoch_floor(standby: str, shard: int) -> int:
    """Highest primary epoch the standby knows about for ``shard``: the
    max of the shipped epoch-named WAL dirs and the replicated epoch
    counter. Every acked push was WAL'd under its server's epoch dir, so
    any epoch that ever acked a push (and shipped) is visible here."""
    root = os.path.join(standby, "ps-wal", f"shard-{shard}")
    wal_max = max((e for e, _d in ps_wal.epoch_dirs(root)), default=0)
    return max(wal_max, ps_registry.shard_epoch(standby, shard))


def fence_standby(standby: str, num_shards: int,
                  margin: int = 0) -> Dict[int, int]:
    """Raise every shard's standby epoch counter to its shipped floor
    (+ ``margin``); returns the floors. After this, ``bump_epoch`` on the
    standby yields epochs strictly above anything the primary served at."""
    floors: Dict[int, int] = {}
    for shard in range(int(num_shards)):
        floor = shipped_epoch_floor(standby, shard) + int(margin)
        ensure_epoch_floor(standby, shard, floor)
        floors[shard] = floor
    return floors


def promoted_marker(standby: str) -> Optional[Dict[str, Any]]:
    """The promotion record, or None while the standby is still a standby."""
    try:
        with open(os.path.join(standby, _SHIP_DIR, _PROMOTED)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def write_promoted_marker(standby: str, doc: Dict[str, Any]) -> str:
    """Persist the one-way promoted switch (atomically); returns the path.
    Must land BEFORE any standby pod serves, so a late shipper pass can
    never interleave a dead primary's bytes with the new lineage's."""
    d = os.path.join(standby, _SHIP_DIR)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, _PROMOTED)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict(doc, promoted=True), f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def promote_standby(standby: str, num_shards: int,
                    spawn: Callable[[int], None],
                    wait_s: float = 90.0, margin: int = 0,
                    cell: str = "standby") -> Dict[str, Any]:
    """Run the full promotion: fence, mark, boot, wait until every shard
    publishes above its floor. ``spawn(shard)`` must start a PS pod on
    the standby workdir WITHOUT an explicit shard index (the rescue path
    resolves and claims it). Returns the promotion record (also persisted
    as the marker), including ``promote_wall_s``."""
    t0 = time.monotonic()
    floors = fence_standby(standby, num_shards, margin=margin)
    write_promoted_marker(standby, {
        "floors": {str(s): f for s, f in floors.items()},
        "num_shards": int(num_shards),
        "promoted_wall": time.time(),
    })
    for shard in range(int(num_shards)):
        spawn(shard)
    deadline = time.monotonic() + float(wait_s)
    epochs: Dict[int, int] = {}
    while time.monotonic() < deadline:
        smap = ps_registry.shard_map(standby)
        epochs = {s: int(doc.get("epoch", 0)) for s, doc in smap.items()}
        if all(epochs.get(s, 0) > floors[s] for s in floors):
            break
        time.sleep(0.2)
    else:
        raise TimeoutError(
            f"promotion of {standby}: shards never published above their "
            f"fence floors (floors={floors}, seen={epochs})")
    wall_s = time.monotonic() - t0
    _metrics()["promotion"].observe(wall_s, cell=cell)
    record = {
        "floors": {str(s): f for s, f in floors.items()},
        "epochs": {str(s): epochs[s] for s in epochs},
        "num_shards": int(num_shards),
        "promote_wall_s": round(wall_s, 3),
        "rto_budget_s": float(knob_float(ENV_RTO_BUDGET_S,
                                         DEFAULT_RTO_BUDGET_S)),
    }
    log.info("promoted standby %s: epochs %s over floors %s in %.2fs",
             standby, epochs, floors, wall_s)
    return record


def probe_fenced_push(standby: str, shard: int, table: str, dim: int,
                      stale_epoch: int, num_shards: int,
                      cell: str = "standby",
                      timeout: float = 10.0) -> Dict[str, Any]:
    """The negative control: push at the PROMOTED shard stamped with the
    old primary lineage's epoch — the worst-case client of a partitioned
    primary that never heard of the failover. The promoted server must
    refuse it with ``stale-epoch`` and never apply it (the drill's digest
    comparison runs AFTER this probe, so an applied row would surface as
    divergence)."""
    import numpy as np

    from easydl_tpu.proto import easydl_pb2 as pb
    from easydl_tpu.ps.server import PS_SERVICE, STALE_EPOCH
    from easydl_tpu.ps.table import shard_of
    from easydl_tpu.utils.rpc import GRPC_MSG_OPTIONS, RpcClient

    doc = ps_registry.shard_map(standby).get(int(shard)) or {}
    address = str(doc.get("address", ""))
    ids = np.arange(4096, dtype=np.int64)
    ids = ids[shard_of(ids, int(num_shards)) == int(shard)][:16]
    grads = np.full((len(ids), int(dim)), 7.0, np.float32)
    out: Dict[str, Any] = {
        "shard": int(shard), "address": address,
        "stale_epoch": int(stale_epoch),
        "served_epoch": int(doc.get("epoch", 0)),
    }
    try:
        cl = RpcClient(PS_SERVICE, address, timeout=timeout,
                       options=GRPC_MSG_OPTIONS)
        try:
            ack = cl.Push(pb.PushRequest(
                table=table, raw_ids=ids.astype("<i8").tobytes(),
                grads=grads.tobytes(), scale=1.0,
                epoch=int(stale_epoch),
            ))
        finally:
            cl.close()
        refused = (not ack.ok and ack.message.startswith(STALE_EPOCH))
        out.update(probe_acked_ok=bool(ack.ok),
                   probe_message=str(ack.message),
                   probe_rejected_stale_epoch=refused)
        if refused:
            _metrics()["fenced"].inc(cell=cell)
    except Exception as e:
        # An unreachable promoted shard refuses nothing — the invariant
        # treats a missing refusal as a violation.
        log.error("fenced-push probe against shard %d (%s) errored: %r",
                  shard, address, e)
        out.update(probe_acked_ok=False, probe_error=repr(e),
                   probe_rejected_stale_epoch=False)
    return out
