#!/usr/bin/env python3
"""Record an XLA trace at the bench config and attribute step time.

VERDICT r3 weak 3: MFU sat at ~0.507 across rounds while the attack was
lever-guessing — this script replaces guesses with a measured breakdown.
It runs bench.py's exact flagship config (GPT-2 345M, seq 1024, bf16,
remat=dots, flash attention) for a few steady-state steps under
``jax.profiler.trace`` (utils/profiling.py), then parses the Chrome-trace
JSON the profiler writes and aggregates TPU-lane op time by category:
flash fwd/bwd custom-calls, matmul fusions, other fusions, collectives,
infeed/outfeed, and gaps (host-bound time between device ops).

Output: one JSON report (``--out``, default PROFILE.json) with per-category
totals per step and the top-N individual ops — the evidence that names the
binding term.

Usage: python scripts/bench_profile.py [--steps 3] [--out PROFILE.json]
(requires the TPU; on CPU it still runs the tiny smoke config)
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def load_trace(logdir: str) -> dict:
    paths = glob.glob(os.path.join(
        logdir, "plugins", "profile", "*", "*.trace.json.gz"))
    if not paths:
        raise FileNotFoundError(f"no trace under {logdir}")
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        return json.load(f)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(REPO, "PROFILE.json"))
    ap.add_argument("--logdir", default="")
    ap.add_argument("--top", type=int, default=15)
    args = ap.parse_args()

    import jax
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig, Trainer
    from easydl_tpu.models.registry import get_model
    from easydl_tpu.utils.profiling import trace

    platform = jax.default_backend()
    n_chips = jax.device_count()
    if platform == "tpu":
        size, seq_len = "345m", 1024
        grad_accum, global_batch = 32, 256 * n_chips
        bundle = get_model("gpt", size=size, seq_len=seq_len, remat=True,
                           remat_policy="dots", dtype="bfloat16",
                           fused_loss=False)
    else:
        size, seq_len, global_batch, grad_accum = "test", 128, 8, 2
        bundle = get_model("gpt", size=size, seq_len=seq_len, vocab=512)

    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adamw(2e-4, weight_decay=0.01),
        config=TrainConfig(global_batch=global_batch, grad_accum=grad_accum),
        mesh_spec=MeshSpec(dp=n_chips),
    )
    state = trainer.init_state()
    data = iter(bundle.make_data(global_batch))

    for _ in range(2):  # compile + warm
        state, metrics = trainer.train_step(state, next(data))
    float(jax.device_get(metrics["loss"]))

    logdir = args.logdir or tempfile.mkdtemp(prefix="bench-profile-")
    t0 = time.perf_counter()
    with trace(logdir):
        for _ in range(args.steps):
            state, metrics = trainer.train_step(state, next(data))
        float(jax.device_get(metrics["loss"]))
    wall = time.perf_counter() - t0

    from easydl_tpu.utils.profiling import attribute_trace

    attribution = attribute_trace(load_trace(logdir), top=args.top)
    busy_us = attribution.get("lane_busy_us", 0.0)
    report = {
        "config": f"gpt-{size} seq{seq_len} b{global_batch}/a{grad_accum} "
                  f"({platform}, {n_chips} chip)",
        "profiled_steps": args.steps,
        "wall_s": round(wall, 3),
        "wall_per_step_s": round(wall / args.steps, 4),
        # The busiest device lane's covered time is the honest per-step
        # device cost (trace collection inflates WALL time ~4x over the
        # tunnel; the lane union does not lie — see PARITY determinism
        # notes). Categories are SELF times on that lane and sum to it by
        # construction; the invariants block would flag any regression.
        "device_busy_per_step_s": round(busy_us / 1e6 / args.steps, 4),
        "category_us_per_step": {
            k: round(v / args.steps, 1)
            for k, v in attribution.get("category_self_us", {}).items()
        },
        "top_ops_us_per_step": [
            {**o, "us": round(o["us"] / args.steps, 1)}
            for o in attribution.get("top_ops_self_us", [])
        ],
        "attribution": attribution,
        "trace_logdir": logdir,
    }
    # Merge, don't clobber: other sections of the same file (pipeline
    # numbers, superseded-history notes) belong to other writers — update
    # the loaded document with this report's keys, preserving the rest.
    doc = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
    doc.update(report)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
