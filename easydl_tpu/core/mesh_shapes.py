"""Logical mesh shapes: the pure (jax-free) half of :mod:`core.mesh`.

:class:`MeshSpec` describes a logical device mesh; this module adds the
**elastic shape algebra** the control plane needs (PR 12):

- a canonical string key (``"dp=8"``, ``"dp=2,fsdp=2,tp=2"``) that rides
  directives, metrics records and policy history — :meth:`MeshSpec.key`
  / :meth:`MeshSpec.parse` round-trip it;
- :class:`MeshConstraints`: the per-model divisibility/memory limits a
  candidate shape must satisfy (tp must divide the head count, pp the
  layer count, the model axes together must shard the model at least
  ``min_model`` ways to fit HBM);
- :func:`enumerate_shapes`: every valid (data x model [x pipeline])
  factorization of a world size under those constraints, in a
  deterministic order that leads with the widest data axis — the
  cold-start preference of the Brain's mesh-shape policy
  (:mod:`easydl_tpu.brain.mesh_policy`).

Deliberately import-light (stdlib only): the membership FSM, the Brain
policy and the offline simulator all consume it, and all three must stay
virtual-clock-pure and jax-free (easylint rule 5). ``core.mesh``
re-exports everything here, so ``from easydl_tpu.core.mesh import
MeshSpec`` keeps working.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Mapping, Tuple

#: Canonical axis order, outermost (DCN-friendly) -> innermost (ICI-hungry).
AXES: Tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")

#: Axes a batch dimension is sharded over (pure data parallelism axes).
BATCH_AXES: Tuple[str, ...] = ("dp", "fsdp")

#: Key/display order for shape strings (data axes first — "dp=8xfsdp=2").
_KEY_ORDER: Tuple[str, ...] = ("dp", "fsdp", "tp", "sp", "ep", "pp")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Unset axes default to 1 and collapse away in the
    physical mesh only if every axis is 1 (we keep all names so PartitionSpecs
    stay valid regardless of shape)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.ep * self.pp

    def axis_sizes(self) -> Tuple[int, ...]:
        m = {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp, "ep": self.ep,
             "sp": self.sp, "tp": self.tp}
        return tuple(m[a] for a in AXES)

    @classmethod
    def from_world(
        cls,
        world: int,
        *,
        tp: int = 1,
        sp: int = 1,
        ep: int = 1,
        pp: int = 1,
        fsdp: int = 1,
    ) -> "MeshSpec":
        """Fill the ``dp`` axis with whatever ``world`` leaves after the model
        axes — the elastic master uses this to rebuild the mesh at a new world
        size without touching the model-parallel layout."""
        denom = tp * sp * ep * pp * fsdp
        if world % denom:
            raise ValueError(
                f"world={world} not divisible by tp*sp*ep*pp*fsdp={denom}"
            )
        return cls(dp=world // denom, fsdp=fsdp, tp=tp, sp=sp, ep=ep, pp=pp)

    def describe(self) -> str:
        parts = [f"{a}={s}" for a, s in zip(AXES, self.axis_sizes()) if s > 1]
        return "x".join(parts) if parts else "single-device"

    # ------------------------------------------------------- canonical key
    def key(self) -> str:
        """Canonical shape string: non-unit axes in ``dp,fsdp,tp,sp,ep,pp``
        order (``"dp=2,fsdp=2,tp=2"``); the all-unit shape is ``"dp=1"`` so
        a key is never empty (empty = "no shape decided" on the wire)."""
        parts = [f"{a}={getattr(self, a)}" for a in _KEY_ORDER
                 if getattr(self, a) > 1]
        return ",".join(parts) if parts else "dp=1"

    @classmethod
    def parse(cls, key: str) -> "MeshSpec":
        """Inverse of :meth:`key` (any axis order, whitespace tolerated).
        Raises ValueError on unknown axes, non-positive sizes, duplicates,
        or an empty string."""
        axes: dict = {}
        for part in str(key).split(","):
            part = part.strip()
            if not part:
                continue
            name, _, val = part.partition("=")
            name = name.strip()
            if name not in AXES:
                raise ValueError(
                    f"unknown mesh axis {name!r} in {key!r} (known: {AXES})")
            if name in axes:
                raise ValueError(f"duplicate mesh axis {name!r} in {key!r}")
            try:
                n = int(val.strip())
            except ValueError:
                raise ValueError(
                    f"mesh axis {name} in {key!r} is not an integer") from None
            if n < 1:
                raise ValueError(f"mesh axis {name}={n} in {key!r} must be "
                                 ">= 1")
            axes[name] = n
        if not axes:
            raise ValueError(f"empty mesh shape {key!r}")
        return cls(**axes)


@dataclass(frozen=True)
class MeshConstraints:
    """Per-model limits a candidate mesh shape must satisfy.

    The defaults admit only pure data parallelism — turning model axes on
    is an explicit, per-job statement about the model's divisibility
    (heads, layers) and memory footprint. ``0`` means "unconstrained" for
    the ``*_divides`` fields and ``max_dp``.
    """

    #: tensor-parallel width ceiling (1 = tp off)
    max_tp: int = 1
    #: tp must divide this (attention head count); 0 = no divisibility tie
    tp_divides: int = 0
    #: fsdp width ceiling (1 = fsdp off)
    max_fsdp: int = 1
    #: pipeline-stage ceiling (1 = pp off)
    max_pp: int = 1
    #: pp must divide this (layer count); 0 = no divisibility tie
    pp_divides: int = 0
    #: the model axes together (fsdp*tp*pp) must shard the model at least
    #: this many ways — the memory floor: a model that does not fit one
    #: chip's HBM unsharded sets this > 1, and any world smaller than it
    #: has NO valid shape
    min_model: int = 1
    #: data-axis ceiling (0 = unbounded) — e.g. a batch size that caps dp
    max_dp: int = 0

    @classmethod
    def from_dict(cls, doc: Mapping[str, Any]) -> "MeshConstraints":
        """Build from a job-config mapping, ignoring unknown keys (job.json
        evolves; an old master must not crash on a newer job spec)."""
        fields = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**{k: int(v) for k, v in dict(doc).items()
                      if k in fields})


def validate_shape(spec: MeshSpec, world: int,
                   constraints: MeshConstraints = MeshConstraints(),
                   ) -> List[str]:
    """Why ``spec`` is not a valid shape for ``world`` chips under
    ``constraints`` — empty list = valid. Used both by enumeration and to
    answer "why was my pinned shape rejected" legibly."""
    problems: List[str] = []
    if spec.size != world:
        problems.append(f"size {spec.size} != world {world}")
    if spec.sp > 1 or spec.ep > 1:
        problems.append("sp/ep axes are not elastic-shape candidates "
                        "(model-structural: set them in the job config)")
    if spec.tp > max(constraints.max_tp, 1):
        problems.append(f"tp={spec.tp} > max_tp={constraints.max_tp}")
    if constraints.tp_divides and spec.tp > 1 \
            and constraints.tp_divides % spec.tp:
        problems.append(f"tp={spec.tp} does not divide "
                        f"tp_divides={constraints.tp_divides} (heads)")
    if spec.fsdp > max(constraints.max_fsdp, 1):
        problems.append(f"fsdp={spec.fsdp} > max_fsdp={constraints.max_fsdp}")
    if spec.pp > max(constraints.max_pp, 1):
        problems.append(f"pp={spec.pp} > max_pp={constraints.max_pp}")
    if constraints.pp_divides and spec.pp > 1 \
            and constraints.pp_divides % spec.pp:
        problems.append(f"pp={spec.pp} does not divide "
                        f"pp_divides={constraints.pp_divides} (layers)")
    if spec.fsdp * spec.tp * spec.pp < max(constraints.min_model, 1):
        problems.append(
            f"model axes fsdp*tp*pp={spec.fsdp * spec.tp * spec.pp} < "
            f"min_model={constraints.min_model} (memory floor)")
    if constraints.max_dp and spec.dp > constraints.max_dp:
        problems.append(f"dp={spec.dp} > max_dp={constraints.max_dp}")
    return problems


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_shapes(world: int,
                     constraints: MeshConstraints = MeshConstraints(),
                     ) -> Tuple[MeshSpec, ...]:
    """Every valid (dp x fsdp x tp [x pp]) factorization of ``world``
    under ``constraints``, deterministically ordered widest-data-axis
    first; at equal dp, the cheaper model axes lead (fsdp before tp
    before pp — fsdp adds only param all-gathers, tp adds per-layer
    activation collectives, pp adds schedule bubbles). The order doubles
    as the mesh policy's cold-start preference AND its probe order.

    Returns an EMPTY tuple when no shape is valid (prime world with a
    mandatory model axis, world below the ``min_model`` memory floor):
    the caller decides the fallback; this function never invents one.
    """
    if world < 1:
        return ()
    out: List[MeshSpec] = []
    for pp in _divisors(world):
        for tp in _divisors(world // pp):
            for fsdp in _divisors(world // (pp * tp)):
                spec = MeshSpec(dp=world // (pp * tp * fsdp), fsdp=fsdp,
                                tp=tp, pp=pp)
                if not validate_shape(spec, world, constraints):
                    out.append(spec)
    out.sort(key=lambda s: (-s.dp, s.pp, s.tp, s.fsdp))
    return tuple(out)
