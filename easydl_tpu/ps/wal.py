"""Per-shard push write-ahead log: the durability half of zero-loss rescue.

A PS shard crash used to fall back to the last sparse snapshot, silently
discarding every push applied since it. The WAL closes that gap: every
applied push is appended — in the exact order the store applied it — to a
size-rotated segment file under the shard's WAL directory, and rescue
(ps/__main__.py) replays surviving segments on top of the restored
snapshot, reproducing the pre-crash table **bit-identically** (replay goes
through the same vectorized store math as the original apply).

Layout::

    <workdir>/ps-wal/shard-<i>/            the shard's WAL root
        epoch-<e>/                         one dir per shard incarnation
            seg-00000001.wal ...           size-rotated record segments
            REPLAYED.json                  written by the rescuer: bytes of
                                           each segment it consumed, so a
                                           zombie's late appends are never
                                           replayed by a LATER rescue

Record framing (little-endian): ``u32 payload_len | u32 crc32(payload) |
payload``. The payload leads with a kind byte — ``0`` = push (table,
scale, ids, grads: the exact decoded arguments the store applied),
``1`` = create_table (the spec JSON, so replay can recreate a table born
after the last snapshot). Readers validate every record's checksum and
stop at the first bad/short frame — a torn tail from a SIGKILL truncates,
it never poisons the replay.

Durability contract: records are ``write()``-en to the OS before the push
is acked (process-crash safe — a SIGKILLed shard loses nothing it acked),
while ``fsync`` runs on a background cadence (``EASYDL_PS_WAL_SYNC_S``),
bounding host-crash loss to one sync interval. This mirrors the PR-5
AsyncPusher discipline: the hot path pays one buffered append, the
expensive barrier runs behind it, and errors surface on the next append
rather than vanishing. Segments are retired atomically when a snapshot
commits (ps/server.py ``save``): once the rows are durably in the
checkpoint lineage a rescue restores from, the log that produced them is
dead weight.

Knobs: ``EASYDL_PS_WAL`` (default on for pod-served shards),
``EASYDL_PS_WAL_SEGMENT_BYTES`` (rotation threshold, default 32 MiB),
``EASYDL_PS_WAL_SYNC_S`` (fsync cadence, default 0.2s; 0 = fsync every
append, negative = never fsync).
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

# The generic framing/segment/offset-marker core is SHARED with the
# feedback spool (loop/spool.py): one frame codec, one segment walker,
# one marker schema — the WAL and the spool cannot drift. This module
# keeps the PS-specific halves: push/create payload codecs, epoch-dir
# layout, replay iteration, and the WAL durability stance (append
# failure FAILS the push).
from easydl_tpu.loop.spool import (
    SegmentWriter,
    frame,  # noqa: F401  (re-export: pre-existing public API)
    list_segments,
    read_offset_marker,
    read_segment,  # noqa: F401  (re-export: pre-existing public API)
    write_offset_marker,
)
from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.env import knob_float, knob_int

log = get_logger("ps", "wal")

ENV_WAL = "EASYDL_PS_WAL"
ENV_SEGMENT_BYTES = "EASYDL_PS_WAL_SEGMENT_BYTES"
ENV_SYNC_S = "EASYDL_PS_WAL_SYNC_S"

DEFAULT_SEGMENT_BYTES = 32 << 20
DEFAULT_SYNC_S = 0.2

REC_PUSH = 0
REC_CREATE = 1

_PUSH_HEAD = struct.Struct("<BHdII")  # kind, table_len, scale, n_ids, dim

REPLAYED_MARKER = "REPLAYED.json"


class WalError(RuntimeError):
    """The WAL could not be appended — durability is broken, so the push
    that triggered it must FAIL (a silent fallback to no-WAL would turn
    the zero-loss promise into a lie)."""


# ------------------------------------------------------------------ encoding
def encode_push_parts(table: str, ids: np.ndarray, grads: np.ndarray,
                      scale: float) -> List[bytes]:
    """Payload for one applied push as scatter-gather parts: the exact
    arguments the store saw (raw-ids wire form — little-endian int64
    bytes, float32 grads). Parts, not one buffer: a push on the wire is a
    few MB, and the hot-path append (:meth:`PsWal.append`) checksums the
    parts incrementally and hands them to ``os.writev`` — zero joins, zero
    full-payload copies. ``ids``/``grads`` decoded off the wire are
    already little-endian contiguous, so the casts below are no-ops
    there."""
    tb = table.encode()
    ids = np.ascontiguousarray(ids, "<i8")
    grads = np.ascontiguousarray(grads, "<f4")
    return [
        _PUSH_HEAD.pack(REC_PUSH, len(tb), float(scale), len(ids),
                        grads.shape[1] if grads.ndim == 2 else 0),
        tb,
        ids.tobytes(),
        grads.tobytes(),
    ]


def encode_push(table: str, ids: np.ndarray, grads: np.ndarray,
                scale: float) -> bytes:
    return b"".join(encode_push_parts(table, ids, grads, scale))


def decode_push(payload: bytes) -> Tuple[str, np.ndarray, np.ndarray, float]:
    kind, tlen, scale, n, dim = _PUSH_HEAD.unpack_from(payload, 0)
    if kind != REC_PUSH:
        raise ValueError(f"not a push record (kind={kind})")
    off = _PUSH_HEAD.size
    table = payload[off:off + tlen].decode()
    off += tlen
    ids = np.frombuffer(payload, "<i8", count=n, offset=off)
    off += 8 * n
    grads = np.frombuffer(payload, "<f4", count=n * dim,
                          offset=off).reshape(n, dim)
    return table, ids, grads, scale


def encode_create(spec_json: str) -> bytes:
    return bytes((REC_CREATE,)) + spec_json.encode()


def decode_create(payload: bytes) -> str:
    return payload[1:].decode()


def record_kind(payload: bytes) -> int:
    return payload[0] if payload else -1


def push_digest(payload) -> bytes:
    """Identity of one applied push, for replay-vs-retry dedupe: a client
    that never saw the ack of a push the dead shard DID apply (and WAL)
    will retry it verbatim against the rescuer — the rescuer recognises
    the payload bytes and acks without applying twice. The digest is over
    the payload only (the stamped epoch is NOT part of it: the retry
    carries the successor's epoch). Accepts the joined payload or its
    scatter-gather parts — both digest identically."""
    h = hashlib.blake2b(digest_size=16)
    for part in ([payload] if isinstance(payload, bytes) else payload):
        h.update(part)
    return h.digest()


# ------------------------------------------------------------------- reading
def _segments(d: str) -> List[str]:
    return list_segments(d, ".wal")


def epoch_dirs(root: str) -> List[Tuple[int, str]]:
    """``(epoch, path)`` of every incarnation dir under a shard WAL root,
    epoch-sorted."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for n in names:
        if n.startswith("epoch-"):
            try:
                out.append((int(n[len("epoch-"):]), os.path.join(root, n)))
            except ValueError:
                continue
    out.sort()
    return out


def read_replay_caps(epoch_dir: str) -> Dict[str, int]:
    """Parse an incarnation dir's ``REPLAYED.json`` consumed-offset caps
    (empty when absent/unreadable). One marker schema, shared with the
    feedback spool's CONSUMED.json via loop/spool.py — replay and the
    chaos zombie-fence check both go through here."""
    return read_offset_marker(epoch_dir, REPLAYED_MARKER)


def iter_replay(root: str, before_epoch: int,
                start: Optional[Tuple[int, str]] = None
                ) -> Iterator[Tuple[int, str, List[bytes], int, bool]]:
    """Yield ``(epoch, segment_path, payloads, consumed, clean)`` for every
    segment of every incarnation older than ``before_epoch``, in apply
    order (epoch, then segment name). Honors a prior rescuer's
    ``REPLAYED.json`` offsets as hard caps.

    ``start`` is the restored snapshot's cut boundary ``(epoch,
    first_live_segment)`` (ps/server.py writes it into every step dir):
    records the snapshot already contains must not replay on top of it.
    Epochs older than the snapshot writer's are skipped whole — any
    record of theirs was replayed (or handed off) into the writer's state
    before it could take a snapshot — and within the writer's epoch only
    segments at or past the cut replay. Without a boundary every
    surviving segment replays, which is the pre-cut-marker contract where
    correctness leaned on retirement alone."""
    for epoch, d in epoch_dirs(root):
        if before_epoch and epoch >= before_epoch:
            continue
        if start is not None and epoch < start[0]:
            continue
        caps = read_replay_caps(d)
        for name in _segments(d):
            if start is not None and epoch == start[0] and name < start[1]:
                continue
            path = os.path.join(d, name)
            payloads, consumed, clean = read_segment(path, caps.get(name))
            yield epoch, path, payloads, consumed, clean


def write_replay_marker(epoch_dir: str, consumed: Dict[str, int]) -> None:
    """Record how far a rescue consumed each segment of a predecessor
    incarnation, so a zombie predecessor's post-rescue appends (acked by
    the SUCCESSOR when the client retried them) are never replayed by a
    later rescue. Merges over an existing marker: a cap, once written,
    never grows."""
    write_offset_marker(epoch_dir, dict(consumed), REPLAYED_MARKER,
                        shrink_only=True)


# ------------------------------------------------------------------- writing
class PsWal(SegmentWriter):
    """The append side: one open segment, size-rotated, background-fsynced
    — the shared :class:`easydl_tpu.loop.spool.SegmentWriter` under the
    WAL's knobs and error class (an unappendable log raises
    :class:`WalError`, and the push that triggered it must FAIL).

    NOT thread-safe by itself — the shard serializes appends (and the
    append→store-apply pair) under its WAL ordering lock, which is what
    guarantees file order == apply order == replay order."""

    def __init__(self, epoch_dir: str,
                 segment_bytes: Optional[int] = None,
                 sync_s: Optional[float] = None):
        super().__init__(
            epoch_dir,
            segment_bytes=int(
                knob_int(ENV_SEGMENT_BYTES, DEFAULT_SEGMENT_BYTES)
                if segment_bytes is None else segment_bytes),
            sync_s=float(
                knob_float(ENV_SYNC_S, DEFAULT_SYNC_S)
                if sync_s is None else sync_s),
            suffix=".wal",
            error_cls=WalError,
        )


def retire_segments(paths, root: Optional[str] = None,
                    before_epoch: int = 0) -> int:
    """Delete retired segment files (and, when ``root``/``before_epoch``
    name them, whole predecessor incarnation dirs) after a snapshot
    commit. Every record in them is durably inside the snapshot a rescue
    would restore, so losing them loses nothing. Returns files removed."""
    removed = 0
    for p in paths:
        try:
            os.remove(p)
            removed += 1
        except OSError:
            pass
    if root and before_epoch:
        import shutil

        for epoch, d in epoch_dirs(root):
            if epoch < before_epoch:
                shutil.rmtree(d, ignore_errors=True)
    return removed
