"""Memory-mapped file-backed datasets, rank-sharded and checkpointable.

Layout conventions:

- **token shards**: a directory of ``tokens-*.npy`` 1-D integer arrays (any
  integer dtype) — the output of ``python -m easydl_tpu.data.encode``. The
  dataset concatenates them logically, cuts non-overlapping ``seq_len+1``
  windows, shuffles window order with an epoch-seeded permutation, and
  yields ``{"inputs", "targets"}`` batches like the synthetic LM stream.
- **array images**: ``images.npy`` ``[N, ...]`` plus ``labels.npy`` ``[N]``
  in one directory (the MNIST/ImageNet-after-preprocessing shape).

Sharding: rank ``r`` of ``world`` takes every ``world``-th window/example —
disjoint and exhaustive, so data-parallel processes never duplicate or skip
data. ``state()``/``restore_state()`` expose the (epoch, cursor) pair the
checkpoint layer persists so a restored job resumes mid-epoch instead of
replaying (SURVEY §5.4: resume covers the input pipeline too).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional

import numpy as np


def hash_split(n: int, split: str, val_fraction: float) -> np.ndarray:
    """Deterministic train/val assignment over ``n`` items.

    Knuth multiplicative hash → uniform in [0, 1); independent of seed,
    epoch, and world, so the holdout can never leak into training. One
    implementation shared by every file dataset."""
    if split not in ("train", "val"):
        raise ValueError(f"split must be 'train' or 'val', got {split!r}")
    if split == "val" and not val_fraction:
        raise ValueError("split='val' requires val_fraction > 0")
    if not val_fraction:
        return np.arange(n)
    u = (np.arange(n, dtype=np.uint64)
         * np.uint64(2654435761) % np.uint64(1 << 32)) / float(1 << 32)
    mask = u < val_fraction
    return np.flatnonzero(mask if split == "val" else ~mask)


class CursorStateMixin:
    """The (epoch, cursor) checkpoint contract shared by the file datasets.

    The state is world/batch-tagged: restoring onto a RESHAPED job (elastic
    scale event between save and resume) rescales the per-rank cursor to the
    same global position."""

    def state(self) -> Dict[str, int]:
        return {"epoch": self.epoch, "cursor": self.cursor,
                "world": self.world, "batch": self.batch_size}

    def restore_state(self, state: Dict[str, int]) -> None:
        self.epoch = int(state.get("epoch", 0))
        cursor = int(state.get("cursor", 0))
        world = int(state.get("world", self.world))
        batch = int(state.get("batch", self.batch_size))
        if (world, batch) != (self.world, self.batch_size):
            consumed = cursor * world * batch  # global items this epoch
            cursor = consumed // (self.world * self.batch_size)
        self.cursor = min(cursor, self.batches_per_epoch)


def write_token_shards(ids, out_dir: str, shard_size: int = 1 << 24,
                       dtype=np.uint16) -> List[str]:
    """Write a token id stream into ``tokens-*.npy`` shards; returns paths.

    dtype uint16 halves disk/IO for vocabs < 65536 (the common case)."""
    arr = np.asarray(ids)
    if arr.size and arr.max() >= np.iinfo(dtype).max:
        dtype = np.uint32
    arr = arr.astype(dtype)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for i, start in enumerate(range(0, max(arr.size, 1), shard_size)):
        path = os.path.join(out_dir, f"tokens-{i:05d}.npy")
        np.save(path, arr[start:start + shard_size])
        paths.append(path)
    return paths


class TokenFileDataset(CursorStateMixin):
    """Fixed-length LM windows over memory-mapped token shard files.

    ``val_fraction`` carves a deterministic held-out split at window
    granularity (:func:`hash_split`): trainers read ``split="train"``, the
    evaluator reads ``split="val"`` of the same directory, and the two
    never overlap.
    """

    def __init__(self, data_dir: str, batch_size: int, seq_len: int,
                 rank: int = 0, world: int = 1, seed: int = 0,
                 loop: bool = True, split: str = "train",
                 val_fraction: float = 0.0):
        self.paths = sorted(glob.glob(os.path.join(data_dir, "tokens-*.npy")))
        if not self.paths:
            raise FileNotFoundError(f"no tokens-*.npy under {data_dir}")
        self._shards = [np.load(p, mmap_mode="r") for p in self.paths]
        if any(s.ndim != 1 for s in self._shards):
            raise ValueError("token shards must be 1-D id arrays")
        self.batch_size = batch_size
        #: kept for ShardedLoader's divisibility check (single-process mode
        #: feeds the global batch, so global == local there)
        self.global_batch = batch_size * world if world > 1 else batch_size
        self.seq_len = seq_len
        self.rank = rank
        self.world = world
        self.seed = seed
        self.loop = loop
        self._sizes = np.array([s.size for s in self._shards])
        self._offsets = np.concatenate([[0], np.cumsum(self._sizes)])
        self.total_tokens = int(self._offsets[-1])
        window = seq_len + 1  # inputs + shifted targets
        self.num_windows = self.total_tokens // window
        self._windows = hash_split(self.num_windows, split, val_fraction)
        mine = len(self._windows) // world  # windows this rank owns per epoch
        self.batches_per_epoch = mine // batch_size
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"{self.total_tokens} tokens is not enough for one "
                f"batch of {batch_size}x{window} on {world} ranks "
                f"(split={split!r})"
            )
        self.epoch = 0
        self.cursor = 0  # batches consumed within the current epoch

    # ------------------------------------------------------------------- read
    def _window(self, index: int) -> np.ndarray:
        window = self.seq_len + 1
        start = index * window
        shard = int(np.searchsorted(self._offsets, start, side="right") - 1)
        local = start - int(self._offsets[shard])
        out = np.empty((window,), np.int64)
        filled = 0
        while filled < window:
            src = self._shards[shard]
            take = min(window - filled, src.size - local)
            out[filled:filled + take] = src[local:local + take]
            filled += take
            shard += 1
            local = 0
        return out

    def _epoch_order(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, epoch))
        return self._windows[rng.permutation(len(self._windows))]

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            order = self._epoch_order(self.epoch)
            mine = order[self.rank::self.world]
            while self.cursor < self.batches_per_epoch:
                lo = self.cursor * self.batch_size
                idx = mine[lo:lo + self.batch_size]
                batch = np.stack([self._window(int(i)) for i in idx])
                self.cursor += 1
                yield {
                    "inputs": batch[:, :-1].astype(np.int32),
                    "targets": batch[:, 1:].astype(np.int32),
                }
            self.epoch += 1
            self.cursor = 0
            if not self.loop:
                return


class ArrayImageDataset(CursorStateMixin):
    """images.npy/labels.npy pairs — the classification-config file format."""

    def __init__(self, data_dir: str, batch_size: int, rank: int = 0,
                 world: int = 1, seed: int = 0, loop: bool = True,
                 normalize: bool = True, split: str = "train",
                 val_fraction: float = 0.0):
        self.images = np.load(os.path.join(data_dir, "images.npy"),
                              mmap_mode="r")
        self.labels = np.load(os.path.join(data_dir, "labels.npy"),
                              mmap_mode="r")
        if len(self.images) != len(self.labels):
            raise ValueError(
                f"images ({len(self.images)}) / labels ({len(self.labels)}) "
                "length mismatch"
            )
        self.batch_size = batch_size
        self.global_batch = batch_size * world if world > 1 else batch_size
        self.rank = rank
        self.world = world
        self.seed = seed
        self.loop = loop
        self.normalize = normalize
        n = len(self.images)
        self._examples = hash_split(n, split, val_fraction)
        mine = len(self._examples) // world
        self.batches_per_epoch = mine // batch_size
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"{n} examples can't fill one batch of "
                f"{batch_size} on {world} ranks (split={split!r})"
            )
        self.epoch = 0
        self.cursor = 0

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            rng = np.random.default_rng((self.seed, self.epoch))
            order = self._examples[
                rng.permutation(len(self._examples))
            ][self.rank::self.world]
            while self.cursor < self.batches_per_epoch:
                lo = self.cursor * self.batch_size
                idx = np.sort(order[lo:lo + self.batch_size])  # mmap-friendly
                images = np.asarray(self.images[idx], np.float32)
                if self.normalize:
                    images = images / 255.0
                self.cursor += 1
                yield {
                    "image": images,
                    "label": np.asarray(self.labels[idx], np.int32),
                }
            self.epoch += 1
            self.cursor = 0
            if not self.loop:
                return
