"""PS client: shard routing, pull/push, and the jit-visible lookup.

Two transports behind one interface:

- :class:`ShardedPsClient` — gRPC to N :class:`~easydl_tpu.ps.server.PsShard`
  servers, ids routed by ``shard_of`` (splitmix64 hash), per-shard requests
  issued concurrently.
- :class:`LocalPsClient` — in-process shards, same routing math, zero RPC;
  single-host runs and tests.

:func:`ps_lookup` makes the PS visible *inside* a jitted step: forward pulls
rows via ``jax.pure_callback``, and the custom VJP pushes gradients back via
``jax.experimental.io_callback`` — so the reference's async PS pull/push hot
loop (SURVEY.md §3.4) becomes two host callbacks flanking an XLA-compiled
dense step. For multi-process meshes prefer the explicit
:class:`~easydl_tpu.ps.trainer.PsTrainer` loop, where each process pulls only
its local batch shard.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

import time

from easydl_tpu.obs import get_registry, tracing
from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.ps import quant as _quant
from easydl_tpu.ps.server import (
    DRAINING,
    PS_SERVICE,
    STALE_EPOCH,
    STALE_ROUTE,
    PsShard,
    spec_to_proto,
)
from easydl_tpu.ps.table import TableSpec, shard_of
from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.env import env_flag as _env_flag
from easydl_tpu.utils.retry import (
    backoff_delay,
    is_transport_error,
    retry_transient,
)
from easydl_tpu.utils.rpc import GRPC_MSG_OPTIONS, RpcClient
from easydl_tpu.obs.errors import count_swallowed
from easydl_tpu.utils.env import knob_int

log = get_logger("ps", "client")


class RoutingChanged(Exception):
    """Internal control flow for live resharding: the registry committed a
    new routing-table generation (different shard count / shard set) while
    an operation was in flight. The failed CHUNK — whose ids the old shard
    provably never applied (it answered `stale-route`, or the transport
    died before an ack) is re-dispatched through the NEW partition via the
    top-level pull/push, which re-routes each id to its new owner."""


class PullVersions:
    """Per-shard table push-versions observed by ONE pull (the meta the
    hot-id serving cache keys invalidation on, see ps/read_client.py).

    Chunk workers record concurrently; the per-shard value kept is the
    MINIMUM seen — chunks of one shard race pushes independently, and the
    oldest version is the only tag under which every chunk's rows are
    provably fresh. A live-reshard re-dispatch (RoutingChanged) marks the
    whole collection incomplete: its rows came from a different routing
    generation and must not be cached under this one's tags. Version 0
    (legacy server, no version info) is never recorded."""

    def __init__(self):
        self._mu = threading.Lock()
        self.versions: Dict[int, int] = {}
        self.complete = True

    def record(self, shard: int, version: int) -> None:
        if not version:
            return
        with self._mu:
            cur = self.versions.get(shard)
            if cur is None or version < cur:
                self.versions[shard] = int(version)

    def invalidate(self) -> None:
        with self._mu:
            self.complete = False


_client_metrics_cache: Optional[tuple] = None


def _client_metrics():
    global _client_metrics_cache
    if _client_metrics_cache is None:
        reg = get_registry()
        _client_metrics_cache = (
            reg.gauge(
                "easydl_ps_client_dedup_ratio",
                "unique/total ids of the last coalesced pull, per table "
                "(client side; 1.0 = no duplicates in the batch).",
                ("table",),
            ),
        )
    return _client_metrics_cache


_shm_metrics_cache: Optional[tuple] = None


def _shm_metrics():
    global _shm_metrics_cache
    if _shm_metrics_cache is None:
        reg = get_registry()
        _shm_metrics_cache = (
            reg.counter(
                "easydl_ps_shm_client_pulls_total",
                "Shard pulls served from the shared-memory mirror "
                "(zero gRPC).", ("table",),
            ),
            reg.counter(
                "easydl_ps_shm_client_ids_total",
                "Embedding ids gathered through the shm transport.",
                ("table",),
            ),
            reg.counter(
                "easydl_ps_shm_client_fallbacks_total",
                "shm attempts that fell back to the wire (open-failed = "
                "remote shard; revoked = cutover/fence/restore/overflow; "
                "contention = persistent seqlock conflict).", ("reason",),
            ),
        )
    return _shm_metrics_cache


class _PsClientBase:
    """Routing + scatter/gather shared by both transports.

    The hot path is *coalesced* by default (``EASYDL_PS_COALESCE=0`` or
    ``coalesce=False`` restores the strict pre-coalescing path): ids are
    deduplicated with ``np.unique`` before any RPC and the pulled rows are
    scattered back on return, so wire bytes and server work scale with the
    batch's UNIQUE ids — on Zipf-distributed recommendation batches that is
    a multiple, not a percentage. Pushes pre-accumulate duplicate ids
    client-side (occurrence order, bit-identical to the server's own
    accumulation) and shard routing uses one argsort-based partition
    instead of ``num_shards`` boolean-mask scans.
    """

    num_shards: int
    coalesce: bool = True
    #: per-job table namespace (ROADMAP item 5): when set, every PUBLIC
    #: table-name argument is prefixed ``<namespace>::`` before it touches
    #: routing, the wire, or the store — N jobs share one shard fleet with
    #: zero overlap, and the WAL / rescue / reshard / shm paths (all keyed
    #: on the full table name) isolate unchanged. ``save``/``restore``/
    #: ``stats`` stay TIER-wide by design: the substrate snapshots every
    #: tenant's tables together (per-job views filter on the prefix).
    namespace: str = ""
    # Guards lazy pool creation (class-level: trivially race-free; contended
    # only during the one-time init).
    _pool_lock = threading.Lock()
    # Set while a thread is re-dispatching a chunk through the top-level
    # pull/push (the RoutingChanged path of a live reshard). Such a thread
    # IS a bounded-pool worker, so its nested operation must run every
    # fan-out INLINE: submitting back into the pools from their own
    # workers deadlocks the moment every worker is a re-dispatcher
    # waiting for a slot. The ordinary shard-pool → chunk-pool nesting is
    # unaffected (two different pools, no cycle).
    _inline_dispatch = threading.local()

    # ------------------------------------------------------- coalescing plan
    def _plan(self, flat: np.ndarray, n: int):
        """(routed, routed_inv, offs) for a flat id batch under an
        ``n``-shard partition, cached for the immediately-following call
        with the SAME ids — the training loop always pushes the exact batch
        it just pulled, so the sort/unique/partition work is paid once per
        step, not twice. The key is the shard count plus the full id buffer
        (exact memcmp, no hashing): a false hit would route gradients to
        wrong rows, so probabilistic keys are out — and a live reshard
        changes ``n``, which invalidates every cached plan by construction.

        ``routed`` is the unique ids already in shard order (shard s owns
        ``routed[offs[s]:offs[s+1]]``) and ``routed_inv`` maps each batch
        position straight to its routed row — so pull scatters with ONE
        fancy gather and push accumulates directly into routed positions.
        """
        key = (n, flat.tobytes())
        # Two entries, not one: the pipelined loop pulls batch k+1 while
        # the write-behind queue pushes batch k, so both plans are live.
        cached = getattr(self, "_plan_cache", ())
        for k, plan in cached:
            if k == key:
                return plan
        uniq, inv = np.unique(flat, return_inverse=True)
        order, offs = self._partition(uniq, n)
        pos = np.empty(len(uniq), np.int64)
        pos[order] = np.arange(len(uniq), dtype=np.int64)
        plan = (uniq[order], pos[inv], offs)
        self._plan_cache = ((key, plan),) + tuple(cached[:1])
        return plan

    # Subclasses implement the per-shard primitives. ``route_gen`` is the
    # routing generation in force when the caller computed its partition
    # (None when the transport has no routing, e.g. Local): the gRPC
    # client's retry loops compare it against the live generation and
    # re-dispatch on a move.
    def _pull_shard(self, shard: int, table: str, ids: np.ndarray,
                    route_gen=None, vout: Optional[PullVersions] = None
                    ) -> np.ndarray:
        raise NotImplementedError

    def _push_shard(self, shard: int, table: str, ids: np.ndarray,
                    grads: np.ndarray, scale: float,
                    route_gen=None) -> None:
        raise NotImplementedError

    def _create_shard(self, shard: int, spec: TableSpec) -> None:
        raise NotImplementedError

    def _for_all(self, fn, n: Optional[int] = None) -> list:
        # One persistent pool per client: _for_all runs twice per training
        # step (pull + push), so per-call pool setup/teardown would sit on
        # the hot path. The pipelined PsTrainer loop drives pull and push
        # from different threads, so the lazy init must be locked — two
        # racing creations would leak an un-shutdown executor. ``n`` pins
        # the fan-out width for one operation: a routing rebuild swapping
        # ``self.num_shards`` mid-flight must not widen/narrow a fan-out
        # whose partition offsets were computed under the old count.
        if n is None:
            n = self.num_shards
        if n == 1 or getattr(_PsClientBase._inline_dispatch, "active",
                             False):
            return [fn(s) for s in range(n)]
        dead = None
        while True:
            pool = getattr(self, "_pool", None)
            if pool is None:
                with _PsClientBase._pool_lock:
                    pool = getattr(self, "_pool", None)
                    if pool is None:
                        pool = self._pool = ThreadPoolExecutor(
                            max_workers=max(n, 2),
                            thread_name_prefix="ps-client",
                        )
            try:
                futures = [pool.submit(fn, s) for s in range(n)]
            except RuntimeError:
                # A routing rebuild shut this pool down between our fetch
                # and the submit; loop to pick up the lazily-recreated one.
                # Same dead pool twice = the client itself was close()d —
                # surface that instead of spinning.
                if pool is dead:
                    raise
                dead = pool
                continue
            return [f.result() for f in futures]

    @staticmethod
    def _dispatch_inline(op, *args):
        """Run a nested top-level pull/push (the reshard re-dispatch) with
        every fan-out forced inline — see ``_inline_dispatch``. Save/
        restore, not set/clear: back-to-back routing moves (a 2→4 split
        then the 4→2 shrink) can nest a re-dispatch inside a re-dispatch,
        and the inner one's exit must not re-enable pool submission for
        the still-running outer one."""
        prev = getattr(_PsClientBase._inline_dispatch, "active", False)
        _PsClientBase._inline_dispatch.active = True
        try:
            return op(*args)
        finally:
            _PsClientBase._inline_dispatch.active = prev

    # --------------------------------------------------------------- routing
    def _partition(self, ids: np.ndarray, n: int):
        """One stable argsort groups ids by owning shard; returns
        ``(order, offsets)`` such that ``ids[order[offs[s]:offs[s+1]]]`` is
        shard ``s``'s slice. Replaces the O(num_shards · n) boolean-mask
        scans of the old path with O(n log n) once."""
        owner = shard_of(ids, n)
        order = np.argsort(owner, kind="stable")
        counts = np.bincount(owner, minlength=n)
        offs = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=offs[1:])
        return order, offs

    def _table_dim(self, table: str) -> int:
        """The table's embedding dim, for empty pulls (shape contract:
        ``ids.shape + (dim,)`` even with zero ids) and empty shard slices."""
        d = self._dims.get(table)
        if not d:
            d = self._lookup_dim(table)
            if d:
                self._dims[table] = d
        return d

    def _lookup_dim(self, table: str) -> int:  # subclass transport-specific
        raise NotImplementedError

    # ------------------------------------------------------------------- api
    def _ns(self, table: str) -> str:
        from easydl_tpu.ps.table import namespaced

        return namespaced(self.namespace, table) if self.namespace else table

    def create_table(self, spec: TableSpec) -> None:
        if self.namespace:
            import dataclasses

            spec = dataclasses.replace(spec, name=self._ns(spec.name))
        self._for_all(lambda s: self._create_shard(s, spec))
        self._dims[spec.name] = spec.dim

    def pull(self, table: str, ids: np.ndarray,
             versions: Optional[PullVersions] = None) -> np.ndarray:
        """ids any shape -> float32 ``ids.shape + (dim,)``. ``versions``
        (optional) collects the per-shard table push-versions the rows
        were read under — the caching layer's invalidation meta; plain
        callers never pay for it."""
        table = self._ns(table)
        ids = np.asarray(ids)
        flat = ids.reshape(-1).astype(np.int64)
        if flat.size == 0:
            return np.zeros(ids.shape + (self._table_dim(table),), np.float32)
        # Capture the routing generation FIRST, then the shard count:
        # partition offsets and the fan-out width must agree even if a
        # live reshard swaps the routing while this pull is in flight
        # (the stale chunks then re-dispatch through the rebuilt routing
        # at the chunk level). The generation is the chunks' staleness
        # check, so it must be the one in force when the partition was
        # computed — captured at chunk time it could post-date a rebuild
        # and silently bless an old-count partition against the new shard
        # set. Rebuilds publish num_shards before the generation, so this
        # read order can only err toward a spurious (safe, idempotent)
        # re-dispatch.
        gen0 = getattr(self, "_route_generation", None)
        n = self.num_shards
        # Resolve (and cache) the dim ONCE before fanning out: the shard
        # worker threads all consult it for chunk sizing, and a cold cache
        # would otherwise send num_shards concurrent Stats calls at shard 0.
        self._table_dim(table)
        if not self.coalesce:
            return self._pull_strict(table, ids, flat, n, gen0, versions)
        # Dedup before the RPC: every duplicate of a hot id would otherwise
        # ride the wire and hit the store once per occurrence.
        routed, routed_inv, offs = self._plan(flat, n)
        _client_metrics()[0].set(len(routed) / len(flat), table=table)
        parts = self._for_all(
            lambda s: self._pull_shard(s, table, routed[offs[s]:offs[s + 1]],
                                       gen0, versions),
            n,
        )
        dim = next((p.shape[-1] for p in parts if p.size),
                   self._table_dim(table))
        self._dims.setdefault(table, dim)
        # Skip zero-row parts: an empty shard slice may carry a (0, 0)
        # placeholder when the table dim could not be resolved, and
        # np.concatenate would reject the column mismatch. At least one
        # part is non-empty (flat.size > 0), and dropping empties keeps
        # shard order, so the result still lines up with ``routed``.
        nonempty = [p for p in parts if len(p)]
        rows = nonempty[0] if len(nonempty) == 1 else np.concatenate(nonempty)
        # Scatter back to batch positions (duplicates fan out here, on the
        # client, for free): one gather, straight from shard-routed rows.
        return rows[routed_inv].reshape(ids.shape + (dim,))

    def _pull_strict(self, table: str, ids: np.ndarray,
                     flat: np.ndarray, n: int,
                     route_gen=None,
                     versions: Optional[PullVersions] = None) -> np.ndarray:
        """Pre-coalescing pull (row per batch position on the wire) — the
        parity/bench baseline."""
        owner = shard_of(flat, n)
        parts = self._for_all(
            lambda s: self._pull_shard(s, table, flat[owner == s],
                                       route_gen, versions), n
        )
        dim = next((p.shape[-1] for p in parts if p.size),
                   self._table_dim(table))
        out = np.zeros((len(flat), dim), np.float32)
        for s, part in enumerate(parts):
            if part.size:
                out[owner == s] = part
        return out.reshape(ids.shape + (dim,))

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray,
             scale: float = 1.0) -> None:
        table = self._ns(table)
        ids = np.asarray(ids)
        flat = ids.reshape(-1).astype(np.int64)
        g = np.ascontiguousarray(grads, np.float32).reshape(len(flat), -1)
        if flat.size == 0:
            return
        # generation-then-count capture order; see pull()
        gen0 = getattr(self, "_route_generation", None)
        n = self.num_shards
        if not self.coalesce:
            owner = shard_of(flat, n)
            self._for_all(
                lambda s: self._push_shard(
                    s, table, flat[owner == s], g[owner == s], scale, gen0
                ),
                n,
            )
            return
        # Pre-accumulate duplicate ids client-side, in batch-occurrence
        # order — bit-identical to the accumulation the store itself would
        # do (np.add.at / embedding_store.cc both sum occurrences in batch
        # order; the shard-order permutation does not change any single
        # id's occurrence sequence), so the optimizer sees the same
        # gradient either way. Accumulation lands directly in routed
        # (shard-order) positions — no post-hoc reorder copy.
        routed, routed_inv, offs = self._plan(flat, n)
        if len(routed) == len(flat):
            acc = np.empty_like(g)  # no duplicates: pure scatter to
            acc[routed_inv] = g     # shard-routed positions
        else:
            # np.add.at is the only vectorized op with the sequential
            # occurrence-order adds parity requires (reduceat/bincount sum
            # pairwise/in float64 — different bits), but it is slow — so
            # route only the rows of genuinely-duplicated ids through it
            # and copy the singletons (typically the majority even on
            # Zipf batches) directly.
            counts = np.bincount(routed_inv, minlength=len(routed))
            single = counts == 1
            acc = np.empty((len(routed), g.shape[1]), np.float32)
            sel_single = single[routed_inv]
            acc[routed_inv[sel_single]] = g[sel_single]
            acc[~single] = 0.0
            sel = ~sel_single
            np.add.at(acc, routed_inv[sel], g[sel])
        self._for_all(
            lambda s: self._push_shard(
                s, table, routed[offs[s]:offs[s + 1]],
                acc[offs[s]:offs[s + 1]], scale, gen0
            ),
            n,
        )

    def save(self, directory: str, step: int) -> None:
        """Snapshot to ``directory``. A namespaced client saves ONLY its
        own tables (wire ``prefix`` scoping): a tenant's checkpoint on a
        shared tier must never contain — let alone later roll back —
        another job's rows."""
        prefix = ""
        if self.namespace:
            from easydl_tpu.ps.table import NAMESPACE_SEP

            prefix = self.namespace + NAMESPACE_SEP
        self._for_all(lambda s: self._save_shard(s, directory, step,
                                                 prefix=prefix))

    def restore(self, directory: str, step: int = -1) -> None:
        if self.namespace:
            # A tier-wide restore from a namespaced client would roll
            # EVERY tenant's tables back to this job's snapshot, and a
            # scoped in-place rollback is not WAL-logged yet — a shard
            # rescue after it would replay the pre-restore pushes on top
            # and silently diverge. Refuse loudly until a logged scoped
            # import exists; tenant shard faults recover through the
            # substrate's own WAL rescue instead (drill-proven).
            raise RuntimeError(
                "restore() is tier-wide and this client is namespaced "
                f"({self.namespace!r}); a shared multi-job tier cannot "
                "be rolled back by one tenant")
        self._for_all(lambda s: self._restore_shard(s, directory, step))

    def stats(self) -> List[pb.PsStatsResponse]:
        return self._for_all(self._stats_shard)

    def total_rows(self, table: str) -> int:
        table = self._ns(table)
        return sum(
            t.rows for st in self.stats() for t in st.tables if t.name == table
        )

    def probe_versions(self, table: str, shards) -> Dict[int, int]:
        """Current push-version of ``table`` on each of ``shards`` — the
        serving cache's cheap freshness probe for batches it can answer
        without any row pull. Best-effort: shards that fail the probe (or
        run legacy code with no version counter) are simply absent, and
        the caller treats their cached rows as unvalidated."""
        return {}


class LocalPsClient(_PsClientBase):
    """In-process PS cluster: N shards, no sockets.

    Coalescing is OFF by default here (unlike the gRPC client): dedup pays
    for itself by shrinking *wire* bytes, and there is no wire — the store
    accumulates duplicates itself either way (bit-identically), so
    client-side np.unique + re-accumulation would be pure added latency.
    """

    def __init__(self, num_shards: int = 1, backend: str = "auto",
                 coalesce: Optional[bool] = None, namespace: str = ""):
        self.num_shards = num_shards
        self.coalesce = (_env_flag("EASYDL_PS_COALESCE", False)
                        if coalesce is None else coalesce)
        self.namespace = namespace
        self._dims: Dict[str, int] = {}
        self.shards = [
            PsShard(shard_index=i, num_shards=num_shards, backend=backend)
            for i in range(num_shards)
        ]

    def _lookup_dim(self, table):
        try:
            return self.shards[0].table(table).dim
        except KeyError:
            return 0

    def _pull_shard(self, s, table, ids, route_gen=None, vout=None):
        if ids.size == 0:
            sh = self.shards[s]
            return np.zeros((0, sh.table(table).dim), np.float32)
        t = self.shards[s].table(table)
        if vout is not None:
            vout.record(s, t.push_version)  # before the gather, like Pull
        return t.pull(ids)

    def probe_versions(self, table, shards):
        table = self._ns(table)
        out = {}
        for s in shards:
            try:
                out[s] = self.shards[s].table(table).push_version
            except (KeyError, IndexError):
                continue
        return out

    def _push_shard(self, s, table, ids, grads, scale, route_gen=None):
        if ids.size:
            self.shards[s].table(table).push(ids, grads, scale)

    def _create_shard(self, s, spec):
        self.shards[s].create_table(spec)

    def _save_shard(self, s, directory, step, prefix=""):
        self.shards[s].save(directory, step, prefix=prefix)

    def _restore_shard(self, s, directory, step):
        self.shards[s].restore(directory, step)

    def _stats_shard(self, s):
        return self.shards[s].Stats(pb.PsStatsRequest(), None)


#: classification now lives in utils/retry.py (shared with the agent's
#: register path); kept under the old name for in-repo callers.
_is_transport_error = is_transport_error


#: Process-wide table-dims cache, one dict per registry-identified PS
#: *cluster*. Every ShardedPsClient against the same workdir shares ONE
#: dict: before this, each new client (the trainer's, a serving
#: replica's, a bench probe's) re-paid a Stats RPC at shard 0 on its
#: first empty pull to learn dims the process already knew. A routing
#: rebuild clears the dict IN PLACE so every sharer sees the
#: invalidation at once. Registry-less clients (plain address lists) get
#: a PRIVATE dict: addresses identify a cluster only for its lifetime,
#: and a later cluster reusing the same ports in this process (tests,
#: benches) must not inherit stale dims.
_SHARED_DIMS: Dict[str, Dict[str, int]] = {}
_SHARED_DIMS_LOCK = threading.Lock()


def _shared_dims_for(registry_workdir: Optional[str]) -> Dict[str, int]:
    if not registry_workdir:
        return {}
    key = os.path.realpath(registry_workdir)
    with _SHARED_DIMS_LOCK:
        return _SHARED_DIMS.setdefault(key, {})


class ShardedPsClient(_PsClientBase):
    """gRPC PS cluster client. ``addresses[i]`` must be shard i of N —
    routing is positional, the same order every worker must use.

    Vertical scaling: while a shard is migrating (replace-then-retire,
    docs/design/elastic-training-operator.md:86-101) its pushes come back
    with a retriable ``draining`` Ack; :meth:`_push_shard` retries — re-
    reading the shard's client each attempt — until :meth:`reroute` points
    it at the replacement, so no update is lost across the handoff."""

    def __init__(self, addresses: Sequence[str], timeout: float = 60.0,
                 drain_retry_s: float = 60.0,
                 transient_retry_s: float = 30.0,
                 registry_workdir: Optional[str] = None,
                 coalesce: Optional[bool] = None,
                 raw_ids: Optional[bool] = None,
                 pull_fp16: Optional[bool] = None,
                 pull_i8: Optional[bool] = None,
                 pull_shm: Optional[bool] = None,
                 chunk_bytes: Optional[int] = None,
                 namespace: str = ""):
        self.addresses = list(addresses)
        self.namespace = namespace
        self.num_shards = len(self.addresses)
        self._timeout = timeout
        self.coalesce = (_env_flag("EASYDL_PS_COALESCE", True)
                         if coalesce is None else coalesce)
        # Wire format: raw_ids (little-endian int64 bytes) replaces the
        # varint-encoded repeated ids — zero encode/decode on the hot path.
        # Back-compat is negotiated per shard: until a PullResponse carries
        # `dtype` (new servers always set it) the request includes BOTH
        # raw_ids and the legacy list, so an old server keeps working and a
        # new one confirms itself on the first round-trip.
        self.raw_ids = (_env_flag("EASYDL_PS_RAW_IDS", True)
                        if raw_ids is None else raw_ids)
        self.pull_fp16 = (_env_flag("EASYDL_PS_PULL_FP16", False)
                          if pull_fp16 is None else pull_fp16)
        # Third rung of the payload ladder (ps/quant.py): int8 + per-row
        # scale, ~0.25x the f32 wire. Requested per pull; the SERVER
        # decides what it can answer (a legacy shard replies f32/f16 and
        # the decode below follows the response's dtype, so a reroute to
        # an older replacement degrades without a hard failure). i8 wins
        # over fp16 when both are set.
        self.pull_i8 = (_env_flag("EASYDL_PS_PULL_I8", False)
                        if pull_i8 is None else pull_i8)
        # Zero-copy shared-memory pulls (EASYDL_PS_SHM / constructor
        # opt-in): when a PullResponse advertises a shm segment this
        # client can actually open (co-located shard, native store), the
        # shard's reads leave gRPC entirely. Negotiated per (shard,
        # table); any mismatch falls back silently to the wire.
        self.pull_shm = (_env_flag("EASYDL_PS_SHM", False)
                         if pull_shm is None else pull_shm)
        #: (shard, table) -> live shm reader; values None = negotiation
        #: failed for the advertised segment (don't retry until the shard
        #: advertises a different name). Guarded by _routing_lock siblings
        #: via _shm_mu (readers are processwide mmaps, cheap to share).
        self._shm_readers: Dict[tuple, object] = {}
        self._shm_failed: Dict[tuple, str] = {}
        self._shm_mu = threading.Lock()
        # Large unary messages are superlinearly slow through python gRPC
        # (measured: one 2 MB pull costs ~2.5x two 1 MB pulls), so per-shard
        # transfers split into ~EASYDL_PS_CHUNK_BYTES value-payload chunks
        # issued concurrently over the shard's HTTP/2 channel. 0 disables.
        self.chunk_bytes = (
            knob_int("EASYDL_PS_CHUNK_BYTES")
            if chunk_bytes is None else chunk_bytes)
        self._chunk_pool: Optional[ThreadPoolExecutor] = None
        self._raw_capable = [False] * self.num_shards
        # Bumped by reroute(): a capability-bearing response only counts if
        # no reroute happened while it was in flight (see _pull_chunk).
        self._reroute_epoch = [0] * self.num_shards
        # Shard fencing epochs (ps/registry.py): the epoch of the
        # publication each shard's route came from, stamped on every push.
        # 0 = unknown (plain-address construction, no registry) — servers
        # accept unstamped pushes, so nothing changes for registry-less
        # deployments; with a registry the stamp is what lets a server
        # reject pushes routed by a superseded publication.
        self._epochs = [0] * self.num_shards
        self._dims = _shared_dims_for(registry_workdir)
        self.drain_retry_s = drain_retry_s
        # Bound for transient-UNAVAILABLE retry on the PULL path (pushes
        # have the drain window): long enough to ride a shard crash +
        # registry rescue, short enough that a dead-and-unreplaced shard
        # still surfaces to the elastic layer as a real failure.
        self.transient_retry_s = transient_retry_s
        # With a registry (ps/registry.py), a gated/unreachable shard is
        # re-resolved from the latest publications mid-retry — the client
        # follows operator-driven replacements without anyone calling
        # reroute() explicitly. `_route_generation` is the routing-table
        # generation the current shard set was built from: when the
        # registry commits a NEWER one (a live reshard), the whole routing
        # — addresses, clients, epochs, partition plans, dims — is rebuilt
        # atomically under `_routing_lock` and in-flight chunks re-dispatch
        # through the new partition (see RoutingChanged).
        self.registry_workdir = registry_workdir
        self._registry_checked_at = 0.0
        self._route_generation = 0
        self._routing_lock = threading.Lock()
        self._clients = [
            RpcClient(PS_SERVICE, a, timeout=timeout,
                      options=GRPC_MSG_OPTIONS) for a in self.addresses
        ]

    @classmethod
    def from_registry(cls, workdir: str, num_shards: Optional[int] = None,
                      wait_s: float = 60.0, **kwargs) -> "ShardedPsClient":
        """Resolve shard addresses from the pod registry (operator-managed
        PS clusters publish there; see easydl_tpu/ps/__main__.py).
        ``num_shards=None`` takes the cluster shape from the registry
        itself (the routing table when one exists, else the publications),
        so callers need no out-of-band shard count."""
        from easydl_tpu.ps import registry

        if num_shards is None:
            num_shards, addrs = registry.discover(workdir, timeout=wait_s)
        else:
            addrs = registry.addresses(workdir, num_shards, timeout=wait_s)
        client = cls(addrs, registry_workdir=workdir, **kwargs)
        smap = registry.shard_map(workdir)
        client._epochs = [
            int(smap.get(s, {}).get("epoch", 0)) for s in range(num_shards)
        ]
        client._route_generation = registry.committed_generation(workdir)
        return client

    # ------------------------------------------------------ routing refresh
    def refresh_routing(self) -> bool:
        """Adopt the registry's committed routing generation if it moved
        (un-throttled). Returns True when the shard set was rebuilt. The
        retry loops call this implicitly; explicit calls are for callers
        about to do shard-shaped work (save/stats) after a possible
        reshard."""
        return self._check_routing_generation(force=True)

    def _check_routing_generation(self, force: bool = False) -> bool:
        """If the registry committed a routing generation NEWER than the
        one this client's shard set was built from, rebuild the whole
        routing. Returns True when a rebuild happened."""
        if not self.registry_workdir:
            return False
        from easydl_tpu.ps import registry

        try:
            rt = registry.routing_table(self.registry_workdir)
        except OSError:
            return False
        gen = int(rt.get("generation", 0))
        if gen <= self._route_generation:
            return False
        n = int(rt.get("num_shards", 0))
        if n <= 0:
            return False
        return self._rebuild_routing(gen, n, force=force)

    def _rebuild_routing(self, gen: int, n: int, force: bool = False) -> bool:
        from easydl_tpu.ps import registry

        with self._routing_lock:
            if gen <= self._route_generation:
                return True  # another thread already rebuilt
            try:
                addrs = registry.addresses(self.registry_workdir, n,
                                           timeout=10.0 if force else 0.0)
            except TimeoutError:
                # Committed but not fully published yet (or a publication
                # race): keep the old routing, the next retry re-checks.
                return False
            smap = registry.shard_map(self.registry_workdir)
            old_clients = self._clients
            old_pool = getattr(self, "_pool", None)
            self._clients = [
                RpcClient(PS_SERVICE, a, timeout=self._timeout,
                          options=GRPC_MSG_OPTIONS) for a in addrs
            ]
            self.addresses = list(addrs)
            self.num_shards = n
            self._epochs = [int(smap.get(s, {}).get("epoch", 0))
                            for s in range(n)]
            self._raw_capable = [False] * n
            self._reroute_epoch = [0] * n
            # A shard-count change invalidates every partition plan and the
            # dims cache (dims re-resolve via Stats on the new shard 0).
            # clear(), not rebind: the dict is shared with every other
            # client of this cluster, and they must see the invalidation.
            self._plan_cache = ()
            self._dims.clear()
            if old_pool is not None:
                self._pool = None  # recreated lazily, sized to the new n
            # Publish the new generation LAST: chunk retry loops key their
            # "did routing change under me" check on it, and must only see
            # it move once the new shard set is fully in place.
            self._route_generation = gen
        # Shard indices renumber under the new generation: every shm
        # reader is bound to an OLD index and must re-negotiate against
        # whatever the new shard set advertises.
        self._shm_reset()
        if old_pool is not None:
            old_pool.shutdown(wait=False)
        for c in old_clients:
            c.close()
        log.info("ps routing rebuilt: generation %d, %d shard(s) (%s)",
                 gen, n, ", ".join(addrs))
        return True

    def _reshard_plan_active(self) -> bool:
        """Whether the registry shows an in-flight reshard plan — the one
        condition under which a shard may legitimately refuse service for
        longer than the transient budget (push-gated source awaiting
        cutover/commit)."""
        if not self.registry_workdir:
            return False
        from easydl_tpu.ps import registry

        try:
            return bool(registry.routing_table(
                self.registry_workdir).get("plan"))
        except OSError:
            return False

    def _maybe_reroute_from_registry(self, shard: int,
                                     force: bool = False) -> bool:
        if not self.registry_workdir:
            return False
        # Throttle: the retry loops call this every ~50ms for the whole
        # drain window; scanning/parsing the registry dir (often network FS)
        # that often is pure waste — publications are seconds apart.
        # ``force`` bypasses it: a stale-epoch/stale-route rejection is
        # PROOF the registry moved, so the refresh must not wait out the
        # throttle.
        now = time.monotonic()
        if not force and now - self._registry_checked_at < 0.5:
            return False
        self._registry_checked_at = now
        from easydl_tpu.ps import registry

        # Routing generation first: after a reshard commit, the per-shard
        # map below describes the NEW shard set — adopting one of its
        # addresses into an old-generation slot would route a partition
        # computed under the old shard count at a shard that owns different
        # ids. A generation move always rebuilds the whole routing.
        if self._check_routing_generation(force=force):
            return True
        if shard >= self.num_shards:
            return False  # stale index from before a shrink; chunk re-checks
        # Per-shard reroute is for SAME-generation replacements (a rescue
        # pod taking over the index) — resolve within the generation THIS
        # client routes by, never the registry's committed one: the commit
        # can land between the generation check above and this read, and
        # the committed map would then hand back the NEW generation's pod
        # for this index. Adopting it (address + epoch) re-aims an
        # OLD-partition chunk at a shard that accepts and applies ids it
        # does not own — rows landing outside the migration lineage, i.e.
        # silent loss. Cross-generation moves must always go through the
        # full rebuild (which raises RoutingChanged up the retry loops).
        entry = registry.shard_map(
            self.registry_workdir,
            generation=self._route_generation).get(shard)
        if entry and entry["address"] != self.addresses[shard]:
            try:
                self.reroute(shard, entry["address"],
                             epoch=int(entry.get("epoch", 0)))
            except Exception as e:
                # The published replacement may itself be gone (double
                # preemption): treat as "no reroute yet" and keep retrying
                # the drain window — a newer publication will arrive.
                log.warning("reroute of shard %d to %s failed: %s",
                            shard, entry["address"], e)
                return False
            return True
        if entry:
            # Same address, newer epoch: an in-place re-publication (e.g. a
            # same-port restart). Adopt the epoch so stamped pushes match.
            ep = int(entry.get("epoch", 0))
            if ep and ep != self._epochs[shard]:
                self._epochs[shard] = ep
                return True
        return False

    def close(self) -> None:
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
        if self._chunk_pool is not None:
            self._chunk_pool.shutdown(wait=False)
        self._shm_reset()
        for c in self._clients:
            c.close()

    # ------------------------------------------------------------- chunking
    def _chunks(self, n: int, dim: int):
        """Row ranges splitting an n-row transfer into ~chunk_bytes value
        payloads. One range (no split) when chunking is off, the payload is
        small, or the dim is still unknown."""
        row_bytes = 4 * max(dim, 1)
        if not self.chunk_bytes or dim <= 0:
            return [(0, n)]
        rows = max(int(self.chunk_bytes // row_bytes), 256)
        # Balanced split: ceil-divide into equal chunks rather than
        # budget-sized chunks plus a runt (a 50-row tail chunk is a whole
        # RPC of overhead for no payload). Slight overshoot past the budget
        # (< 1.5x) beats an extra round trip.
        if n <= (rows * 3) // 2:
            return [(0, n)]
        nchunks = -(-n // rows)
        size = -(-n // nchunks)
        return [(i, min(i + size, n)) for i in range(0, n, size)]

    def _chunk_fan(self, tasks):
        """Run chunk thunks concurrently (shared bounded pool, lazily
        created under the same class-level lock as the shard pool). From a
        thread that is ITSELF a fan-out worker (a reshard re-dispatch),
        run inline — submitting back into the bounded pool from its own
        workers deadlocks once every worker is a re-dispatcher waiting
        for a slot."""
        if (len(tasks) == 1
                or getattr(_PsClientBase._inline_dispatch, "active",
                           False)):
            return [t() for t in tasks]
        pool = self._chunk_pool
        if pool is None:
            with _PsClientBase._pool_lock:
                pool = self._chunk_pool
                if pool is None:
                    pool = self._chunk_pool = ThreadPoolExecutor(
                        max_workers=8, thread_name_prefix="ps-chunk",
                    )
        futures = [pool.submit(t) for t in tasks]
        return [f.result() for f in futures]

    def _lookup_dim(self, table):
        try:
            for st in self._stats_shard(0).tables:
                if st.name == table:
                    return st.dim
        except Exception as e:
            count_swallowed("ps.client.lookup_dim", e)
        return 0

    def _wire_ids(self, s, ids) -> dict:
        """Request kwargs for the id list: raw bytes by default, plus the
        legacy varint list until shard ``s`` has proven (via
        PullResponse.dtype) that it understands raw_ids."""
        if not self.raw_ids:
            return {"ids": ids.tolist()}
        kwargs = {"raw_ids": np.ascontiguousarray(ids, "<i8").tobytes()}
        if not self._raw_capable[s]:
            kwargs["ids"] = ids.tolist()
        return kwargs

    def _pull_shard(self, s, table, ids, route_gen=None, vout=None):
        if ids.size == 0:
            return np.zeros((0, self._table_dim(table)), np.float32)
        if self.pull_shm:
            rows = self._shm_pull(s, table, ids, route_gen, vout)
            if rows is not None:
                return rows
        return self._wire_pull(s, table, ids, route_gen, vout)

    def _wire_pull(self, s, table, ids, route_gen=None, vout=None):
        """The chunked gRPC pull for one shard's id slice."""
        ranges = self._chunks(len(ids), self._table_dim(table))
        parts = self._chunk_fan(
            [lambda lo=lo, hi=hi: self._pull_chunk(s, table, ids[lo:hi],
                                                   route_gen, vout)
             for lo, hi in ranges]
        )
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    # ------------------------------------------------------- shm transport
    def _shm_pull(self, s, table, ids, route_gen=None, vout=None):
        """Serve this shard slice straight from the shard's shm mirror, or
        None to take the wire. The mirror rides every server-side
        consistency gate by REVOCATION: a cutover/fenced/restored shard
        revokes its segments, the gather fails `revoked`, and the client
        silently returns to gRPC — where stale-route/stale-epoch handling
        lives. A rebuilt routing drops all readers outright (shard
        indices renumber), and a stale in-flight generation skips shm so
        the wire's RoutingChanged re-dispatch stays authoritative."""
        if route_gen is not None and self._route_generation != route_gen:
            return None
        with self._shm_mu:
            reader = self._shm_readers.get((s, table))
        if reader is None:
            return None
        from easydl_tpu.ps import shm as _shm

        m = _shm_metrics()
        try:
            if reader.tiered:
                # Tiered store behind the mirror: only the HOT tier is
                # mirrored. Misses may be cold rows with real trained
                # state, so they come back as a mask and are fetched on
                # the wire — a partial fallback, not a full one (the
                # segment is NOT revoked by demotion).
                rows, version, miss = reader.pull_partial(ids)
            else:
                rows, version = reader.pull(ids)
                miss = None
        except _shm.ShmUnavailable as e:
            m[2].inc(reason="revoked" if e.revoked else "contention")
            if e.revoked:
                with self._shm_mu:
                    if self._shm_readers.get((s, table)) is reader:
                        self._shm_readers.pop((s, table), None)
                reader.close()
            return None
        if miss is not None:
            m[2].inc(reason="cold-miss")
            rows[miss] = self._wire_pull(s, table, ids[miss], route_gen,
                                         vout)
        if vout is not None:
            vout.record(s, version)
        m[0].inc(table=table)
        m[1].inc(int(ids.size - (0 if miss is None else int(miss.sum()))),
                 table=table)
        return rows

    def _shm_negotiate(self, s, table, name, nonce) -> None:
        """Adopt a PullResponse's shm advertisement: open+verify the
        segment once per (shard, table, name); an un-openable name (a
        REMOTE shard — this is the co-location test) is remembered so the
        hot path never re-pays the open."""
        key = (s, table)
        with self._shm_mu:
            cur = self._shm_readers.get(key)
            if cur is not None and cur.name == name and cur.nonce == nonce:
                return
            if self._shm_failed.get(key) == name:
                return
        from easydl_tpu.ps import shm as _shm

        reader = _shm.open_reader(name, int(nonce))
        old = None
        with self._shm_mu:
            if reader is None:
                self._shm_failed[key] = name
            else:
                old = self._shm_readers.pop(key, None)
                self._shm_readers[key] = reader
                self._shm_failed.pop(key, None)
        if old is not None:
            old.close()
        if reader is None:
            _shm_metrics()[2].inc(reason="open-failed")
        else:
            log.info("ps shard %d: table %r pulls via shm segment %s",
                     s, table, name)

    def _shm_reset(self, shard: Optional[int] = None) -> None:
        """Drop shm readers (all, or one shard's) — routing rebuilds and
        reroutes renumber/replace shards, so their segments mean nothing."""
        with self._shm_mu:
            keys = [k for k in self._shm_readers
                    if shard is None or k[0] == shard]
            dropped = [self._shm_readers.pop(k) for k in keys]
            if shard is None:
                self._shm_failed.clear()
            else:
                for k in [k for k in self._shm_failed if k[0] == shard]:
                    self._shm_failed.pop(k, None)
        for r in dropped:
            r.close()

    def probe_versions(self, table, shards):
        """Zero-id Pull per shard: the response carries the table's
        push-version and dim but no rows — a few hundred bytes of wire,
        issued concurrently over the chunk pool (the probe sits on the
        serving hot path, and N sequential RTTs would tax exactly the
        all-hit batches the cache exists to make cheap). Errors (dead
        shard, fenced zombie, cut-over source, no such table) just omit
        the shard: the caller's cached rows for it count as unvalidated,
        which degrades to a plain re-pull — the retriable path — never
        to serving a possibly-stale row."""
        table = self._ns(table)

        def probe(s):
            try:
                with self._routing_lock:
                    if s >= len(self._clients):
                        return None
                    client = self._clients[s]
                resp = client.Pull(pb.PullRequest(table=table))
            except Exception:
                return None
            return (int(s), int(resp.version)) if resp.version else None

        shards = list(shards)
        results = self._chunk_fan([lambda s=s: probe(s) for s in shards])
        return dict(r for r in results if r is not None)

    def _pull_chunk(self, s, table, ids, route_gen=None, vout=None):
        # Pulls are read-only — retrying a transient transport failure is
        # unconditionally safe, and without it ONE sporadic UNAVAILABLE
        # (shard crash, connection refused during a pod replacement) killed
        # the training job: the first bug the chaos drills surfaced. Each
        # retry first re-resolves the shard from the registry, so the loop
        # follows a rescue pod to its new address mid-outage. ONLY the RPC
        # itself is inside the retry: reshape of a malformed response
        # raises ValueError, which the transport classifier would read as
        # "closed channel" and spin on for the whole budget — a corrupt
        # reply must surface immediately, as before. The request is
        # REBUILT on every attempt: a mid-retry reroute() resets the
        # shard's raw-capability, and the retried RPC must re-include the
        # legacy ids list in case the replacement runs older code.
        # The epoch is re-read per attempt: only a response from the
        # CURRENT routing may arm the raw capability below — a reply from
        # the pre-reroute server arriving after reroute()'s capability
        # reset must not re-arm it for a replacement that may run older
        # code (concurrent chunks make that interleaving real).
        # len() guard, not num_shards: a concurrent routing rebuild assigns
        # num_shards before it swaps the per-shard lists, and this read sits
        # outside the RoutingChanged-mapping try below.
        state = {"epoch": self._reroute_epoch[s]
                 if s < len(self._reroute_epoch) else 0}
        # A live reshard invalidates this chunk's shard index itself (the
        # ids repartition under the new count): every attempt first checks
        # the routing generation, and a move re-dispatches the chunk
        # through the top-level pull — the registry-rebuilt partition then
        # routes each id to its new owner. Reads are idempotent, so the
        # re-dispatch is unconditionally safe. The generation is the one
        # captured by the TOP-LEVEL op next to its shard count (a chunk-
        # time capture could post-date a rebuild and bless an old-count
        # partition against the new shard set); None only on internal
        # callers with no partition at stake.
        if route_gen is None:
            route_gen = self._route_generation

        def attempt():
            # Generation check and per-shard reads under ONE hold of the
            # routing lock: checked lock-free, a rebuild completing between
            # the check and the reads would hand this old-partition chunk a
            # NEW-generation client+epoch — it would pass the new shard's
            # fence and read rows it doesn't own. The RPC itself runs
            # outside the lock (a rebuild mid-RPC closes the old channel,
            # which surfaces as a retriable transport error).
            try:
                with self._routing_lock:
                    if self._route_generation != route_gen:
                        raise RoutingChanged()
                    state["epoch"] = self._reroute_epoch[s]
                    req = pb.PullRequest(
                        table=table,
                        value_dtype=self._value_dtype(),
                        **self._wire_ids(s, ids),
                    )
                    client = self._clients[s]
            except IndexError:
                raise RoutingChanged()  # rebuilt to fewer shards mid-flight
            return client.Pull(req)

        # Span per chunk; utils/retry.py stamps every transient retry as an
        # event inside it, so a slow pull names its retries. No-op with
        # tracing disabled. The outer loop is the live-reshard ride-out:
        # a push-gated source (a rescue born mid-plan, or the brief
        # cutover→commit window) aborts pulls UNAVAILABLE for as long as
        # the migration runs, which can legitimately exceed the transient
        # budget sized for dead-shard detection — so an exhausted budget
        # only becomes a hard failure once no reshard plan is in flight
        # (or the overall drain budget, the same bound pushes get, is
        # spent). Pulls are idempotent, so re-entering the retry is free.
        try:
            ride_deadline = time.monotonic() + max(self.drain_retry_s,
                                                   self.transient_retry_s)
            while True:
                try:
                    with tracing.start_span("ps_pull", shard=s, table=table,
                                            ids=int(ids.size)):
                        resp = retry_transient(
                            attempt,
                            max_elapsed_s=self.transient_retry_s,
                            on_retry=lambda e:
                                self._maybe_reroute_from_registry(s),
                            describe=f"ps shard {s} pull",
                        )
                    break
                except RoutingChanged:
                    raise
                except Exception as e:
                    if (not _is_transport_error(e)
                            or time.monotonic() > ride_deadline
                            or not self._reshard_plan_active()):
                        raise
        except RoutingChanged:
            # Inline: this thread is a chunk/shard pool worker — the nested
            # pull must not submit back into the bounded pools (deadlock
            # once every worker is a re-dispatcher waiting for a slot).
            # The re-dispatched rows come from a DIFFERENT routing
            # generation: the whole version collection is void (a cache
            # must not tag them under this generation's shard indices).
            if vout is not None:
                vout.invalidate()
            return np.ascontiguousarray(
                self._dispatch_inline(self.pull, table, ids)
                .reshape(len(ids), -1))
        if vout is not None:
            vout.record(s, resp.version)
        if (s < len(self._reroute_epoch) and resp.dtype
                and self._reroute_epoch[s] == state["epoch"]
                and self._route_generation == route_gen):
            # A dtype-bearing response is the raw-capability handshake:
            # later requests to this shard drop the duplicate legacy list.
            self._raw_capable[s] = True
        if self.pull_shm and resp.shm_segment:
            self._shm_negotiate(s, table, resp.shm_segment, resp.shm_nonce)
        # Decode follows the RESPONSE's dtype, not the request's: the
        # serving shard answers the best encoding it supports, so a legacy
        # server (or an older replacement after a reroute) degrades an i8
        # request to f16/f32 without any hard failure.
        if resp.dtype == "f16":
            vals = np.frombuffer(resp.values, "<f2").astype(np.float32)
        elif resp.dtype == _quant.I8:
            return _quant.decode_payload(resp.values, resp.row_scales,
                                         resp.dim)
        else:
            vals = np.frombuffer(resp.values, "<f4")
        return vals.reshape(len(ids), resp.dim)

    def _value_dtype(self) -> str:
        return _quant.I8 if self.pull_i8 else ("f16" if self.pull_fp16
                                               else "")

    def _push_shard(self, s, table, ids, grads, scale, route_gen=None):
        if ids.size == 0:
            return
        # Chunking is safe ONLY on the coalesced path, where ids are unique:
        # chunks then carry DISJOINT ids, so concurrent application on the
        # shard cannot interleave updates to one row, and a drain gate
        # landing between chunks retries only the unapplied remainder —
        # exactly the semantics of two back-to-back smaller pushes. The
        # strict path may repeat an id; splitting its occurrences across
        # concurrent chunks would apply the nonlinear (adagrad) update to
        # partial sums in nondeterministic order, so it keeps the pre-PR
        # one-message-per-shard shape.
        ranges = (self._chunks(len(ids), grads.shape[1])
                  if self.coalesce else [(0, len(ids))])
        self._chunk_fan(
            [lambda lo=lo, hi=hi: self._push_chunk(
                s, table, ids[lo:hi], grads[lo:hi], scale, route_gen)
             for lo, hi in ranges]
        )

    def _push_chunk(self, s, table, ids, grads, scale, route_gen=None):
        grads_bytes = grads.tobytes()

        def make_req():
            # Rebuilt per attempt: a mid-retry reroute() resets the shard's
            # raw-capability, and the retried push must re-include the
            # legacy ids list in case the replacement runs older code (the
            # grads payload is reused — only the id encoding can change).
            # The epoch stamp is re-read too: a reroute or a stale-epoch
            # rejection refreshes it, and the retried push must carry the
            # successor's epoch to pass its fence.
            return pb.PushRequest(
                table=table, grads=grads_bytes, scale=scale,
                epoch=self._epochs[s],
                **self._wire_ids(s, ids),
            )

        deadline = time.monotonic() + self.drain_retry_s
        # Span per chunk; the drain/transport retry loop below stamps each
        # wait as an event inside it (tracing disabled: all no-ops).
        span = tracing.start_span("ps_push", shard=s, table=table,
                                  ids=int(ids.size))
        try:
            # The staleness baseline is the TOP-LEVEL op's captured
            # generation (see pull) — a chunk-time capture could post-date
            # a rebuild and bless an old-count partition.
            self._push_with_retries(
                s, make_req, deadline, span,
                self._route_generation if route_gen is None else route_gen)
        except RoutingChanged:
            # Live reshard: this chunk's ids repartition under the new
            # shard count — and possibly across SEVERAL new shards — so the
            # per-shard loop cannot simply re-aim. Re-dispatch through the
            # top-level push, which re-partitions under the rebuilt
            # routing. Exactly-once: the old shard rejected the chunk
            # (`stale-route`, applied nothing) or the transport died before
            # an ack — and a WAL'd-but-unacked apply is recognised by the
            # destination's replay-digest dedupe.
            span.add_event("rerouted-reshard")
            # Inline for the same reason as the pull re-dispatch: no pool
            # re-entry from a pool worker.
            self._dispatch_inline(self.push, table, ids, grads, scale)
        finally:
            span.end()

    def _push_with_retries(self, s, make_req, deadline, span,
                           route_gen=None):
        transport_fails = 0
        last_ack = ""  # the last retriable Ack.message, for error context
        while True:
            # Snapshot under the routing lock — same rationale as the pull
            # attempt: the generation check and the client/epoch reads
            # must come from ONE routing state, or a rebuild landing
            # between them sends this old-partition chunk to a
            # new-generation shard that will accept and misapply it.
            try:
                with self._routing_lock:
                    if (route_gen is not None
                            and self._route_generation != route_gen):
                        raise RoutingChanged()
                    # re-read client AND rebuild request: reroute may swap
                    # both
                    client = self._clients[s]
                    req = make_req()
            except IndexError:
                raise RoutingChanged()  # rebuilt to fewer shards mid-flight
            try:
                ack = client.Push(req)
            except Exception as e:
                # Transport failure mid-handoff: reroute() may close the old
                # client while this retry loop holds it (the next iteration
                # re-reads the swapped client), or the old pod may already be
                # retired. ONLY those are retriable — a server-side handler
                # error surfaces as RpcError(UNKNOWN) and must raise now with
                # its real cause, not stall out the drain window. Re-applying
                # on retry cannot double-count: during a handoff the old
                # shard is gated (DRAINING), and across a crash rescue the
                # WAL-replay dedupe on the rescuer recognises a retried
                # push it already replayed.
                if not _is_transport_error(e):
                    raise
                if time.monotonic() > deadline:
                    addr = (self.addresses[s] if s < len(self.addresses)
                            else "?")
                    raise RuntimeError(
                        f"ps shard {s} ({addr}) unreachable "
                        f"past {self.drain_retry_s}s: {e}"
                        + (f"; last ack: {last_ack!r}" if last_ack else "")
                    ) from e
                span.add_event("retry", error=repr(e),
                               attempt=transport_fails + 1)
                self._maybe_reroute_from_registry(s)
                # Exponential backoff + jitter (vs the old fixed 50ms):
                # every worker thread of the fleet hits this loop together
                # when a shard dies — decorrelate their re-arrival at the
                # rescue pod.
                transport_fails += 1
                time.sleep(backoff_delay(transport_fails, base_s=0.05,
                                         cap_s=1.0))
                continue
            transport_fails = 0
            if ack.ok:
                return
            retriable_fence = ack.message.startswith(STALE_EPOCH)
            retriable_route = ack.message.startswith(STALE_ROUTE)
            if not (ack.message.startswith(DRAINING) or retriable_fence
                    or retriable_route):
                raise RuntimeError(f"ps shard {s} push failed: {ack.message}")
            last_ack = ack.message
            if time.monotonic() > deadline:
                # Exhausted the drain/reroute window: name the shard AND
                # the last Ack so the failure is debuggable from the
                # message alone — this raise typically surfaces through an
                # AsyncPusher drain several call frames from the push site.
                raise RuntimeError(
                    f"ps shard {s} ({self.addresses[s]}) kept rejecting "
                    f"pushes past {self.drain_retry_s}s with no reroute; "
                    f"last ack: {last_ack!r}"
                )
            span.add_event("fence" if retriable_fence
                           else "stale-route" if retriable_route
                           else "draining")
            # A stale-epoch/stale-route Ack is proof the registry moved on:
            # refresh immediately (bypass the reroute throttle) so the
            # retried push carries the successor's route + epoch — or, for
            # stale-route, so the routing-generation rebuild fires the
            # moment the reshard coordinator commits (the gen check at the
            # loop top then raises RoutingChanged and the chunk
            # re-partitions).
            self._maybe_reroute_from_registry(
                s, force=retriable_fence or retriable_route)
            time.sleep(0.05)

    # ------------------------------------------------------------- migration
    def reroute(self, shard: int, address: str,
                epoch: Optional[int] = None) -> None:
        """Point ``shard``'s traffic at a replacement server (handoff step
        3). In-flight draining pushes pick up the new client on their next
        retry. ``epoch`` is the replacement publication's fencing epoch
        (None keeps the current stamp — manual reroutes without a
        registry)."""
        client = RpcClient(PS_SERVICE, address, timeout=60.0,
                           options=GRPC_MSG_OPTIONS)
        try:
            client.wait_ready(30.0)
        except Exception:
            client.close()  # don't leak the channel on a dead replacement
            raise
        old, self._clients[shard] = self._clients[shard], client
        self.addresses[shard] = address
        if epoch is not None:
            self._epochs[shard] = int(epoch)
        # The replacement may run older code: re-negotiate the raw_ids
        # capability from scratch (one both-fields request, then raw-only).
        # The epoch bump invalidates capability signals from responses
        # still in flight to the OLD server, so they cannot re-arm it.
        self._reroute_epoch[shard] += 1
        self._raw_capable[shard] = False
        # The replacement is a different process: its mirror (if any) will
        # be advertised on its own first response.
        self._shm_reset(shard)
        old.close()
        log.info("ps shard %d rerouted to %s", shard, address)

    def migrate_shard(self, shard: int, new_address: str, directory: str,
                      step: int) -> None:
        """The full vertical-scaling handoff for one live shard:

        1. Drain the old pod (pushes gated + rows saved under ``directory``);
        2. the replacement (already serving at ``new_address``) restores
           that save;
        3. reroute this client — retried pushes land on the replacement.

        The operator created the replacement via ``resource_updation``
        replace-then-retire; once this returns, the old pod is safe to
        retire."""
        ack = self._clients[shard].Drain(
            pb.PsSaveRequest(directory=directory, step=step)
        )
        if not ack.ok:
            raise RuntimeError(f"ps shard {shard} drain failed: {ack.message}")
        repl = RpcClient(PS_SERVICE, new_address, timeout=60.0,
                         options=GRPC_MSG_OPTIONS)
        try:
            repl.wait_ready(30.0)
            rack = repl.Restore(
                pb.PsRestoreRequest(directory=directory, step=step)
            )
            if not rack.ok:
                raise RuntimeError(
                    f"replacement restore failed: {rack.message}"
                )
        finally:
            repl.close()
        self.reroute(shard, new_address)

    def _create_shard(self, s, spec):
        ack = self._clients[s].CreateTable(spec_to_proto(spec))
        if not ack.ok:
            raise RuntimeError(f"ps shard {s} create_table failed: {ack.message}")

    def _save_shard(self, s, directory, step, prefix=""):
        ack = self._clients[s].Save(pb.PsSaveRequest(
            directory=directory, step=step, prefix=prefix))
        if not ack.ok:
            raise RuntimeError(f"ps shard {s} save failed: {ack.message}")

    def _restore_shard(self, s, directory, step):
        ack = self._clients[s].Restore(
            pb.PsRestoreRequest(directory=directory, step=step)
        )
        if not ack.ok:
            raise RuntimeError(f"ps shard {s} restore failed: {ack.message}")

    def _stats_shard(self, s):
        return self._clients[s].Stats(pb.PsStatsRequest())


# --------------------------------------------------------------- jit lookup

_LOOKUP_CLIENTS: Dict[int, tuple] = {}
_next_handle = [0]


def register_lookup(client: _PsClientBase, table: str, dim: int,
                    scale: float = 1.0) -> int:
    """Register a (client, table) pair for :func:`ps_lookup`; returns the
    static handle to pass into jitted code."""
    h = _next_handle[0]
    _next_handle[0] += 1
    _LOOKUP_CLIENTS[h] = (client, table, dim, scale)
    return h


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def ps_lookup(handle: int, ids: jax.Array, anchor: jax.Array) -> jax.Array:
    """Differentiable embedding lookup against a host PS.

    Forward: host pulls rows for ``ids`` (shape ``[...]``) → ``[..., dim]``
    float32. Backward: host pushes the cotangent to the PS (the table's own
    sparse optimizer applies it); no gradient flows to ``ids``.

    ``anchor`` must be a float scalar whose gradient the caller requests
    (e.g. a zero parameter — see :func:`easydl_tpu.ps.trainer.make_ps_model`).
    ``ids`` are integers with no tangent space, so without a differentiable
    input on the path JAX's partial evaluation would prune this VJP — and the
    push with it.
    """
    client, table, dim, _ = _LOOKUP_CLIENTS[handle]
    out_shape = jax.ShapeDtypeStruct(ids.shape + (dim,), jnp.float32)
    emb = jax.pure_callback(
        lambda i: client.pull(table, np.asarray(i)), out_shape, ids,
        vmap_method="sequential",
    )
    return emb + anchor.astype(jnp.float32) * 0.0


def _lookup_fwd(handle, ids, anchor):
    return ps_lookup(handle, ids, anchor), ids


def _lookup_bwd(handle, ids, g):
    client, table, _, scale = _LOOKUP_CLIENTS[handle]

    def push(i, grad):
        client.push(table, np.asarray(i), np.asarray(grad, np.float32), scale)

    # io_callback is effectful — it survives DCE even with no outputs, so the
    # push happens exactly once per backward pass, in program order.
    io_callback(push, None, ids, g, ordered=True)
    # ids are integers: no tangent space — float0 cotangent.
    return (np.zeros(ids.shape, jax.dtypes.float0), jnp.zeros((), jnp.float32))


ps_lookup.defvjp(_lookup_fwd, _lookup_bwd)
