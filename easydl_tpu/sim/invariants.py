"""Policy invariants over a simulation result — the assertion half of the
offline control plane, mirroring chaos/invariants.py's stance: a replay
that merely *runs* proves little; the verdict is named checks with
evidence, and vacuous passes are refused.

Expectations are a plain dict (scenarios stay declarative)::

    expect = {
        "target_step": 2000,            # some member reached this step
        "max_steps_lost": 200,          # worst generation switch
        "final_workers": 1,
        "max_reshapes": 2,              # total reshape initiations
        "straggler_evicted": "a0",      # this agent ends up excluded
        "evict_budget_s": 30.0,         # onset → eviction latency bound
        "holddown_quiet": True,         # NO reshape inside the hold-down
        "proactive_drain": True,        # drain strictly before the kill
        "min_scale_ups": 2,             # autoscaler really climbed
        "final_desired_workers": 4,
    }
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping

# The window/race cores are SHARED with the live drill checker — the two
# implementations of a same-named invariant must never drift.
from easydl_tpu.chaos.invariants import drain_race, holddown_violations


def check(result: Mapping[str, Any], expect: Dict[str, Any],
          timeline: Mapping[str, Any]) -> Dict[str, Any]:
    checks: Dict[str, Dict[str, Any]] = {}
    final = dict(result.get("final", {}))
    reshapes: List[Dict[str, Any]] = list(result.get("reshapes", []))
    evictions: List[Dict[str, Any]] = list(result.get("evictions", []))
    switches: List[Dict[str, Any]] = list(result.get("switches", []))
    drains: List[Dict[str, Any]] = list(result.get("drains", []))
    kills: List[Dict[str, Any]] = list(result.get("kills", []))
    preempts: List[Dict[str, Any]] = list(result.get("preempts", []))
    faults: List[Dict[str, Any]] = list(timeline.get("faults", []))

    # ------------------------------------------------- reached_target_step
    target = expect.get("target_step")
    if target is not None:
        max_step = int(final.get("max_step", 0))
        done = final.get("phase") == "done"
        checks["reached_target_step"] = {
            "ok": done or max_step >= int(target),
            "target": int(target), "max_step": max_step, "done": done,
        }

    # --------------------------------------------------- steps_lost_bounded
    bound = expect.get("max_steps_lost")
    if bound is not None:
        worst = max((int(s.get("steps_lost", 0)) for s in switches),
                    default=0)
        checks["steps_lost_bounded"] = {
            "ok": worst <= int(bound), "bound": int(bound), "worst": worst,
            "switches": switches,
        }

    # -------------------------------------------------- membership_converged
    want_workers = expect.get("final_workers")
    if want_workers is not None:
        members = list(final.get("members", []))
        checks["membership_converged"] = {
            "ok": len(members) == int(want_workers),
            "final_members": members, "want_workers": int(want_workers),
        }

    # ------------------------------------------------ no_directive_ping_pong
    max_reshapes = expect.get("max_reshapes")
    if max_reshapes is not None:
        checks["no_directive_ping_pong"] = {
            "ok": len(reshapes) <= int(max_reshapes),
            "reshapes": len(reshapes),
            "max_reshapes": int(max_reshapes),
            "by_reason": _count_by(reshapes, "reason"),
        }

    # ----------------------------------------------------- straggler_evicted
    evicted = expect.get("straggler_evicted")
    if evicted is not None:
        hits = [e for e in evictions if e.get("agent") == evicted]
        onset = min(
            (float(f["t"]) for f in faults
             if f.get("kind") == "straggler" and f.get("agent") == evicted),
            default=None,
        )
        budget = expect.get("evict_budget_s")
        ok = bool(hits) and evicted not in final.get("members", [])
        latency = None
        if hits and onset is not None:
            latency = round(float(hits[0]["t"]) - onset, 6)
            if budget is not None:
                ok = ok and latency <= float(budget)
        elif budget is not None and onset is None:
            # A latency budget against a timeline with no straggler marker
            # can only pass vacuously — refuse it.
            ok = False
        checks["straggler_evicted"] = {
            "ok": ok, "agent": evicted, "evictions": hits,
            "onset_t": onset, "latency_s": latency,
            "evict_budget_s": budget,
            "final_members": list(final.get("members", [])),
        }

    # -------------------------------------------------------- holddown_quiet
    if expect.get("holddown_quiet"):
        if not evictions:
            checks["holddown_quiet"] = {
                "ok": False,
                "reason": "no eviction happened — the anti-ping-pong "
                          "window was never exercised (vacuous)",
            }
        else:
            violations = holddown_violations(evictions, reshapes)
            checks["holddown_quiet"] = {
                "ok": not violations,
                "evictions": evictions,
                "violations": violations,
            }

    # --------------------------------------------------------- eviction churn
    max_evictions = expect.get("max_evictions")
    if max_evictions is not None:
        checks["eviction_churn_bounded"] = {
            "ok": len(evictions) <= int(max_evictions),
            "evictions": len(evictions),
            "max_evictions": int(max_evictions),
        }

    # ------------------------------------------------ proactive_drain (race)
    if expect.get("proactive_drain"):
        noticed = {str(p.get("agent", "")) for p in preempts}
        races = [k for k in kills if str(k.get("agent", "")) in noticed]
        if not races:
            checks["proactive_drain_before_kill"] = {
                "ok": False,
                "reason": "no kill of a noticed agent in the replay — the "
                          "race was never run (vacuous)",
            }
        else:
            evidence = []
            for k in races:
                aid, tk = str(k["agent"]), float(k["t"])
                drain_ts = [float(d["t"]) for d in drains
                            if d.get("agent") == aid]
                race = drain_race(drain_ts, tk,
                                  bool(k.get("worker_alive")))
                race["agent"] = aid
                evidence.append(race)
            checks["proactive_drain_before_kill"] = {
                "ok": all(e["won"] for e in evidence),
                "races": evidence,
            }

    # -------------------------------------------------- mesh_shape_converged
    mc = expect.get("mesh_converged")
    if mc is not None:
        mc = dict(mc) if isinstance(mc, Mapping) else {}
        tol = float(mc.get("tolerance", 0.05))
        mesh = dict(result.get("mesh") or {})
        final_shape = str(mesh.get("final_shape", ""))
        final_world = int(mesh.get("final_world", 0))
        prof = dict(timeline.get("meta", {}).get("shape_profile", {}))
        cells = {str(k): float(v[1])
                 for k, v in dict(prof.get(str(final_world), {})).items()}
        doc: Dict[str, Any] = {
            "final_world": final_world, "final_shape": final_shape,
            "tolerance": tol,
        }
        if not cells or not final_shape:
            # A convergence claim with no performance surface (or no mesh
            # decision at all) can only pass vacuously — refuse it.
            doc.update(ok=False, reason=(
                "no shape_profile cells for the final world, or no mesh "
                "decision in the result (vacuous)"))
        else:
            # The static-pod oracle: the best factorization at the final
            # world, run from t0 with no reshapes. Converged = the chosen
            # shape's steady-state throughput is within `tolerance` of it.
            oracle_shape = max(cells, key=lambda k: (cells[k], k))
            oracle = cells[oracle_shape]
            chosen = cells.get(final_shape)
            loss = None if chosen is None else 1.0 - chosen / oracle
            doc.update(
                ok=(chosen is not None and loss is not None
                    and loss <= tol),
                oracle_shape=oracle_shape,
                oracle_samples_per_sec=oracle,
                chosen_samples_per_sec=chosen,
                throughput_loss=(None if loss is None
                                 else round(loss, 6)),
            )
        checks["mesh_shape_converged"] = doc

    # ------------------------------------------------------- autoscaler path
    min_ups = expect.get("min_scale_ups")
    if min_ups is not None:
        ups = [s for s in result.get("scale_decisions", [])
               if int(s.get("to_workers", 0)) > int(s.get("from_workers", 0))]
        checks["autoscaler_scaled_up"] = {
            "ok": len(ups) >= int(min_ups),
            "scale_ups": ups, "min_scale_ups": int(min_ups),
        }
    want_desired = expect.get("final_desired_workers")
    if want_desired is not None:
        got = int(final.get("desired_workers", 0))
        checks["autoscaler_converged"] = {
            "ok": got == int(want_desired),
            "final_desired_workers": got, "want": int(want_desired),
        }

    return {
        "passed": all(c["ok"] for c in checks.values()),
        "checks": checks,
    }


def _count_by(entries: List[Dict[str, Any]], key: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for e in entries:
        k = str(e.get(key, ""))
        out[k] = out.get(k, 0) + 1
    return out
