"""File-backed data: tokenizer, token shards, array datasets.

The synthetic streams (core/data.py) keep benchmarks hermetic; this package
is the real-data path the BASELINE configs name (MNIST/ImageNet-style array
files, LM token shards): a trainable byte-level BPE tokenizer with no
external downloads, a corpus encoder CLI, and memory-mapped datasets that
shard by data-parallel rank and checkpoint their cursor.
"""

from easydl_tpu.data.clicks import (  # noqa: F401
    ClickLogDataset,
    encode_click_tsv,
)
from easydl_tpu.data.datasets import (  # noqa: F401
    ArrayImageDataset,
    TokenFileDataset,
    write_token_shards,
)
from easydl_tpu.data.images import (  # noqa: F401
    convert_mnist,
    import_image_folder,
    read_idx,
)
from easydl_tpu.data.tokenizer import ByteBpeTokenizer  # noqa: F401
