"""Chaos: deterministic fault injection + recovery-invariant checking.

The elasticity claims (survives preemption, auto-recovers, bounded lost
work) are verified continuously by seed-deterministic drills instead of a
one-off measurement:

- :mod:`easydl_tpu.chaos.spec` — declarative scenarios compiled by a seeded
  PRNG into byte-identical fault timelines;
- :mod:`easydl_tpu.chaos.injectors` — env-gated hooks in the RPC layer,
  agent, worker, and storage (all inert unless ``EASYDL_CHAOS_SPEC`` is
  set);
- :mod:`easydl_tpu.chaos.invariants` — post-run assertions over the job's
  artifacts (target step reached, generation monotonic, bounded lost work,
  membership convergence, no directive ping-pong);
- :mod:`easydl_tpu.chaos.harness` — runs a scenario on the simulated
  distributed runtime (``scripts/chaos_run.py`` is the CLI).

This module stays import-light: services import it for the two functions
below without pulling grpc/jax-adjacent machinery.
"""

from __future__ import annotations

from easydl_tpu.chaos.spec import (  # noqa: F401 (public API)
    ChaosSpec,
    FaultSpec,
    compile_schedule,
    schedule_bytes,
)
from easydl_tpu.utils.env import knob_raw

ENV_VAR = "EASYDL_CHAOS_SPEC"


def chaos_enabled() -> bool:
    """The one cheap flag check every hook point gates on."""
    return bool(knob_raw(ENV_VAR))


def banner(component: str) -> None:
    """Loud one-liner each long-running service logs at startup when fault
    injection is armed — an operator must never discover a chaos drill from
    the failures themselves."""
    if chaos_enabled():
        from easydl_tpu.utils.logging import get_logger

        get_logger("chaos", component).warning(
            "CHAOS FAULT INJECTION ARMED in %s (EASYDL_CHAOS_SPEC=%s) — "
            "this process may be injected with failures",
            component, knob_raw(ENV_VAR),
        )
