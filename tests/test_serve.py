"""Serving tier: hot-id cache invalidation contract, micro-batch queue
semantics, the shared read client, the Serve gRPC surface, per-client
fp16, the shared dims cache, and the replica scale policy.

The invalidation tests are the tier-1 face of the `serve_during_reshard`
chaos drill: same contract (a cached row is never served past a trainer
push or a routing-generation flip), in-process servers instead of pods.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from easydl_tpu.controller.reconciler import serve_scale_decision
from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.ps import registry, reshard
from easydl_tpu.ps.client import LocalPsClient, PullVersions, ShardedPsClient
from easydl_tpu.ps.read_client import PsReadClient
from easydl_tpu.ps.server import PS_SERVICE, PsShard
from easydl_tpu.ps.table import TableSpec, shard_of
from easydl_tpu.serve import HotIdCache, ServeConfig, ServeFrontend
from easydl_tpu.serve.frontend import SERVE_SERVICE, OVERLOADED
from easydl_tpu.utils.rpc import GRPC_MSG_OPTIONS, RpcClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spec(**kw):
    kw.setdefault("name", "emb")
    kw.setdefault("dim", 8)
    kw.setdefault("optimizer", "sgd")
    kw.setdefault("lr", 0.1)
    kw.setdefault("seed", 3)
    return TableSpec(**kw)


def _ids(*vals):
    return np.asarray(vals, np.int64)


# ------------------------------------------------------------ hot-id cache
class TestHotIdCache:
    def _put(self, cache, ids, dim=8, shard=0, version=1, table="emb"):
        ids = np.asarray(ids, np.int64)
        cache.put(table, ids, np.ones((len(ids), dim), np.float32),
                  np.full(len(ids), shard, np.int32),
                  np.full(len(ids), version, np.uint64))

    def test_byte_bound_holds_and_evicts_lru(self):
        from easydl_tpu.serve.cache import ENTRY_OVERHEAD_BYTES

        row_cost = 8 * 4 + ENTRY_OVERHEAD_BYTES
        cache = HotIdCache(max_bytes=8 * row_cost)
        cache.set_generation(0)
        self._put(cache, range(8))
        assert cache.entries == 8
        # Touch ids 0..3 (newer tick), then overflow: the UNTOUCHED half
        # must be the evicted half.
        cache.lookup("emb", _ids(0, 1, 2, 3))
        self._put(cache, range(100, 104))
        assert cache.bytes <= 8 * row_cost
        assert cache.evictions >= 4
        slots, _, _ = cache.lookup("emb", _ids(0, 1, 2, 3))
        assert (slots >= 0).all(), "recently-used entries were evicted"
        slots, _, _ = cache.lookup("emb", _ids(4, 5, 6, 7))
        assert (slots < 0).all(), "LRU entries survived the byte bound"

    def test_generation_change_drops_everything(self):
        cache = HotIdCache(max_bytes=1 << 20)
        cache.set_generation(0)
        self._put(cache, range(16))
        assert not cache.set_generation(0)  # unchanged: keep
        assert cache.entries == 16
        assert cache.set_generation(1)      # reshard committed: drop all
        assert cache.entries == 0
        assert cache.invalidations == 16

    def test_put_overwrites_in_place(self):
        cache = HotIdCache(max_bytes=1 << 20)
        cache.set_generation(0)
        self._put(cache, [5], version=1)
        self._put(cache, [5], version=2)
        assert cache.entries == 1
        _, _, versions = cache.lookup("emb", _ids(5))
        assert versions[0] == 2

    def test_demote_moves_hit_to_miss(self):
        cache = HotIdCache(max_bytes=1 << 20)
        cache.set_generation(0)
        self._put(cache, [1, 2])
        slots, _, _ = cache.lookup("emb", _ids(1, 2))
        cache.demote("emb", _ids(1, 2), slots)
        assert cache.hits == 0 and cache.misses == 2
        assert cache.entries == 0


# ----------------------------------------------- read client invalidation
class TestReadClientInvalidation:
    def _tier(self, shards=2, dim=8):
        client = LocalPsClient(num_shards=shards)
        client.create_table(spec(dim=dim))
        reads = PsReadClient(client, cache=HotIdCache(1 << 20))
        return client, reads

    def test_push_epoch_invalidation(self):
        """The contract the ISSUE names: a serving replica never returns
        a stale row after a trainer push — the push bumps the shard's
        table version and the next validated read re-pulls."""
        client, reads = self._tier()
        ids = np.arange(40, dtype=np.int64)
        before = reads.pull("emb", ids)
        assert np.array_equal(before, reads.pull("emb", ids))
        assert reads.counters["hits"] == 40  # fully cache-served
        client.push("emb", ids, np.ones((40, 8), np.float32))
        after = reads.pull("emb", ids)
        assert np.array_equal(after, client.pull("emb", ids))
        assert not np.array_equal(after, before)
        assert reads.counters["demoted"] == 40

    def test_partial_shard_push_invalidates_only_that_shard(self):
        client, reads = self._tier(shards=2)
        ids = np.arange(64, dtype=np.int64)
        owner = shard_of(ids, 2)
        reads.pull("emb", ids)
        # Push ONLY to shard-0-owned ids: shard 1's entries stay valid.
        s0 = ids[owner == 0]
        client.push("emb", s0, np.ones((len(s0), 8), np.float32))
        reads.pull("emb", ids)
        assert reads.counters["demoted"] == len(s0)
        assert np.array_equal(reads.pull("emb", ids),
                              client.pull("emb", ids))

    def test_import_rows_invalidates(self):
        """A restore/migration import rewrites values without a push —
        the version must still move (the reshard drill depends on it)."""
        client, reads = self._tier(shards=1)
        ids = _ids(1, 2, 3)
        reads.pull("emb", ids)
        t = client.shards[0].table("emb")
        t.import_rows(ids, np.full((3, 8), 7.0, np.float32))
        got = reads.pull("emb", ids)
        assert np.array_equal(got, np.full((3, 8), 7.0, np.float32))

    def test_no_cache_is_passthrough(self):
        client = LocalPsClient(num_shards=2)
        client.create_table(spec())
        reads = PsReadClient(client)
        ids = np.arange(10, dtype=np.int64).reshape(2, 5)
        assert np.array_equal(reads.pull("emb", ids),
                              client.pull("emb", ids))
        assert reads.counters["batches"] == 0

    def test_probe_throttle_allows_bounded_staleness(self):
        client = LocalPsClient(num_shards=1)
        client.create_table(spec())
        reads = PsReadClient(client, cache=HotIdCache(1 << 20),
                             max_probe_age_s=30.0)
        ids = _ids(1, 2, 3)
        reads.pull("emb", ids)
        reads.pull("emb", ids)
        probes_before = reads.counters["probes"]
        stale = reads.pull("emb", ids)
        assert reads.counters["probes"] == probes_before
        # Within the probe window a push MAY be missed (the documented
        # trade) — strict mode (default 0) is what the drills verify.
        client.push("emb", ids, np.ones((3, 8), np.float32))
        assert np.array_equal(stale, reads.pull("emb", ids))


# -------------------------------------- generation flip on a live reshard
class _Cluster:
    """In-process gRPC shard servers published to a real registry (the
    test_ps_reshard idiom, trimmed to what the cache tests need)."""

    def __init__(self, workdir: str):
        self.workdir = workdir
        self.live = []

    def start_set(self, num_shards, generation=0, prefix="src"):
        for i in range(num_shards):
            epoch = registry.bump_epoch(self.workdir, i)
            shard = PsShard(
                shard_index=i, num_shards=num_shards, epoch=epoch,
                wal_root=os.path.join(self.workdir, "ps-wal", f"shard-{i}"),
                workdir=self.workdir,
                rescue_dir=os.path.join(self.workdir, "ps-ckpt"),
                route_generation=generation,
            )
            server = shard.serve()
            registry.publish(self.workdir, f"{prefix}-{num_shards}-{i}", i,
                             num_shards, server.address, epoch=epoch,
                             generation=generation)
            self.live.append((shard, server))

    def ensure_destinations(self, plan):
        self.start_set(int(plan["to_shards"]),
                       generation=int(plan["generation"]),
                       prefix=f"dst-g{plan['generation']}")

    def stop(self):
        for shard, _server in self.live:
            shard.stop()
        self.live.clear()


def test_routing_generation_invalidation_across_live_reshard(tmp_path):
    """A serving replica's cache rides a live 2→4 split: the committed
    routing generation drops every entry, and post-split reads are
    bit-identical to a fresh client on the new shard set — including
    rows a trainer push changed mid-migration."""
    w = str(tmp_path)
    cluster = _Cluster(w)
    cluster.start_set(2)
    writer = ShardedPsClient.from_registry(w, 2, timeout=5.0,
                                           drain_retry_s=60.0,
                                           transient_retry_s=30.0)
    serving = ShardedPsClient.from_registry(w, 2, timeout=5.0,
                                            drain_retry_s=60.0,
                                            transient_retry_s=30.0)
    reads = PsReadClient(serving, cache=HotIdCache(1 << 20))
    try:
        writer.create_table(spec(optimizer="adagrad", lr=0.05))
        rng = np.random.default_rng(11)
        ids = np.arange(600, dtype=np.int64)
        writer.push("emb", ids, rng.standard_normal((600, 8)).astype(
            np.float32), scale=0.5)
        writer.save(os.path.join(w, "ps-ckpt"), step=1)  # rescue lineage
        before = reads.pull("emb", ids)
        assert reads.cache.generation == 0
        assert np.array_equal(before, reads.pull("emb", ids))

        summary = reshard.run_reshard(
            w, 4, "test-serve",
            ensure_destinations=cluster.ensure_destinations,
            rpc_timeout=5.0, phase_timeout_s=60.0, dest_wait_s=30.0)
        assert summary["committed_routing"]["num_shards"] == 4
        # A trainer push lands on the NEW shard set...
        writer.push("emb", ids, rng.standard_normal((600, 8)).astype(
            np.float32), scale=0.5)
        # ...and the serving cache path must converge: generation flip
        # drops the cache, the re-pull routes by the new partition.
        after = reads.pull("emb", ids)
        assert reads.cache.generation == 1
        assert serving.num_shards == 4
        fresh = ShardedPsClient.from_registry(w, timeout=5.0)
        try:
            assert np.array_equal(after, fresh.pull("emb", ids))
        finally:
            fresh.close()
        assert not np.array_equal(after, before)
    finally:
        reads.client.close()
        writer.close()
        cluster.stop()


# ----------------------------------------------------- micro-batch queue
class TestBatchQueue:
    def _frontend(self, forward=None, **cfg_kw):
        client = LocalPsClient(num_shards=1)
        client.create_table(spec(dim=4))
        reads = PsReadClient(client, cache=HotIdCache(1 << 20))
        cfg_kw.setdefault("table", "emb")
        cfg_kw.setdefault("fields", 2)
        cfg_kw.setdefault("dense_dim", 0)
        fe = ServeFrontend(reads, ServeConfig(**cfg_kw), forward=forward)
        return fe

    def test_max_wait_deadline_honored(self):
        """A lone request must leave the queue at ~max_wait, not wait for
        a full batch."""
        fe = self._frontend(max_batch=1024, max_wait_ms=40.0)
        try:
            t0 = time.monotonic()
            r = fe.infer(np.arange(2, dtype=np.int64).reshape(1, 2))
            elapsed = time.monotonic() - t0
            assert r.ok
            assert 0.02 <= elapsed < 2.0, elapsed
            assert fe.recent_batches[-1] == (1,)
        finally:
            fe.stop()

    def test_shed_past_depth_bound_is_retriable(self):
        gate = threading.Event()

        def slow_forward(emb, dense):
            gate.wait(10.0)
            return emb.reshape(len(emb), -1).sum(1)

        fe = self._frontend(forward=slow_forward, max_batch=4,
                            max_wait_ms=1.0, max_pending=8)
        try:
            results = []
            threads = [
                threading.Thread(target=lambda: results.append(
                    fe.infer(np.arange(8, dtype=np.int64).reshape(4, 2))))
                for _ in range(6)
            ]
            for t in threads:
                t.start()
                time.sleep(0.05)  # let the runner claim the first batch
            gate.set()
            for t in threads:
                t.join(timeout=30.0)
            shed = [r for r in results if not r.ok]
            served = [r for r in results if r.ok]
            assert shed, "queue never shed past the bound"
            assert served, "everything shed — the bound is broken"
            for r in shed:
                assert r.retriable
                assert r.verdict.startswith(OVERLOADED)
        finally:
            gate.set()
            fe.stop()

    def test_batch_order_deterministic_fifo(self):
        gate = threading.Event()

        def slow_forward(emb, dense):
            gate.wait(10.0)
            return emb.reshape(len(emb), -1).sum(1)

        fe = self._frontend(forward=slow_forward, max_batch=4,
                            max_wait_ms=1.0, max_pending=1024)
        try:
            threads = []
            for _ in range(8):
                t = threading.Thread(
                    target=fe.infer,
                    args=(np.arange(2, dtype=np.int64).reshape(1, 2),))
                t.start()
                time.sleep(0.03)  # serialize arrival order
                threads.append(t)
            gate.set()
            for t in threads:
                t.join(timeout=30.0)
            order = [s for batch in fe.recent_batches for s in batch]
            assert order == sorted(order), (
                "requests ran out of arrival order: "
                f"{list(fe.recent_batches)}")
        finally:
            gate.set()
            fe.stop()

    def test_scores_map_back_to_their_requests(self):
        fe = self._frontend(max_batch=64, max_wait_ms=20.0)
        try:
            client = fe.reads.client
            results = {}

            def one(tag, ids):
                results[tag] = (ids, fe.infer(ids))

            threads = [
                threading.Thread(target=one, args=(
                    i, np.asarray([[2 * i, 2 * i + 1]], np.int64)))
                for i in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            for tag, (ids, r) in results.items():
                assert r.ok
                expected = client.pull("emb", ids).reshape(1, -1).sum(1)
                assert np.allclose(r.scores, expected), tag
        finally:
            fe.stop()


# ------------------------------------------------------------ gRPC surface
def test_frontend_grpc_infer_roundtrip():
    client = LocalPsClient(num_shards=1)
    client.create_table(spec(dim=4))
    reads = PsReadClient(client, cache=HotIdCache(1 << 20))
    fe = ServeFrontend(
        reads, ServeConfig(table="emb", fields=3, dense_dim=2,
                           max_batch=32, max_wait_ms=5.0))
    server = fe.serve()
    rpc = RpcClient(SERVE_SERVICE, f"localhost:{server.port}",
                    timeout=30.0, options=GRPC_MSG_OPTIONS)
    try:
        ids = np.arange(6, dtype=np.int64)
        dense = np.ones((2, 2), np.float32)
        resp = rpc.Infer(pb.InferRequest(
            raw_ids=ids.astype("<i8").tobytes(), fields=3,
            dense=dense.tobytes(), dense_dim=2))
        assert resp.ok, resp.verdict
        scores = np.frombuffer(resp.scores, "<f4")
        direct = client.pull("emb", ids.reshape(2, 3))
        expected = direct.reshape(2, -1).sum(1) + dense.sum(1)
        assert np.allclose(scores, expected)
        # malformed: ids not divisible by fields — a verdict, not a crash
        bad = rpc.Infer(pb.InferRequest(
            raw_ids=ids[:5].astype("<i8").tobytes(), fields=3))
        assert not bad.ok and bad.verdict.startswith("error")
    finally:
        rpc.close()
        fe.stop()


# ---------------------------------------------- wire version + per-client
class _OneShard:
    def __enter__(self):
        self.shard = PsShard(shard_index=0, num_shards=1)
        self.server = self.shard.serve()
        self.addr = self.server.address
        return self

    def __exit__(self, *exc):
        self.shard.stop()


def test_pull_response_carries_push_version():
    with _OneShard() as s:
        s.shard.create_table(spec(dim=4))
        rpc = RpcClient(PS_SERVICE, s.addr, timeout=10.0,
                        options=GRPC_MSG_OPTIONS)
        try:
            ids = np.arange(3, dtype=np.int64)
            r1 = rpc.Pull(pb.PullRequest(
                table="emb", raw_ids=ids.astype("<i8").tobytes()))
            assert r1.version == 1  # fresh table starts at 1 (0 = legacy)
            probe = rpc.Pull(pb.PullRequest(table="emb"))  # zero-id probe
            assert probe.version == r1.version
            assert len(probe.values) == 0
            s.shard.table("emb").push(ids, np.ones((3, 4), np.float32))
            r2 = rpc.Pull(pb.PullRequest(
                table="emb", raw_ids=ids.astype("<i8").tobytes()))
            assert r2.version == r1.version + 1
            st = rpc.Stats(pb.PsStatsRequest())
            assert st.tables[0].version == r2.version
        finally:
            rpc.close()


def test_fp16_is_a_per_client_opt_in(monkeypatch):
    """The serving replica opts into fp16 pulls via the CONSTRUCTOR; the
    process env (the trainer's) is never consulted or mutated."""
    monkeypatch.delenv("EASYDL_PS_PULL_FP16", raising=False)
    with _OneShard() as s:
        s.shard.create_table(spec(dim=4))
        ids = np.arange(8, dtype=np.int64)
        s.shard.table("emb").push(
            ids, np.random.default_rng(0).standard_normal(
                (8, 4)).astype(np.float32))
        c32 = ShardedPsClient([s.addr], timeout=10.0)
        c16 = ShardedPsClient([s.addr], timeout=10.0, pull_fp16=True)
        try:
            full = c32.pull("emb", ids)
            half = c16.pull("emb", ids)
            assert c16.pull_fp16 and not c32.pull_fp16
            assert "EASYDL_PS_PULL_FP16" not in os.environ
            assert np.array_equal(
                half, full.astype("<f2").astype(np.float32))
        finally:
            c32.close()
            c16.close()


def test_dims_cache_shared_across_clients_of_one_cluster(tmp_path):
    """Satellite: a second client to the same registry-identified cluster
    must not re-probe Stats for table dims — the process already knows
    them. Registry-less clients keep PRIVATE dims (ephemeral ports can
    recycle across cluster lifetimes in one process)."""
    w = str(tmp_path)
    cluster = _Cluster(w)
    cluster.start_set(1)
    first = ShardedPsClient.from_registry(w, 1, timeout=10.0)
    try:
        first.create_table(spec(dim=8))
        second = ShardedPsClient.from_registry(w, 1, timeout=10.0)
        try:
            # Sever the probe path entirely: a shared-dims hit needs no
            # Stats round trip.
            second._lookup_dim = None  # type: ignore[assignment]
            out = second.pull("emb", np.zeros((0,), np.int64))
            assert out.shape == (0, 8)
        finally:
            second.close()
        third = ShardedPsClient([cluster.live[0][1].address], timeout=10.0)
        try:
            assert third._dims == {}
            assert third._dims is not first._dims
        finally:
            third.close()
    finally:
        first.close()
        cluster.stop()


def test_version_collector_records_per_shard_minimum():
    v = PullVersions()
    v.record(0, 5)
    v.record(0, 3)   # older chunk wins: the only safe tag
    v.record(1, 7)
    v.record(1, 0)   # legacy server: never recorded
    assert v.versions == {0: 3, 1: 7}
    assert v.complete
    v.invalidate()
    assert not v.complete


# ------------------------------------------------------- replica policy
class TestServeScaleDecision:
    def test_scales_up_on_qps_pressure(self):
        got = serve_scale_decision({"a": 900.0, "b": 950.0},
                                   {"a": 0.01, "b": 0.012},
                                   target_qps=500.0)
        assert got == 4  # ceil(1850/500)

    def test_scales_up_on_p99_even_under_qps_target(self):
        got = serve_scale_decision({"a": 100.0, "b": 100.0},
                                   {"a": 0.02, "b": 0.30},
                                   target_qps=500.0, p99_budget_s=0.05)
        assert got == 3  # queueing started: +1 beats the qps math

    def test_steady_state_returns_none(self):
        assert serve_scale_decision({"a": 400.0}, {"a": 0.01},
                                    target_qps=500.0) is None

    def test_scale_down_needs_headroom_and_quiet_p99(self):
        # 3 replicas at 100 qps total, p99 tiny: shrink by one.
        assert serve_scale_decision(
            {"a": 30.0, "b": 40.0, "c": 30.0},
            {"a": 0.001, "b": 0.001, "c": 0.001},
            target_qps=500.0) == 2
        # same load but one replica's p99 is hot: DON'T shrink
        assert serve_scale_decision(
            {"a": 30.0, "b": 40.0, "c": 30.0},
            {"a": 0.001, "b": 0.030, "c": 0.001},
            target_qps=500.0, p99_budget_s=0.05) is None

    def test_clamps_and_floors(self):
        assert serve_scale_decision({"a": 1e9}, {"a": 1.0},
                                    target_qps=500.0,
                                    max_replicas=8) == 8
        assert serve_scale_decision({"a": 0.0}, {"a": 0.0},
                                    target_qps=500.0,
                                    min_replicas=1) is None
        assert serve_scale_decision({}, {}) is None


# ---------------------------------------------------------- bench smoke
def test_bench_serve_smoke(tmp_path):
    """The CI face of BENCH_SERVE.json: in-process PS, tiny model, and —
    non-negotiable even at smoke size — zero stale reads under the
    interleaved trainer push."""
    out = tmp_path / "bench_serve.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_serve.py"),
         "--smoke", "--out", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=560,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-2000:]
    import json

    doc = json.loads(out.read_text())
    for mode in ("cache_off", "cache_on"):
        r = doc["results"][mode]
        assert r["requests"] > 0 and r["errors"] == 0
        assert r["p99_ms"] >= r["p50_ms"] > 0
    assert doc["results"]["cache_on"]["hit_ratio"] > 0.2
    assert doc["stale_check"]["mismatches"] == 0
    assert doc["acceptance"]["zero_stale_reads"]
    assert "pull_path" in doc["results"]
