"""``python -m easydl_tpu.ps`` — the parameter-server pod entrypoint.

This is what the operator actually launches for the ``parameter_server``
role, and the piece that turns the operator's generic replace-then-retire
into the reference's zero-lost-updates vertical scaling
(docs/design/elastic-training-operator.md:86-101):

- **fresh pod** (initial creation): the trailing index of the pod name
  (``job-parameter_server-3`` → shard 3) is a HINT, checked against the
  registry: if some shard's latest publication is dead (its pod crashed and
  the reconciler levelled THIS pod in under a fresh name with no
  ``replaces``), the fresh pod adopts that orphaned shard instead —
  claiming it via an O_EXCL file so concurrent rescues can't collide — and
  restores its rows from the last complete ``ps-ckpt`` save. Then serve,
  publish to the registry, touch the ready file.
- **replacement pod** (``resource_updation`` → the operator created it with
  ``replaces=<old>``): inherit the OLD pod's shard index from the registry,
  then run the handoff — Drain the old pod (its pushes gate + rows save),
  Restore those rows here, publish (clients reroute on their next retried
  push), and only THEN touch the ready file. The operator retires the old
  pod when the replacement looks Running-and-ready, so retirement is
  ordered strictly after the handoff — the window in which an acked update
  could be lost never exists.

The pod name / replaces / workdir arrive via argv or the EASYDL_POD_*
environment the pod backend exports.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time
from typing import Optional, Tuple

from easydl_tpu.ps import registry
from easydl_tpu.ps.server import PS_SERVICE, PsShard
from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.rpc import RpcClient
from easydl_tpu.utils.env import knob_bool, knob_float, knob_int, knob_str

log = get_logger("ps", "main")


def shard_index_from_name(name: str) -> Optional[int]:
    tail = name.rsplit("-", 1)[-1]
    return int(tail) if tail.isdigit() else None


def probe_alive(address: str, timeout: float = 5.0, attempts: int = 2) -> bool:
    """Is a PS actually serving at this registry address? Registry entries
    outlive their pods (a crashed shard's file stays on disk), so liveness
    is decided by the socket, not the file.

    Retried: declaring a LIVE shard dead is far worse than a slow rescue —
    a rescue pod would hijack the healthy shard and re-publish it with
    stale checkpoint rows. One slow Stats reply (load, GC pause) must not
    read as death. (Hijack is additionally bounded by the epoch fence now:
    a wrongly-rescued live shard gets fenced, clients reroute, and its
    WAL is replayed — but the probe stays conservative.)

    ``EASYDL_PS_PROBE_TIMEOUT_S`` overrides the per-attempt timeout and
    ``EASYDL_PS_PROBE_RETRIES`` the attempt count (chaos drills shrink
    them so a SIGSTOP'd zombie is declared dead quickly; a flaky network
    raises them). The verdict and its latency are logged per probe —
    slow-rescue triage reads this line instead of attaching a debugger."""
    from easydl_tpu.proto import easydl_pb2 as pb

    timeout = knob_float("EASYDL_PS_PROBE_TIMEOUT_S", timeout)
    attempts = max(1, knob_int("EASYDL_PS_PROBE_RETRIES", attempts))
    t0 = time.monotonic()
    last = ""
    for attempt in range(attempts):
        client = RpcClient(PS_SERVICE, address, timeout=timeout)
        try:
            client.Stats(pb.PsStatsRequest())
            log.info("probe %s: ALIVE in %.3fs (attempt %d/%d)", address,
                     time.monotonic() - t0, attempt + 1, attempts)
            return True
        except Exception as e:
            last = repr(e)
            if attempt + 1 < attempts:
                time.sleep(0.5)
        finally:
            client.close()
    log.info("probe %s: DEAD after %.3fs (%d attempt(s), timeout %.1fs "
             "each; last: %s)", address, time.monotonic() - t0, attempts,
             timeout, last)
    return False


#: Read-check-write a claim file atomically under an exclusive flock — the
#: idiom now lives in registry.py (the epoch counter needed it too); the
#: old name stays for in-repo callers and tests.
_locked_claim = registry.locked_mutate


def claim_owner(path: str) -> Optional[str]:
    """Current claim owner, read under the same lock writers hold."""
    return _locked_claim(path, lambda doc: None).get("pod")


def claim_orphan_shard(workdir: str, pod: str, orphans,
                       stale_s: float = 30.0) -> Tuple[Optional[int],
                                                       Optional[str]]:
    """Claim one orphaned shard via an O_EXCL claim file so two concurrent
    failure replacements can't adopt the same shard. A claim older than
    ``stale_s`` whose shard is still unserved is presumed abandoned (the
    claimant crashed mid-rescue) and stolen — the age re-check and the
    overwrite happen atomically under the claim flock, so two stealers
    can't both win and a resumed claimant can't clobber the steal. The
    original claimant notices at publish time (ownership re-checked) and
    exits."""
    claim_dir = os.path.join(workdir, registry.REG_DIR)
    os.makedirs(claim_dir, exist_ok=True)
    for s in orphans:
        path = os.path.join(claim_dir, f"claim-shard-{s}.json")
        created = False
        try:
            # O_EXCL decides who the CREATOR is, but the content is written
            # under the flock like every other mutation — an unlocked
            # initial write could interleave with (and tear) a concurrent
            # steal that read the still-empty file as a stale claim.
            os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            created = True
        except FileExistsError:
            pass

        def take(doc, creator=created):
            if not doc and creator:
                return {"pod": pod, "t": time.time()}  # our fresh file
            age = (time.time() - float(doc.get("t", 0))
                   if doc else stale_s + 1)
            if age > stale_s:
                return {"pod": pod, "t": time.time()}  # stale: steal
            return None

        if _locked_claim(path, take).get("pod") == pod:
            return s, path
    return None, None


def release_claim(claim_path: str, pod: str) -> bool:
    """Drop our claim file after a clean publish: the claim exists to
    serialize RESCUES, and once the shard is served (published, clients
    routed) it has done its job — leaving it would make the next rescue
    of this shard wait out the staleness window before stealing. Owner-
    checked under the flock (a thief's claim must survive us); the unlink
    races nothing: a concurrent O_EXCL creator simply gets a fresh file.
    Returns True when the file was actually removed.

    The ownership check and the unlink happen under ONE hold of the
    flock: a check-then-remove would let a steal land in between and our
    unlink would destroy the thief's claim. (A waiter blocked on the
    flock when we unlink holds the dead inode's lock — harmless: its
    mutation writes to an unlinked file, and its publish-time ownership
    re-check runs against the fresh claim file.)"""
    import fcntl
    import json as _json

    try:
        with open(claim_path, "r+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                try:
                    doc = _json.load(f)
                except ValueError:
                    doc = {}
                if doc.get("pod") != pod:
                    return False
                os.remove(claim_path)
                return True
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)
    except OSError:
        return False


def _gate_watchdog(shard, workdir: str, plan_generation: int,
                   interval: float = 2.0) -> None:
    """Un-gate a push-gated source pod whose migration was aborted behind
    its back. A pod that gated itself against an in-flight plan but was
    not yet in the committed ``shard_map`` at rollback time (a rescuer
    mid-publish) never receives the rollback's ``ReshardResume`` — so it
    watches the plan itself: the plan vanishing WITHOUT the routing
    generation reaching it means abort, and the gate must lift or the
    shard is permanently unavailable. The plan committing (generation
    reaches ours) correctly leaves the gate down — the generation this
    pod serves is superseded."""
    while shard._cutover:
        try:
            rt = registry.routing_table(workdir)
        except OSError:
            time.sleep(interval)
            continue
        plan = rt.get("plan")
        if plan and int(plan.get("generation", -1)) == plan_generation:
            time.sleep(interval)  # still in flight
            continue
        if int(rt.get("generation", 0)) >= plan_generation:
            return  # committed: stay gated, we are superseded
        log.warning("reshard plan generation %d vanished uncommitted "
                    "(rollback missed this pod) — lifting the push gate",
                    plan_generation)
        shard.reshard_resume()
        return


def claim_heartbeat(claim_path: str, pod: str, stop, interval: float) -> None:
    """Refresh our claim's timestamp while the restore runs, so an ACTIVE
    claimant can never look stale: a steal then only happens to a claimant
    genuinely wedged for longer than ``stale_s``. The ownership check and
    the timestamp write are one atomic operation under the claim flock —
    a resumed-from-wedge heartbeat that already lost the claim observes
    that INSIDE the lock and stands down, rather than resurrecting its
    ownership over a legitimate steal (the round-4 review's interleaving)."""
    while not stop.wait(interval):
        def refresh(doc):
            if doc.get("pod") != pod:
                return None  # lost the claim; publish-time check handles it
            return {"pod": pod, "t": time.time()}

        try:
            if _locked_claim(claim_path, refresh).get("pod") != pod:
                return
        except OSError:
            pass


def prior_shard_state_exists(workdir: str, shard: int) -> bool:
    """Is there on-disk PS state a newly-assigned shard must recover
    instead of starting empty? True when a complete ps-ckpt save exists or
    the shard's WAL root holds surviving segments. This decides "rescue"
    independently of a dead registry publication — the startup sweep
    (registry.sweep_stale) removes dead entries, and a rescue decision
    that hinged on seeing one would silently skip the restore after a
    sweep (or on a reused workdir)."""
    from easydl_tpu.ps import wal as ps_wal
    from easydl_tpu.ps.server import PsShard

    if PsShard.saved_steps(os.path.join(workdir, "ps-ckpt")):
        return True
    root = os.path.join(workdir, "ps-wal", f"shard-{shard}")
    return any(
        name.startswith("seg-")
        for _epoch, d in ps_wal.epoch_dirs(root)
        for name in os.listdir(d)
    )


def resolve_fresh_shard(workdir: str, pod: str,
                        num_shards: int) -> Tuple[int, bool, Optional[str]]:
    """Decide which shard a fresh (non-replacement) PS pod serves.

    The pod name's trailing index is only a HINT: the reconciler replaces a
    Failed pod via replica levelling under a fresh name with no ``replaces``
    (reconciler.py), so ``job-parameter_server-2`` may well be the rescue of
    crashed shard 0. The registry decides: a shard whose latest publication
    no longer answers is orphaned, and an orphan outranks the name. Returns
    (shard index, rescued — prior shard state must be recovered, claim
    path)."""
    smap = registry.shard_map(workdir)
    live, dead = set(), set()
    for s, doc in smap.items():
        if 0 <= s < num_shards:
            (live if probe_alive(doc["address"]) else dead).add(s)
    name_idx = shard_index_from_name(pod)
    if (name_idx is not None and 0 <= name_idx < num_shards
            and name_idx not in live and name_idx not in dead and not dead):
        # The normal initial-creation path: the name is a valid
        # never-published shard and nothing needs rescue. ANY rescue —
        # including the in-place restart of our own named shard — must go
        # through the claim below: a same-name restart and a levelled-in
        # fresh pod can race for the same dead shard, and without a claim
        # both would restore and publish it (round-4 review). "Nothing
        # needs rescue" now also requires no recoverable on-disk state:
        # after the startup sweep a crashed predecessor leaves no dead
        # entry, only its checkpoint/WAL — which must be restored, not
        # shadowed by an empty table.
        if not prior_shard_state_exists(workdir, name_idx):
            return name_idx, False, None
    orphans = [s for s in range(num_shards) if s not in live]
    # Prefer the name's own shard when it is among the orphans (less churn).
    orphans.sort(key=lambda s: (s != name_idx, s))
    if not orphans:
        raise SystemExit(
            f"pod {pod!r}: every shard 0..{num_shards - 1} is already "
            "served; nothing to do (scale-down should delete this pod)"
        )
    s, claim = claim_orphan_shard(workdir, pod, orphans)
    if s is None:
        raise SystemExit(
            f"pod {pod!r}: shards {orphans} unserved but all freshly "
            "claimed by other pods"
        )
    log.info("pod %s adopting orphaned shard %d (name suggested %s)",
             pod, s, name_idx)
    return s, s in dead or prior_shard_state_exists(workdir, s), claim


def wait_registry_entry(workdir: str, pod: str, wait_s: float = 60.0) -> dict:
    deadline = time.monotonic() + wait_s
    doc = registry.entry_for_pod(workdir, pod)
    while doc is None and time.monotonic() < deadline:
        time.sleep(0.2)
        doc = registry.entry_for_pod(workdir, pod)
    if doc is None:
        raise SystemExit(
            f"replaces={pod!r} but it never published to the registry"
        )
    return doc


def run_handoff(old: dict, workdir: str, shard: PsShard) -> None:
    """Drain the predecessor into a handoff dir, restore its rows here."""
    old_pod = old["pod"]
    handoff_dir = os.path.join(workdir, "ps-handoff", old_pod)
    client = RpcClient(PS_SERVICE, old["address"], timeout=120.0)
    try:
        from easydl_tpu.proto import easydl_pb2 as pb

        ack = client.Drain(pb.PsSaveRequest(directory=handoff_dir, step=0))
        if not ack.ok:
            raise SystemExit(f"drain of {old_pod} failed: {ack.message}")
    finally:
        client.close()
    shard.restore(handoff_dir, step=0)
    log.info("handoff from %s complete: shard %d restored from %s",
             old_pod, shard.shard_index, handoff_dir)


def main() -> None:
    ap = argparse.ArgumentParser(description="easydl_tpu PS pod")
    ap.add_argument("--name", default=knob_str("EASYDL_POD_NAME"))
    ap.add_argument("--workdir", default=knob_str("EASYDL_WORKDIR", ""))
    ap.add_argument("--num-shards", type=int, required=True)
    ap.add_argument("--shard-index", type=int, default=-1,
                    help="default: trailing index of the pod name (fresh "
                         "pods) or inherited from the replaced pod")
    ap.add_argument("--replaces",
                    default=knob_str("EASYDL_REPLACES"))
    ap.add_argument("--reshard-dest", action="store_true",
                    default=knob_bool("EASYDL_RESHARD_DEST"),
                    help="this pod is a DESTINATION shard of an in-flight "
                         "online reshard (ps/reshard.py): skip rescue/claim "
                         "discovery, publish under the migration plan's "
                         "routing generation (invisible to clients until "
                         "the coordinator commits), and wait for the "
                         "coordinator's Restore/ReshardReplay RPCs")
    ap.add_argument("--ready-file", default="",
                    help="touched once serving (and any handoff) is "
                         "complete — the pod backend's readiness gate")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    if not args.name or not args.workdir:
        ap.error("--name and --workdir (or EASYDL_POD_NAME/EASYDL_WORKDIR) "
                 "are required")

    # Registry hygiene first: a crashed pod never retracts its entry, so a
    # reused workdir accumulates dead publications that rescue discovery
    # pays a probe timeout for and a rerouting client could briefly adopt.
    # Rescue-worthiness does NOT depend on the swept entries (see
    # prior_shard_state_exists); the epoch counters survive the sweep.
    registry.sweep_stale(args.workdir)

    old = None
    rescued, claim_path = False, None
    if args.reshard_dest:
        # Migration destination: the shard index is assigned by the
        # coordinator (argv or the name's trailing index), never rescued —
        # its rows arrive via the coordinator's Restore + ReshardReplay,
        # not from this workdir's ps-ckpt (which belongs to the SOURCE
        # generation's lineage until the post-commit save).
        num_shards = args.num_shards
        index = (args.shard_index if args.shard_index >= 0
                 else shard_index_from_name(args.name))
        if index is None or not 0 <= index < num_shards:
            ap.error("--reshard-dest needs a shard index (argv or a "
                     "numeric name suffix) in [0, num_shards)")
    elif args.replaces:
        # The shard identity is inherited from the pod being replaced — the
        # operator names replacements with a fresh trailing index, so the
        # name is NOT the shard.
        old = wait_registry_entry(args.workdir, args.replaces)
        index, num_shards = int(old["shard"]), int(old["num_shards"])
    else:
        num_shards = args.num_shards
        if args.shard_index >= 0:
            index = args.shard_index
        else:
            index, rescued, claim_path = resolve_fresh_shard(
                args.workdir, args.name, num_shards
            )
    from easydl_tpu.obs import tracing

    # Trace/exporter identity is the POD, not the shard index: indices are
    # shared across reshard generations (source, rescuer, destinations),
    # and per-process artifact files keyed by index would collide.
    tracing.configure(f"ps-{args.name}", args.workdir)
    # Fencing epoch: strictly monotonic per shard, taken by every
    # incarnation before it serves — pushes stamped with any OTHER epoch
    # are rejected retriably, and the first evidence of a successor (a
    # newer stamp, or a newer registry publication) fences this server for
    # good. The WAL lives under an epoch-named dir so a zombie predecessor
    # and its rescuer never write to the same segment files.
    epoch = registry.bump_epoch(args.workdir, index)
    # The routing generation this pod publishes under: a DECLARED reshard
    # destination publishes under the in-flight plan's generation —
    # invisible to clients until the coordinator commits; everyone else
    # under the committed one (shard-count coincidence with a plan target
    # is deliberately not enough — see generation_for_publication).
    route_gen = registry.generation_for_publication(
        args.workdir, num_shards, dest=args.reshard_dest)
    shard = PsShard(
        shard_index=index, num_shards=num_shards, epoch=epoch,
        wal_root=os.path.join(args.workdir, "ps-wal", f"shard-{index}"),
        workdir=args.workdir,
        # Only snapshots committing to the rescue lineage may retire WAL
        # segments (server.save): a save anywhere else — the chaos
        # harness's verify dumps, ad-hoc Save RPCs — must leave the log
        # intact or a later failure rescue silently loses those pushes.
        rescue_dir=os.path.join(args.workdir, "ps-ckpt"),
        route_generation=route_gen,
    )
    server = shard.serve(port=args.port, obs_workdir=args.workdir,
                         obs_name=f"ps-{args.name}")
    log.info("ps pod %s serving shard %d/%d on %s",
             args.name, shard.shard_index, num_shards, server.address)

    hb_stop = hb_thread = None
    if claim_path is not None:
        import threading

        hb_stop = threading.Event()
        hb_thread = threading.Thread(
            target=claim_heartbeat, args=(claim_path, args.name, hb_stop, 10.0),
            daemon=True)
        hb_thread.start()

    if old is not None:
        # No WAL replay here: the drain snapshot is complete by
        # construction (the predecessor gated new pushes and exported
        # under the gate), so every record in its surviving segments is
        # ALREADY in the restored rows — replaying them would double-
        # apply. The segments still outlive the handoff (retire_wal=False
        # on the drain path) for the one reader that does need them: a
        # failure rescue of THIS replacement before its first ps-ckpt
        # save, which restores the older ps-ckpt and replays predecessor
        # + own segments in epoch order.
        run_handoff(old, args.workdir, shard)
    elif rescued:
        # Failure rescue: the shard's previous server died without a drain.
        # Recover its rows from the last complete PS checkpoint (workers
        # save the PS tier alongside dense checkpoints; restore() keeps
        # only this shard's ids) and then REPLAY the surviving WAL segments
        # on top — every push the dead server acked since that checkpoint,
        # re-applied through the same store math, so the recovered table is
        # bit-identical to the pre-crash one (zero lost updates, the bound
        # the snapshot-only rescue could not give).
        ckpt_dir = os.path.join(args.workdir, "ps-ckpt")
        try:
            step = shard.restore(ckpt_dir)
            log.info("rescued shard %d from %s at step %d",
                     index, ckpt_dir, step)
        except FileNotFoundError:
            log.warning("no complete PS checkpoint under %s; rescued shard "
                        "%d starts from its WAL alone", ckpt_dir, index)
        # Last line of defense against hijacking a live shard: the restore
        # took time — if the shard's prior publication answers NOW, the
        # "dead" verdict was a slow probe, not a death. Stand down. This
        # MUST precede the WAL replay: replay caps the predecessor's
        # segments with REPLAYED markers, which would wrongly freeze a
        # still-living shard's log.
        prior = registry.shard_map(args.workdir).get(index)
        if prior is not None and probe_alive(prior["address"]):
            server.stop()
            raise SystemExit(
                f"shard {index}'s prior server {prior['pod']!r} answers "
                "again — it was slow, not dead; standing down"
            )
        stats = shard.replay_wal()
        if stats["torn"]:
            log.warning("rescue of shard %d truncated %d torn wal tail(s)",
                        index, stats["torn"])

    if not args.reshard_dest:
        # A SOURCE-generation pod coming up while a reshard plan is in
        # flight starts push-GATED (the same gate ReshardCutover sets):
        # by the time a mid-migration rescue serves, some destination may
        # already have replayed this shard's WAL tail — a push accepted
        # here now would be invisible to that replay and silently lost at
        # commit. Gated, the push bounces with a retriable `stale-route`
        # until the coordinator either commits (client re-partitions onto
        # the new set) or aborts (its rollback sends ReshardResume, which
        # lifts the gate). The coordinator's cutover phase re-resolves
        # this rescuer from the registry, so the migration completes
        # through it rather than stalling on the dead predecessor.
        plan = registry.routing_table(args.workdir).get("plan")
        if plan and int(plan.get("from_shards", -1)) == num_shards:
            shard.cutover()
            log.warning("ps pod %s (shard %d/%d) starts push-gated: "
                        "reshard plan generation %s is in flight",
                        args.name, index, num_shards,
                        plan.get("generation"))
            # Gate watchdog: the rollback of an aborted migration sends
            # ReshardResume to the COMMITTED shard_map — a rescuer that
            # gated itself here but had not yet published is invisible to
            # it and would stay gated forever with no coordinator left to
            # un-gate it. Watch the plan instead: if it disappears
            # without the routing generation moving (abort, not commit),
            # lift our own gate. A commit leaves us gated — correctly:
            # this generation is superseded.
            threading.Thread(
                target=_gate_watchdog,
                args=(shard, args.workdir, int(plan["generation"])),
                daemon=True, name=f"ps-gate-watchdog-{index}",
            ).start()

    if hb_stop is not None:
        hb_stop.set()
        hb_thread.join(timeout=1.0)
    if claim_path is not None:
        # A stale-claim thief may have taken the shard while we restored;
        # the registry must not see two publications racing for it.
        owner = claim_owner(claim_path)
        if owner != args.name:
            server.stop()
            raise SystemExit(
                f"claim on shard {index} taken over by {owner!r}; exiting"
            )
    registry.publish(args.workdir, args.name, shard.shard_index,
                     num_shards, server.address, epoch=epoch,
                     generation=route_gen)
    if claim_path is not None:
        # Close the remaining check-then-publish window: if ownership moved
        # between the check above and our publish, bow out LOUDLY (stop
        # serving, exit non-zero) — a bounded, visible failure instead of a
        # silent split-brain with pushes split across two servers.
        owner = claim_owner(claim_path)
        if owner != args.name:
            server.stop()
            raise SystemExit(
                f"claim on shard {index} lost to {owner!r} at publish; "
                "exiting"
            )
        # Published and authoritative: the claim has done its job — drop
        # it so the shard's NEXT rescue starts from a fresh O_EXCL create
        # instead of waiting out the staleness window to steal ours.
        release_claim(claim_path, args.name)
    if args.ready_file:
        with open(args.ready_file, "w") as f:
            f.write(server.address)

    stop = {"flag": False}

    def on_term(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    shard.stop()  # gRPC server + metrics exporter (retracts the obs file)
    log.info("ps pod %s exiting", args.name)
    sys.exit(0)


if __name__ == "__main__":
    main()
