"""MLP classifier — BASELINE config 1 (the reference quickstart:
``model_zoo.iris.dnn_estimator``, docs/design/elastic-training-operator.md:37,
and "MNIST MLP" in BASELINE.json).

Parameters carry logical axis names so the same model runs pure-DP, FSDP, or
TP by changing sharding rules only.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from easydl_tpu.core.data import SyntheticImages
from easydl_tpu.models.registry import ModelBundle, register_model


class MLP(nn.Module):
    features: Sequence[int] = (128, 128)
    classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for i, width in enumerate(self.features):
            x = nn.Dense(
                width,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "mlp")
                ),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("mlp",)
                ),
                name=f"dense_{i}",
            )(x)
            x = nn.relu(x)
        return nn.Dense(
            self.classes,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("vocab",)
            ),
            name="head",
        )(x)


@register_model("mlp")
def make_mlp(
    input_shape=(28, 28, 1),
    features=(128, 128),
    classes: int = 10,
) -> ModelBundle:
    model = MLP(features=tuple(features), classes=classes)

    def init_fn(rng):
        x = jnp.zeros((1, *input_shape), jnp.float32)
        return model.init(rng, x)["params"]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = (jnp.argmax(logits, -1) == batch["label"]).mean()
        return loss, {"accuracy": acc}

    def make_data(global_batch: int, seed: int = 0):
        return SyntheticImages(global_batch, shape=input_shape, classes=classes, seed=seed)

    return ModelBundle(
        name="mlp",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_data=make_data,
        eval_fn=loss_fn,
        param_count_hint=int(
            np.prod(input_shape) * features[0]
            + sum(a * b for a, b in zip(features[:-1], features[1:]))
            + features[-1] * classes
        ),
    )
