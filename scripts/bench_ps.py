#!/usr/bin/env python
"""PS hot-path microbenchmark: pull/push round-trips against REAL out-of-
process gRPC shards (plus an in-process Local run), uniform vs Zipf id
streams, pre-PR baseline vs the coalesced/raw-wire/vectorized path.

Baseline = the pre-PR data path, reconstructed exactly: strict per-position
wire rows (no dedup), varint ``repeated int64 ids`` encoding, boolean-mask
shard partition, one unary message per shard per op, synchronous push, and
the per-id python-loop numpy store (``EASYDL_PS_STORE_LOOP=1``). Optimized
= the defaults after this PR: ``np.unique`` coalescing with
scatter-on-return, client-side duplicate-grad accumulation, argsort
partition, zero-copy ``raw_ids`` bytes, ~1MB chunked concurrent transfers,
write-behind async push (drained inside the timed region), and the
batched-gather/scatter store.

The default store backend is ``numpy`` — the store this PR vectorized, so
the sharded cells measure the complete pre/post delta (and what any
deployment without a C++ toolchain runs). ``--backend auto``/``native``
swaps in the C++ store, which is byte-identical pre/post PR, isolating the
client+wire portion of the win.

Shard servers run as SUBPROCESSES (like production pods) so the client and
servers don't share a GIL; wire bytes are the shards' own
``easydl_ps_{pull,push}_bytes_total`` counters, scraped from their /metrics
exporters. The Local transport stays in-process (that IS its deployment
shape) and uses the numpy backend so the store vectorization is visible.

JSON lands next to the other bench artifacts::

    python scripts/bench_ps.py --out BENCH_PS.json
    python scripts/bench_ps.py --smoke          # seconds, CI-sized
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from easydl_tpu.ps.client import LocalPsClient, ShardedPsClient  # noqa: E402
from easydl_tpu.ps.table import TableSpec  # noqa: E402
from easydl_tpu.ps.trainer import AsyncPusher  # noqa: E402

TABLE = "bench"

_SERVE_SHARD = r"""
import sys, time
from easydl_tpu.ps.server import PsShard
idx, n, backend, addr_file, obs_dir = sys.argv[1:6]
wal_root = sys.argv[6] if len(sys.argv) > 6 else ""
shard = PsShard(shard_index=int(idx), num_shards=int(n), backend=backend,
                epoch=1 if wal_root else 0, wal_root=wal_root or None)
server = shard.serve(obs_workdir=obs_dir or None)
with open(addr_file + ".tmp", "w") as f:
    f.write(server.address)
import os as _os
_os.replace(addr_file + ".tmp", addr_file)
while True:
    time.sleep(1)
"""


def make_stream(kind: str, steps: int, batch: int, vocab: int,
                zipf_a: float, seed: int = 0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        if kind == "zipf":
            ids = (rng.zipf(zipf_a, batch) % vocab).astype(np.int64)
        else:
            ids = rng.integers(0, vocab, batch).astype(np.int64)
        out.append(ids)
    return out


def _spawn_shards(n: int, backend: str, workdir: str, store_loop: bool,
                  wal: bool = False):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("EASYDL_PS_STORE_LOOP", None)
    if store_loop:
        env["EASYDL_PS_STORE_LOOP"] = "1"
    procs, addr_files = [], []
    for i in range(n):
        addr_file = os.path.join(workdir, f"shard-{i}.addr")
        addr_files.append(addr_file)
        wal_root = (os.path.join(workdir, "ps-wal", f"shard-{i}")
                    if wal else "")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _SERVE_SHARD, str(i), str(n), backend,
             addr_file, workdir, wal_root],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ))
    addrs = []
    deadline = time.monotonic() + 60
    for path in addr_files:
        while not os.path.exists(path):
            if time.monotonic() > deadline:
                for p in procs:
                    p.kill()
                raise TimeoutError("ps shard subprocess never published "
                                   f"{path}")
            time.sleep(0.05)
        with open(path) as f:
            addrs.append(f.read().strip())
    return procs, addrs


def _scrape_wire_bytes(workdir: str) -> float:
    from easydl_tpu.obs.scrape import merge_snapshot

    merged = merge_snapshot(workdir=workdir).get("merged", {})
    return sum(v for k, v in merged.items()
               if k.startswith("easydl_ps_pull_bytes_total")
               or k.startswith("easydl_ps_push_bytes_total"))


def _scrape_wal_counters(workdir: str) -> dict:
    from easydl_tpu.obs.scrape import merge_snapshot

    merged = merge_snapshot(workdir=workdir).get("merged", {})

    def total(name: str) -> float:
        return sum(v for k, v in merged.items() if k.startswith(name))

    return {
        "appends": int(total("easydl_ps_wal_appends_total")),
        "bytes": int(total("easydl_ps_wal_bytes_total")),
    }


def _pass(client, stream, grads, scale: float = 0.125,
          async_push: bool = False) -> float:
    """One pull+push round trip per batch. ``async_push`` runs the pushes
    through the write-behind queue exactly as the pipelined training loop
    does (ps/trainer.py train_steps); the queue is fully DRAINED inside the
    timed region, so every measured pass ends with all updates applied."""
    pusher = AsyncPusher(client, depth=2) if async_push else None
    t0 = time.perf_counter()
    try:
        for ids in stream:
            client.pull(TABLE, ids)
            if pusher is not None:
                pusher.submit(TABLE, ids, grads, scale)
            else:
                client.push(TABLE, ids, grads, scale)
        if pusher is not None:
            pusher.drain()
        return time.perf_counter() - t0
    finally:
        if pusher is not None:
            pusher.close()


def _result(elapsed: float, stream, wire: float) -> dict:
    n_ids = sum(len(s) for s in stream)
    return {
        "elapsed_s": round(elapsed, 4),
        "roundtrips_per_s": round(len(stream) / elapsed, 2),
        "ids_per_s": round(n_ids / elapsed, 1),
        "wire_bytes": int(wire),
        "wire_bytes_per_roundtrip": int(wire / len(stream)),
    }


def run_sharded(optimized: bool, stream, dim: int, shards: int,
                backend: str, fp16: bool = False,
                async_push: bool = False, repeats: int = 3,
                wal: bool = False) -> dict:
    spec = TableSpec(name=TABLE, dim=dim, optimizer="adagrad", seed=11)
    with tempfile.TemporaryDirectory(prefix="bench_ps_") as workdir:
        procs, addrs = _spawn_shards(shards, backend, workdir,
                                     store_loop=not optimized, wal=wal)
        client = None
        try:
            client = ShardedPsClient(addrs, coalesce=optimized,
                                     raw_ids=optimized, pull_fp16=fp16,
                                     chunk_bytes=None if optimized else 0)
            client.create_table(spec)
            grads = np.ones((len(stream[0]), dim), np.float32)
            # Untimed warm pass: channels, pools, lazy row init — one-time
            # table-population costs a real job amortises away. The timed
            # passes are the steady state a training step actually pays;
            # best-of-N filters scheduler noise (this box is small).
            _pass(client, stream, grads)
            b0 = _scrape_wire_bytes(workdir)
            elapsed = min(_pass(client, stream, grads, async_push=async_push)
                          for _ in range(repeats))
            wire = (_scrape_wire_bytes(workdir) - b0) / repeats
            out = _result(elapsed, stream, wire)
            if wal:
                out["wal"] = _scrape_wal_counters(workdir)
            return out
        finally:
            if client is not None:
                client.close()
            for p in procs:
                p.kill()
            for p in procs:
                p.wait()


def run_local(optimized: bool, stream, dim: int, shards: int,
              backend: str, repeats: int = 3) -> dict:
    os.environ.pop("EASYDL_PS_STORE_LOOP", None)
    if not optimized:
        os.environ["EASYDL_PS_STORE_LOOP"] = "1"
    try:
        client = LocalPsClient(num_shards=shards, backend=backend)
        client.create_table(
            TableSpec(name=TABLE, dim=dim, optimizer="adagrad", seed=11)
        )
        grads = np.ones((len(stream[0]), dim), np.float32)
        _pass(client, stream, grads)  # warm: lazy row init off the clock
        elapsed = min(_pass(client, stream, grads) for _ in range(repeats))
        return _result(elapsed, stream, 0.0)
    finally:
        os.environ.pop("EASYDL_PS_STORE_LOOP", None)


def run_wal_mode(args) -> int:
    """WAL-overhead mode: the full post-PR sharded hot path (coalesced raw
    wire, chunked transfers, async push) measured with the push WAL off vs
    on — the only delta is the log append + background fsync on every
    applied push. When a prior ``BENCH_PS.json`` exists its optimized
    round-trip rate is folded in as a cross-run reference (same machine,
    different boot: same-run wal_off is the honest denominator; the
    reference guards against the wal_off run itself having regressed)."""
    doc = {
        "bench": "ps_wal_overhead",
        "config": {
            "shards": args.shards, "dim": args.dim, "batch": args.batch,
            "steps": args.steps, "repeats": args.repeats,
            "vocab": args.vocab, "zipf_a": args.zipf_a,
            "backend": args.backend, "smoke": bool(args.smoke),
        },
        "machine": {
            "platform": platform.platform(),
            "python": platform.python_version(),
            "numpy": np.__version__,
        },
        "results": {},
    }
    reference = {}
    if args.reference:
        try:
            with open(args.reference) as f:
                reference = json.load(f)
        except (OSError, ValueError):
            print(f"note: no reference artifact at {args.reference}")
    for kind in args.streams.split(","):
        stream = make_stream(kind, args.steps, args.batch, args.vocab,
                             args.zipf_a)
        off = run_sharded(True, stream, args.dim, args.shards, args.backend,
                          async_push=True, repeats=args.repeats)
        on = run_sharded(True, stream, args.dim, args.shards, args.backend,
                         async_push=True, repeats=args.repeats, wal=True)
        cell = {
            "wal_off": off,
            "wal_on": on,
            # overhead = throughput lost to the log, as a fraction
            "overhead": round(
                1.0 - on["roundtrips_per_s"] / off["roundtrips_per_s"], 4),
            "wal_bytes_per_roundtrip": int(
                on.get("wal", {}).get("bytes", 0) / max(len(stream), 1)
                / max(args.repeats + 1, 1)),
        }
        ref_cell = (reference.get("results", {}).get("sharded", {})
                    .get(kind, {}).get("optimized"))
        if ref_cell:
            cell["reference_roundtrips_per_s"] = ref_cell["roundtrips_per_s"]
            cell["overhead_vs_reference"] = round(
                1.0 - on["roundtrips_per_s"] / ref_cell["roundtrips_per_s"],
                4)
        doc["results"][kind] = cell
        line = (f"wal/{kind:<8s} off {off['roundtrips_per_s']:8.1f} rt/s  "
                f"on {on['roundtrips_per_s']:8.1f} rt/s  "
                f"overhead {cell['overhead'] * 100:5.1f}%")
        if ref_cell:
            line += (f"  vs-ref {cell['overhead_vs_reference'] * 100:5.1f}%"
                     f" (ref {ref_cell['roundtrips_per_s']:.1f})")
        print(line)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description="PS pull/push microbenchmark")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed passes per mode; best is reported")
    ap.add_argument("--vocab", type=int, default=200_000)
    ap.add_argument("--zipf-a", type=float, default=1.1)
    ap.add_argument("--backend", default="numpy",
                    help="sharded-store backend: numpy (default — the "
                         "store this PR vectorized, i.e. the full pre/post "
                         "delta and what runs without a C++ toolchain) | "
                         "auto | native (C++ store, identical pre/post PR: "
                         "isolates the client+wire win alone)")
    ap.add_argument("--local-backend", default="numpy",
                    help="Local-transport store backend (numpy shows the "
                         "store vectorization; native is pre/post identical)")
    ap.add_argument("--transports", default="local,sharded")
    ap.add_argument("--streams", default="uniform,zipf")
    ap.add_argument("--fp16", action="store_true",
                    help="add an optimized+fp16-pull variant (sharded only)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized: runs in seconds on CPU")
    ap.add_argument("--wal", action="store_true",
                    help="WAL-overhead mode: the post-PR sharded hot path "
                         "with the push write-ahead log OFF vs ON (same "
                         "stream, same shards); compares against "
                         "BENCH_PS.json when present. Acceptance: ≤10%% "
                         "round-trip overhead on the Zipf(1.1) stream.")
    ap.add_argument("--reference", default=os.path.join(REPO, "BENCH_PS.json"),
                    help="--wal mode: prior bench artifact to compare "
                         "against ('' skips)")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.smoke:
        args.shards, args.dim = 2, 8
        args.batch, args.steps, args.vocab = 1024, 4, 20_000
        args.repeats = 1
    if args.wal:
        return run_wal_mode(args)

    doc = {
        "bench": "ps_hot_path",
        "config": {
            "shards": args.shards, "dim": args.dim, "batch": args.batch,
            "steps": args.steps, "repeats": args.repeats,
            "vocab": args.vocab, "zipf_a": args.zipf_a,
            "backend": args.backend, "local_backend": args.local_backend,
            "smoke": bool(args.smoke),
        },
        "machine": {
            "platform": platform.platform(),
            "python": platform.python_version(),
            "numpy": np.__version__,
        },
        "results": {},
        "dedup_ratio": {},
    }
    for kind in args.streams.split(","):
        stream = make_stream(kind, args.steps, args.batch, args.vocab,
                             args.zipf_a)
        total = sum(len(s) for s in stream)
        uniq = sum(len(np.unique(s)) for s in stream)
        doc["dedup_ratio"][kind] = round(uniq / total, 4)
    for transport in args.transports.split(","):
        doc["results"][transport] = {}
        for kind in args.streams.split(","):
            stream = make_stream(kind, args.steps, args.batch, args.vocab,
                                 args.zipf_a)
            if transport == "sharded":
                # Baseline = the full pre-PR loop: strict per-position wire,
                # no chunking, synchronous push on the critical path.
                # Optimized = the full post-PR data path, async push
                # included (drained inside the timed region) — exactly what
                # the pipelined training loop runs. optimized_strict keeps
                # the push synchronous, isolating the wire/store win.
                base = run_sharded(False, stream, args.dim, args.shards,
                                   args.backend, repeats=args.repeats)
                opt_strict = run_sharded(True, stream, args.dim, args.shards,
                                         args.backend, repeats=args.repeats)
                opt = run_sharded(True, stream, args.dim, args.shards,
                                  args.backend, async_push=True,
                                  repeats=args.repeats)
            else:
                base = run_local(False, stream, args.dim, args.shards,
                                 args.local_backend, repeats=args.repeats)
                opt_strict = None
                opt = run_local(True, stream, args.dim, args.shards,
                                args.local_backend, repeats=args.repeats)
            cell = {
                "baseline": base,
                "optimized": opt,
                "speedup": round(opt["roundtrips_per_s"]
                                 / base["roundtrips_per_s"], 2),
                "wire_bytes_ratio": round(
                    opt["wire_bytes"] / max(base["wire_bytes"], 1), 4),
            }
            if opt_strict is not None:
                cell["optimized_strict"] = opt_strict
                cell["speedup_strict"] = round(
                    opt_strict["roundtrips_per_s"]
                    / base["roundtrips_per_s"], 2)
            if transport == "sharded" and args.fp16:
                cell["optimized_fp16"] = run_sharded(
                    True, stream, args.dim, args.shards, args.backend,
                    fp16=True, async_push=True, repeats=args.repeats,
                )
            doc["results"][transport][kind] = cell
            print(f"{transport:>8s}/{kind:<8s} "
                  f"base {base['roundtrips_per_s']:8.1f} rt/s  "
                  f"opt {opt['roundtrips_per_s']:8.1f} rt/s  "
                  f"speedup {cell['speedup']:5.2f}x  "
                  f"wire {cell['wire_bytes_ratio']:.3f}x")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
