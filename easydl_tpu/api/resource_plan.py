"""ResourcePlan — the system's materialised resource decision (≙ JobResource CRD).

The reference's JobResource (docs/design/elastic-training-operator.md:50-101)
carries:

- ``spec.selector.name`` binding the plan to a job (:61-62),
- per-role ``replicas`` + ``resource`` blocks for parameter_server / worker /
  evaluator (:63-85),
- a ``resource_updation`` list for per-pod **vertical scaling with
  replace-then-retire semantics**: "launch a new Pod with the ``resource`` ...
  to replace the Pod with the ``resource_updation.name``" (:86-101).

Either the trainer (normal path, :107-108) or an advanced user (:50-55) creates
it; the operator reconciles pods against it (:97-98). We keep that contract and
extend ``resource`` with TPU chips/topology so a plan can demand pod slices.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import yaml

from easydl_tpu.api.job_spec import (
    API_VERSION,
    ROLES,
    ResourceSpec,
    SpecError,
)

PLAN_KIND = "JobResource"

#: Roles that may appear in a plan (the trainer pod is created from the
#: ElasticJob itself, before any plan exists — :47-48 — but including it here
#: lets a plan vertically scale the trainer too).
PLAN_ROLES = ("parameter_server", "worker", "evaluator", "trainer")


@dataclass
class RolePlan:
    """``replicas`` + per-replica ``resource`` for one role
    (docs/design/elastic-training-operator.md:63-85)."""

    replicas: int = 0
    resource: ResourceSpec = field(default_factory=ResourceSpec)

    def validate(self) -> None:
        if self.replicas < 0:
            raise SpecError(f"replicas must be >= 0, got {self.replicas}")
        self.resource.validate()

    def to_dict(self) -> Dict[str, Any]:
        return {"replicas": self.replicas, "resource": self.resource.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RolePlan":
        return cls(
            replicas=int(d.get("replicas", 0)),
            resource=ResourceSpec.from_dict(d.get("resource")),
        )


@dataclass
class ResourceUpdation:
    """One vertical-scaling entry: replace the pod named ``name`` with a new
    pod using ``resource`` (docs/design/elastic-training-operator.md:86-101).

    Field name kept as the reference spells it ("updation") for manifest
    compatibility.
    """

    name: str
    resource: ResourceSpec = field(default_factory=ResourceSpec)

    def validate(self) -> None:
        if not self.name:
            raise SpecError("resource_updation entry needs a pod name")
        self.resource.validate()

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "resource": self.resource.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceUpdation":
        return cls(
            name=str(d.get("name", "")),
            resource=ResourceSpec.from_dict(d.get("resource")),
        )


@dataclass
class ResourcePlan:
    """The full plan document (≙ JobResource)."""

    name: str = ""
    job_name: str = ""  # spec.selector.name (:61-62)
    roles: Dict[str, RolePlan] = field(default_factory=dict)
    resource_updation: List[ResourceUpdation] = field(default_factory=list)
    #: monotonically increasing version so the operator/master can order plans
    #: (the reference relies on k8s resourceVersion implicitly; we make it explicit)
    version: int = 0

    def validate(self) -> None:
        if not self.job_name:
            raise SpecError("ResourcePlan.job_name (spec.selector.name) is required")
        for role, rp in self.roles.items():
            if role not in PLAN_ROLES:
                raise SpecError(f"unknown role {role!r}; valid: {PLAN_ROLES}")
            rp.validate()
        for u in self.resource_updation:
            u.validate()

    def replicas(self, role: str) -> int:
        rp = self.roles.get(role)
        return rp.replicas if rp else 0

    @property
    def total_tpu_chips(self) -> int:
        n = 0
        for rp in self.roles.values():
            if rp.resource.tpu:
                n += rp.replicas * rp.resource.tpu.chips
        return n

    def with_role(self, role: str, replicas: int, resource: Optional[ResourceSpec] = None) -> "ResourcePlan":
        """Functional update: new plan with ``role`` set, version bumped."""
        roles = dict(self.roles)
        old = roles.get(role)
        roles[role] = RolePlan(
            replicas=replicas,
            resource=resource if resource is not None else (old.resource if old else ResourceSpec()),
        )
        return ResourcePlan(
            name=self.name,
            job_name=self.job_name,
            roles=roles,
            resource_updation=list(self.resource_updation),
            version=self.version + 1,
        )

    # ------------------------------------------------------------------ CRD IO
    def to_crd(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"selector": {"name": self.job_name}}
        for role, rp in self.roles.items():
            spec[role] = rp.to_dict()
        if self.resource_updation:
            spec["resource_updation"] = [u.to_dict() for u in self.resource_updation]
        meta: Dict[str, Any] = {"version": self.version}
        if self.name:
            meta["name"] = self.name
        return {
            "apiVersion": API_VERSION,
            "kind": PLAN_KIND,
            "metadata": meta,
            "spec": spec,
        }

    @classmethod
    def from_crd(cls, doc: Dict[str, Any]) -> "ResourcePlan":
        if not isinstance(doc, dict):
            raise SpecError(f"expected a mapping document, got {type(doc).__name__}")
        if doc.get("kind") != PLAN_KIND:
            raise SpecError(f"expected kind {PLAN_KIND}, got {doc.get('kind')!r}")
        meta = doc.get("metadata") or {}
        spec = doc.get("spec") or {}
        known = set(PLAN_ROLES) | {"selector", "resource_updation"}
        unknown = sorted(k for k in spec if k not in known)
        if unknown:
            raise SpecError(
                f"unknown spec field(s) {unknown} in JobResource "
                f"{meta.get('name')!r}; valid roles: {PLAN_ROLES}"
            )
        selector = spec.get("selector") or {}
        roles = {}
        for role in PLAN_ROLES:
            if role not in spec:
                continue
            if not isinstance(spec[role], dict):
                raise SpecError(
                    f"role {role!r} must be a mapping, got {type(spec[role]).__name__}"
                )
            roles[role] = RolePlan.from_dict(spec[role])
        plan = cls(
            name=str(meta.get("name", "")),
            job_name=str(selector.get("name", "")),
            roles=roles,
            resource_updation=[
                ResourceUpdation.from_dict(u) for u in spec.get("resource_updation") or []
            ],
            version=int(meta.get("version", 0)),
        )
        plan.validate()
        return plan

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_crd(), sort_keys=False)

    @classmethod
    def from_yaml(cls, text: str) -> "ResourcePlan":
        return cls.from_crd(yaml.safe_load(text))

    # ------------------------------------------------------------------ diffing
    def diff(self, other: "ResourcePlan") -> Dict[str, Any]:
        """Role-level delta from ``self`` to ``other`` — what the operator must
        reconcile (create/delete pods) and the master must absorb (world-size
        change)."""
        delta: Dict[str, Any] = {"scale": {}, "replace": []}
        for role in set(self.roles) | set(other.roles):
            before, after = self.replicas(role), other.replicas(role)
            if before != after:
                delta["scale"][role] = (before, after)
        def key(u: "ResourceUpdation") -> Tuple[str, str]:
            return (u.name, json.dumps(u.resource.to_dict(), sort_keys=True))

        seen = {key(u) for u in self.resource_updation}
        delta["replace"] = [u.name for u in other.resource_updation if key(u) not in seen]
        return delta
