"""Process-environment recipes shared across subprocess launchers."""

from __future__ import annotations

import os
from typing import Dict, Mapping, Optional


def cpu_subprocess_env(
    n_devices: int, base: Optional[Mapping[str, str]] = None
) -> Dict[str, str]:
    """Environment for a subprocess that must initialise JAX on a forced
    ``n_devices``-device CPU platform.

    Neutralises the image's TPU tunnel plugin (PALLAS_AXON_POOL_IPS) so the
    child cannot re-attach to the chip — the single authoritative copy of the
    recipe used by the elastic agent's worker spawns and the driver's
    ``dryrun_multichip`` bootstrap.
    """
    env = dict(base if base is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    return env
