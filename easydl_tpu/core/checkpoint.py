"""Sharded, async, reshard-on-restore checkpointing.

The reference promises "resume the training" after failures (README.md:27)
with no mechanism; for TPU elasticity the checkpoint layer is the linchpin
(SURVEY.md §5.4, §7): a save taken on an 8-chip mesh must restore onto a
32-chip mesh (and vice versa) without materialising full arrays on any single
host.

Layout (one directory per step)::

    <dir>/step_00000010/
        manifest.json            # leaf keys, shapes, dtypes, mesh meta
        leaf_00003/0-128_0-64.npy   # chunk covering [0:128, 0:64]
        ...
        COMMITTED                # written last — step is valid iff present

Mechanics:
- **save**: every process writes the chunks for its addressable, replica-0
  shards (`jax.Array.addressable_shards`), so write bandwidth scales with
  hosts and nothing is gathered. Host copies are snapshotted synchronously
  (donation-safe), chunk IO runs on a background thread.
- **restore**: ``jax.make_array_from_callback`` asks for exactly the slices
  the *new* sharding places on local devices; the reader assembles them from
  whichever chunks overlap, so an 8→32 or 32→8 reshard reads only what each
  host needs (memory-mapped on POSIX).
- **storage**: chunk IO is pluggable (core/storage.py). POSIX backends
  commit by renaming per-process tmp dirs into the step dir (atomic rename);
  object stores (``gs://``) write chunks directly to their final keys —
  atomic puts — and commit is marker-after-all-puts, ordered by a collective
  barrier. The ``directory`` argument is a URL; plain paths mean POSIX.
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from easydl_tpu.core.chunk_cache import ChunkCache
from easydl_tpu.core.storage import CheckpointStorage, get_storage
from easydl_tpu.utils.logging import get_logger

log = get_logger("core", "checkpoint")

_STEP_RE = re.compile(r"^step_(\d{8})$")
_COMMITTED = "COMMITTED"
#: written by quarantine(): the step's bytes proved unreadable at restore
#: time (truncated chunk, bad manifest). Kept alongside the demoted dir so
#: operators can autopsy it; a later re-save of the same step clears the
#: whole dir through the ordinary uncommitted-debris path.
_CORRUPT = "CORRUPT"


def _keystr(path) -> str:
    return jax.tree_util.keystr(path)


def _chunk_name(index: Tuple[slice, ...], shape: Tuple[int, ...]) -> str:
    if not shape:
        return "scalar.npy"
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        parts.append(f"{start}-{stop}")
    return "_".join(parts) + ".npy"


def _parse_chunk_name(name: str) -> Optional[List[Tuple[int, int]]]:
    if name == "scalar.npy":
        return []
    if not name.endswith(".npy"):
        return None
    try:
        return [
            (int(a), int(b))
            for a, b in (p.split("-") for p in name[:-4].split("_"))
        ]
    except ValueError:
        return None


class _LeafReader:
    """Assembles arbitrary slices of one leaf from its saved chunks.

    With a host-local :class:`ChunkCache` and this save's token, chunk loads
    try tmpfs first — the survivor fast path: a rank whose host wrote a
    chunk reads it back from memory; only chunks other hosts wrote (i.e.
    slices that actually moved in a reshard) hit shared storage."""

    def __init__(self, storage: CheckpointStorage, leaf_dir: str,
                 shape: Tuple[int, ...], dtype: np.dtype,
                 cache: Optional[ChunkCache] = None, cache_token: str = "",
                 cache_rel: str = ""):
        self.storage = storage
        self.shape = shape
        self.dtype = dtype
        self._cache = cache
        self._cache_token = cache_token
        self._cache_rel = cache_rel
        self._chunks: List[Tuple[List[Tuple[int, int]], str, str]] = []
        # make_array_from_callback calls read() once per local device; on
        # object stores each uncached load_array is a full HTTP download, so
        # overlapping device slices would re-fetch the same chunk per device.
        # The reader lives only for one leaf's restore — the cache is small
        # and short-lived. (POSIX load_array returns an mmap: caching it
        # just keeps the fd.)
        self._loaded: Dict[str, np.ndarray] = {}
        # Chunk inventory is the union of storage and cache listings: after
        # a same-host restart the cache alone can carry the whole leaf, and
        # the token gate (manifest-recorded) makes cached names as
        # authoritative as stored ones.
        names = set(storage.listdir(leaf_dir))
        if cache is not None:
            names.update(
                n for n in cache.listdir(cache_token, cache_rel)
                if not n.endswith(".tmp"))
        for name in sorted(names):
            bounds = _parse_chunk_name(name)
            if bounds is not None:
                self._chunks.append((bounds, f"{leaf_dir}/{name}", name))
        if not self._chunks:
            raise FileNotFoundError(f"no chunks in {leaf_dir}")

    def _load(self, path: str, name: str) -> np.ndarray:
        arr = self._loaded.get(path)
        if arr is None:
            if self._cache is not None:
                arr = self._cache.load(self._cache_token,
                                       f"{self._cache_rel}/{name}")
            if arr is None:
                arr = self.storage.load_array(path)
            self._loaded[path] = arr
        return arr

    def read(self, index: Tuple[slice, ...]) -> np.ndarray:
        if not self.shape:
            return self._load(*self._chunks[0][1:])
        want = [
            (0 if sl.start is None else sl.start, dim if sl.stop is None else sl.stop)
            for sl, dim in zip(index, self.shape)
        ]
        for bounds, path, name in self._chunks:
            if bounds == want:
                # exact-chunk hit (the same-sharding restore): hand the
                # mmap/array straight through — no assembly copy
                return self._load(path, name)
        out = np.empty([b - a for a, b in want], dtype=self.dtype)
        filled = 0
        for bounds, path, name in self._chunks:
            # overlap of chunk bounds with wanted region
            inter = [
                (max(a, ca), min(b, cb))
                for (a, b), (ca, cb) in zip(want, bounds)
            ]
            if any(a >= b for a, b in inter):
                continue
            data = self._load(path, name)
            src = tuple(
                slice(a - ca, b - ca) for (a, b), (ca, cb) in zip(inter, bounds)
            )
            dst = tuple(
                slice(a - wa, b - wa) for (a, b), (wa, wb) in zip(inter, want)
            )
            out[dst] = data[src]
            filled += int(np.prod([b - a for a, b in inter]))
        if filled != out.size:
            raise ValueError(
                f"chunks cover {filled}/{out.size} elements of requested slice "
                f"{want} (shape {self.shape})"
            )
        return out


class CheckpointManager:
    """Save/restore sharded pytrees, keeping the last ``keep`` committed steps.

    ``directory`` is a URL: a plain path (or ``file://``) selects the POSIX
    backend; ``gs://bucket/prefix`` the object-store backend.
    """

    def __init__(self, directory: str, keep: int = 3, async_save: bool = True,
                 storage: Optional[CheckpointStorage] = None):
        self.directory = directory
        self.keep = keep
        self.async_save = async_save
        self.storage = storage if storage is not None else get_storage(directory)
        #: host-local tmpfs cache (core/chunk_cache.py): same-host restores
        #: read back this host's own chunk writes from memory instead of
        #: shared storage — the generation-switch restore fast path. Cache
        #: retention tracks checkpoint retention: every restorable step
        #: should be cache-servable, not just the newest two.
        self.cache = ChunkCache.for_directory(directory, keep=keep)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # Multi-process async saves split in two: chunk IO runs on a
        # background thread (no collectives), while the commit — whose
        # barriers are collectives and must run on the main thread — is
        # deferred until :meth:`finalize` (or :meth:`wait`) is called from
        # the training loop at a later step boundary.
        self._pending_commit = None
        self.storage.makedirs("")

    # ------------------------------------------------------------------ save
    def save(self, step: int, state: Any, metadata: Optional[Dict[str, Any]] = None) -> None:
        """Snapshot shards to host, then write asynchronously (unless
        ``async_save=False``). Call :meth:`wait` before donating buffers is
        NOT needed — the snapshot happens here, synchronously. In
        multi-process runs an async save defers its commit barrier: call
        :meth:`finalize` each step (all ranks together) to complete it."""
        self.wait()
        storage = self.storage
        multiproc = jax.process_count() > 1
        # Skip if already committed (e.g. quiesce landing on a periodic-save
        # step). The decision must be COLLECTIVE: with per-process storage
        # views (GCS/NFS lag) some ranks could skip while others enter the
        # save's barriers and hang — so process 0's verdict is broadcast.
        skip = step in self.steps()
        if multiproc:
            from jax.experimental import multihost_utils

            skip = bool(
                multihost_utils.broadcast_one_to_all(np.asarray(skip, np.int32))
            )
        if skip:
            log.info("step %d already checkpointed; skipping", step)
            return
        # Per-save cache token: leading step number keeps token dirs
        # sortable for GC; the uuid suffix makes chunks from an aborted save
        # of the SAME step unservable (different token). Rank 0's token is
        # broadcast so every rank caches under the name the manifest records.
        cache_token = f"{step:08d}-{uuid.uuid4().hex[:12]}"
        if multiproc:
            from jax.experimental import multihost_utils

            raw = np.frombuffer(cache_token.encode().ljust(32), np.uint8)
            cache_token = bytes(
                np.asarray(multihost_utils.broadcast_one_to_all(raw))
            ).decode().strip()
        leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        snapshot = []  # (leaf_idx, keystr, global_shape, dtype, [(bounds, np.ndarray)])
        for i, (path, leaf) in enumerate(leaves):
            key = _keystr(path)
            if isinstance(leaf, jax.Array):
                shape, dtype = tuple(leaf.shape), np.dtype(leaf.dtype)
                chunks = []
                for shard in leaf.addressable_shards:
                    if shard.replica_id != 0:
                        continue
                    chunks.append((shard.index, np.asarray(shard.data)))
                snapshot.append((i, key, shape, dtype, chunks))
            else:
                arr = np.asarray(leaf)
                snapshot.append(
                    (i, key, tuple(arr.shape), arr.dtype,
                     [(tuple(slice(0, d) for d in arr.shape), arr)])
                )

        t0 = time.perf_counter()
        step_dir = f"step_{step:08d}"
        # POSIX: stage in a per-process tmp dir, commit by rename.
        # Object store: write straight to the final keys (puts are atomic and
        # restore gates on the marker) — but then debris from an aborted save
        # at this step must be cleared BEFORE any rank writes, not at commit.
        direct = not storage.atomic_rename
        write_dir = step_dir if direct else step_dir + f".tmp.{jax.process_index()}"
        if direct:
            if jax.process_index() == 0 and self._uncommitted_debris(step_dir):
                log.warning("clearing aborted save at %s", step_dir)
                storage.delete_tree(step_dir)
            if multiproc:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(f"easydl_ckpt_clean_{step}")

        def write_chunks():
            # Chunk IO only (no collectives) — safe on a background thread.
            if not direct:
                # Our own tmp dir may hold chunks from a save that crashed
                # mid-way (possibly under a different sharding); the commit
                # loop moves every file in it, so start from a clean slate.
                # Per-process dir — a local decision, no barrier needed.
                storage.delete_tree(write_dir)
                storage.makedirs(write_dir)
            manifest = {
                "step": step,
                "metadata": metadata or {},
                "cache_token": cache_token,
                "leaves": [
                    {"index": i, "key": key, "shape": list(shape), "dtype": str(dtype)}
                    for i, key, shape, dtype, _ in snapshot
                ],
            }
            for i, key, shape, dtype, chunks in snapshot:
                leaf_dir = f"{write_dir}/leaf_{i:05d}"
                storage.makedirs(leaf_dir)
                for index, data in chunks:
                    name = _chunk_name(index, shape)
                    storage.save_array(f"{leaf_dir}/{name}", data)
                    if self.cache is not None:
                        self.cache.put(cache_token, f"leaf_{i:05d}/{name}",
                                       data)
            if jax.process_index() == 0:
                storage.write_bytes(
                    f"{write_dir}/manifest.json", json.dumps(manifest).encode()
                )

        def commit():
            # Contains the collective barriers — must run on the MAIN thread
            # in multi-process runs (via finalize()/wait() or the sync path).
            if not direct:
                # A step_dir without COMMITTED is debris from an aborted save
                # (we may be retraining through the same step after a
                # restore): clear it so stale chunks can't mix into — or
                # block — this commit. Process 0 decides and clears; the
                # barrier is UNCONDITIONAL in multi-process runs so every
                # rank enters the same collectives regardless of its local
                # FS view.
                if jax.process_index() == 0 and self._uncommitted_debris(step_dir):
                    log.warning("clearing aborted save at %s", step_dir)
                    storage.delete_tree(step_dir)
                if multiproc:
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices(
                        f"easydl_ckpt_clean_{step}"
                    )
                # Single-host commit: rename tmp → final. Multi-host: every
                # process renames its own tmp dir contents in.
                if jax.process_count() == 1:
                    storage.rename(write_dir, step_dir)
                else:
                    storage.makedirs(step_dir)
                    for name in storage.listdir(write_dir):
                        src, dst = f"{write_dir}/{name}", f"{step_dir}/{name}"
                        if storage.isdir(src):
                            storage.makedirs(dst)
                            for chunk in storage.listdir(src):
                                storage.rename(f"{src}/{chunk}", f"{dst}/{chunk}")
                        else:
                            storage.rename(src, dst)
                    storage.delete_tree(write_dir)
            if multiproc:
                # Every process has written/renamed its chunks in; only then
                # may the marker appear (restore treats COMMITTED as "all
                # shards present").
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(f"easydl_ckpt_{step}")
            if jax.process_index() == 0:
                storage.write_bytes(f"{step_dir}/{_COMMITTED}", str(step).encode())
            log.info("saved step %d in %.2fs -> %s/%s",
                     step, time.perf_counter() - t0, self.directory, step_dir)
            self._gc()
            if self.cache is not None:
                self.cache.gc()

        if self.async_save:
            def run_io():
                try:
                    write_chunks()
                    if not multiproc:
                        # No collectives involved — commit on the IO thread
                        # so single-process saves complete with no further
                        # calls (pre-existing contract).
                        commit()
                except BaseException as e:  # surfaced on next wait()/save()
                    self._error = e

            if multiproc:
                self._pending_commit = commit
            self._thread = threading.Thread(target=run_io, daemon=True)
            self._thread.start()
        else:
            write_chunks()
            commit()

    def _uncommitted_debris(self, step_dir: str) -> bool:
        return (
            bool(self.storage.listdir(step_dir))
            and not self.storage.exists(f"{step_dir}/{_COMMITTED}")
        )

    def finalize(self, block: bool = False) -> bool:
        """Complete a pending deferred commit, running its collective
        barriers on the caller's (main) thread.

        Multi-process contract: every process calls this at the same step
        boundary with the same ``block`` value. With ``block=False`` the
        commit happens only once ALL ranks' chunk IO has finished (agreed via
        a tiny allgather, so no rank enters the barrier alone). The allgather
        carries a tri-state (pending / ready / failed), not just completion:
        if any rank's chunk IO raised, EVERY rank drops the pending commit
        and raises instead of entering the commit collectives — otherwise the
        healthy ranks would hang in ``sync_global_devices`` waiting for the
        failed rank, until external failure detection killed the job.
        Returns True when nothing remains pending."""
        if self._pending_commit is None:
            return True
        # Reap the IO thread if finished (or block for it): joining is safe
        # here — the thread does chunk IO only, no collectives.
        if self._thread is not None and (block or not self._thread.is_alive()):
            self._thread.join()
            self._thread = None
        io_done = self._thread is None
        # 0 = chunk IO still running, 1 = ready to commit, 2 = IO failed.
        local = 2 if (io_done and self._error is not None) else int(io_done)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            states = multihost_utils.process_allgather(
                np.asarray([local], np.int32)
            )
            if int(states.max()) == 2:
                self._pending_commit = None
                if self._error is not None:
                    err, self._error = self._error, None
                    raise RuntimeError(
                        f"async checkpoint save failed: {err!r}"
                    ) from err
                raise RuntimeError(
                    "async checkpoint save failed on another process; "
                    "commit dropped on all ranks"
                )
            ready = bool(states.min() == 1)
        else:
            ready = local >= 1  # single-process: wait() raises on failure
        if not ready:
            return False
        self.wait()
        return True

    def wait(self) -> None:
        """Block until any in-flight save (IO + deferred commit) completes."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            self._pending_commit = None  # chunks incomplete: never commit
            raise RuntimeError(f"async checkpoint save failed: {err!r}") from err
        if self._pending_commit is not None:
            commit, self._pending_commit = self._pending_commit, None
            commit()

    # ---------------------------------------------------------------- restore
    def steps(self) -> List[int]:
        out = []
        for name in self.storage.listdir(""):
            m = _STEP_RE.match(name)
            if m and self.storage.exists(f"{name}/{_COMMITTED}"):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def metadata(self, step: int) -> Dict[str, Any]:
        return json.loads(
            self.storage.read_bytes(f"step_{step:08d}/manifest.json")
        )

    def restore(
        self,
        step: int,
        abstract_state: Any,
        shardings: Any,
    ) -> Any:
        """Rebuild ``abstract_state``'s tree with arrays sharded per
        ``shardings`` — which may describe a completely different mesh than
        the one that saved. Leaf matching is by tree-path key."""
        step_dir = f"step_{step:08d}"
        manifest = self.metadata(step)
        by_key = {leaf["key"]: leaf for leaf in manifest["leaves"]}

        flat_abs = jax.tree_util.tree_flatten_with_path(abstract_state)
        flat_shd = jax.tree_util.tree_flatten(shardings)[0]
        leaves_abs, treedef = flat_abs
        if len(flat_shd) != len(leaves_abs):
            raise ValueError(
                f"shardings tree has {len(flat_shd)} leaves, state has {len(leaves_abs)}"
            )
        out_leaves = []
        for (path, abs_leaf), sharding_ in zip(leaves_abs, flat_shd):
            key = _keystr(path)
            if key not in by_key:
                raise KeyError(f"checkpoint step {step} missing leaf {key}")
            rec = by_key[key]
            saved_shape = tuple(rec["shape"])
            want_shape = tuple(abs_leaf.shape)
            if saved_shape != want_shape:
                raise ValueError(
                    f"{key}: saved shape {saved_shape} != target {want_shape}"
                )
            dtype = np.dtype(rec["dtype"])
            reader = _LeafReader(
                self.storage, f"{step_dir}/leaf_{rec['index']:05d}",
                saved_shape, dtype,
                cache=self.cache,
                cache_token=manifest.get("cache_token", ""),
                cache_rel=f"leaf_{rec['index']:05d}",
            )
            arr = jax.make_array_from_callback(
                want_shape, sharding_, lambda idx, r=reader: r.read(idx)
            )
            if arr.dtype != abs_leaf.dtype:
                arr = arr.astype(abs_leaf.dtype)
            out_leaves.append(arr)
        return jax.tree_util.tree_unflatten(treedef, out_leaves)

    # ------------------------------------------------------------ quarantine
    def quarantine(self, step: int) -> None:
        """Demote a committed step whose bytes failed to restore: write the
        CORRUPT marker first (evidence), then remove COMMITTED — after
        which :meth:`steps` no longer offers the step and the next
        :func:`restore_with_fallback` candidate is the previous one. Marker
        order matters: a crash between the two writes must leave the step
        either still-committed or visibly corrupt, never silently absent.

        Multi-process callers gate this to one process and barrier after
        (see elastic/worker.py) — the markers live in shared storage."""
        step_dir = f"step_{step:08d}"
        try:
            self.storage.write_bytes(f"{step_dir}/{_CORRUPT}",
                                     str(step).encode())
        except OSError as e:  # marker is evidence, not a gate
            log.warning("could not write corrupt marker for step %d: %s",
                        step, e)
        self.storage.delete_tree(f"{step_dir}/{_COMMITTED}")
        log.warning("quarantined checkpoint step %d (%s/%s)", step,
                    self.directory, step_dir)

    # -------------------------------------------------------------------- gc
    def _gc(self) -> None:
        if jax.process_index() != 0:
            return
        steps = self.steps()
        for old in steps[: -self.keep] if self.keep > 0 else []:
            step_dir = f"step_{old:08d}"
            # Marker first: a half-deleted step must read as uncommitted,
            # not as a committed step with missing chunks.
            self.storage.delete_tree(f"{step_dir}/{_COMMITTED}")
            self.storage.delete_tree(step_dir)


def restore_with_fallback(
    manager: CheckpointManager,
    restore_fn,
    agree_int=None,
    all_ok=None,
    quarantine=None,
    max_attempts: int = 8,
):
    """Restore the newest committed step, falling back past corrupt ones.

    The linchpin of the corrupted-checkpoint chaos scenario: a COMMITTED
    step whose bytes are damaged (truncated chunk, unreadable manifest)
    must cost one quarantine + one older restore, not a crash-loop. Loop:

    1. agree on the newest committed step (``agree_int`` broadcasts rank 0's
       candidate in multi-process runs — two ranks restoring different
       steps would split the world);
    2. every rank attempts ``restore_fn(step)``;
    3. ``all_ok`` agrees the verdict across ranks (corruption often bites
       only the ranks whose slices overlap the bad chunk — the survivors
       must discard their restored state and fall back WITH the victims,
       or they'd hang in the next collective);
    4. on any failure, ``quarantine(step)`` demotes the step (default:
       ``manager.quarantine`` — multi-process callers pass a rank-gated,
       barriered wrapper) and the loop retries one step older.

    Returns ``(state, step)``; ``(None, -1)`` means no restorable
    checkpoint (callers fresh-init, their pre-existing path). The defaults
    are the single-process wiring; elastic/worker.py supplies the
    collective versions."""
    agree_int = agree_int or (lambda v: v)
    all_ok = all_ok or (lambda ok: ok)
    quarantine = quarantine or manager.quarantine
    for _ in range(max_attempts):
        local = manager.latest_step()
        step = int(agree_int(-1 if local is None else local))
        if step < 0:
            return None, -1
        state = None
        try:
            state = restore_fn(step)
            ok = True
        except Exception as e:
            log.warning("restore of step %d failed: %r", step, e)
            ok = False
        if all_ok(ok):
            return state, step
        del state  # a survivor's state from a bad step must not leak
        quarantine(step)
    raise RuntimeError(
        f"no restorable checkpoint under {manager.directory} after "
        f"{max_attempts} quarantine fallbacks"
    )
