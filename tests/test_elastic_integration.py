"""Simulated-distributed elastic tests (SURVEY.md §4 item 2): real master
(gRPC), real agents (threads), real worker subprocesses running
jax.distributed over CPU with forced device counts.

Covers the full elastic paths the reference promises but never specifies:
scale-up mid-run (README.md:31-35), worker preemption recovery
(README.md:25-29), and checkpoint-carried membership changes.
"""

import json
import os
import time

import pytest

from easydl_tpu.elastic.agent import Agent
from easydl_tpu.elastic.master import Master

from envprobe import requires_multiproc_cpu

#: every test here except the 1-agent pipeline one forms a >1-process
#: jax world; on jaxlibs whose CPU backend lacks cross-process collectives
#: those worlds can never form (workers crash-loop in the restore-agree
#: broadcast) and each test would burn its full timeout — skip with the
#: capability named instead (tests/envprobe.py).
multiproc = requires_multiproc_cpu()

JOB_CFG = {
    "model": "mlp",
    "model_kwargs": {"input_shape": [8, 8, 1], "features": [32, 32]},
    "global_batch": 32,
    "total_steps": 24,
    "ckpt_interval": 4,
    "lr": 0.01,
    "seed": 0,
}


def wait_for(cond, timeout=120.0, interval=0.2, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}")


def read_metrics(workdir, agent_id):
    path = os.path.join(workdir, f"metrics-{agent_id}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture
def workdir(tmp_path):
    return str(tmp_path)


@multiproc
def test_elastic_end_to_end_two_workers(workdir):
    master = Master(
        job_name="mnist-mlp",
        workdir=workdir,
        desired_workers=2,
        min_workers=2,
        worker_config=JOB_CFG,
    ).start()
    agents = [
        Agent(f"a{i}", master.address, workdir, slots=2).start() for i in range(2)
    ]
    try:
        assert master.wait_done(timeout=180), f"job did not finish: {master.status()}"
        assert os.path.exists(os.path.join(workdir, "DONE"))
        # both agents trained at generation 1, world 2 (4 devices)
        m0 = read_metrics(workdir, "a0")
        assert m0 and m0[-1]["step"] == JOB_CFG["total_steps"]
        assert m0[-1]["world_size"] == 4
        # checkpoints were taken and retained
        ckpts = os.listdir(os.path.join(workdir, "ckpt"))
        assert any(n.startswith("step_") for n in ckpts)
    finally:
        for a in agents:
            a.stop()
        master.stop()


@multiproc
def test_scale_up_mid_run(workdir):
    cfg = dict(JOB_CFG, total_steps=600, ckpt_interval=50, sync_every=5)
    # prepare disabled: this test pins the direct quiesce->reshape semantics
    # (zero lost work at the boundary); the preflight path has its own e2e
    # test below.
    master = Master(
        job_name="scale-up",
        workdir=workdir,
        desired_workers=1,
        min_workers=1,
        worker_config=cfg,
        prepare_timeout_s=0.0,
    ).start()
    agents = [
        Agent(f"a{i}", master.address, workdir, slots=2).start() for i in range(2)
    ]
    try:
        # One member running (whichever registered first), one standby.
        def member_progressing():
            st = master.status()
            return st["members"] and any(
                st["agents"][m]["step"] >= 5 for m in st["members"]
            )

        wait_for(member_progressing, desc="member worker to reach step 5")
        assert master.status()["generation"] == 1

        # Brain-style plan: scale workers 1 -> 2 (the JobResource-update path)
        from easydl_tpu.api import ResourcePlan, RolePlan

        plan = ResourcePlan(job_name="scale-up", version=1,
                            roles={"worker": RolePlan(replicas=2)})
        master.apply_plan(plan)

        assert master.wait_done(timeout=240), f"stuck: {master.status()}"
        st = master.status()
        assert st["generation"] >= 2, st
        # After the reshape, steps ran at world 2 (4 devices across 2 procs).
        m = read_metrics(workdir, "a0") + read_metrics(workdir, "a1")
        gen2 = [r for r in m if r["generation"] >= 2]
        assert gen2 and all(r["world_size"] == 4 for r in gen2)
        assert max(r["step"] for r in gen2) == cfg["total_steps"]
        # Quiesce was graceful: training resumed exactly one step after the
        # quiesce boundary (zero lost work).
        gen1_last = max(r["step"] for r in m if r["generation"] == 1)
        gen2_first = min(r["step"] for r in gen2)
        assert gen2_first == gen1_last + 1, (gen1_last, gen2_first)
    finally:
        for a in agents:
            a.stop()
        master.stop()


@multiproc
def test_preemption_kill_recovery(workdir):
    cfg = dict(JOB_CFG, total_steps=30, ckpt_interval=3)
    master = Master(
        job_name="preempt",
        workdir=workdir,
        desired_workers=2,
        min_workers=1,
        heartbeat_timeout=2.0,
        worker_config=cfg,
    ).start()
    a0 = Agent("a0", master.address, workdir, slots=2).start()
    a1 = Agent("a1", master.address, workdir, slots=2).start()
    try:
        wait_for(
            lambda: min(
                master.status()["agents"].get("a0", {}).get("step", 0),
                master.status()["agents"].get("a1", {}).get("step", 0),
            ) >= 6,
            desc="both workers past step 6",
        )
        # Hard preemption: kill a1's worker AND its agent (no notice).
        t_kill = time.monotonic()
        a1.kill_worker_hard()
        a1.stop()
        # Master must detect, reshape to world 1, and finish the job.
        assert master.wait_done(timeout=240), f"stuck: {master.status()}"
        st = master.status()
        assert st["generation"] >= 2
        assert st["agents"]["a1"]["state"] in ("lost", "idle")
        m0 = read_metrics(workdir, "a0")
        assert m0[-1]["step"] == 30
        # Recovery happened: the job finished in a generation without a1
        # (intermediate generations may briefly include a1 — its agent can
        # report the crash before going silent; that's two-phase recovery).
        final_gen = st["generation"]
        final = [r for r in m0 if r["generation"] == final_gen]
        assert final and all(r["world_size"] == 2 for r in final)
        # Lost work bounded by ckpt_interval: recovery resumed within interval
        merged = m0 + read_metrics(workdir, "a1")
        pre_last = max(r["step"] for r in merged if r["generation"] < final_gen)
        resumed_first = min(r["step"] for r in final)
        assert resumed_first >= pre_last - cfg["ckpt_interval"]
        recovery_s = time.monotonic() - t_kill
        print(f"preemption recovery (kill -> job done path resumed): {recovery_s:.1f}s")
    finally:
        a0.stop()
        a1.stop()
        master.stop()


@multiproc
def test_elastic_worker_with_ps_embedding(workdir):
    """Config 5 under the FULL elastic runtime, multi-process: two elastic
    workers (world 2) discover the operator-launched PS pods through the
    registry and train the dense model on the mesh (worker.py PS mode),
    each rank pushing only its own gradient rows. Paired dense+sparse
    checkpoints land (ps-ckpt/ matches the dense steps); the PS tier's
    rows live outside the worker lifecycle."""
    import subprocess
    import sys as _sys

    from easydl_tpu.ps.client import ShardedPsClient
    from easydl_tpu.ps.server import PsShard

    ps_pods = []
    master = None
    agents = []
    try:
        for i in range(2):
            ps_pods.append(subprocess.Popen(
                [_sys.executable, "-m", "easydl_tpu.ps",
                 "--name", f"eps-{i}", "--workdir", workdir,
                 "--num-shards", "2", "--shard-index", str(i)],
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            ))
        cfg = {
            "model": "widedeep",
            "model_kwargs": {"embedding": "ps", "vocab": 2000, "dim": 8,
                             "hidden": [32], "num_sparse": 5, "num_dense": 4},
            "global_batch": 32, "total_steps": 10, "ckpt_interval": 5,
            "lr": 3e-3, "seed": 0,
        }
        master = Master(job_name="cfg5-elastic", workdir=workdir,
                        desired_workers=2, min_workers=2,
                        worker_config=cfg).start()
        agents = [Agent(f"a{i}", master.address, workdir, slots=2).start()
                  for i in range(2)]
        assert master.wait_done(timeout=300), master.status()
        m0 = read_metrics(workdir, "a0")
        assert m0 and m0[-1]["step"] == cfg["total_steps"]
        assert m0[-1]["world_size"] == 4  # 2 procs x 2 devices
        # the embedding rows landed on the REAL PS shards
        client = ShardedPsClient.from_registry(workdir, 2, wait_s=10)
        try:
            assert client.total_rows("emb") > 0
        finally:
            client.close()
        # sparse snapshots paired with the dense checkpoint steps
        ps_steps = PsShard.saved_steps(os.path.join(workdir, "ps-ckpt"))
        assert cfg["total_steps"] in ps_steps, ps_steps
    finally:
        for a in agents:
            a.stop()
        if master is not None:
            master.stop()
        for p in ps_pods:
            p.terminate()
        for p in ps_pods:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def test_elastic_worker_with_pipeline_mesh(workdir):
    """A pp axis in the job's mesh config turns on the GPipe schedule
    inside the elastic worker (the pipeline_fn is rebuilt per generation,
    like the mesh): one agent, 4 devices, pp=2 x dp=2, trains to DONE."""
    cfg = {
        "model": "gpt",
        "model_kwargs": {"size": "test", "seq_len": 32, "vocab": 256},
        "mesh": {"pp": 2},
        "pp_microbatches": 2,
        "global_batch": 8,
        "total_steps": 6,
        "ckpt_interval": 3,
        "lr": 1e-3,
        "seed": 0,
    }
    master = Master(job_name="pp-job", workdir=workdir, desired_workers=1,
                    min_workers=1, worker_config=cfg).start()
    agent = Agent("a0", master.address, workdir, slots=4).start()
    try:
        assert master.wait_done(timeout=240), f"no finish: {master.status()}"
        m0 = read_metrics(workdir, "a0")
        assert m0 and m0[-1]["step"] == 6
        assert all(r["loss"] == r["loss"] for r in m0)  # finite
    finally:
        agent.stop()
        master.stop()


@multiproc
def test_preflight_scale_up_adopts_precompiled_generation(workdir):
    """The r5 recovery centerpiece, end to end with real processes: a
    planned scale-up announces the next generation while generation 1
    keeps training; both agents spawn preflight workers that dist-join the
    NEXT coordinator and compile; the drain waits for their readiness; and
    the switch promotes them (timeline spawn mode == "preflight") instead
    of cold-starting anything."""
    cfg = dict(JOB_CFG, total_steps=100_000, ckpt_interval=25, sync_every=5)
    master = Master(
        job_name="preflight-up",
        workdir=workdir,
        desired_workers=1,
        min_workers=1,
        worker_config=cfg,
        prepare_timeout_s=180.0,
        prepare_min_uptime_s=0.0,
    ).start()
    agents = [
        Agent(f"a{i}", master.address, workdir, slots=2).start()
        for i in range(2)
    ]
    try:
        wait_for(
            lambda: master.status()["members"]
            and any(master.status()["agents"][m]["step"] >= 3
                    for m in master.status()["members"]),
            desc="member worker to reach step 3",
        )
        from easydl_tpu.api import ResourcePlan, RolePlan

        plan = ResourcePlan(job_name="preflight-up", version=1,
                            roles={"worker": RolePlan(replicas=2)})
        master.apply_plan(plan)

        wait_for(lambda: master.status()["generation"] >= 2, timeout=240,
                 desc="preflighted generation to form")
        final_gen = master.status()["generation"]
        wait_for(
            lambda: all(
                a["state"] == "running" and a["gen"] == final_gen
                for a in master.status()["agents"].values()
            ),
            timeout=120, desc="both members running the new generation",
        )
        # Both agents promoted their PREFLIGHT workers — the dist-joined,
        # pre-compiled next generation — not warm/cold spawns.
        from easydl_tpu.elastic import timeline

        for aid in ("a0", "a1"):
            spawns = [
                r for r in timeline.read(
                    os.path.join(workdir, f"timeline-{aid}.jsonl"))
                if r.get("phase") == "spawn" and r.get("gen") == final_gen
            ]
            assert spawns, f"no spawn event for {aid} at gen {final_gen}"
            assert spawns[-1]["mode"] == "preflight", spawns
        # Work continuity: the new generation resumed from the quiesce
        # boundary (graceful drain, zero lost work). Wait for its first
        # recorded step — promote happens before restore+step complete.
        wait_for(
            lambda: any(
                r["generation"] == final_gen
                for r in read_metrics(workdir, "a0")
                + read_metrics(workdir, "a1")
            ),
            timeout=120, desc="first step of the preflighted generation",
        )
        m = read_metrics(workdir, "a0") + read_metrics(workdir, "a1")
        gen_new = [r for r in m if r["generation"] == final_gen]
        gen_old = [r for r in m if r["generation"] < final_gen]
        assert gen_new and all(r["world_size"] == 4 for r in gen_new)
        assert min(r["step"] for r in gen_new) == (
            max(r["step"] for r in gen_old) + 1
        )
    finally:
        for a in agents:
            a.stop()
        master.stop()


@multiproc
def test_preflight_crash_falls_back_to_plain_drain(workdir):
    """Every preflight failure path must degrade to the ordinary switch:
    here every preflight worker crashes on arrival (a compile-OOM stand-
    in), agents remember the failed signature instead of crash-looping,
    the prepare window expires, and the reshape completes through the
    plain drain with cold/warm spawns."""
    import sys as _sys

    # Wrapper worker: dies immediately in preflight mode, real otherwise.
    crasher = os.path.join(workdir, "crashy_worker.py")
    with open(crasher, "w") as f:
        f.write(
            "import os, sys\n"
            "if os.environ.get('EASYDL_GO_FILE'):\n"
            "    sys.exit(9)\n"
            "from easydl_tpu.elastic.worker import main\n"
            "main()\n"
        )
    cfg = dict(JOB_CFG, total_steps=100_000, ckpt_interval=25, sync_every=5)
    master = Master(
        job_name="preflight-crash",
        workdir=workdir,
        desired_workers=1,
        min_workers=1,
        worker_config=cfg,
        prepare_timeout_s=6.0,
        prepare_min_uptime_s=0.0,
    ).start()
    agents = [
        Agent(f"a{i}", master.address, workdir,
              worker_argv=[_sys.executable, crasher], slots=2).start()
        for i in range(2)
    ]
    try:
        wait_for(
            lambda: master.status()["members"]
            and any(master.status()["agents"][m]["step"] >= 3
                    for m in master.status()["members"]),
            desc="member worker to reach step 3",
        )
        from easydl_tpu.api import ResourcePlan, RolePlan

        master.apply_plan(ResourcePlan(
            job_name="preflight-crash", version=1,
            roles={"worker": RolePlan(replicas=2)},
        ))
        wait_for(lambda: master.status()["generation"] >= 2, timeout=180,
                 desc="reshape to complete despite crashed preflights")
        wait_for(
            lambda: any(
                r["generation"] >= 2
                for r in read_metrics(workdir, "a0")
                + read_metrics(workdir, "a1")
            ),
            timeout=120, desc="new generation training",
        )
        # The switch happened WITHOUT preflight promotion...
        from easydl_tpu.elastic import timeline

        for aid in ("a0", "a1"):
            modes = [
                r.get("mode")
                for r in timeline.read(
                    os.path.join(workdir, f"timeline-{aid}.jsonl"))
                if r.get("phase") == "spawn"
            ]
            assert "preflight" not in modes, modes
        # ...and nobody crash-looped: the failed signature is remembered
        # and the preflight for it was spawned once, not once per
        # heartbeat. (Asserted on the agents' own counters — the crashing
        # preflight never writes any on-disk marker to count.)
        for a in agents:
            assert a._preflight_failed_sig is not None
            assert a._preflight_count <= 2, a._preflight_count
        m = read_metrics(workdir, "a0") + read_metrics(workdir, "a1")
        gen_new = [r for r in m if r["generation"] >= 2]
        assert gen_new and all(r["world_size"] == 4 for r in gen_new)
    finally:
        for a in agents:
            a.stop()
        master.stop()


@multiproc
def test_standing_preflight_adopts_on_unplanned_kill(workdir):
    """Opt-in standing preflight, end to end: in steady state the master
    keeps the next generation pre-formed (same members, fresh
    coordinator); agents hold dist-joined, pre-compiled preflight workers
    at the gate. A SIGKILL preemption must then promote THEM — timeline
    spawn mode 'preflight' on the post-kill generation."""
    cfg = dict(JOB_CFG, total_steps=100_000, ckpt_interval=10, sync_every=5)
    master = Master(
        job_name="standing",
        workdir=workdir,
        desired_workers=2,
        min_workers=2,
        heartbeat_timeout=2.0,
        worker_config=cfg,
        prepare_timeout_s=300.0,
        prepare_min_uptime_s=0.0,
        standing_preflight=True,
    ).start()
    agents = [
        Agent(f"a{i}", master.address, workdir, slots=2).start()
        for i in range(2)
    ]
    try:
        # Steady state with the standing preflight armed AND ready: both
        # agents must report the prepared coordinator before the kill.
        def standing_ready():
            st = master.status()
            prep = st.get("prepare")
            if not prep or st["phase"] != "stable":
                return False
            views = master.rendezvous.agents
            return all(
                views[m].prepared == prep["coordinator"]
                for m in prep["members"]
            )

        wait_for(standing_ready, timeout=240,
                 desc="standing preflight compiled and gated")
        gen1 = master.status()["generation"]

        agents[1].kill_worker_hard()
        wait_for(lambda: master.status()["generation"] > gen1, timeout=120,
                 desc="post-kill generation")
        gen2 = master.status()["generation"]
        wait_for(
            lambda: any(
                r["generation"] >= gen2
                for r in read_metrics(workdir, "a0")
                + read_metrics(workdir, "a1")
            ),
            timeout=120, desc="adopted generation training",
        )
        from easydl_tpu.elastic import timeline

        for aid in ("a0", "a1"):
            spawns = [
                r["mode"]
                for r in timeline.read(
                    os.path.join(workdir, f"timeline-{aid}.jsonl"))
                if r.get("phase") == "spawn" and r.get("gen") == gen2
            ]
            assert spawns and spawns[-1] == "preflight", (aid, spawns)
    finally:
        for a in agents:
            a.stop()
        master.stop()
