"""Online PS resharding: routing-table registry semantics, the cutover
gates, the coordinator protocol end-to-end (real gRPC shards, concurrent
pushes, bit-identical digests), and the hot-shard split policy.

The e2e tests are the tier-1 face of the `ps_reshard_under_fire` chaos
drill: same protocol, in-process servers instead of pods, deterministic
phase-hook pushes instead of wall-clock racing."""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from easydl_tpu.chaos.harness import _table_digests
from easydl_tpu.controller.reconciler import ps_split_decision
from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.ps import registry, reshard
from easydl_tpu.ps.client import LocalPsClient, ShardedPsClient
from easydl_tpu.ps.server import STALE_ROUTE, PsShard
from easydl_tpu.ps.table import TableSpec, shard_of


def spec(**kw):
    kw.setdefault("name", "emb")
    kw.setdefault("dim", 8)
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("lr", 0.05)
    kw.setdefault("seed", 3)
    return TableSpec(**kw)


# ------------------------------------------------------ registry routing
class TestRoutingTable:
    def test_begin_commit_lifecycle(self, tmp_path):
        w = str(tmp_path)
        assert registry.committed_generation(w) == 0
        plan = registry.begin_reshard(w, 2, 4, "me")
        assert plan["generation"] == 1
        assert plan["from_shards"] == 2 and plan["to_shards"] == 4
        # the slot is exclusive while the plan is fresh
        assert registry.begin_reshard(w, 2, 8, "other") is None
        # publication generations: only a DECLARED destination publishes
        # under the plan; count coincidence alone never does.
        assert registry.generation_for_publication(w, 2) == 0
        assert registry.generation_for_publication(w, 4) == 0
        assert registry.generation_for_publication(w, 4, dest=True) == 1
        # a declared destination whose count matches neither the plan nor
        # the committed routing is a config error, not a silent publish
        with pytest.raises(ValueError, match="matches neither"):
            registry.generation_for_publication(w, 8, dest=True)
        doc = registry.commit_reshard(w, "me")
        assert doc == {"generation": 1, "num_shards": 4}
        rt = registry.routing_table(w)
        assert rt["generation"] == 1 and rt["num_shards"] == 4
        assert "plan" not in rt
        # post-commit, 4 IS the committed count (a restarting destination
        # resolves to the committed generation)
        assert registry.generation_for_publication(w, 4) == 1
        assert registry.generation_for_publication(w, 4, dest=True) == 1

    def test_commit_is_owner_checked(self, tmp_path):
        w = str(tmp_path)
        registry.begin_reshard(w, 2, 4, "me")
        with pytest.raises(RuntimeError, match="no reshard plan owned"):
            registry.commit_reshard(w, "impostor")
        assert registry.committed_generation(w) == 0

    def test_abort_keeps_committed_routing(self, tmp_path):
        w = str(tmp_path)
        registry.begin_reshard(w, 2, 4, "me")
        assert registry.abort_reshard(w, "impostor") is False
        assert registry.abort_reshard(w, "me") is True
        assert registry.committed_generation(w) == 0
        assert "plan" not in registry.routing_table(w)
        # the slot is free again
        assert registry.begin_reshard(w, 2, 4, "me2") is not None

    def test_stale_plan_is_stolen(self, tmp_path):
        w = str(tmp_path)
        registry.begin_reshard(w, 2, 4, "dead-coordinator")
        # age the plan past the staleness window
        path = os.path.join(w, registry.REG_DIR, registry.ROUTING_FILE)
        registry.locked_mutate(
            path, lambda doc: dict(
                doc, plan=dict(doc["plan"], t=time.time() - 1e4)))
        plan = registry.begin_reshard(w, 2, 8, "thief", stale_s=600.0)
        assert plan is not None and plan["owner"] == "thief"
        assert plan["to_shards"] == 8
        # the dead coordinator can no longer commit its torn migration
        with pytest.raises(RuntimeError):
            registry.commit_reshard(w, "dead-coordinator")

    def test_noop_and_invalid_reshards_rejected(self, tmp_path):
        w = str(tmp_path)
        with pytest.raises(ValueError):
            registry.begin_reshard(w, 2, 2, "me")
        with pytest.raises(ValueError):
            registry.begin_reshard(w, 2, 0, "me")

    def test_shard_map_filters_by_generation(self, tmp_path):
        w = str(tmp_path)
        registry.publish(w, "src-0", 0, 2, "h1:1", epoch=1, generation=0)
        registry.publish(w, "dst-0", 0, 4, "h2:1", epoch=2, generation=1)
        # committed generation is 0: the destination stays invisible even
        # though its epoch is higher
        assert registry.shard_map(w)[0]["pod"] == "src-0"
        assert registry.shard_map(w, generation=1)[0]["pod"] == "dst-0"
        registry.begin_reshard(w, 2, 4, "me")
        registry.commit_reshard(w, "me")
        assert registry.shard_map(w)[0]["pod"] == "dst-0"

    def test_shard_map_filters_dead_local_pids_at_read_time(self, tmp_path):
        """The reroute-never-targets-a-ghost satellite: a dead-pid
        localhost publication is invisible to readers even when no
        startup sweep ran."""
        w = str(tmp_path)
        registry.publish(w, "ghost", 0, 1, "localhost:1", epoch=5)
        # forge a provably-dead pid into the entry
        path = os.path.join(w, registry.REG_DIR, "ps-ghost.json")
        doc = json.load(open(path))
        doc["pid"] = 2 ** 22 + 9  # beyond this container's pid space
        json.dump(doc, open(path, "w"))
        assert 0 not in registry.shard_map(w)
        # non-localhost entries are never pid-filtered (other host)
        registry.publish(w, "remote", 0, 1, "otherhost:1", epoch=1)
        path = os.path.join(w, registry.REG_DIR, "ps-remote.json")
        doc = json.load(open(path))
        doc["pid"] = 2 ** 22 + 9
        json.dump(doc, open(path, "w"))
        assert registry.shard_map(w)[0]["pod"] == "remote"

    def test_discover_prefers_routing_table_shape(self, tmp_path):
        w = str(tmp_path)
        registry.publish(w, "a", 0, 2, "h:1", epoch=1)
        registry.publish(w, "b", 1, 2, "h:2", epoch=1)
        n, addrs = registry.discover(w, timeout=5.0)
        assert n == 2 and addrs == ("h:1", "h:2")
        # a committed routing table overrides the publications' count
        registry.begin_reshard(w, 2, 4, "me")
        registry.commit_reshard(w, "me")
        for d in range(4):
            registry.publish(w, f"d{d}", d, 4, f"h:{10 + d}", epoch=2,
                             generation=1)
        n, addrs = registry.discover(w, timeout=5.0)
        assert n == 4 and addrs == tuple(f"h:{10 + d}" for d in range(4))


# ------------------------------------------------------------ server gates
class TestCutoverGates:
    def _push_req(self, ids, dim=8, scale=0.5, table="emb"):
        ids = np.asarray(ids, np.int64)
        return pb.PushRequest(
            table=table, raw_ids=ids.astype("<i8").tobytes(),
            grads=np.ones((len(ids), dim), np.float32).tobytes(),
            scale=scale)

    def test_cutover_gates_push_and_pull_retriably(self):
        shard = PsShard()
        shard.create_table(spec())
        shard.cutover()
        ack = shard.Push(self._push_req([1, 2]), None)
        assert not ack.ok and ack.message.startswith(STALE_ROUTE)
        with pytest.raises(RuntimeError, match=STALE_ROUTE):
            shard.Pull(pb.PullRequest(table="emb", ids=[1]), None)
        # nothing was applied behind the gate
        assert shard.table("emb").rows == 0
        # cutover is idempotent; resume (abort rollback) lifts the gate
        shard.cutover()
        shard.reshard_resume()
        assert shard.Push(self._push_req([1, 2]), None).ok
        assert shard.table("emb").rows == 2

    def test_push_ownership_gate_bounces_foreign_ids(self):
        """A push whose ids do not hash to the serving shard means the
        client's partition and the server disagree about the routing (the
        mid-reshard wrong-generation-reroute race): applying it would
        create foreign rows outside the migration lineage — silent loss.
        It must bounce retriably instead."""
        shard = PsShard(shard_index=1, num_shards=2)
        shard.create_table(spec())
        ids = np.arange(64, dtype=np.int64)
        mine = ids[shard_of(ids, 2) == 1]
        foreign = ids[shard_of(ids, 2) == 0]
        ack = shard.Push(self._push_req(foreign), None)
        assert not ack.ok and ack.message.startswith(STALE_ROUTE)
        assert shard.table("emb").rows == 0
        # a mixed batch is equally mis-partitioned — all-or-nothing
        ack = shard.Push(self._push_req(ids), None)
        assert not ack.ok and ack.message.startswith(STALE_ROUTE)
        assert shard.table("emb").rows == 0
        assert shard.Push(self._push_req(mine), None).ok
        assert shard.table("emb").rows == len(mine)

    def test_per_shard_reroute_never_adopts_other_generation(self, tmp_path):
        """The race behind a real drill failure: a reshard commit landing
        between the reroute's generation check and its shard_map read used
        to hand back the NEW generation's pod for an old-partition slot —
        the client adopted its address+epoch without rebuilding, and the
        old-count chunk was applied wholesale on a shard that does not own
        its ids. Per-shard reroutes must resolve strictly within the
        client's own routing generation."""
        from easydl_tpu.ps.client import ShardedPsClient

        w = str(tmp_path)
        new1 = PsShard(shard_index=1, num_shards=4, epoch=2)
        server = new1.serve()
        try:
            registry.publish(w, "old-0", 0, 2, "localhost:1111", epoch=1,
                             generation=0)
            registry.publish(w, "old-1", 1, 2, "localhost:1112", epoch=1,
                             generation=0)
            client = ShardedPsClient(["localhost:1111", "localhost:1112"],
                                     registry_workdir=w)
            client._epochs = [1, 1]
            # a committed reshard: generation 1, 4 shards, a LIVE new pod
            # for index 1 (live so the buggy path's adoption would succeed)
            registry.begin_reshard(w, 2, 4, "c")
            registry.publish(w, "new-1", 1, 4, server.address, epoch=2,
                             generation=1)
            registry.commit_reshard(w, "c")
            # the per-shard path must NOT adopt the generation-1
            # publication into the generation-0 slot, whatever the
            # full-rebuild path reported
            client._maybe_reroute_from_registry(1, force=False)
            assert client.addresses[1] == "localhost:1112"
            assert client._epochs[1] == 1
            client.close()
        finally:
            new1.stop()

    def test_reshard_export_freezes_wal_retirement(self, tmp_path):
        w = str(tmp_path)
        shard = PsShard(shard_index=0, num_shards=1, epoch=1,
                        wal_root=os.path.join(w, "ps-wal", "shard-0"),
                        workdir=w, rescue_dir=os.path.join(w, "ps-ckpt"))
        shard.create_table(spec())
        assert shard.Push(self._push_req([1, 2, 3]), None).ok
        shard.reshard_export(os.path.join(w, "ps-reshard", "gen-1"), 1)
        assert shard.Push(self._push_req([4, 5]), None).ok  # NOT gated
        # a rescue-lineage save mid-migration must NOT retire the tail
        shard.save(os.path.join(w, "ps-ckpt"), step=10)
        segs = [
            name
            for _e, d in __import__(
                "easydl_tpu.ps.wal", fromlist=["epoch_dirs"]
            ).epoch_dirs(os.path.join(w, "ps-wal", "shard-0"))
            for name in os.listdir(d) if name.startswith("seg-")
        ]
        assert segs, "export froze retirement, segments must survive"
        shard.stop()

    def test_replay_dedupes_repartitioned_subset_retry(self, tmp_path):
        """The applied-but-unacked race across a reshard: a push the dying
        source WAL'd lands on the destination twice — once via the tail
        replay, once as the client's re-partitioned retry (the SUBSET of
        the record this destination owns). The second arrival must ack
        without applying."""
        w = str(tmp_path)
        src = PsShard(shard_index=0, num_shards=1, epoch=1,
                      wal_root=os.path.join(w, "ps-wal", "shard-0"),
                      workdir=w, rescue_dir=os.path.join(w, "ps-ckpt"))
        src.create_table(spec())
        export = os.path.join(w, "ps-reshard", "gen-1")
        src.reshard_export(export, 1)
        ids = np.arange(64, dtype=np.int64)  # tail record, ids span shards
        assert src.Push(self._push_req(ids), None).ok
        src.cutover()

        dst = PsShard(shard_index=1, num_shards=2, epoch=2,
                      wal_root=os.path.join(w, "ps-wal", "shard-1"),
                      workdir=w, rescue_dir=os.path.join(w, "ps-ckpt"))
        dst.restore(export, step=1)
        stats = dst.reshard_replay(export, 1)
        assert stats["pushes"] == 1 and stats["foreign_ids"] > 0
        mine = ids[shard_of(ids, 2) == 1]
        assert stats["ids"] == len(mine)
        before = dst.table("emb").pull(mine).copy()
        # the client's retry: the SAME update re-partitioned onto this
        # destination — exactly the subset it already replayed
        ack = dst.Push(self._push_req(mine), None)
        assert ack.ok and "dedup" in ack.message
        after = dst.table("emb").pull(mine)
        np.testing.assert_array_equal(before, after)
        # a genuinely new push with the same ids is NOT swallowed
        ack = dst.Push(self._push_req(mine), None)
        assert ack.ok and "dedup" not in ack.message
        src.stop()
        dst.stop()

    def test_reshard_replay_is_idempotent_under_rpc_retry(self, tmp_path):
        """The coordinator re-issues ReshardReplay when the RPC deadline
        beats a long tail; the second call must return the first call's
        stats WITHOUT re-applying the tail — and a fresh restore (a
        stolen plan's retry) must re-arm the real replay."""
        w = str(tmp_path)
        src = PsShard(shard_index=0, num_shards=1, epoch=1,
                      wal_root=os.path.join(w, "ps-wal", "shard-0"),
                      workdir=w, rescue_dir=os.path.join(w, "ps-ckpt"))
        src.create_table(spec())
        export = os.path.join(w, "ps-reshard", "gen-1")
        src.reshard_export(export, 1)
        ids = np.arange(64, dtype=np.int64)
        assert src.Push(self._push_req(ids), None).ok
        src.cutover()

        dst = PsShard(shard_index=1, num_shards=2, epoch=2,
                      wal_root=os.path.join(w, "ps-wal", "shard-1"),
                      workdir=w, rescue_dir=os.path.join(w, "ps-ckpt"))
        dst.restore(export, step=1)
        first = dst.reshard_replay(export, 1)
        mine = ids[shard_of(ids, 2) == 1]
        once = dst.table("emb").pull(mine).copy()
        again = dst.reshard_replay(export, 1)  # the coordinator's retry
        assert again == first
        np.testing.assert_array_equal(dst.table("emb").pull(mine), once)
        # a re-restore re-arms: the replay then really runs again
        dst.restore(export, step=1)
        rerun = dst.reshard_replay(export, 1)
        assert rerun["pushes"] == first["pushes"]
        np.testing.assert_array_equal(dst.table("emb").pull(mine), once)
        src.stop()
        dst.stop()


# --------------------------------------------------------- split policy
class TestSplitDecision:
    def test_needs_heat_and_size(self):
        # balanced tier: no split however big
        assert ps_split_decision({0: 5e5, 1: 5e5}, 2) is None
        # hot but tiny: not worth a migration
        assert ps_split_decision({0: 900, 1: 100}, 2) is None
        # hot and big: double
        assert ps_split_decision({0: 4e5, 1: 1e5}, 2) == 4
        # capped
        assert ps_split_decision({0: 4e5, 1: 1e5}, 2, max_shards=2) is None
        assert ps_split_decision({}, 2) is None
        assert ps_split_decision({0: 1e6}, 0) is None

    def test_access_skew_triggers_without_row_skew(self):
        """The two-tier trigger: rows perfectly balanced, but one shard
        concentrates the hot working set — split anyway."""
        rows = {0: 5e5, 1: 5e5}
        assert ps_split_decision(rows, 2) is None  # rows alone: no
        # at the default 2.0x ratio, 2 shards trigger only on total
        # concentration; exactly at the threshold counts as hot
        assert ps_split_decision(rows, 2,
                                 shard_access={0: 6e6, 1: 0.0}) == 4
        assert ps_split_decision(rows, 2,
                                 shard_access={0: 9e6, 1: 1e6}) is None
        # a tuned ratio sees the 90/10 skew
        assert ps_split_decision(rows, 2, access_ratio=1.5,
                                 shard_access={0: 9e6, 1: 1e6}) == 4
        # balanced traffic does not trip the access trigger
        assert ps_split_decision(rows, 2,
                                 shard_access={0: 5e6, 1: 5e6}) is None
        # wider tiers make the default ratio reachable: 4 shards, one
        # serving half the traffic (2x its fair quarter)
        rows4 = {i: 2.5e5 for i in range(4)}
        assert ps_split_decision(
            rows4, 4,
            shard_access={0: 5e6, 1: 2e6, 2: 2e6, 3: 1e6}) == 8

    def test_access_skew_shares_floor_and_cap(self):
        # a tiny table never splits, however skewed its traffic (the
        # same access pattern splits once the table clears the floor)
        skew = {0: 9e6, 1: 1e6}
        assert ps_split_decision({0: 5e5, 1: 5e5}, 2, access_ratio=1.5,
                                 shard_access=skew) == 4
        assert ps_split_decision({0: 500, 1: 500}, 2, access_ratio=1.5,
                                 shard_access=skew) is None
        # max_shards caps the access trigger exactly like the row one
        assert ps_split_decision({0: 5e5, 1: 5e5}, 2, max_shards=2,
                                 access_ratio=1.5,
                                 shard_access=skew) is None
        # zero traffic is not skew
        assert ps_split_decision({0: 5e5, 1: 5e5}, 2,
                                 shard_access={0: 0.0, 1: 0.0}) is None

    def test_access_and_row_triggers_are_an_or(self):
        # row skew alone still decides, with access balanced
        assert ps_split_decision({0: 4e5, 1: 1e5}, 2,
                                 shard_access={0: 5e6, 1: 5e6}) == 4

    def test_no_access_input_keeps_legacy_verdict(self):
        """Callers that pass no access counts get the row-count verdict
        bit for bit — the pre-tier policy surface is frozen."""
        cases = [({0: 5e5, 1: 5e5}, None), ({0: 900, 1: 100}, None),
                 ({0: 4e5, 1: 1e5}, 4)]
        for rows, want in cases:
            assert ps_split_decision(rows, 2) == \
                ps_split_decision(rows, 2, shard_access=None) == want


# ----------------------------------------------------------- coordinator
class _Cluster:
    """In-process gRPC shard servers published to a real registry — the
    coordinator and client see exactly what pods would give them."""

    def __init__(self, workdir: str):
        self.workdir = workdir
        self.live = []  # (shard, server)

    def start_set(self, num_shards: int, generation: int = 0,
                  prefix: str = "src") -> None:
        for i in range(num_shards):
            epoch = registry.bump_epoch(self.workdir, i)
            shard = PsShard(
                shard_index=i, num_shards=num_shards, epoch=epoch,
                wal_root=os.path.join(self.workdir, "ps-wal", f"shard-{i}"),
                workdir=self.workdir,
                rescue_dir=os.path.join(self.workdir, "ps-ckpt"),
                route_generation=generation,
            )
            server = shard.serve()
            registry.publish(self.workdir, f"{prefix}-{num_shards}-{i}", i,
                             num_shards, server.address, epoch=epoch,
                             generation=generation)
            self.live.append((shard, server))

    def ensure_destinations(self, plan):
        self.start_set(int(plan["to_shards"]),
                       generation=int(plan["generation"]),
                       prefix=f"dst-g{plan['generation']}")

    def stop(self):
        for shard, _server in self.live:
            shard.stop()
        self.live.clear()


def _storm(n_batches, batch=96, vocab=1200, seed=7):
    rng = np.random.default_rng(seed)
    return [
        ((rng.zipf(1.1, batch) % vocab).astype(np.int64),
         rng.standard_normal((batch, 8)).astype(np.float32))
        for _ in range(n_batches)
    ]


def test_online_reshard_grow_and_shrink_bit_identical(tmp_path):
    """The tentpole, end to end in-process: a 2→4 online split and a 4→2
    shrink run under a live push stream. Deterministic mid-migration
    traffic is injected at the phase boundaries (a push after `exported`
    is provably in the WAL tail; a push after `cutover` provably rides
    the stale-route bounce into the new shard set), and after both
    migrations every table digest-matches a never-resharded reference —
    optimizer rows included."""
    w = str(tmp_path)
    cluster = _Cluster(w)
    cluster.start_set(2)
    client = ShardedPsClient.from_registry(w, 2, timeout=5.0,
                                           drain_retry_s=60.0,
                                           transient_retry_s=30.0)
    reference = LocalPsClient(num_shards=2, coalesce=False)
    stream = iter(_storm(64))
    try:
        for c in (client, reference):
            c.create_table(spec())
        def push_batches(n):
            for _ in range(n):
                ids, g = next(stream)
                client.push("emb", ids, g, scale=0.125)
                reference.push("emb", ids, g, scale=0.125)

        push_batches(6)
        client.save(os.path.join(w, "ps-ckpt"), step=5)  # rescue lineage

        tail_pushes = {"n": 0}

        def on_phase(phase, plan):
            # Mid-migration traffic at exact protocol points: after the
            # export cut (tail records) and after cutover (stale-route →
            # re-partition onto the new set once committed — run async:
            # the bounce only resolves when the coordinator commits).
            if phase == "exported":
                push_batches(2)
                tail_pushes["n"] += 2
            if phase == "cutover":
                t = threading.Thread(target=push_batches, args=(2,))
                t.start()
                on_phase.cut_thread = t

        summary = reshard.run_reshard(
            w, 4, "test-grow", ensure_destinations=cluster.ensure_destinations,
            on_phase=on_phase, rpc_timeout=5.0, phase_timeout_s=60.0,
            dest_wait_s=30.0)
        on_phase.cut_thread.join(timeout=60.0)
        assert not on_phase.cut_thread.is_alive()
        assert summary["committed_routing"] == {"generation": 1,
                                                "num_shards": 4}
        assert summary["rows_migrated"] > 0
        assert summary["tail_pushes_replayed"] >= 1
        assert summary["tail_foreign_ids_filtered"] > 0
        # the post-commit rescue-lineage checkpoint landed (4 markers)
        assert summary["post_commit_ckpt_step"] in PsShard.saved_steps(
            os.path.join(w, "ps-ckpt"))
        # the client converged onto the new shard set via stale-route
        push_batches(4)
        assert client.num_shards == 4
        assert registry.committed_generation(w) == 1

        # ------------------------------------------------------ shrink back
        summary2 = reshard.run_reshard(
            w, 2, "test-shrink",
            ensure_destinations=cluster.ensure_destinations,
            on_phase=on_phase, rpc_timeout=5.0, phase_timeout_s=60.0,
            dest_wait_s=30.0)
        on_phase.cut_thread.join(timeout=60.0)
        assert not on_phase.cut_thread.is_alive()
        assert summary2["committed_routing"] == {"generation": 2,
                                                 "num_shards": 2}
        assert summary2["tail_pushes_replayed"] >= 1
        push_batches(4)
        assert client.num_shards == 2

        # ---------------------------------------------------- digest parity
        live_dir, ref_dir = os.path.join(w, "live"), os.path.join(w, "ref")
        client.save(live_dir, 999)
        reference.save(ref_dir, 999)
        live = _table_digests(live_dir, 999)
        ref = _table_digests(ref_dir, 999)
        assert live and live == ref, (live, ref)
    finally:
        client.close()
        cluster.stop()


def test_reshard_abort_rolls_back_and_sources_resume(tmp_path):
    """A phase failure (destinations never publish) aborts: the plan is
    dropped, sources are un-gated, the committed routing never moved, and
    the client stream continues against the source set as if nothing
    happened."""
    w = str(tmp_path)
    cluster = _Cluster(w)
    cluster.start_set(2)
    client = ShardedPsClient.from_registry(w, 2, timeout=5.0)
    try:
        client.create_table(spec())
        ids = np.arange(100, dtype=np.int64)
        g = np.ones((100, 8), np.float32)
        client.push("emb", ids, g, scale=0.1)
        with pytest.raises(reshard.ReshardError,
                           match="never published"):
            reshard.run_reshard(w, 4, "test-abort",
                                rpc_timeout=2.0, phase_timeout_s=10.0,
                                dest_wait_s=1.0)
        assert registry.committed_generation(w) == 0
        assert "plan" not in registry.routing_table(w)
        # sources serve again (rollback resumed any gate)
        client.push("emb", ids, g, scale=0.1)
        assert client.num_shards == 2
    finally:
        client.close()
        cluster.stop()


def test_second_coordinator_is_locked_out(tmp_path):
    w = str(tmp_path)
    registry.begin_reshard(w, 2, 4, "first")
    cluster = _Cluster(w)
    cluster.start_set(2)
    try:
        with pytest.raises(reshard.ReshardInProgress):
            reshard.run_reshard(w, 4, "second", rpc_timeout=1.0,
                                phase_timeout_s=2.0, dest_wait_s=1.0)
        # the loser must not have damaged the winner's plan
        assert registry.routing_table(w)["plan"]["owner"] == "first"
    finally:
        cluster.stop()
