#!/usr/bin/env python3
"""Measure elastic-event cost over a LONG window — a measurement, not a
projection.

PARITY.md's north-star status was amortizing the measured generation-switch
cost over an *assumed* event cadence (the round-3 advisor flagged it). This
script measures it: two runs of identical wall length and steady-state
world size —

- **baseline**: 2 workers, no events;
- **elastic**: 2 workers, a SIGKILL preemption injected every
  ``--event-every`` seconds (the failure → heartbeat-detect → re-rendezvous
  → reshard-restore path, i.e. the same machinery a scale event exercises,
  at a world size whose steady-state throughput matches the baseline's so
  the comparison isolates the event cost);

then reports the measured throughput loss at the tested cadence and the
per-event cost, from which the loss at any cadence follows by linear
amortization of a *measured* quantity.

Writes/merges a ``long_window`` section into RECOVERY.json (``--out``).

Usage (forced-CPU env, like measure_recovery.py):
  EASYDL_RECOVERY_CHILD=1 JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 PALLAS_AXON_POOL_IPS= \
  PYTHONPATH=/root/repo python scripts/measure_longwindow.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def read_metrics(workdir, agent_id):
    path = os.path.join(workdir, f"metrics-{agent_id}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def samples_in_window(workdir, agents, t0, t1, global_batch):
    """Steps completed inside [t0, t1] across the job.

    Keyed by the job-level step ALONE: after a preemption the restored
    generation replays the steps between the last checkpoint and the kill,
    and counting those replays as fresh progress (e.g. keying by
    (generation, step)) would bias the elastic run's throughput optimistic
    by ~ckpt_interval/2 steps per event."""
    seen = set()
    for a in agents:
        for r in read_metrics(workdir, a):
            if t0 <= r["t"] <= t1:
                seen.add(r["step"])
    return len(seen) * global_batch


def run_window(window_s, event_every, cache_dir):
    from easydl_tpu.elastic.agent import Agent
    from easydl_tpu.elastic.master import Master

    os.environ["EASYDL_COMPILE_CACHE"] = cache_dir
    wd = tempfile.mkdtemp(prefix="longwindow-")
    cfg = {
        "model": "mlp",
        "model_kwargs": {"input_shape": [8, 8, 1], "features": [32, 32]},
        "global_batch": 64, "total_steps": 10_000_000,
        # Auto cadence: bound work-at-risk by wall clock (~2s) instead of a
        # fixed step count — with the switch itself fast, replayed steps
        # between the last save and the kill are the avoidable loss.
        "ckpt_interval": "auto", "ckpt_target_s": 2.0,
        "lr": 0.01, "seed": 0,
    }
    master = Master(job_name="lw", workdir=wd, desired_workers=2,
                    min_workers=1, heartbeat_timeout=1.5,
                    worker_config=cfg).start()
    # warm_start: the production recovery posture (the preemption scenario
    # measures with it; the long window should exercise the same machinery)
    agents = [Agent(f"a{i}", master.address, wd, slots=2,
                    warm_start=True).start()
              for i in range(2)]
    events = 0
    try:
        # steady state before the window opens
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            steps = [a.get("step", 0)
                     for a in master.status()["agents"].values()]
            if steps and min(steps) >= 20:
                break
            time.sleep(0.5)
        else:
            raise TimeoutError("never reached steady state")
        t0 = time.time()
        t_end = t0 + window_s
        next_event = t0 + event_every if event_every else float("inf")
        victim = 1
        while time.time() < t_end:
            if time.time() >= next_event:
                agents[victim].kill_worker_hard()
                events += 1
                victim = 1 - victim
                next_event += event_every
            time.sleep(0.5)
        t1 = time.time()
        samples = samples_in_window(wd, [f"a{i}" for i in range(2)],
                                    t0, t1, cfg["global_batch"])
        return samples, t1 - t0, events
    finally:
        for a in agents:
            a.stop()
        master.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--window", type=float, default=360.0)
    ap.add_argument("--event-every", type=float, default=90.0)
    ap.add_argument("--out", default=os.path.join(REPO, "RECOVERY.json"))
    args = ap.parse_args()

    cache = tempfile.mkdtemp(prefix="longwindow-jaxcache-")
    base_samples, base_dt, _ = run_window(args.window, 0.0, cache)
    el_samples, el_dt, events = run_window(args.window, args.event_every,
                                           cache)
    base_rate = base_samples / base_dt
    el_rate = el_samples / el_dt
    loss_pct = 100.0 * (1.0 - el_rate / base_rate)
    per_event_s = ((base_rate - el_rate) * el_dt / base_rate / events
                   if events else 0.0)
    section = {
        "scenario": f"{args.window:.0f}s window, SIGKILL preemption every "
                    f"{args.event_every:.0f}s vs identical static run "
                    "(same steady-state world: isolates the event cost)",
        "events": events,
        "baseline_samples_per_s": round(base_rate, 1),
        "elastic_samples_per_s": round(el_rate, 1),
        "measured_loss_pct_at_tested_cadence": round(loss_pct, 2),
        "equivalent_stall_per_event_s": round(per_event_s, 2),
        "loss_pct_at_10min_events": round(
            100.0 * per_event_s / 600.0, 2),
        "loss_pct_at_30min_events": round(
            100.0 * per_event_s / 1800.0, 2),
        "note": "10/30-min numbers amortize the MEASURED per-event stall "
                "(not an assumed switch time) over those cadences",
    }
    doc = {}
    if os.path.exists(args.out):
        with open(args.out) as f:
            doc = json.load(f)
    doc["long_window"] = section
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(section, indent=2))


if __name__ == "__main__":
    main()
