"""Minimal gRPC service plumbing without protoc's grpc plugin.

This image ships ``protoc`` (message codegen) and the ``grpcio`` runtime but
not ``grpc_python_plugin``, so instead of generated ``_pb2_grpc`` stubs each
service declares a method table and we register it with
``grpc.method_handlers_generic_handler``. Clients go through
:class:`RpcClient`, which builds unary-unary callables lazily.

Usage::

    SERVICE = ServiceDef("easydl.Brain", {
        "GetStartupPlan": (pb.JobFeatures, pb.PlanResponse),
        ...
    })

    server = serve(SERVICE, handler_obj, port=0)   # handler_obj.GetStartupPlan(req, ctx)
    client = RpcClient(SERVICE, f"localhost:{server.port}")
    resp = client.GetStartupPlan(pb.JobFeatures(job_name="j"))
"""

from __future__ import annotations

import os
import threading
import time
from concurrent import futures
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import grpc

from easydl_tpu.obs import get_registry
from easydl_tpu.obs import tracing
from easydl_tpu.utils.env import knob_raw


@dataclass(frozen=True)
class ServiceDef:
    """A gRPC service: full name + {method: (request_cls, response_cls)}."""

    name: str
    methods: Dict[str, Tuple[Any, Any]]


# --------------------------------------------------------------- telemetry
# Every RPC in the system flows through this module (servers via
# _handlers_for, clients via RpcClient), so instrumenting here makes the
# whole control plane's request counts / error counts / latency histograms
# appear in each process' /metrics with zero per-service work. Interceptor
# shape: the handler/stub callable is wrapped, not the grpc channel — this
# codebase builds its own method tables, so the wrap IS the interceptor.
_RPC_LABELS = ("service", "method")
_rpc_metrics_cache: Dict[str, tuple] = {}


def _rpc_metrics(side: str):
    cached = _rpc_metrics_cache.get(side)
    if cached is not None:
        return cached
    reg = get_registry()
    _rpc_metrics_cache[side] = metrics = (
        reg.counter(
            f"easydl_rpc_{side}_requests_total",
            f"RPCs handled ({side} side), by service/method.",
            _RPC_LABELS,
        ),
        reg.counter(
            f"easydl_rpc_{side}_errors_total",
            f"RPCs that raised ({side} side), by service/method.",
            _RPC_LABELS,
        ),
        reg.histogram(
            f"easydl_rpc_{side}_latency_seconds",
            f"RPC wall-clock latency ({side} side), by service/method.",
            _RPC_LABELS,
        ),
    )
    return metrics


def _instrument(fn: Callable, side: str, service: str,
                method: str) -> Callable:
    requests, errors, latency = _rpc_metrics(side)

    def wrapped(*args, **kwargs):
        t0 = time.perf_counter()
        # Tracing hook (obs/tracing.py): a span per SERVER handler call,
        # child of the caller's injected `easydl-trace` metadata when
        # present, a fresh root otherwise — absent/malformed metadata can
        # never fail the RPC. Disabled (the default) this is one env
        # lookup; client-side spans live in RpcClient.invoke, where the
        # metadata is built.
        span = (tracing.start_rpc_server_span(service, method,
                                              args[1] if len(args) > 1
                                              else None)
                if side == "server" else tracing.NULL_SPAN)
        try:
            # Chaos hook point (docs/design/chaos.md): with EASYDL_CHAOS_SPEC
            # unset this is ONE env-dict lookup — no import, no call. Armed,
            # the injector may delay the call, raise UNAVAILABLE (drop), or
            # raise a handler-class error, per the scenario's scheduled
            # windows. Inside the try so injected faults land in the same
            # request/error/latency series as real ones.
            if knob_raw("EASYDL_CHAOS_SPEC"):
                from easydl_tpu.chaos.injectors import (
                    ChaosUnavailable,
                    rpc_fault,
                )

                try:
                    rpc_fault(side, service, method)
                except ChaosUnavailable as e:
                    # A server-side drop must reach the CLIENT as transport
                    # loss: a python exception from a servicer becomes
                    # status UNKNOWN (handler-bug class, never retried), so
                    # abort with UNAVAILABLE instead. abort() itself raises.
                    if side == "server" and len(args) >= 2 \
                            and hasattr(args[1], "abort"):
                        args[1].abort(grpc.StatusCode.UNAVAILABLE,
                                      e.details())
                    raise
            return fn(*args, **kwargs)
        except Exception as e:
            errors.inc(service=service, method=method)
            span.add_event("error", error=repr(e))
            raise
        finally:
            span.end()
            requests.inc(service=service, method=method)
            latency.observe(
                time.perf_counter() - t0, service=service, method=method
            )

    return wrapped


class Server:
    """A running gRPC server bound to ``port`` (picks a free one if 0)."""

    def __init__(self, server: grpc.Server, port: int):
        self._server = server
        self.port = port

    @property
    def address(self) -> str:
        return f"localhost:{self.port}"

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)

    def wait(self) -> None:
        self._server.wait_for_termination()


def _handlers_for(service: ServiceDef, impl: Any) -> grpc.GenericRpcHandler:
    table = {}
    for method, (req_cls, resp_cls) in service.methods.items():
        fn = _instrument(getattr(impl, method), "server", service.name, method)
        table[method] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(service.name, table)


#: gRPC's 4 MB default message cap is too small for the PS tier (a single
#: un-chunked 8192-id pull at dim 128 already exceeds it). The PS client
#: keeps typical messages ~1 MB via chunking; this is the hard ceiling,
#: not the operating point. ONLY the PS server/client pass these — the
#: control plane (master/agent/brain) keeps the 4 MB default so a
#: misbehaving peer cannot make those processes buffer giant messages.
GRPC_MSG_OPTIONS = (
    ("grpc.max_send_message_length", 256 << 20),
    ("grpc.max_receive_message_length", 256 << 20),
)


def serve(
    service: ServiceDef,
    impl: Any,
    port: int = 0,
    max_workers: int = 16,
    extra: Optional[list] = None,
    options: Optional[Tuple] = None,
) -> Server:
    """Start a server hosting ``service`` (and optionally more
    ``(ServiceDef, impl)`` pairs via ``extra``)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers),
                         options=list(options) if options else None)
    server.add_generic_rpc_handlers((_handlers_for(service, impl),))
    for svc, obj in extra or []:
        server.add_generic_rpc_handlers((_handlers_for(svc, obj),))
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise OSError(f"failed to bind gRPC server to port {port}")
    server.start()
    return Server(server, bound)


class RpcClient:
    """Typed unary-unary client for a :class:`ServiceDef`."""

    def __init__(self, service: ServiceDef, address: str,
                 timeout: float = 30.0, options: Optional[Tuple] = None):
        self._service = service
        self._address = address
        self._timeout = timeout
        self._channel = grpc.insecure_channel(
            address, options=list(options) if options else None)
        self._calls: Dict[str, Callable] = {}
        self._lock = threading.Lock()

    def _call(self, method: str) -> Callable:
        with self._lock:
            if method not in self._calls:
                req_cls, resp_cls = self._service.methods[method]
                self._calls[method] = self._channel.unary_unary(
                    f"/{self._service.name}/{method}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                )
            return self._calls[method]

    def __getattr__(self, method: str) -> Callable:
        if method.startswith("_"):
            raise AttributeError(method)
        if method not in self._service.methods:
            raise AttributeError(f"{self._service.name} has no method {method}")
        call = self._call(method)
        timeout = self._timeout

        service = self._service.name

        def invoke(request, timeout_s: Optional[float] = None):
            if not tracing.enabled():
                return call(request, timeout=timeout_s or timeout)
            # Traced path: inject the current context as `easydl-trace`
            # request metadata (a client span is opened only when a parent
            # span is active — steady-state heartbeat loops must not mint a
            # root trace per beat), and collect the reply's trailing
            # metadata: directives are responses, so the master's
            # generation-switch context rides back to the agent here.
            span = (tracing.start_span(f"rpc:{service}/{method}",
                                       service=service, method=method)
                    if tracing.current_span() is not None
                    else tracing.NULL_SPAN)
            try:
                header = tracing.inject()
                resp, grpc_call = call.with_call(
                    request, timeout=timeout_s or timeout,
                    metadata=((tracing.METADATA_KEY, header),)
                    if header else None,
                )
                tracing.note_reply_metadata(grpc_call.trailing_metadata())
                return resp
            except Exception as e:
                tracing.note_reply_metadata(None)
                span.add_event("error", error=repr(e))
                raise
            finally:
                span.end()

        return _instrument(invoke, "client", service, method)

    def call_future(self, method: str, request,
                    timeout_s: Optional[float] = None):
        """Issue a unary RPC WITHOUT blocking: returns the grpc future
        (``.result(timeout)`` / ``.cancel()`` / ``.add_done_callback``).
        The seam the serve router's request hedging needs — two in-flight
        calls, first answer wins, loser cancelled. Deliberately outside
        the instrumented sync path: the caller owns completion, so it
        owns the accounting too."""
        return self._call(method).future(request,
                                         timeout=timeout_s or self._timeout)

    def wait_ready(self, timeout: float = 10.0) -> None:
        grpc.channel_ready_future(self._channel).result(timeout=timeout)

    def close(self) -> None:
        self._channel.close()
