"""Live mesh-shape elasticity (ISSUE 12): a real master + agent + worker
subprocess where the generation switch that changes the mesh
factorization is driven end-to-end by the Brain's mesh-shape policy —
cold-start shape, observed-throughput intake, a policy-initiated PLANNED
reshape, and the worker rebuilding its jitted step on the decided shape
(EASYDL_MESH) with a checkpoint-carried restore.

Single agent with 4 device slots, so the whole world lives in ONE worker
process — no cross-process collectives (which this container's jaxlib
lacks; see tests/envprobe.py) are needed to exercise a multi-device mesh.
"""

import json
import os
import time

from easydl_tpu.elastic.agent import Agent
from easydl_tpu.elastic.master import Master

JOB_CFG = {
    "model": "mlp",
    "model_kwargs": {"input_shape": [8, 8, 1], "features": [32, 32]},
    "global_batch": 8,
    "total_steps": 100000,   # never finishes inside the test window
    "ckpt_interval": 4,
    "lr": 0.01,
    "seed": 0,
    # The PR-12 opt-in: enumerate dp x fsdp factorizations of the world,
    # probe aggressively (tiny min_samples/cooldown so the test sees a
    # shape change within seconds).
    "mesh_policy": {
        "constraints": {"max_fsdp": 2},
        "min_samples": 2,
        "probe_cooldown_s": 1.0,
        "max_probes_per_world": 1,
    },
}


def wait_for(cond, timeout=150.0, interval=0.2, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}")


def read_metrics(workdir, agent_id):
    path = os.path.join(workdir, f"metrics-{agent_id}.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                pass  # torn tail from a mid-write read
    return out


def test_mesh_shape_policy_drives_a_live_generation_switch(tmp_path):
    workdir = str(tmp_path)
    master = Master(
        job_name="mesh-elastic",
        workdir=workdir,
        desired_workers=1,
        min_workers=1,
        worker_config=JOB_CFG,
        prepare_timeout_s=0.0,       # immediate drains: fast switches
        prepare_min_uptime_s=0.0,
    ).start()
    agent = Agent("a0", master.address, workdir, slots=4).start()
    try:
        # Generation 1 runs the cold-start shape: widest data axis = dp=4.
        wait_for(
            lambda: any(r.get("mesh") == "dp=4" and r.get("step", 0) >= 2
                        for r in read_metrics(workdir, "a0")),
            desc="worker training on the cold-start dp=4 mesh",
        )
        # The policy observes per-shape throughput from heartbeats and
        # probes the one other candidate (dp=2,fsdp=2) via a planned
        # mesh-shape reshape; the switched worker restores the quiesce
        # checkpoint onto the new factorization and keeps stepping.
        wait_for(
            lambda: any(
                r.get("mesh") == "dp=2,fsdp=2" and r.get("step", 0) >= 2
                for r in read_metrics(workdir, "a0")),
            desc="worker training on the probed dp=2,fsdp=2 mesh",
        )
        recs = read_metrics(workdir, "a0")
        switched = [r for r in recs if r.get("mesh") == "dp=2,fsdp=2"]
        pre = [r for r in recs if r.get("mesh") == "dp=4"]
        assert pre and switched
        # the quiesce checkpoint carried: the probed generation resumed at
        # (or past) the drained step, not from scratch
        assert min(r["step"] for r in switched) >= 2
        assert all(r["world_size"] == 4 for r in recs)

        # Control-plane evidence: the reshape was counted under its own
        # reason and the WAL stamped the decision inputs.
        events = []
        with open(os.path.join(workdir, "events.jsonl")) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        pass
        reshapes = [e for e in events if e.get("kind") == "reshape"]
        assert any(e.get("reason") == "mesh-shape" and e.get("planned")
                   for e in reshapes), reshapes
        mesh_events = [e for e in events if e.get("kind") == "mesh_shape"]
        assert any(e.get("mesh") == "dp=4" for e in mesh_events)
        probe = next(e for e in mesh_events
                     if e.get("mesh") == "dp=2,fsdp=2")
        assert probe["chips"] == 4
        inputs = probe.get("inputs") or {}
        assert inputs.get("reason") == "probe"
        assert "dp=4" in (inputs.get("candidates") or [])
        assert (inputs.get("measured") or {}).get("dp=4", {}).get("n", 0) \
            >= 2
        # status surfaces the policy's per-shape history
        st = master.status()
        assert st["mesh"] in ("dp=4", "dp=2,fsdp=2")
        assert "dp=4" in st["mesh_policy"]["history"].get("4", {})
    finally:
        agent.stop()
        master.stop()
