"""Shard-address registry for PS pods (file-based service discovery).

The operator creates/retires PS pods by *name* (replace-then-retire,
docs/design/elastic-training-operator.md:86-101) and knows nothing about
shards; clients route by *shard index*. This registry is the join between
the two worlds: every PS pod publishes one JSON file
``<workdir>/ps/ps-<pod>.json`` with its shard index, address, a publish
timestamp — and, since the WAL/fencing PR, the shard *epoch* and the
publishing pid. Readers resolve "who serves shard i" as the
highest-epoch (then latest) publication for that shard — a replacement
pod publishes only after it has drained its predecessor and restored the
rows, so the newest entry is by construction the authoritative one.

The epoch is the fencing token: a strictly monotonic per-shard counter
kept in ``epoch-shard-<i>.json`` and advanced under an exclusive flock
(:func:`bump_epoch`) by every pod that takes the shard over. It survives
entry sweeps and workdir reuse, so a zombie predecessor can always be
recognised as superseded — the server rejects pushes whose stamped epoch
does not match its own (ps/server.py), and fences itself permanently on
proof of a successor.

Atomic single-file writes (tmp + rename) on a shared workdir for the
entries; the epoch counter is the one piece that genuinely needs
read-modify-write, so it reuses the in-place flock idiom of the claim
files (stable inode — a rename-based update would drop the lock's
protection).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

from easydl_tpu.utils.logging import get_logger

log = get_logger("ps", "registry")

REG_DIR = "ps"


def locked_mutate(path: str, mutate) -> dict:
    """Read-check-write a JSON doc atomically under an exclusive flock.

    ``mutate(doc) -> new_doc | None`` runs with the lock held; None leaves
    the file unchanged. The file's inode is stable (in-place truncate +
    write, never os.replace), so the flock actually serializes every
    writer. Returns the doc now in the file; a missing file returns {}.
    Shared by the shard-claim files (ps/__main__.py) and the epoch
    counter below."""
    import fcntl

    try:
        with open(path, "r+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                try:
                    doc = json.load(f)
                except ValueError:
                    doc = {}  # torn write from a crashed claimant
                new = mutate(doc)
                if new is not None:
                    f.seek(0)
                    f.truncate()
                    json.dump(new, f)
                    f.flush()
                    os.fsync(f.fileno())
                return new if new is not None else doc
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)
    except FileNotFoundError:
        return {}


def _dir(workdir: str) -> str:
    return os.path.join(workdir, REG_DIR)


def publish(workdir: str, pod: str, shard: int, num_shards: int,
            address: str, epoch: int = 0) -> str:
    """Publish/overwrite this pod's registry entry; returns the file path.

    ``epoch`` is the fencing token from :func:`bump_epoch`; 0 means the
    publisher predates fencing (readers treat it as the lowest epoch)."""
    os.makedirs(_dir(workdir), exist_ok=True)
    path = os.path.join(_dir(workdir), f"ps-{pod}.json")
    doc = {
        "pod": pod,
        "shard": int(shard),
        "num_shards": int(num_shards),
        "address": address,
        "epoch": int(epoch),
        "pid": os.getpid(),
        "published_at": time.time(),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def bump_epoch(workdir: str, shard: int) -> int:
    """Advance and return the shard's fencing epoch (first call returns 1).

    Strictly monotonic across pod restarts, entry sweeps and workdir reuse:
    the counter lives in its own flock-serialized file, never in the
    publications (which are swept when their pod dies). Two pods that both
    bump get DISTINCT epochs — the claim file decides who may publish, the
    epoch decides who the servers obey; a wasted bump by a loser is
    harmless."""
    d = _dir(workdir)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"epoch-shard-{int(shard)}.json")
    try:  # O_EXCL create so the first bump has a file to flock
        os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except FileExistsError:
        pass
    doc = locked_mutate(
        path, lambda doc: {"epoch": int(doc.get("epoch", 0)) + 1}
    )
    return int(doc["epoch"])


def shard_epoch(workdir: str, shard: int) -> int:
    """Current fencing epoch for a shard (0 = never bumped). Read under the
    same flock writers hold."""
    path = os.path.join(_dir(workdir), f"epoch-shard-{int(shard)}.json")
    return int(locked_mutate(path, lambda doc: None).get("epoch", 0))


def sweep_stale(workdir: str) -> int:
    """Drop publications whose publishing process is dead; returns the
    number removed.

    Mirrors the obs-exporter discovery sweep (obs/exporter.py): a
    SIGKILLed pod never retracts its entry, so a reused workdir
    accumulates dead addresses that rescue discovery must probe (paying a
    timeout per ghost) and that a client reroute could briefly adopt.
    Only single-host publications (advertised as ``localhost``) with a
    recorded pid are swept — a pid check is meaningless for another
    host's process. Epoch counters and claim files are never touched (the
    fencing history must survive the sweep)."""
    removed = 0
    d = _dir(workdir)
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for name in names:
        if not (name.startswith("ps-") and name.endswith(".json")):
            continue
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                doc = json.load(f)
            addr = str(doc.get("address", ""))
            pid = int(doc.get("pid", 0))
            if not addr.startswith("localhost:") or pid <= 0:
                continue
            if pid == os.getpid():
                continue
            os.kill(pid, 0)  # raises ProcessLookupError when dead
        except ProcessLookupError:
            try:
                os.remove(path)
                removed += 1
                log.info("swept stale ps publication %s (pid dead)", name)
            except OSError:
                pass
        except (OSError, ValueError, PermissionError):
            continue  # torn file, or alive-but-not-ours: leave it
    return removed


def entries(workdir: str) -> Dict[str, dict]:
    """All registry entries keyed by pod name (unreadable files skipped)."""
    out: Dict[str, dict] = {}
    try:
        names = os.listdir(_dir(workdir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("ps-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(_dir(workdir), name)) as f:
                doc = json.load(f)
            out[doc["pod"]] = doc
        except (OSError, ValueError, KeyError):
            continue  # torn write in progress; next read sees it
    return out


def entry_for_pod(workdir: str, pod: str) -> Optional[dict]:
    return entries(workdir).get(pod)


def shard_map(workdir: str) -> Dict[int, dict]:
    """shard index -> the authoritative entry for the shard: highest epoch
    wins (the fencing order), publish time breaks ties among epoch-less
    legacy entries."""
    latest: Dict[int, dict] = {}
    for doc in entries(workdir).values():
        s = int(doc["shard"])
        key = (int(doc.get("epoch", 0)), doc["published_at"])
        if s not in latest or key > (int(latest[s].get("epoch", 0)),
                                     latest[s]["published_at"]):
            latest[s] = doc
    return latest


def discover(workdir: str, timeout: float = 120.0) -> Tuple[int, Tuple[str, ...]]:
    """Learn the cluster shape from the registry itself: wait (one deadline)
    until some pod has published — its entry carries ``num_shards`` — and
    every shard of that count is present. Returns (num_shards, addresses)."""
    deadline = time.monotonic() + timeout
    while True:
        ents = entries(workdir)
        if ents:
            n = max(int(d["num_shards"]) for d in ents.values())
            m = shard_map(workdir)
            if all(s in m for s in range(n)):
                return n, tuple(m[s]["address"] for s in range(n))
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"ps registry under {workdir} incomplete after {timeout:.0f}s"
                f" ({len(ents)} publication(s))"
            )
        time.sleep(0.1)


def addresses(workdir: str, num_shards: int,
              timeout: float = 0.0) -> Tuple[str, ...]:
    """Shard-ordered address tuple; with ``timeout`` waits for completeness.

    Raises TimeoutError when shards are still missing after the wait — a
    cluster that never fully published is a deployment error, not a routing
    table."""
    deadline = time.monotonic() + timeout
    while True:
        m = shard_map(workdir)
        if all(s in m for s in range(num_shards)):
            return tuple(m[s]["address"] for s in range(num_shards))
        if time.monotonic() >= deadline:
            missing = [s for s in range(num_shards) if s not in m]
            raise TimeoutError(
                f"ps registry incomplete: shards {missing} unpublished"
            )
        time.sleep(0.1)
