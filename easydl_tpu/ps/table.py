"""Sparse embedding tables: ctypes front-end over the C++ store, with a
bit-compatible numpy fallback.

The table is the unit the PS serves (reference PS role,
docs/design/elastic-training-operator.md:39-40). Rows materialise lazily with
a deterministic per-id init (splitmix64 of ``seed ^ id``), so any shard
layout — or a restore onto a different shard count — produces identical
parameters for the same ids.

Optimizers live *in* the table (classic PS design): ``push`` applies a sparse
SGD/Adagrad update; duplicate ids within one push accumulate first, matching
the dense scatter-add gradient semantics of the on-device embedding path
(easydl_tpu/models/deepfm.py DeviceEmbedding).
"""

from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from easydl_tpu.ps import build as _build
from easydl_tpu.utils.env import env_flag as _env_flag

OPTIMIZERS = {"sgd": 0, "adagrad": 1}

#: Separator between a job namespace and the table's own name. Chosen to
#: be filename-safe (shard snapshots are ``<table>.shard-i-of-n.npz``) and
#: impossible in a valid namespace, so :func:`split_namespace` is
#: unambiguous.
NAMESPACE_SEP = "::"

_NS_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def namespaced(namespace: str, table: str) -> str:
    """Prefix ``table`` with a job namespace — the multi-tenancy seam
    (ROADMAP item 5): N jobs share one shard fleet, and every path keyed
    on the table NAME (store maps, WAL records, snapshot files, reshard
    exports, shm segments, metric labels) isolates for free because the
    namespace rides inside the name. Raises on a namespace that could
    break a filename or make the split ambiguous."""
    if not namespace:
        raise ValueError("namespace must be non-empty")
    if not set(namespace) <= _NS_OK:
        raise ValueError(
            f"namespace {namespace!r} has characters outside [A-Za-z0-9._-]"
        )
    if NAMESPACE_SEP in table:
        raise ValueError(
            f"table {table!r} already carries a namespace separator"
        )
    return f"{namespace}{NAMESPACE_SEP}{table}"


def split_namespace(table: str) -> Tuple[str, str]:
    """Inverse of :func:`namespaced`: ``(namespace, base_name)`` — with
    ``("", table)`` for un-namespaced tables."""
    head, sep, tail = table.partition(NAMESPACE_SEP)
    return (head, tail) if sep else ("", table)

#: Debug/benchmark escape hatch: force the pre-vectorization per-id python
#: loops in _NumpyStore (the pre-PR hot path). Parity tests compare the two;
#: scripts/bench_ps.py uses it for honest before/after numbers.
_STORE_LOOP = "EASYDL_PS_STORE_LOOP"

_SQRT3 = np.float32(1.7320508075688772)
_U24 = np.float32(1.0 / 16777216.0)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorised splitmix64 — identical to the C++ core's."""
    x = np.asarray(x).astype(np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def shard_of(ids: np.ndarray, num_shards: int) -> np.ndarray:
    """Which PS shard owns each id. Hash-based (not modulo on the raw id) so
    skewed id spaces still balance."""
    return (splitmix64(ids) % np.uint64(num_shards)).astype(np.int64)


def init_rows(ids: np.ndarray, dim: int, row_width: int, seed: int,
              init_std: float) -> np.ndarray:
    """The deterministic lazy row init, as a pure function of (id, spec):
    identical bits to the C++ store's InitRow and to what any shard would
    materialise for an untouched id. Shared by the numpy store AND the
    shared-memory pull client (ps/shm.py), which computes rows absent from
    a shard's shm mirror locally instead of paying a per-miss RPC — an id
    missing from the mirror has never been pushed/imported, so its value
    IS this init."""
    ids = np.asarray(ids, np.int64)
    base = splitmix64(np.uint64(seed) ^ ids.astype(np.uint64))
    with np.errstate(over="ignore"):
        bits = splitmix64(
            base[:, None] + np.arange(dim, dtype=np.uint64)[None, :]
        )
    u = (bits >> np.uint64(40)).astype(np.float32) * _U24
    a = np.float32(init_std) * _SQRT3
    rows = np.zeros((len(ids), row_width), np.float32)
    rows[:, :dim] = (np.float32(2.0) * u - np.float32(1.0)) * a
    return rows


@dataclass(frozen=True)
class TableSpec:
    name: str
    dim: int
    init_std: float = 0.01
    seed: int = 0
    optimizer: str = "adagrad"
    lr: float = 0.05
    eps: float = 1e-8

    def __post_init__(self):
        if self.optimizer not in OPTIMIZERS:
            raise ValueError(f"unknown optimizer {self.optimizer!r}")

    @property
    def row_width(self) -> int:
        return 2 * self.dim if self.optimizer == "adagrad" else self.dim


class _NumpyStore:
    """Fallback store; same math as embedding_store.cc, pure numpy.

    One coarse lock stands in for the C++ store's stripe locks: the gRPC
    shard serves pulls/pushes from a thread pool, so the fallback must be
    just as safe under concurrent workers (it only trades throughput).

    Rows live in ONE contiguous ``(capacity, row_width)`` float32 array with
    an id→row-index dict on the side, so pull is a batched gather, push a
    batched scatter, and the splitmix64 lazy init runs vectorized over all
    missing ids of a batch at once — the per-id python loop the mutex used
    to serialize is gone (it was the whole embedding tier's throughput
    ceiling whenever the C++ store isn't buildable). ``EASYDL_PS_STORE_LOOP``
    forces the old loop for parity tests and before/after benchmarks; both
    paths are bit-identical.
    """

    def __init__(self, spec: TableSpec):
        self.spec = spec
        self._index: dict = {}  # id -> row index into _data/_ids
        self._ids = np.zeros(0, np.int64)  # insertion order, first _n valid
        self._data = np.zeros((0, spec.row_width), np.float32)
        self._n = 0
        self._mu = threading.Lock()
        self._loop = _env_flag(_STORE_LOOP, False)

    # ----------------------------------------------------------- row init
    def _init_rows(self, ids: np.ndarray) -> np.ndarray:
        """Vectorized lazy init for a batch of ids — identical bits to the
        old one-id-at-a-time loop (same splitmix64 stream per id)."""
        return init_rows(ids, self.spec.dim, self.spec.row_width,
                         self.spec.seed, self.spec.init_std)

    def _init_row(self, id_: int) -> np.ndarray:
        return self._init_rows(np.asarray([id_], np.int64))[0]

    def _grow(self, extra: int) -> None:
        need = self._n + extra
        cap = len(self._data)
        if need <= cap:
            return
        new_cap = max(64, 2 * cap, need)
        data = np.zeros((new_cap, self.spec.row_width), np.float32)
        data[: self._n] = self._data[: self._n]
        ids = np.zeros(new_cap, np.int64)
        ids[: self._n] = self._ids[: self._n]
        self._data, self._ids = data, ids

    def _indices(self, ids: np.ndarray, init_missing=None) -> np.ndarray:
        """Row index per id, materialising missing rows. Caller holds _mu.

        ``init_missing``: None → deterministic lazy init; else a callable
        ``(missing_ids) -> rows`` (import path supplies the restored rows).
        """
        index = self._index
        idx = np.fromiter(
            (index.get(i, -1) for i in ids.tolist()), np.int64, len(ids)
        )
        miss = idx < 0
        if miss.any():
            # A batch may repeat a missing id (duplicate-heavy pushes on the
            # loop-free path): materialise each missing id once.
            new_ids = np.unique(ids[miss])
            rows = (self._init_rows(new_ids) if init_missing is None
                    else init_missing(new_ids))
            self._grow(len(new_ids))
            n = self._n
            self._data[n: n + len(new_ids)] = rows
            self._ids[n: n + len(new_ids)] = new_ids
            index.update(zip(new_ids.tolist(), range(n, n + len(new_ids))))
            self._n = n + len(new_ids)
            sub = np.fromiter(
                (index[i] for i in ids[miss].tolist()), np.int64,
                int(miss.sum()),
            )
            idx[miss] = sub
        return idx

    # ------------------------------------------------------------ pull/push
    def pull(self, ids: np.ndarray, out: np.ndarray) -> None:
        dim = self.spec.dim
        with self._mu:
            if self._loop:
                for i, id_ in enumerate(ids):
                    out[i] = self._row_loop(int(id_))[:dim]
                return
            idx = self._indices(ids)
            out[:] = self._data[idx, :dim]

    def push(self, ids: np.ndarray, grads: np.ndarray, scale: float) -> None:
        spec = self.spec
        dim = spec.dim
        uniq, first, inv = np.unique(ids, return_index=True,
                                     return_inverse=True)
        if len(uniq) == len(ids):
            # Already deduplicated (the coalescing client's steady state):
            # skip the np.add.at scatter, just reorder into unique order.
            acc = np.ascontiguousarray(grads[first])
        else:
            acc = np.zeros((len(uniq), dim), np.float32)
            np.add.at(acc, inv, grads)
        lr, eps = np.float32(spec.lr), np.float32(spec.eps)
        with self._mu:
            if self._loop:
                self._push_loop(uniq, acc, scale, lr, eps)
                return
            idx = self._indices(uniq)
            g = acc * np.float32(scale)
            if spec.optimizer == "adagrad":
                slot = self._data[idx, dim:] + g * g
                self._data[idx, dim:] = slot
                self._data[idx, :dim] -= lr * g / (np.sqrt(slot) + eps)
            else:
                self._data[idx, :dim] -= lr * g

    # ---------------------------------------------- pre-vectorization path
    def _row_loop(self, id_: int) -> np.ndarray:
        j = self._index.get(id_)
        if j is None:
            self._grow(1)
            j = self._n
            self._data[j] = self._init_row(id_)
            self._ids[j] = id_
            self._index[id_] = j
            self._n += 1
        return self._data[j]

    def _push_loop(self, uniq, acc, scale, lr, eps) -> None:
        spec = self.spec
        for u, id_ in enumerate(uniq):
            row = self._row_loop(int(id_))
            g = acc[u] * np.float32(scale)
            if spec.optimizer == "adagrad":
                slot = row[spec.dim:]
                slot += g * g
                row[: spec.dim] -= lr * g / (np.sqrt(slot) + eps)
            else:
                row[: spec.dim] -= lr * g

    # ------------------------------------------------------------- admin
    def size(self) -> int:
        with self._mu:
            return self._n

    def export_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        with self._mu:
            return self._ids[: self._n].copy(), self._data[: self._n].copy()

    def import_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64)
        rows = np.ascontiguousarray(rows, np.float32)
        with self._mu:
            # Existing ids overwrite in place; new ids append with the
            # imported bytes (never the lazy init).
            order = {int(i): k for k, i in enumerate(ids)}  # last dup wins
            idx = self._indices(
                ids, init_missing=lambda missing: rows[
                    [order[int(i)] for i in missing]
                ],
            )
            self._data[idx] = rows


class _NativeStore:
    """ctypes wrapper over the C++ store."""

    def __init__(self, spec: TableSpec, lib: ctypes.CDLL):
        self.spec = spec
        self._lib = lib
        self._h = lib.eds_create(
            spec.dim,
            ctypes.c_float(spec.init_std),
            ctypes.c_uint64(np.uint64(spec.seed)),
            OPTIMIZERS[spec.optimizer],
            ctypes.c_float(spec.lr),
            ctypes.c_float(spec.eps),
        )

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.eds_destroy(h)

    @staticmethod
    def _i64p(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

    @staticmethod
    def _f32p(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    def pull(self, ids: np.ndarray, out: np.ndarray) -> None:
        self._lib.eds_pull(self._h, self._i64p(ids), len(ids), self._f32p(out))

    def push(self, ids: np.ndarray, grads: np.ndarray, scale: float) -> None:
        grads = np.ascontiguousarray(grads, np.float32)
        self._lib.eds_push(
            self._h, self._i64p(ids), len(ids), self._f32p(grads), ctypes.c_float(scale)
        )

    def size(self) -> int:
        return self._lib.eds_size(self._h)

    def export_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        # eds_export_snapshot sizes and exports under one exclusive barrier,
        # so the result is a consistent point-in-time snapshot even while
        # workers keep pushing; retry only when rows materialised between our
        # capacity estimate and the barrier acquisition (rare).
        n = max(self.size(), 1)
        while True:
            ids = np.zeros(n, np.int64)
            rows = np.zeros((n, self.spec.row_width), np.float32)
            true_size = np.zeros(1, np.int64)
            written = self._lib.eds_export_snapshot(
                self._h, self._i64p(ids), self._f32p(rows), n,
                self._i64p(true_size),
            )
            if true_size[0] <= n:
                return ids[:written], rows[:written]
            n = int(true_size[0])

    def import_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64)
        rows = np.ascontiguousarray(rows, np.float32)
        self._lib.eds_import(self._h, self._i64p(ids), self._f32p(rows), len(ids))

    # ------------------------------------------------------------ shm mirror
    def shm_export(self, name: str, nonce: int, capacity_rows: int) -> bool:
        return self._lib.eds_shm_export(
            self._h, name.encode(), ctypes.c_uint64(nonce),
            int(capacity_rows)) == 0

    def shm_set_version(self, version: int) -> None:
        self._lib.eds_shm_set_version(self._h, ctypes.c_uint64(version))

    def shm_revoke(self) -> None:
        self._lib.eds_shm_revoke(self._h)

    # ------------------------------------------------------------ two-tier
    def tier_enable(self, path: str, hot_budget_bytes: int,
                    cold_capacity_bytes: int) -> bool:
        return self._lib.eds_tier_enable(
            self._h, path.encode(), int(hot_budget_bytes),
            int(cold_capacity_bytes)) == 0

    def tier_maintain(self, decay: float, promote_min_freq: float,
                      swap_margin: float, hot_target_rows: int,
                      max_moves: int) -> Tuple[int, int]:
        out = np.zeros(2, np.int64)
        self._lib.eds_tier_maintain(
            self._h, ctypes.c_double(decay), ctypes.c_double(promote_min_freq),
            ctypes.c_double(swap_margin), int(hot_target_rows),
            int(max_moves), self._i64p(out))
        return int(out[0]), int(out[1])

    def tier_stats(self, warm_min_freq: float = 1.0) -> dict:
        out = np.zeros(10, np.float64)
        self._lib.eds_tier_stats(
            self._h, ctypes.c_double(warm_min_freq),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return {
            "tiered": bool(out[0]),
            "hot_rows": int(out[1]),
            "cold_rows": int(out[2]),
            "promotions": int(out[3]),
            "demotions": int(out[4]),
            "cold_hits": int(out[5]),
            "hot_bytes": int(out[6]),
            "cold_bytes": int(out[7]),
            "warm_cold_rows": int(out[8]),
            "hot_cap_rows": int(out[9]),
        }


class EmbeddingTable:
    """One named table. ``backend`` is ``"auto"`` (native if buildable),
    ``"native"`` (require C++), or ``"numpy"``."""

    def __init__(self, spec: TableSpec, backend: str = "auto",
                 version_base: int = 0):
        self.spec = spec
        lib = _build.load_native() if backend in ("auto", "native") else None
        if backend == "native" and lib is None:
            raise RuntimeError("native embedding store requested but unavailable")
        self._store = _NativeStore(spec, lib) if lib is not None else _NumpyStore(spec)
        self.backend = "native" if lib is not None else "numpy"
        # Push-version counter for client-side caching (PullResponse.version):
        # bumped AFTER every applied mutation, under its own lock so
        # concurrent pushes can never lose an increment — "version unchanged
        # between two reads" must mean "no push completed in between", or a
        # serving cache would keep an entry a trainer push just made stale.
        # Starts at base+1: 0 is the wire's "no version info" (legacy
        # server). ``version_base`` makes version SPACES disjoint across
        # shard incarnations (PsShard passes epoch << 32): a rescuer's
        # counter restarting from 1 could otherwise numerically collide
        # with a pre-crash tag while holding newer rows, and the equality
        # check would bless a stale cache entry.
        self._push_version = int(version_base) + 1
        self._version_mu = threading.Lock()
        #: (segment name, nonce) once the native store mirrors this table
        #: into a named shm segment (see shm_export); None otherwise.
        self._shm: Optional[Tuple[str, int]] = None

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def dim(self) -> int:
        return self.spec.dim

    @property
    def rows(self) -> int:
        return self._store.size()

    @property
    def push_version(self) -> int:
        """Monotonic per-table mutation counter. Read it BEFORE pulling
        rows: apply-then-bump ordering means a concurrent push can only
        make the tag too OLD (spurious cache invalidation — safe), never
        too new (a stale row believed fresh)."""
        return self._push_version

    def _bump_version(self) -> None:
        with self._version_mu:
            self._push_version += 1
            if self._shm is not None:
                # Header write-through AFTER the python counter moves,
                # inside the version lock: the mirror's advertised version
                # is therefore always <= the version the wire would report
                # — a shm row can never be believed FRESHER than a gRPC
                # pull of the same instant (the safe direction: at worst a
                # caching client spuriously revalidates).
                self._store.shm_set_version(self._push_version)

    # ------------------------------------------------------------ shm mirror
    def shm_export(self, max_bytes: int) -> bool:
        """Mirror this table into a named shm segment (native store only).
        ``max_bytes`` caps the segment; a table outgrowing it revokes the
        mirror and clients fall back to the wire. Returns True when the
        segment is live; False (numpy backend, creation failure, already
        exported) leaves the wire path untouched."""
        if self.backend != "native" or self._shm is not None:
            return False
        # Capacity from the REAL segment layout, so max_bytes is an
        # honest cap: header + nslots*(8+4) index (nslots = next power
        # of two >= 2*capacity, i.e. up to 4*capacity -> 48 bytes/row
        # worst case) + dim*4 row bytes.
        capacity = (int(max_bytes) - 4096) // (self.spec.dim * 4 + 48)
        if capacity <= 0:
            return False
        # Name + nonce minted HERE so the server can advertise them on the
        # wire handshake. The nonce (verified inside the segment header)
        # is what makes a same-named segment on a DIFFERENT host — or a
        # stale predecessor's leftover — unopenable.
        nonce = int.from_bytes(os.urandom(8), "little") | 1
        name = f"/eds-{os.getpid()}-{nonce & 0xFFFFFFFF:08x}"
        if not self._store.shm_export(name, nonce, capacity):
            return False
        with self._version_mu:
            self._store.shm_set_version(self._push_version)
            self._shm = (name, nonce)
        return True

    def shm_info(self) -> Optional[Tuple[str, int]]:
        """(segment name, nonce) advertised on PullResponse, or None."""
        return self._shm

    def shm_revoke(self) -> None:
        """Kill the mirror and stop advertising it. Every server-side
        consistency gate routes through here: a cut-over reshard source,
        a fenced zombie, and a restore all revoke, so a co-located reader
        falls back to the wire — where stale-route / stale-epoch handling
        lives — instead of gathering frozen rows forever."""
        if self._shm is not None:
            self._shm = None
            self._store.shm_revoke()

    # ------------------------------------------------------------ two-tier
    def tier_enable(self, path: str, hot_budget_bytes: int,
                    cold_capacity_bytes: int) -> bool:
        """Split this table's storage into a byte-budgeted hot tier (the
        stripe arenas) and an mmap'd cold file at ``path`` (native store
        only — the numpy fallback stays single-tier and this is a no-op
        returning False, the same honest gating as :meth:`shm_export`).
        Must run BEFORE :meth:`shm_export` so the mirror is born with the
        tiered flag (a miss then means "maybe cold", and the client
        fetches it on the wire instead of lazy-initialising locally)."""
        if self.backend != "native":
            return False
        if self._shm is not None:
            raise RuntimeError("tier_enable must precede shm_export")
        return self._store.tier_enable(path, hot_budget_bytes,
                                       cold_capacity_bytes)

    def tier_maintain(self, decay: float, promote_min_freq: float,
                      swap_margin: float, hot_target_rows: int,
                      max_moves: int = 0) -> Tuple[int, int]:
        """Execute one promotion/demotion round (native + tiered only).
        Returns ``(promoted, demoted)``. Tier moves copy row bytes without
        changing them, so the push-version does NOT bump — cached rows stay
        exactly as fresh as before the move."""
        if self.backend != "native":
            return (0, 0)
        return self._store.tier_maintain(decay, promote_min_freq,
                                         swap_margin, hot_target_rows,
                                         max_moves)

    def tier_stats(self, warm_min_freq: float = 1.0) -> dict:
        """Tier occupancy/counter snapshot (``tiered`` False on the numpy
        backend or before :meth:`tier_enable`)."""
        if self.backend != "native":
            return {"tiered": False, "hot_rows": self._store.size(),
                    "cold_rows": 0, "promotions": 0, "demotions": 0,
                    "cold_hits": 0,
                    "hot_bytes": self._store.size() * self.spec.row_width * 4,
                    "cold_bytes": 0, "warm_cold_rows": 0,
                    "hot_cap_rows": 0}
        return self._store.tier_stats(warm_min_freq)

    def pull(self, ids: np.ndarray) -> np.ndarray:
        """ids of any shape -> float32 values of shape ``ids.shape + (dim,)``."""
        ids = np.asarray(ids)
        flat = np.ascontiguousarray(ids.reshape(-1), np.int64)
        out = np.zeros((len(flat), self.spec.dim), np.float32)
        self._store.pull(flat, out)
        return out.reshape(ids.shape + (self.spec.dim,))

    def push(self, ids: np.ndarray, grads: np.ndarray, scale: float = 1.0) -> None:
        """Apply one sparse optimizer step. ``grads`` shape must be
        ``ids.shape + (dim,)``; duplicates accumulate before the update."""
        ids = np.asarray(ids)
        grads = np.asarray(grads)
        if grads.shape != ids.shape + (self.spec.dim,):
            raise ValueError(
                f"grads shape {grads.shape} != ids {ids.shape} + (dim={self.spec.dim},)"
            )
        flat = np.ascontiguousarray(ids.reshape(-1), np.int64)
        g = np.ascontiguousarray(grads.reshape(len(flat), self.spec.dim), np.float32)
        self._store.push(flat, g, scale)
        self._bump_version()

    def export_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """(ids [n], rows [n, row_width]) — embedding values + optimizer slots."""
        return self._store.export_rows()

    def import_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        if rows.shape[1:] != (self.spec.row_width,):
            raise ValueError(
                f"rows width {rows.shape[1:]} != ({self.spec.row_width},)"
            )
        self._store.import_rows(ids, rows)
        # A restore/migration rewrites row values too — cached copies of
        # the pre-import rows are just as stale as after a push.
        self._bump_version()
