"""Global chip arbiter: ONE pure decision function allocating a fixed chip
supply across N concurrent ElasticJobs by priority (ROADMAP item 5).

Until this module, every Brain policy scoped to ONE job (autoscale its
workers, pick its mesh shape); the chip supply itself was nobody's
decision — N jobs on one substrate would each believe they own the
machine. The arbiter is the missing global half: given every job's claim
(priority, min/max chips, current demand and holding) and the total chip
supply, it computes the target allocation and the bounded set of chip
MOVES that walk the fleet toward it.

Design rules (each one is a drill/sim invariant, not prose):

- **priorities honored** — targets come from a two-pass priority
  water-fill: every job's ``min_chips`` floor first (highest priority
  first when even the floors don't fit), then remaining supply by
  strictly descending priority up to each job's clamped demand. A
  lower-priority job never holds above-floor chips while a higher-
  priority job's demand is unmet.
- **no starvation** — ``min_chips`` is a hard floor: preemption never
  takes a job below it, no matter how hungry a higher-priority job is.
  (A claim declaring ``min_chips=0`` has opted out of the floor — the
  simulator's starvation negative control exploits exactly that.)
- **preemption is strictly upward** — a chip is taken from a donor only
  for a receiver of strictly higher priority; equal-priority jobs can
  never preempt each other (two peers would otherwise ping-pong a chip
  through every demand wobble).
- **hold-down / no-thrash** — both parties of a preemption are frozen
  (neither donates nor receives — not even from the free pool) for
  ``holddown_s``; since every possible A→B→A ping-pong pair has a
  preemption leg, the bounce is structurally impossible inside one
  window, while free-pool grants (which take nothing from anyone) stay
  unthrottled so fleet bootstrap is instant. Preemptions are further
  capped per decision (``max_preemptions_per_decision``) so one scale-up
  burst never drains half the fleet in a single tick — each preempted
  chip pays a real drain, and drains should be paced.

Pure and virtual-clock-pure (easylint rule 5 — this file is in the
simulator's PURE_PATHS set): the caller supplies ``now`` and the
hold-down state; same inputs ⇒ byte-identical decision
(:func:`decision_bytes`). That identity is the drill's offline-replay
acceptance gate: every live decision is logged with its FULL inputs, and
:func:`replay_decision_log` re-derives each verdict through this very
function and byte-compares (chaos/invariants.py ``arbiter_replay``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional, Sequence

__all__ = [
    "ArbiterConfig",
    "GlobalChipArbiter",
    "JobClaim",
    "arbiter_decision",
    "claim_from_dict",
    "decision_bytes",
    "replay_decision_log",
    "target_allocations",
]


@dataclass(frozen=True)
class JobClaim:
    """One job's standing in the arbitration — the CR's scheduling block
    (priority, min/max replicas) plus its live demand and holding."""

    name: str
    #: larger = more important (matches k8s PriorityClass semantics)
    priority: int = 0
    #: hard floor — the no-starvation guarantee; preemption never goes
    #: below it. 0 opts the job out of the floor.
    min_chips: int = 0
    #: cap on what the job may hold (>= min_chips)
    max_chips: int = 1
    #: chips the job wants right now (its plan / autoscaler ask)
    demand: int = 0
    #: chips it currently holds
    allocated: int = 0

    def clamped_demand(self) -> int:
        """Demand folded into the [min_chips, max_chips] envelope."""
        hi = max(self.max_chips, self.min_chips)
        return max(self.min_chips, min(self.demand, hi))

    def to_dict(self) -> Dict[str, int]:
        return {
            "name": self.name, "priority": self.priority,
            "min_chips": self.min_chips, "max_chips": self.max_chips,
            "demand": self.demand, "allocated": self.allocated,
        }


def claim_from_dict(d: Mapping[str, Any]) -> JobClaim:
    return JobClaim(
        name=str(d["name"]), priority=int(d.get("priority", 0)),
        min_chips=int(d.get("min_chips", 0)),
        max_chips=int(d.get("max_chips", 1)),
        demand=int(d.get("demand", 0)),
        allocated=int(d.get("allocated", 0)),
    )


@dataclass(frozen=True)
class ArbiterConfig:
    """Damping knobs. The defaults suit a real fleet where a preempted
    chip pays a multi-second drain; drills/sims shrink them."""

    #: both parties of a preemption are frozen (no further gains OR
    #: losses) for this long — the anti-ping-pong window
    holddown_s: float = 30.0
    #: preemptions (not free-pool grants) per decision
    max_preemptions_per_decision: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return {
            "holddown_s": self.holddown_s,
            "max_preemptions_per_decision":
                self.max_preemptions_per_decision,
        }


def _order(claims: Sequence[JobClaim]) -> List[JobClaim]:
    """Deterministic arbitration order: priority descending, then name —
    byte-identical decisions require a total order over claims."""
    return sorted(claims, key=lambda c: (-c.priority, c.name))


def target_allocations(claims: Sequence[JobClaim],
                       total_chips: int) -> Dict[str, int]:
    """The pure water-fill: floors first (priority order, so an
    infeasible floor set starves the LOWEST priority floors), then
    remaining supply by priority up to each job's clamped demand."""
    alloc: Dict[str, int] = {c.name: 0 for c in claims}
    left = max(0, int(total_chips))
    for c in _order(claims):
        take = min(max(0, c.min_chips), left)
        alloc[c.name] = take
        left -= take
    for c in _order(claims):
        want = c.clamped_demand()
        extra = min(max(0, want - alloc[c.name]), left)
        alloc[c.name] += extra
        left -= extra
    return alloc


def arbiter_decision(claims: Sequence[JobClaim], total_chips: int,
                     now: float,
                     last_move_at: Optional[Mapping[str, float]] = None,
                     config: Optional[ArbiterConfig] = None
                     ) -> Dict[str, Any]:
    """One arbitration round → the canonical decision document.

    Returns::

        {"target": {job: chips},          # the water-fill ideal
         "allocations": {job: chips},     # holdings AFTER the moves
         "grants": [{"to", "chips"}],     # free-pool chips handed out
         "preemptions": [{"from", "to", "chips", "from_priority",
                          "to_priority"}],
         "reclaims": [{"from", "chips"}], # overcommit shed (see below)
         "held": [job, ...],              # frozen by hold-down this round
         "feasible": bool,                # sum of floors fit the supply
         "total_chips", "free_chips", "now"}

    ``grants`` + ``preemptions`` are the moves the caller actuates; a
    preemption means "drain one chip's worth of the donor through the
    preempt-notice path, then hand it to the receiver". The function
    never mutates its inputs — hold-down bookkeeping belongs to the
    caller (:class:`GlobalChipArbiter` for the common case)."""
    cfg = config or ArbiterConfig()
    moves_at = dict(last_move_at or {})
    claims = list(claims)
    by_name = {c.name: c for c in claims}
    target = target_allocations(claims, total_chips)
    feasible = sum(max(0, c.min_chips) for c in claims) <= int(total_chips)
    held = sorted(
        name for name, t in moves_at.items()
        if name in by_name and now - float(t) < cfg.holddown_s
    )
    frozen = set(held)
    free = int(total_chips) - sum(max(0, c.allocated) for c in claims)

    # Working copy of holdings the moves below mutate.
    have = {c.name: max(0, c.allocated) for c in claims}

    grants: List[Dict[str, Any]] = []
    for c in _order(claims):
        if free <= 0:
            break
        if c.name in frozen:
            continue
        need = target[c.name] - have[c.name]
        if need <= 0:
            continue
        take = min(need, free)
        have[c.name] += take
        free -= take
        grants.append({"to": c.name, "chips": take})

    preemptions: List[Dict[str, Any]] = []
    budget = max(0, cfg.max_preemptions_per_decision)
    # Receivers: still under target after the free grants, richest
    # priority first. Donors: above target, POOREST priority first —
    # and strictly below the receiver's priority, never below min.
    receivers = [c for c in _order(claims)
                 if c.name not in frozen and have[c.name] < target[c.name]]
    donors = [c for c in reversed(_order(claims))
              if c.name not in frozen]
    for r in receivers:
        while have[r.name] < target[r.name] and budget > 0:
            donor = next(
                (d for d in donors
                 if d.priority < r.priority
                 and have[d.name] > max(target[d.name], d.min_chips)),
                None,
            )
            if donor is None:
                break
            have[donor.name] -= 1
            have[r.name] += 1
            budget -= 1
            preemptions.append({
                "from": donor.name, "from_priority": donor.priority,
                "to": r.name, "to_priority": r.priority, "chips": 1,
            })
        if budget <= 0:
            break

    # Supply correction: when the fleet transiently holds MORE than the
    # supply (a preemption's receiver leveled up before its donor
    # finished draining — the normal actuation order: grant fast, drain
    # slowly), shed the excess from above-target holdings, poorest
    # priority first. Not paced and hold-down-exempt: each such chip's
    # move was already paced when its preemption was DECIDED — this stage
    # only completes it, and leaving a supply violation open for a whole
    # hold-down window would be worse than the thrash the window guards.
    reclaims: List[Dict[str, Any]] = []
    excess = sum(have.values()) - int(total_chips)
    if excess > 0:
        for c in reversed(_order(claims)):
            while excess > 0 and have[c.name] > target[c.name]:
                have[c.name] -= 1
                excess -= 1
                reclaims.append({"from": c.name, "chips": 1})

    return {
        "now": round(float(now), 6),
        "total_chips": int(total_chips),
        "free_chips": int(total_chips) - sum(have.values()),
        "feasible": feasible,
        "target": {name: int(n) for name, n in sorted(target.items())},
        #: holdings AFTER this round's moves actuate — what the operator
        #: levels pod replicas to and the fleet walks agents toward
        "allocations": {name: int(n) for name, n in sorted(have.items())},
        "grants": grants,
        "preemptions": preemptions,
        "reclaims": reclaims,
        "held": held,
    }


def decision_bytes(decision: Mapping[str, Any]) -> bytes:
    """Canonical serialization — the byte identity the offline replay
    gate (and the determinism tests) are stated over."""
    return json.dumps(decision, sort_keys=True,
                      separators=(",", ":")).encode()


class GlobalChipArbiter:
    """Stateful wrapper owning the hold-down bookkeeping — shared
    VERBATIM between the live fleet (controller/fleet.py, the operator's
    chip-budget leveling) and the offline simulator (sim/multijob.py),
    so the two can never drift. Virtual-clock-pure: every entry point
    takes ``now``."""

    def __init__(self, config: Optional[ArbiterConfig] = None):
        self.config = config or ArbiterConfig()
        #: job -> time of its last chip gain/loss (the hold-down anchor)
        self.last_move_at: Dict[str, float] = {}
        #: decision log records ({"inputs": ..., "verdict": ...}) in
        #: decision order — what the drill writes and the replay re-derives
        self.log: List[Dict[str, Any]] = []

    def decide(self, claims: Sequence[JobClaim], total_chips: int,
               now: float) -> Dict[str, Any]:
        """Arbitrate once; stamps hold-down on both preemption parties and
        appends the full (inputs, verdict) record to :attr:`log`. The
        inputs snapshot is taken BEFORE the stamp — replaying it through
        :func:`arbiter_decision` must reproduce the verdict bytes. The
        decision is computed from the SAME 6-decimal-rounded clock the
        log records (and the stamps store), so the replay is
        self-consistent by construction — deciding on unrounded values
        could flip a hold-down comparison sitting within 1e-6 s of the
        window edge and fail the byte gate on a correct run."""
        now = round(float(now), 6)
        inputs = {
            "claims": [c.to_dict() for c in _order(claims)],
            "total_chips": int(total_chips),
            "now": now,
            "last_move_at": {k: float(v)
                             for k, v in sorted(self.last_move_at.items())},
            "config": self.config.to_dict(),
        }
        decision = arbiter_decision(claims, total_chips, now,
                                    self.last_move_at, self.config)
        # Hold-down anchors on PREEMPTIONS only: a free-pool grant took
        # nothing from anyone (freezing its recipient would stall fleet
        # bootstrap for a whole window), while every possible ping-pong
        # pair has a preemption leg — stamping both of its parties blocks
        # the bounce. Frozen jobs are still excluded from grants, so a
        # just-preempted donor can't refill from the free pool either.
        for p in decision["preemptions"]:
            self.last_move_at[str(p["from"])] = now
            self.last_move_at[str(p["to"])] = now
        self.log.append({"inputs": inputs, "verdict": decision})
        return decision


def replay_decision_log(records: Sequence[Mapping[str, Any]]
                        ) -> Dict[str, Any]:
    """Re-derive every logged verdict from its own recorded inputs
    through the pure function and byte-compare — the offline half of the
    multi-tenant drill's acceptance gate. Returns::

        {"decisions": N, "identical": bool, "mismatches": [...]}
    """
    mismatches: List[Dict[str, Any]] = []
    for i, rec in enumerate(records):
        inputs = dict(rec.get("inputs") or {})
        want = rec.get("verdict")
        cfg_doc = dict(inputs.get("config") or {})
        got = arbiter_decision(
            [claim_from_dict(c) for c in inputs.get("claims", [])],
            int(inputs.get("total_chips", 0)),
            float(inputs.get("now", 0.0)),
            {str(k): float(v)
             for k, v in dict(inputs.get("last_move_at") or {}).items()},
            ArbiterConfig(
                holddown_s=float(cfg_doc.get("holddown_s", 30.0)),
                max_preemptions_per_decision=int(
                    cfg_doc.get("max_preemptions_per_decision", 1)),
            ),
        )
        if want is None or decision_bytes(got) != decision_bytes(want):
            mismatches.append({
                "index": i, "recorded": want, "replayed": got,
            })
    return {
        "decisions": len(records),
        "identical": not mismatches and len(records) > 0,
        "mismatches": mismatches[:5],
    }
