"""Deterministic replay tests for the elastic rendezvous FSM
(SURVEY.md §5.2: deterministic replay of the rendezvous state machine)."""

import itertools

from easydl_tpu.elastic.membership import AgentState, JobPhase, Rendezvous

ports = itertools.count(9000)


def mk(desired=2, **kw):
    return Rendezvous(desired_workers=desired, port_alloc=lambda: next(ports), **kw)


def start_gen(rdv, agents):
    """Register agents and walk them into RUNNING at the current generation."""
    for a in agents:
        rdv.register(a, host="localhost", slots=2)
    for a in agents:
        d = rdv.directive_for(a)
        if d.kind == "run":
            rdv.heartbeat(a, d.generation, "running")
    return rdv.generation


def test_initial_formation():
    rdv = mk(desired=2)
    d0 = rdv.register("a0", "h0", 2)
    # only one agent, min_workers=1 -> forms immediately with world 1
    assert d0.kind == "run" and d0.world_size == 1
    rdv.heartbeat("a0", d0.generation, "running")
    d1 = rdv.register("a1", "h1", 2)
    # second agent arrives -> planned reshape to world 2
    assert rdv.phase == JobPhase.DRAINING
    assert rdv.directive_for("a0").kind == "quiesce"
    rdv.heartbeat("a0", rdv.generation, "quiesced")
    assert rdv.phase == JobPhase.STABLE and rdv.generation == 2
    d0 = rdv.directive_for("a0")
    d1 = rdv.directive_for("a1")
    assert d0.kind == d1.kind == "run"
    assert d0.world_size == 2 and d0.hosts == ("a0", "a1")
    assert d0.coordinator.startswith("h0:")


def test_min_workers_gate():
    rdv = mk(desired=4, min_workers=2)
    d = rdv.register("a0", "h0", 2)
    assert d.kind == "noop" and rdv.phase == JobPhase.INIT
    d = rdv.register("a1", "h1", 2)
    assert d.kind == "run" and d.world_size == 2


def test_scale_up_via_plan():
    rdv = mk(desired=2)
    gen = start_gen(rdv, ["a0", "a1"])
    rdv.register("a2", "h2", 2)
    assert rdv.phase == JobPhase.STABLE  # desired still 2: standby agent
    assert rdv.directive_for("a2").kind == "noop"
    rdv.set_desired_workers(3)
    assert rdv.phase == JobPhase.DRAINING
    for a in ("a0", "a1"):
        assert rdv.directive_for(a).kind == "quiesce"
        rdv.heartbeat(a, gen, "quiesced")
    assert rdv.generation == gen + 1
    d = rdv.directive_for("a2")
    assert d.kind == "run" and d.world_size == 3


def test_scale_down():
    rdv = mk(desired=3)
    gen = start_gen(rdv, ["a0", "a1", "a2"])
    rdv.set_desired_workers(1)
    for a in ("a0", "a1", "a2"):
        if rdv.directive_for(a).kind == "quiesce":
            rdv.heartbeat(a, gen, "quiesced")
    assert rdv.generation == gen + 1
    assert len(rdv.members) == 1
    # the non-members stand by
    standby = [a for a in ("a0", "a1", "a2") if a not in rdv.members]
    assert all(rdv.directive_for(a).kind == "noop" for a in standby)


def test_unplanned_member_loss():
    rdv = mk(desired=2, heartbeat_timeout=0.0)
    gen = start_gen(rdv, ["a0", "a1"])
    # a1 stops heartbeating; tick() with timeout 0 marks everything stale —
    # keep a0 fresh by heartbeating right after tick.
    rdv.agents["a1"].last_heartbeat -= 100.0
    rdv.heartbeat_timeout = 5.0
    rdv.tick()
    assert rdv.agents["a1"].state == AgentState.LOST
    assert rdv.phase == JobPhase.DRAINING
    # survivors get KILL (peers hung in collectives), not graceful quiesce
    assert rdv.directive_for("a0").kind == "kill"
    rdv.heartbeat("a0", gen, "idle")
    assert rdv.phase == JobPhase.STABLE and rdv.generation == gen + 1
    d = rdv.directive_for("a0")
    assert d.kind == "run" and d.world_size == 1 and d.hosts == ("a0",)


def test_worker_crash_triggers_unplanned_reshape():
    rdv = mk(desired=2)
    gen = start_gen(rdv, ["a0", "a1"])
    # a1's worker process dies; agent reports idle at the current generation
    rdv.heartbeat("a1", gen, "idle")
    assert rdv.phase == JobPhase.DRAINING
    assert rdv.directive_for("a0").kind == "kill"
    rdv.heartbeat("a0", gen, "idle")
    # a1's agent is healthy -> rejoins the new generation
    assert rdv.generation == gen + 1 and set(rdv.members) == {"a0", "a1"}


def test_preemption_notice_drains_gracefully():
    rdv = mk(desired=2)
    gen = start_gen(rdv, ["a0", "a1"])
    rdv.register("a2", "h2", 2)  # standby replacement
    rdv.heartbeat("a1", gen, "running", preempting=True)
    assert rdv.phase == JobPhase.DRAINING
    # planned drain: graceful quiesce, zero lost work
    assert rdv.directive_for("a0").kind == "quiesce"
    rdv.heartbeat("a0", gen, "quiesced")
    rdv.heartbeat("a1", gen, "quiesced")
    assert rdv.phase == JobPhase.STABLE
    assert set(rdv.members) == {"a0", "a2"}  # preempting a1 excluded


def test_done_propagates_shutdown():
    rdv = mk(desired=1)
    gen = start_gen(rdv, ["a0"])
    rdv.heartbeat("a0", gen, "done")
    assert rdv.phase == JobPhase.DONE
    assert rdv.directive_for("a0").kind == "shutdown"


def test_generation_run_directive_idempotent():
    rdv = mk(desired=2)
    gen = start_gen(rdv, ["a0", "a1"])
    # running members get noop, not repeated run
    assert rdv.directive_for("a0").kind == "noop"
    status = rdv.status()
    assert status["phase"] == "stable" and len(status["members"]) == 2
