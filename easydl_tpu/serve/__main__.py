"""``python -m easydl_tpu.serve`` — one serving-replica process.

The SIGKILL-able unit of the serve fleet: builds a registry-backed
sharded PS read client (hot-id cached, and — co-located with its
shards — shm/quantized pulls per the ``EASYDL_PS_SHM`` /
``EASYDL_PS_PULL_I8`` knobs), wraps it in a :class:`ServeFrontend`, and
publishes itself for router discovery under ``<workdir>/serve/``. The
chaos fleet drill and ``bench_serve.py --fleet`` launch several of these
and kill them mid-flood; production would run one per pod, exactly like
the PS entrypoint.

The default scorer is the deterministic numpy fallback — scores are a
pure function of the pulled rows, which is what lets the drills verify
freshness BIT-EXACTLY from the outside. ``--deepfm`` swaps in the jitted
model. ``--device-ms`` adds a fixed per-batch service floor standing in
for an accelerator-bound forward on boxes that have none (the fleet
bench's scale-out cells document it).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
import time

from easydl_tpu.ps.client import ShardedPsClient
from easydl_tpu.ps.read_client import PsReadClient
from easydl_tpu.serve.cache import HotIdCache
from easydl_tpu.serve.frontend import (
    ServeConfig,
    ServeFrontend,
    _numpy_forward,
    make_deepfm_forward,
)
from easydl_tpu.utils.logging import get_logger

log = get_logger("serve", "main")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="easydl serving replica")
    ap.add_argument("--workdir", required=True,
                    help="job workdir (PS registry + serve discovery)")
    ap.add_argument("--name", required=True, help="replica name")
    ap.add_argument("--table", required=True)
    ap.add_argument("--fields", type=int, required=True)
    ap.add_argument("--dense-dim", type=int, default=0)
    ap.add_argument("--dim", type=int, default=16,
                    help="embedding dim (deepfm forward only)")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-pending", type=int, default=2048)
    ap.add_argument("--cache-mb", type=int, default=32)
    ap.add_argument("--shards", type=int, default=None,
                    help="PS shard count (default: registry discovery)")
    ap.add_argument("--deepfm", action="store_true",
                    help="jitted DeepFM forward instead of the "
                         "deterministic numpy scorer")
    ap.add_argument("--device-ms", type=float, default=0.0,
                    help="fixed per-batch service floor (simulated "
                         "accelerator time; 0 = none)")
    args = ap.parse_args(argv)

    client = ShardedPsClient.from_registry(
        args.workdir, args.shards, timeout=10.0,
        drain_retry_s=60.0, transient_retry_s=30.0)
    reads = PsReadClient(client, cache=HotIdCache(args.cache_mb << 20))
    if args.deepfm:
        forward = make_deepfm_forward(args.fields, args.dim,
                                      args.dense_dim,
                                      max_batch=args.max_batch)
    else:
        forward = _numpy_forward
    if args.device_ms > 0:
        inner = forward
        floor_s = args.device_ms / 1000.0

        def forward(emb, dense):  # noqa: F811 - deliberate wrap
            t0 = time.monotonic()
            out = inner(emb, dense)
            rest = floor_s - (time.monotonic() - t0)
            if rest > 0:
                time.sleep(rest)
            return out

    frontend = ServeFrontend(
        reads,
        ServeConfig(table=args.table, fields=args.fields,
                    dense_dim=args.dense_dim, max_batch=args.max_batch,
                    max_wait_ms=args.max_wait_ms,
                    max_pending=args.max_pending),
        forward=forward, name=args.name)
    frontend.serve(port=args.port, obs_workdir=args.workdir,
                   obs_name=args.name)

    stop = threading.Event()

    def _sig(_s, _f):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    log.info("serving replica %s up (table %s)", args.name, args.table)
    while not stop.is_set():
        stop.wait(0.5)
    frontend.stop()
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
