"""Sharding rules: logical parameter axes → mesh axes.

Models annotate parameters with *logical* axis names
(``nn.with_logical_partitioning``); one rule table maps those names onto the
mesh axes of :mod:`easydl_tpu.core.mesh`. Changing a job from pure DP to
FSDP+TP is a rule/mesh change only — no model edits — which is exactly what
elastic resharding needs: the master rebuilds the mesh at a new world size and
re-derives every sharding from the same rules.

For models without annotations (plain flax params), :func:`infer_shardings`
applies a size-threshold FSDP heuristic.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import numpy as np
from flax import traverse_util
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: logical axis → mesh axis (or tuple of mesh axes, or None = replicated).
#: The vocabulary follows the t5x/maxtext convention.
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp")),
    ("embed", "fsdp"),          # d_model dim of weights: sharded for FSDP
    ("mlp", "tp"),              # FFN hidden dim
    ("heads", "tp"),            # attention heads
    ("kv", None),               # per-head dim: replicated
    ("qkv", "tp"),
    ("vocab", "tp"),
    ("seq", "sp"),              # sequence dim of activations
    ("expert", "ep"),
    ("conv_in", None),
    ("conv_out", "fsdp"),
    ("stage", "pp"),
    ("layers", None),           # nn.scan'd block axis (stacked layer params)
    ("table", None),            # sparse embedding tables live on host PS
    ("table_vocab", "fsdp"),    # on-device embedding tables: shard the vocab dim
)


def logical_axis_rules(rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES):
    """Context manager enabling the rules for flax's spmd machinery."""
    return nn.spmd.logical_axis_rules(rules)


def mesh_sharding(mesh: Mesh, spec: Optional[P]) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None else P())


def state_shardings(
    abstract_state: Any,
    mesh: Mesh,
    rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES,
) -> Any:
    """NamedSharding tree for a (possibly nn.Partitioned-annotated) state tree.

    ``abstract_state`` is typically the result of ``jax.eval_shape`` over the
    init function, with flax ``Partitioned`` metadata boxes intact.
    """
    logical_specs = nn.get_partition_spec(abstract_state)
    return nn.logical_to_mesh_sharding(logical_specs, mesh, list(rules))


def infer_shardings(
    params: Any,
    mesh: Mesh,
    axis: str = "fsdp",
    min_size: int = 2**14,
) -> Any:
    """FSDP heuristic for unannotated params: shard the largest dimension that
    divides evenly by ``mesh.shape[axis]``; small params stay replicated."""
    n = mesh.shape[axis]

    def spec_for(x) -> NamedSharding:
        shape = getattr(x, "shape", ())
        if n > 1 and np.prod(shape, dtype=np.int64) >= min_size:
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for dim in order:
                if shape[dim] % n == 0:
                    spec = [None] * len(shape)
                    spec[dim] = axis
                    return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec_for, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [global_batch, ...] input: batch over the dp axes."""
    return NamedSharding(mesh, P(("dp", "fsdp")))


def unbox(tree: Any) -> Any:
    """Strip flax ``Partitioned`` metadata boxes, keeping raw arrays."""
    return nn.meta.unbox(tree)


def flatten_dict(params: Any) -> dict:
    if isinstance(params, FrozenDict):
        params = params.unfreeze()
    return {"/".join(map(str, k)): v for k, v in traverse_util.flatten_dict(params).items()}


def unflatten_dict(flat: dict) -> dict:
    return traverse_util.unflatten_dict({tuple(k.split("/")): v for k, v in flat.items()})
