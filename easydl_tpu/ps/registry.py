"""Shard-address registry for PS pods (file-based service discovery).

The operator creates/retires PS pods by *name* (replace-then-retire,
docs/design/elastic-training-operator.md:86-101) and knows nothing about
shards; clients route by *shard index*. This registry is the join between
the two worlds: every PS pod publishes one JSON file
``<workdir>/ps/ps-<pod>.json`` with its shard index, address, a publish
timestamp — and, since the WAL/fencing PR, the shard *epoch* and the
publishing pid. Readers resolve "who serves shard i" as the
highest-epoch (then latest) publication for that shard — a replacement
pod publishes only after it has drained its predecessor and restored the
rows, so the newest entry is by construction the authoritative one.

The epoch is the fencing token: a strictly monotonic per-shard counter
kept in ``epoch-shard-<i>.json`` and advanced under an exclusive flock
(:func:`bump_epoch`) by every pod that takes the shard over. It survives
entry sweeps and workdir reuse, so a zombie predecessor can always be
recognised as superseded — the server rejects pushes whose stamped epoch
does not match its own (ps/server.py), and fences itself permanently on
proof of a successor.

Since the live-resharding PR the registry also holds the **routing
table** (``routing.json``): the committed ``(generation, num_shards)``
pair every client routes by, plus — while a reshard is in flight — the
migration *plan* (target shard count, the new generation, the claiming
coordinator). Publications carry the generation they serve, and
:func:`shard_map` filters to the committed generation, so a half-built
destination shard set is invisible to clients until the coordinator
commits the cutover — and a superseded source set becomes invisible the
instant it does. The per-shard epoch counters are shared across
generations (one monotonic lineage per shard *index*), which is what
lets the same fencing machinery arbitrate a source, its rescuer, and
the destination that inherits the index.

Atomic single-file writes (tmp + rename) on a shared workdir for the
entries; the epoch counter and the routing table are the pieces that
genuinely need read-modify-write, so they reuse the in-place flock idiom
of the claim files (stable inode — a rename-based update would drop the
lock's protection).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

from easydl_tpu.utils.logging import get_logger

log = get_logger("ps", "registry")

REG_DIR = "ps"


def locked_mutate(path: str, mutate) -> dict:
    """Read-check-write a JSON doc atomically under an exclusive flock.

    ``mutate(doc) -> new_doc | None`` runs with the lock held; None leaves
    the file unchanged. The file's inode is stable (in-place truncate +
    write, never os.replace), so the flock actually serializes every
    writer. Returns the doc now in the file; a missing file returns {}.
    Shared by the shard-claim files (ps/__main__.py) and the epoch
    counter below."""
    import fcntl

    try:
        with open(path, "r+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                try:
                    doc = json.load(f)
                except ValueError:
                    doc = {}  # torn write from a crashed claimant
                new = mutate(doc)
                if new is not None:
                    f.seek(0)
                    f.truncate()
                    json.dump(new, f)
                    f.flush()
                    os.fsync(f.fileno())
                return new if new is not None else doc
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)
    except FileNotFoundError:
        return {}


def _dir(workdir: str) -> str:
    return os.path.join(workdir, REG_DIR)


def publish(workdir: str, pod: str, shard: int, num_shards: int,
            address: str, epoch: int = 0, generation: int = 0) -> str:
    """Publish/overwrite this pod's registry entry; returns the file path.

    ``epoch`` is the fencing token from :func:`bump_epoch`; 0 means the
    publisher predates fencing (readers treat it as the lowest epoch).
    ``generation`` is the routing-table generation this pod serves
    (:func:`generation_for_publication`); readers resolve shards within
    ONE generation, so a reshard's destination set stays invisible to
    clients until the coordinator commits the new generation."""
    os.makedirs(_dir(workdir), exist_ok=True)
    path = os.path.join(_dir(workdir), f"ps-{pod}.json")
    doc = {
        "pod": pod,
        "shard": int(shard),
        "num_shards": int(num_shards),
        "address": address,
        "epoch": int(epoch),
        "generation": int(generation),
        "pid": os.getpid(),
        "published_at": time.time(),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def bump_epoch(workdir: str, shard: int) -> int:
    """Advance and return the shard's fencing epoch (first call returns 1).

    Strictly monotonic across pod restarts, entry sweeps and workdir reuse:
    the counter lives in its own flock-serialized file, never in the
    publications (which are swept when their pod dies). Two pods that both
    bump get DISTINCT epochs — the claim file decides who may publish, the
    epoch decides who the servers obey; a wasted bump by a loser is
    harmless."""
    d = _dir(workdir)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"epoch-shard-{int(shard)}.json")
    try:  # O_EXCL create so the first bump has a file to flock
        os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except FileExistsError:
        pass
    doc = locked_mutate(
        path, lambda doc: {"epoch": int(doc.get("epoch", 0)) + 1}
    )
    return int(doc["epoch"])


def shard_epoch(workdir: str, shard: int) -> int:
    """Current fencing epoch for a shard (0 = never bumped). Read under the
    same flock writers hold."""
    path = os.path.join(_dir(workdir), f"epoch-shard-{int(shard)}.json")
    return int(locked_mutate(path, lambda doc: None).get("epoch", 0))


# ------------------------------------------------------------ routing table
#: The one file clients route by: committed ``generation``/``num_shards``
#: plus, while a reshard is in flight, the migration ``plan``. Lives next
#: to the publications; mutated only under its flock (locked_mutate).
ROUTING_FILE = "routing.json"


def _routing_path(workdir: str) -> str:
    return os.path.join(_dir(workdir), ROUTING_FILE)


def routing_table(workdir: str) -> dict:
    """The routing doc as-is ({} when the job predates routing tables —
    readers then treat the committed generation as 0)."""
    return locked_mutate(_routing_path(workdir), lambda doc: None)


def committed_generation(workdir: str) -> int:
    return int(routing_table(workdir).get("generation", 0))


def generation_for_publication(workdir: str, num_shards: int,
                               dest: bool = False) -> int:
    """Which generation a pod serving ``num_shards`` shards publishes
    under. ``dest`` is the pod's EXPLICIT destination role
    (``--reshard-dest``): only a declared destination may publish under
    an in-flight plan's generation — shard-count coincidence must not be
    enough, or an ordinary pod whose count happens to equal a later
    plan's target (a 4→2 shrink while generation-0 ran 2 shards) would
    silently publish into the uncommitted destination set, un-gated.

    Non-destination pods always publish under the committed generation.
    A destination publishes under the matching in-flight plan's
    generation; after the commit (e.g. a destination pod restarting) the
    committed generation IS its generation — matched by shard count.
    Anything else is a config error and raises."""
    doc = routing_table(workdir)
    plan = doc.get("plan")
    if not dest:
        return int(doc.get("generation", 0))
    if plan and int(plan.get("to_shards", -1)) == int(num_shards):
        return int(plan["generation"])
    if int(doc.get("num_shards", 0)) == int(num_shards):
        return int(doc.get("generation", 0))
    raise ValueError(
        f"reshard destination serving {num_shards} shards matches neither "
        f"the in-flight plan ({plan and plan.get('to_shards')}) nor the "
        f"committed routing ({doc.get('num_shards')})")


def begin_reshard(workdir: str, from_shards: int, to_shards: int,
                  owner: str, stale_s: float = 600.0) -> Optional[dict]:
    """Claim the (single) reshard slot and write the migration plan:
    generation ``committed+1``, target ``to_shards``. Returns the plan
    dict, or None when another coordinator's plan is active. A plan whose
    ``t`` is older than ``stale_s`` with no commit is presumed abandoned
    (the coordinator died mid-migration) and stolen — the age re-check and
    the overwrite are one atomic mutation under the routing flock, the
    same discipline as the shard-claim files."""
    if int(to_shards) <= 0:
        raise ValueError(f"to_shards must be positive, got {to_shards}")
    if int(to_shards) == int(from_shards):
        raise ValueError(
            f"reshard {from_shards}->{to_shards} is a no-op (and would make "
            "the destination set indistinguishable from the source set)")
    path = _routing_path(workdir)
    os.makedirs(_dir(workdir), exist_ok=True)
    try:  # O_EXCL create so the first plan has a file to flock
        os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except FileExistsError:
        pass
    out: Dict[str, Optional[dict]] = {"plan": None}

    def mutate(doc):
        plan = doc.get("plan")
        if plan and time.time() - float(plan.get("t", 0)) <= stale_s:
            return None  # an active migration owns the slot
        gen = int(doc.get("generation", 0))
        committed = int(doc.get("num_shards") or from_shards)
        out["plan"] = {
            "generation": gen + 1,
            "from_shards": committed,
            "to_shards": int(to_shards),
            "owner": owner,
            "t": time.time(),
        }
        return {"generation": gen, "num_shards": committed,
                "plan": out["plan"]}

    locked_mutate(path, mutate)
    if out["plan"] is not None:
        log.info("reshard plan claimed by %r: %d -> %d shards (generation "
                 "%d)", owner, out["plan"]["from_shards"],
                 out["plan"]["to_shards"], out["plan"]["generation"])
    return out["plan"]


def touch_reshard(workdir: str, owner: str) -> bool:
    """Refresh the in-flight plan's timestamp — the coordinator's
    liveness heartbeat, the same role claim_heartbeat plays for shard
    claims. Without it a healthy migration whose phase budgets sum past
    ``stale_s`` would be stolen mid-flight, and the loser's rollback
    would un-gate sources the thief already cut over. Owner-checked;
    returns False (without touching anything) when the plan is gone or
    stolen — the next owner-checked operation will fail loudly."""
    touched: Dict[str, bool] = {"v": False}

    def mutate(doc):
        plan = doc.get("plan")
        if not plan or plan.get("owner") != owner:
            return None
        plan["t"] = time.time()
        touched["v"] = True
        return doc

    locked_mutate(_routing_path(workdir), mutate)
    return touched["v"]


def commit_reshard(workdir: str, owner: str) -> dict:
    """Atomically switch the committed routing to the plan's generation /
    shard count — the cutover instant every client converges on. Only the
    plan's owner may commit; raises on a lost/stolen plan rather than
    committing someone else's migration."""
    state: Dict[str, object] = {}

    def mutate(doc):
        plan = doc.get("plan")
        if not plan or plan.get("owner") != owner:
            state["error"] = (f"no reshard plan owned by {owner!r} "
                              f"(found {plan!r})")
            return None
        new = {"generation": int(plan["generation"]),
               "num_shards": int(plan["to_shards"])}
        state["doc"] = new
        return new

    locked_mutate(_routing_path(workdir), mutate)
    if "error" in state:
        raise RuntimeError(f"commit_reshard: {state['error']}")
    log.info("reshard committed: routing generation %d, %d shards",
             state["doc"]["generation"], state["doc"]["num_shards"])
    return state["doc"]  # type: ignore[return-value]


def abort_reshard(workdir: str, owner: str) -> bool:
    """Drop an in-flight plan (rollback: the committed routing is
    untouched, clients never left the source set). Owner-checked; returns
    True when a plan was actually dropped."""
    dropped: Dict[str, bool] = {"v": False}

    def mutate(doc):
        plan = doc.get("plan")
        if not plan or plan.get("owner") != owner:
            return None
        dropped["v"] = True
        return {k: v for k, v in doc.items() if k != "plan"}

    locked_mutate(_routing_path(workdir), mutate)
    if dropped["v"]:
        log.warning("reshard plan owned by %r aborted; committed routing "
                    "unchanged", owner)
    return dropped["v"]


def _published_by_dead_local_pid(doc: dict) -> bool:
    """True when the entry's publisher is provably dead: a single-host
    (``localhost``) publication whose recorded pid no longer exists. Any
    doubt (other host, no pid, permissions) reads as alive — the filter
    must never hide a live shard."""
    try:
        addr = str(doc.get("address", ""))
        pid = int(doc.get("pid", 0))
        if not addr.startswith("localhost:") or pid <= 0:
            return False
        if pid == os.getpid():
            return False
        os.kill(pid, 0)
        return False
    except ProcessLookupError:
        return True
    except (OSError, ValueError, PermissionError, TypeError):
        return False  # alive-but-not-ours, or malformed: leave it


def sweep_stale(workdir: str) -> int:
    """Drop publications whose publishing process is dead; returns the
    number removed.

    Mirrors the obs-exporter discovery sweep (obs/exporter.py): a
    SIGKILLed pod never retracts its entry, so a reused workdir
    accumulates dead addresses that rescue discovery must probe (paying a
    timeout per ghost) and that a client reroute could briefly adopt.
    Only single-host publications (advertised as ``localhost``) with a
    recorded pid are swept — a pid check is meaningless for another
    host's process. Epoch counters and claim files are never touched (the
    fencing history must survive the sweep)."""
    removed = 0
    d = _dir(workdir)
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for name in names:
        if not (name.startswith("ps-") and name.endswith(".json")):
            continue
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # torn file: leave it
        if _published_by_dead_local_pid(doc):
            try:
                os.remove(path)
                removed += 1
                log.info("swept stale ps publication %s (pid dead)", name)
            except OSError:
                pass
    return removed


def entries(workdir: str) -> Dict[str, dict]:
    """All registry entries keyed by pod name (unreadable files skipped)."""
    out: Dict[str, dict] = {}
    try:
        names = os.listdir(_dir(workdir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("ps-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(_dir(workdir), name)) as f:
                doc = json.load(f)
            out[doc["pod"]] = doc
        except (OSError, ValueError, KeyError):
            continue  # torn write in progress; next read sees it
    return out


def entry_for_pod(workdir: str, pod: str) -> Optional[dict]:
    return entries(workdir).get(pod)


def shard_map(workdir: str,
              generation: Optional[int] = None) -> Dict[int, dict]:
    """shard index -> the authoritative entry for the shard, within ONE
    routing generation (default: the committed one — mid-reshard that is
    still the source set, so clients never adopt a half-built destination
    shard). Within the generation the highest epoch wins (the fencing
    order), publish time breaks ties among epoch-less legacy entries.
    Entries whose publishing process is provably dead (localhost pid gone)
    are filtered at read time: ``sweep_stale`` only runs at pod startup,
    and a reroute mid-job must never adopt a ghost."""
    if generation is None:
        generation = committed_generation(workdir)
    latest: Dict[int, dict] = {}
    for doc in entries(workdir).values():
        if int(doc.get("generation", 0)) != int(generation):
            continue
        if _published_by_dead_local_pid(doc):
            continue
        s = int(doc["shard"])
        key = (int(doc.get("epoch", 0)), doc["published_at"])
        if s not in latest or key > (int(latest[s].get("epoch", 0)),
                                     latest[s]["published_at"]):
            latest[s] = doc
    return latest


def discover(workdir: str, timeout: float = 120.0) -> Tuple[int, Tuple[str, ...]]:
    """Learn the cluster shape from the registry itself: wait (one deadline)
    until the shape is known — the routing table's committed ``num_shards``
    when one exists, else some pod's published ``num_shards`` — and every
    shard of that count is present in the committed generation. Returns
    (num_shards, addresses)."""
    deadline = time.monotonic() + timeout
    while True:
        m = shard_map(workdir)
        if m:
            n = int(routing_table(workdir).get("num_shards", 0) or
                    max(int(d["num_shards"]) for d in m.values()))
            if all(s in m for s in range(n)):
                return n, tuple(m[s]["address"] for s in range(n))
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"ps registry under {workdir} incomplete after {timeout:.0f}s"
                f" ({len(m)} live publication(s))"
            )
        time.sleep(0.1)


def addresses(workdir: str, num_shards: int,
              timeout: float = 0.0) -> Tuple[str, ...]:
    """Shard-ordered address tuple; with ``timeout`` waits for completeness.

    Raises TimeoutError when shards are still missing after the wait — a
    cluster that never fully published is a deployment error, not a routing
    table."""
    deadline = time.monotonic() + timeout
    while True:
        m = shard_map(workdir)
        if all(s in m for s in range(num_shards)):
            return tuple(m[s]["address"] for s in range(num_shards))
        if time.monotonic() >= deadline:
            missing = [s for s in range(num_shards) if s not in m]
            raise TimeoutError(
                f"ps registry incomplete: shards {missing} unpublished"
            )
        time.sleep(0.1)
