"""Sparse embedding tables: ctypes front-end over the C++ store, with a
bit-compatible numpy fallback.

The table is the unit the PS serves (reference PS role,
docs/design/elastic-training-operator.md:39-40). Rows materialise lazily with
a deterministic per-id init (splitmix64 of ``seed ^ id``), so any shard
layout — or a restore onto a different shard count — produces identical
parameters for the same ids.

Optimizers live *in* the table (classic PS design): ``push`` applies a sparse
SGD/Adagrad update; duplicate ids within one push accumulate first, matching
the dense scatter-add gradient semantics of the on-device embedding path
(easydl_tpu/models/deepfm.py DeviceEmbedding).
"""

from __future__ import annotations

import ctypes
import threading
from dataclasses import dataclass
from typing import Tuple

import numpy as np

from easydl_tpu.ps import build as _build

OPTIMIZERS = {"sgd": 0, "adagrad": 1}

_SQRT3 = np.float32(1.7320508075688772)
_U24 = np.float32(1.0 / 16777216.0)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorised splitmix64 — identical to the C++ core's."""
    x = np.asarray(x).astype(np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def shard_of(ids: np.ndarray, num_shards: int) -> np.ndarray:
    """Which PS shard owns each id. Hash-based (not modulo on the raw id) so
    skewed id spaces still balance."""
    return (splitmix64(ids) % np.uint64(num_shards)).astype(np.int64)


@dataclass(frozen=True)
class TableSpec:
    name: str
    dim: int
    init_std: float = 0.01
    seed: int = 0
    optimizer: str = "adagrad"
    lr: float = 0.05
    eps: float = 1e-8

    def __post_init__(self):
        if self.optimizer not in OPTIMIZERS:
            raise ValueError(f"unknown optimizer {self.optimizer!r}")

    @property
    def row_width(self) -> int:
        return 2 * self.dim if self.optimizer == "adagrad" else self.dim


class _NumpyStore:
    """Fallback store; same math as embedding_store.cc, pure numpy.

    One coarse lock stands in for the C++ store's stripe locks: the gRPC
    shard serves pulls/pushes from a thread pool, so the fallback must be
    just as safe under concurrent workers (it only trades throughput)."""

    def __init__(self, spec: TableSpec):
        self.spec = spec
        self._rows: dict = {}
        self._mu = threading.Lock()

    def _init_row(self, id_: int) -> np.ndarray:
        base = splitmix64(np.uint64(self.spec.seed) ^ np.uint64(np.int64(id_)))
        with np.errstate(over="ignore"):
            bits = splitmix64(base + np.arange(self.spec.dim, dtype=np.uint64))
        u = (bits >> np.uint64(40)).astype(np.float32) * _U24
        a = np.float32(self.spec.init_std) * _SQRT3
        row = np.zeros(self.spec.row_width, np.float32)
        row[: self.spec.dim] = (np.float32(2.0) * u - np.float32(1.0)) * a
        return row

    def _row(self, id_: int) -> np.ndarray:
        r = self._rows.get(id_)
        if r is None:
            r = self._rows[id_] = self._init_row(id_)
        return r

    def pull(self, ids: np.ndarray, out: np.ndarray) -> None:
        dim = self.spec.dim
        with self._mu:
            for i, id_ in enumerate(ids):
                out[i] = self._row(int(id_))[:dim]

    def push(self, ids: np.ndarray, grads: np.ndarray, scale: float) -> None:
        spec = self.spec
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((len(uniq), spec.dim), np.float32)
        np.add.at(acc, inv, grads)
        lr, eps = np.float32(spec.lr), np.float32(spec.eps)
        with self._mu:
            for u, id_ in enumerate(uniq):
                row = self._row(int(id_))
                g = acc[u] * np.float32(scale)
                if spec.optimizer == "adagrad":
                    slot = row[spec.dim:]
                    slot += g * g
                    row[: spec.dim] -= lr * g / (np.sqrt(slot) + eps)
                else:
                    row[: spec.dim] -= lr * g

    def size(self) -> int:
        with self._mu:
            return len(self._rows)

    def export_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        with self._mu:
            n = len(self._rows)
            ids = np.fromiter(self._rows.keys(), np.int64, n)
            rows = np.stack([self._rows[int(i)] for i in ids]) if n else np.zeros(
                (0, self.spec.row_width), np.float32
            )
        return ids, rows

    def import_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        with self._mu:
            for i, id_ in enumerate(ids):
                self._rows[int(id_)] = rows[i].astype(np.float32).copy()


class _NativeStore:
    """ctypes wrapper over the C++ store."""

    def __init__(self, spec: TableSpec, lib: ctypes.CDLL):
        self.spec = spec
        self._lib = lib
        self._h = lib.eds_create(
            spec.dim,
            ctypes.c_float(spec.init_std),
            ctypes.c_uint64(np.uint64(spec.seed)),
            OPTIMIZERS[spec.optimizer],
            ctypes.c_float(spec.lr),
            ctypes.c_float(spec.eps),
        )

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.eds_destroy(h)

    @staticmethod
    def _i64p(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

    @staticmethod
    def _f32p(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    def pull(self, ids: np.ndarray, out: np.ndarray) -> None:
        self._lib.eds_pull(self._h, self._i64p(ids), len(ids), self._f32p(out))

    def push(self, ids: np.ndarray, grads: np.ndarray, scale: float) -> None:
        grads = np.ascontiguousarray(grads, np.float32)
        self._lib.eds_push(
            self._h, self._i64p(ids), len(ids), self._f32p(grads), ctypes.c_float(scale)
        )

    def size(self) -> int:
        return self._lib.eds_size(self._h)

    def export_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        # eds_export_snapshot sizes and exports under one exclusive barrier,
        # so the result is a consistent point-in-time snapshot even while
        # workers keep pushing; retry only when rows materialised between our
        # capacity estimate and the barrier acquisition (rare).
        n = max(self.size(), 1)
        while True:
            ids = np.zeros(n, np.int64)
            rows = np.zeros((n, self.spec.row_width), np.float32)
            true_size = np.zeros(1, np.int64)
            written = self._lib.eds_export_snapshot(
                self._h, self._i64p(ids), self._f32p(rows), n,
                self._i64p(true_size),
            )
            if true_size[0] <= n:
                return ids[:written], rows[:written]
            n = int(true_size[0])

    def import_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64)
        rows = np.ascontiguousarray(rows, np.float32)
        self._lib.eds_import(self._h, self._i64p(ids), self._f32p(rows), len(ids))


class EmbeddingTable:
    """One named table. ``backend`` is ``"auto"`` (native if buildable),
    ``"native"`` (require C++), or ``"numpy"``."""

    def __init__(self, spec: TableSpec, backend: str = "auto"):
        self.spec = spec
        lib = _build.load_native() if backend in ("auto", "native") else None
        if backend == "native" and lib is None:
            raise RuntimeError("native embedding store requested but unavailable")
        self._store = _NativeStore(spec, lib) if lib is not None else _NumpyStore(spec)
        self.backend = "native" if lib is not None else "numpy"

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def dim(self) -> int:
        return self.spec.dim

    @property
    def rows(self) -> int:
        return self._store.size()

    def pull(self, ids: np.ndarray) -> np.ndarray:
        """ids of any shape -> float32 values of shape ``ids.shape + (dim,)``."""
        ids = np.asarray(ids)
        flat = np.ascontiguousarray(ids.reshape(-1), np.int64)
        out = np.zeros((len(flat), self.spec.dim), np.float32)
        self._store.pull(flat, out)
        return out.reshape(ids.shape + (self.spec.dim,))

    def push(self, ids: np.ndarray, grads: np.ndarray, scale: float = 1.0) -> None:
        """Apply one sparse optimizer step. ``grads`` shape must be
        ``ids.shape + (dim,)``; duplicates accumulate before the update."""
        ids = np.asarray(ids)
        grads = np.asarray(grads)
        if grads.shape != ids.shape + (self.spec.dim,):
            raise ValueError(
                f"grads shape {grads.shape} != ids {ids.shape} + (dim={self.spec.dim},)"
            )
        flat = np.ascontiguousarray(ids.reshape(-1), np.int64)
        g = np.ascontiguousarray(grads.reshape(len(flat), self.spec.dim), np.float32)
        self._store.push(flat, g, scale)

    def export_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """(ids [n], rows [n, row_width]) — embedding values + optimizer slots."""
        return self._store.export_rows()

    def import_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        if rows.shape[1:] != (self.spec.row_width,):
            raise ValueError(
                f"rows width {rows.shape[1:]} != ({self.spec.row_width},)"
            )
        self._store.import_rows(ids, rows)
