"""Declarative chaos scenarios → deterministic fault timelines.

The north-star claim ("survives preemption, auto-recovers") was backed by
exactly one hand-rolled SIGKILL in scripts/measure_recovery.py; everything
else — RPC loss, agent hangs, checkpoint corruption, PS-shard crashes,
stragglers — was unexercised and unasserted. This module is the declarative
half of the chaos subsystem (docs/design/chaos.md): a :class:`ChaosSpec`
lists *faults* (what, roughly when, against whom), and
:func:`compile_schedule` resolves them — through a PRNG seeded ONLY by the
spec's seed — into a concrete, sorted timeline of *events*. Same spec + same
seed ⇒ byte-identical schedule (asserted by tests/test_chaos.py), so a
failing drill is replayable, Jepsen-style, from its seed alone.

The compiled schedule is a plain JSON document; the harness writes it to
``<workdir>/chaos-plan.json``, points ``EASYDL_CHAOS_SPEC`` at it, and stamps
``t0`` (wall clock) once the job reaches steady state. Every event window is
``[t0+start_s, t0+end_s)``. Until ``t0`` is stamped the plan is inert even
with the env var set — processes can start in any order.

Two classes of event kind:

- **inline** (consulted by in-process injectors at their hook points):
  ``rpc_drop``, ``rpc_delay``, ``rpc_error``, ``heartbeat_suppress``,
  ``straggler``, ``ckpt_corrupt_write``.
- **process** (executed by the harness at the scheduled offset, through the
  agent / controller process APIs): ``worker_kill``, ``worker_pause``,
  ``agent_stop``, ``ps_kill``, ``corrupt_latest_ckpt``, ``master_crash``
  (stop the control plane abruptly; a fresh Master restarts over the same
  workdir after ``restart_after_s``), ``preempt_notice`` (deliver the cloud
  preemption notice to an agent).
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

#: Kinds the in-process injectors act on at their hook points.
INLINE_KINDS = frozenset({
    "rpc_drop", "rpc_delay", "rpc_error",
    "heartbeat_suppress", "straggler", "ckpt_corrupt_write",
})
#: Kinds the harness executes itself (process-level faults).
PROCESS_KINDS = frozenset({
    "worker_kill", "worker_pause", "agent_stop", "ps_kill", "ps_pause",
    "corrupt_latest_ckpt", "master_crash", "preempt_notice",
})
ALL_KINDS = INLINE_KINDS | PROCESS_KINDS


@dataclass(frozen=True)
class FaultSpec:
    """One declared fault.

    ``at_s`` is the nominal offset from the scenario's t0; ``jitter_s`` lets
    the compiler smear it by a seeded-uniform draw in ``[0, jitter_s)`` so a
    scenario family can explore timings without losing replayability.
    ``target`` narrows where the fault applies (keys the hook points match
    on: ``agent``, ``rank``, ``service``, ``method``, ``side``, ``shard``,
    ``path_contains``); ``params`` carries kind-specific knobs (``p``,
    ``delay_s``, ``sleep_s``, ``mode``, ``respawn_after_s``, ...)."""

    kind: str
    at_s: float
    duration_s: float = 0.0
    jitter_s: float = 0.0
    target: Mapping[str, Any] = field(default_factory=dict)
    params: Mapping[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.kind not in ALL_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (known: {sorted(ALL_KINDS)})"
            )
        if self.at_s < 0 or self.duration_s < 0 or self.jitter_s < 0:
            raise ValueError("at_s/duration_s/jitter_s must be >= 0")
        if self.kind in INLINE_KINDS and self.duration_s <= 0:
            # inline faults fire only while their window is OPEN; a
            # zero-length window compiles fine and then silently injects
            # nothing — the spec must reject it where the author typed it
            raise ValueError(
                f"inline fault {self.kind!r} needs duration_s > 0 "
                "(a zero-length window never fires)"
            )


@dataclass(frozen=True)
class ChaosSpec:
    """A named scenario: seed + fault list (declaration order is part of the
    identity — the compiler consumes PRNG draws in that order)."""

    name: str
    seed: int
    faults: Tuple[FaultSpec, ...] = ()
    notes: str = ""

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "seed": self.seed,
            "notes": self.notes,
            "faults": [
                {
                    "kind": f.kind,
                    "at_s": f.at_s,
                    "duration_s": f.duration_s,
                    "jitter_s": f.jitter_s,
                    "target": dict(f.target),
                    "params": dict(f.params),
                }
                for f in self.faults
            ],
        }

    @classmethod
    def from_json(cls, doc: Mapping[str, Any]) -> "ChaosSpec":
        return cls(
            name=str(doc["name"]),
            seed=int(doc["seed"]),
            notes=str(doc.get("notes", "")),
            faults=tuple(
                FaultSpec(
                    kind=str(f["kind"]),
                    at_s=float(f["at_s"]),
                    duration_s=float(f.get("duration_s", 0.0)),
                    jitter_s=float(f.get("jitter_s", 0.0)),
                    target=dict(f.get("target", {})),
                    params=dict(f.get("params", {})),
                )
                for f in doc.get("faults", [])
            ),
        )


def compile_schedule(spec: ChaosSpec) -> Dict[str, Any]:
    """Resolve a spec into the concrete event timeline.

    Deterministic by construction: the ONLY entropy source is
    ``random.Random(spec.seed)``, consumed in fault-declaration order (one
    draw per fault, jittered or not, so adding jitter to one fault never
    shifts another's draw). Events are sorted by (start_s, id) and carry a
    stable integer id — probability decisions at injection time are hashed
    off (seed, event id, call index), never off wall clock."""
    rng = random.Random(spec.seed)
    events: List[Dict[str, Any]] = []
    for i, f in enumerate(spec.faults):
        jitter = rng.random() * f.jitter_s  # one draw per fault, always
        start = round(f.at_s + jitter, 6)
        events.append({
            "id": i,
            "kind": f.kind,
            "start_s": start,
            "end_s": round(start + f.duration_s, 6),
            "target": dict(f.target),
            "params": dict(f.params),
        })
    events.sort(key=lambda e: (e["start_s"], e["id"]))
    return {
        "scenario": spec.name,
        "seed": spec.seed,
        "t0": None,  # stamped by the harness at steady state
        "events": events,
    }


def schedule_bytes(schedule: Mapping[str, Any]) -> bytes:
    """Canonical serialization — the byte-identity the determinism contract
    (and its test) is stated over."""
    return json.dumps(schedule, sort_keys=True,
                      separators=(",", ":")).encode()


def process_events(schedule: Mapping[str, Any]) -> List[Dict[str, Any]]:
    """The harness-executed subset, in timeline order."""
    return [e for e in schedule["events"] if e["kind"] in PROCESS_KINDS]


def inline_events(schedule: Mapping[str, Any]) -> List[Dict[str, Any]]:
    return [e for e in schedule["events"] if e["kind"] in INLINE_KINDS]
