"""Process-environment recipes shared across subprocess launchers."""

from __future__ import annotations

import os
from typing import Dict, Mapping, Optional


def env_flag(name: str, default: bool) -> bool:
    """Boolean EASYDL_* knob convention: unset → ``default``; ``"0"``,
    ``"false"``/``"False"`` and empty mean off; anything else means on."""
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("", "0", "false", "False")


def obs_port_from_env(component: str, default: int = 0):
    """Resolve a service's metrics-exporter port from the environment.

    Precedence: ``EASYDL_METRICS_PORT_<COMPONENT>`` (component upper-cased,
    non-alnum → ``_``) > ``EASYDL_METRICS_PORT`` > ``default`` (0 = pick a
    free port). ``off``/``disabled``/negative disables the exporter —
    returns None. Unparseable values fall back to the default rather than
    killing the service: observability must never be load-bearing."""
    key = "EASYDL_METRICS_PORT_" + "".join(
        c if c.isalnum() else "_" for c in component
    ).upper()
    raw = os.environ.get(key) or os.environ.get("EASYDL_METRICS_PORT")
    if raw is None:
        return default
    raw = raw.strip().lower()
    if raw in ("off", "disabled", "none", "false"):
        return None
    try:
        port = int(raw)
    except ValueError:
        return default
    if port < 0:
        return None
    if port > 65535:  # a typo'd port must not take the service down
        return default
    return port


def cpu_subprocess_env(
    n_devices: int, base: Optional[Mapping[str, str]] = None
) -> Dict[str, str]:
    """Environment for a subprocess that must initialise JAX on a forced
    ``n_devices``-device CPU platform.

    Neutralises the image's TPU tunnel plugin (PALLAS_AXON_POOL_IPS) so the
    child cannot re-attach to the chip — the single authoritative copy of the
    recipe used by the elastic agent's worker spawns and the driver's
    ``dryrun_multichip`` bootstrap.
    """
    env = dict(base if base is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    return env


def join_rank_processes(procs, timeout: float = 900.0, poll_s: float = 0.25):
    """Join coordinated rank subprocesses (stdout/stderr PIPEd), fail-fast.

    A crashed rank leaves its peers blocked in a collective; waiting out the
    full timeout hides the root cause for minutes and then discards the
    failing rank's stderr. Poll instead: the moment any rank exits non-zero
    (or the deadline passes) kill the stragglers, then harvest every rank's
    output. Pipes are drained CONCURRENTLY by reader threads — draining
    only after exit would deadlock any child whose chatter exceeds the OS
    pipe buffer (it blocks in write(), never exits, and a passing run turns
    into a full-timeout kill). Returns ``[(returncode, stdout, stderr)]``
    in rank order — killed stragglers report negative returncodes; the
    caller should report the *non-signal* failures first.
    """
    import threading
    import time

    def drain(stream, sink):
        if stream is None:
            return
        while True:  # empty-chunk EOF test works for text AND binary pipes
            chunk = stream.read(8192)
            if not chunk:
                return
            sink.append(chunk)

    buffers = []
    readers = []
    for p in procs:
        out_buf, err_buf = [], []
        buffers.append((out_buf, err_buf))
        for stream, sink in ((p.stdout, out_buf), (p.stderr, err_buf)):
            t = threading.Thread(target=drain, args=(stream, sink),
                                 daemon=True)
            t.start()
            readers.append(t)

    deadline = time.monotonic() + timeout
    try:
        while True:
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                break
            if any(c not in (None, 0) for c in codes):
                break  # a rank failed: don't wait for the blocked peers
            if time.monotonic() > deadline:
                break
            time.sleep(poll_s)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p in procs:
        p.wait()
    for t in readers:
        t.join(timeout=10.0)
    def joined(buf):
        return (b"" if buf and isinstance(buf[0], bytes) else "").join(buf)

    return [
        (p.returncode, joined(out_buf), joined(err_buf))
        for p, (out_buf, err_buf) in zip(procs, buffers)
    ]


def run_cpu_rank_fleet(argvs, n_local_devices: int, timeout: float = 900.0,
                       cwd=None):
    """Spawn one forced-CPU jax subprocess per argv (a coordinated rank
    fleet), join with fail-fast, and surface failures.

    The single authoritative copy of the spawn/report idiom shared by
    ``dryrun_multichip``'s multi-process leg and the measurement scripts:
    per-rank ``cpu_subprocess_env`` + repo PYTHONPATH, concurrent pipe
    drains via :func:`join_rank_processes`, stdouts replayed in rank order,
    and failures reported with *real* (non-signal) exits first — a killed
    straggler's -9 must not mask the rank whose stderr holds the root
    cause. Raises RuntimeError naming the failing rank; returns the list
    of rank stdouts on success."""
    import os
    import subprocess
    import sys

    root = cwd or os.getcwd()
    procs = []
    for argv in argvs:
        env = cpu_subprocess_env(n_local_devices)
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            argv, env=env, cwd=root,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = join_rank_processes(procs, timeout=timeout)
    for rc, out, err in results:
        sys.stdout.write(out)
    for rank, (rc, out, err) in sorted(
            enumerate(results), key=lambda kv: kv[1][0] >= 0, reverse=True):
        if rc != 0:
            sys.stderr.write(err)
            raise RuntimeError(f"rank {rank} failed rc={rc}")
    return [out for _, out, _ in results]

def pin_cpu_platform_if_requested() -> None:
    """Honor ``JAX_PLATFORMS=cpu`` even where a sitecustomize pins an
    accelerator plugin via jax.config (which outranks env vars).

    The in-process half of the forced-CPU recipe — the single copy every
    entrypoint (zoo runner, elastic worker, warm standby, evaluator pod)
    calls right after importing jax. Without it, a CPU-deployed process
    attaches to the accelerator plugin and hangs or fails whenever that
    backend is unreachable."""
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
