"""Scrape-side half of the telemetry layer: fetch, parse, and merge.

``scripts/obs_scrape.py`` is a thin CLI over this module; the functions live
in the package so tests (and the Brain, later) can consume fleet snapshots
programmatically. Discovery reads the address files every exporter publishes
under ``<workdir>/obs/`` (easydl_tpu/obs/exporter.py) — the shared job
workdir already is the rendezvous point for master.json and the PS registry,
so it is the natural scrape inventory too.
"""

from __future__ import annotations

import json
import os
import re
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from easydl_tpu.obs.exporter import OBS_DIR
from easydl_tpu.utils.env import knob_int

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?\s+(?P<value>\S+)"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_text(text: str) -> Dict[str, float]:
    """Prometheus text format → flat ``{'name{k="v"}': value}`` dict.

    Labels are re-serialized in sorted-key order so the same series from
    two scrapes always merges onto one key."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = m.group("labels") or ""
        if labels:
            pairs = sorted(_LABEL_RE.findall(labels))
            labels = "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"
        out[m.group("name") + labels] = value
    return out


def fetch(address: str, path: str = "/metrics",
          timeout: float = 5.0) -> str:
    url = f"http://{address}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", errors="replace")


def scrape_target(address: str, timeout: float = 5.0) -> Dict[str, object]:
    """One endpoint → {'ok', 'metrics', 'health'} (never raises: a dead
    service is a data point, not a scrape failure)."""
    doc: Dict[str, object] = {"address": address, "ok": False,
                              "metrics": {}, "health": None}
    try:
        doc["metrics"] = parse_text(fetch(address, "/metrics", timeout))
        doc["ok"] = True
    except Exception as e:
        doc["error"] = repr(e)
        return doc
    try:
        doc["health"] = json.loads(fetch(address, "/healthz", timeout))
    except Exception:
        pass  # metrics answered; health is advisory
    return doc


def scrape_fleet(targets: Dict[str, str], timeout: float = 5.0,
                 pool: Optional[int] = None) -> Dict[str, Dict[str, object]]:
    """``{component: address}`` → ``{component: scrape_target(...)}``,
    fetched CONCURRENTLY through a bounded worker pool (default
    ``EASYDL_SCRAPE_POOL``). Serial scraping does not survive scale: a
    100-replica fleet with one dead exporter at the 5 s per-target
    timeout turns every snapshot into minutes of wall clock, which is
    exactly when the snapshot matters most.

    Every attempt increments ``easydl_scrape_attempts_total{target}`` in
    this process' registry and every failed one
    ``easydl_scrape_failures_total{target}`` — a dead exporter is itself
    a detectable signal (the ``fleet_scrape_health`` SLO pages on the
    failure counter's burn, which is how process-kill drills are
    detected at all)."""
    from easydl_tpu.obs.registry import get_registry

    reg = get_registry()
    attempts = reg.counter(
        "easydl_scrape_attempts_total",
        "Fleet scrape attempts by target component.", ("target",))
    failures = reg.counter(
        "easydl_scrape_failures_total",
        "Fleet scrape attempts that got no /metrics answer, by target "
        "component.", ("target",))
    workers = max(1, int(pool if pool is not None
                         else knob_int("EASYDL_SCRAPE_POOL")))
    items = sorted(targets.items())
    out: Dict[str, Dict[str, object]] = {}
    if not items:
        return out
    with ThreadPoolExecutor(max_workers=min(workers, len(items))) as ex:
        docs = ex.map(lambda kv: scrape_target(kv[1], timeout=timeout),
                      items)
        for (component, _), doc in zip(items, docs):
            attempts.inc(target=component)
            if not doc.get("ok"):
                failures.inc(target=component)
            out[component] = doc
    return out


def discover_docs(workdir: str) -> Dict[str, dict]:
    """{component: publication doc} from the exporters' address files."""
    docs: Dict[str, dict] = {}
    d = os.path.join(workdir, OBS_DIR)
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return docs
    for name in names:
        # torn publications are <component>.json.tmp — filtered here too
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
            docs[str(doc["component"])] = doc
        except (OSError, ValueError, KeyError):
            continue  # torn publication; next scrape sees it
    return docs


def discover(workdir: str) -> Dict[str, str]:
    """{component: address} from the exporters' publication files."""
    return {c: str(doc["address"]) for c, doc in discover_docs(workdir).items()
            if "address" in doc}


def merge_snapshot(
    workdir: Optional[str] = None,
    targets: Dict[str, str] | None = None,
    timeout: float = 5.0,
) -> Dict[str, object]:
    """Poll every service and fold the results into one document:

    ``{"services": {component: scrape_target(...)}, "merged": {series: v}}``

    Identical series from different services DO happen — every process
    exports the same ``easydl_rpc_client_*{method,service}`` families and
    the unlabeled ``easydl_train_*`` gauges — so the merge must not simply
    last-write-win: additive series (``_total``/``_count``/``_sum``/
    ``_bucket`` suffixes — counters and histogram components) are SUMMED
    across services, which keeps fleet-wide RPC totals correct; gauges keep
    the last scraped value (per-service values stay exact under
    ``services[component]["metrics"]``). Exporters co-hosted in ONE process
    (a local job running master + agents in-process) all serve the same
    registry, so summing across them would multiply real values by the
    exporter count — publications carry the exporter's pid, and services
    sharing a (host, pid) contribute each series once."""
    # source key -> {series: value}; one source = one process registry.
    all_targets: Dict[str, Tuple[str, tuple]] = {}
    if workdir:
        for component, doc in discover_docs(workdir).items():
            addr = str(doc.get("address", ""))
            if not addr:
                continue
            host = addr.rsplit(":", 1)[0]
            pid = doc.get("pid")
            reg = doc.get("registry")
            key = ((host, pid, reg) if pid is not None and reg is not None
                   else ("component", component))
            all_targets[component] = (addr, key)
    for component, addr in (targets or {}).items():
        all_targets[component] = (addr, ("target", component))
    services = scrape_fleet(
        {c: addr for c, (addr, _) in all_targets.items()}, timeout=timeout)
    by_source: Dict[tuple, Dict[str, float]] = {}
    for component, (_, key) in sorted(all_targets.items()):
        doc = services[component]
        if doc["ok"]:
            by_source.setdefault(key, {}).update(doc["metrics"])  # type: ignore[arg-type]
    merged: Dict[str, float] = {}
    for metrics in by_source.values():
        for series, value in metrics.items():
            if series in merged and _is_additive(series):
                merged[series] += value
            else:
                merged[series] = value
    return {"services": services, "merged": merged}


def _is_additive(series: str) -> bool:
    name = series.split("{", 1)[0]
    return name.endswith(("_total", "_count", "_sum", "_bucket"))


def format_console(snapshot: Dict[str, object],
                   pattern: Optional[str] = None) -> str:
    """Human console rendering of a merged snapshot."""
    rx = re.compile(pattern) if pattern else None
    lines: List[str] = []
    services: Dict[str, Dict[str, object]] = snapshot["services"]  # type: ignore[assignment]
    for component, doc in services.items():
        status = "up" if doc.get("ok") else f"DOWN ({doc.get('error')})"
        health = doc.get("health") or {}
        up = (f", uptime {health.get('uptime_s')}s"
              if isinstance(health, dict) and "uptime_s" in health else "")
        lines.append(f"== {component} @ {doc.get('address')} [{status}{up}]")
        metrics: Dict[str, float] = doc.get("metrics") or {}  # type: ignore[assignment]
        for series in sorted(metrics):
            if rx is not None and not rx.search(series):
                continue
            lines.append(f"  {series} = {metrics[series]}")
    return "\n".join(lines)
