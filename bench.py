"""Benchmark entry: one JSON line for the driver.

Measures flagship (GPT-2 345M) training throughput on the attached
accelerator — samples/sec/chip, the BASELINE.json headline metric. The
reference publishes no numbers (``"published": {}``), so ``vs_baseline``
reports against this framework's own recorded best (bench_baseline.json, if
present) and 1.0 otherwise.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax

    # Keep the TPU runtime quiet and deterministic for timing.
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig, Trainer
    from easydl_tpu.models.registry import get_model

    platform = jax.default_backend()
    n_chips = jax.device_count()
    if platform == "tpu":
        size, seq_len, global_batch, steps = "345m", 1024, 8 * n_chips, 20
        # dots_saveable remat: keep matmul outputs, recompute elementwise —
        # measured ~8% over full-block remat at this batch on one chip.
        bundle = get_model("gpt", size=size, seq_len=seq_len, remat=True,
                           remat_policy="dots")
    else:  # CPU smoke mode: tiny model, same code path
        size, seq_len, global_batch, steps = "test", 128, 8, 5
        bundle = get_model("gpt", size=size, seq_len=seq_len, vocab=512)

    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adamw(2e-4, weight_decay=0.01),
        config=TrainConfig(global_batch=global_batch),
        mesh_spec=MeshSpec(dp=n_chips),
    )
    state = trainer.init_state()
    data = iter(bundle.make_data(global_batch))

    # Warmup: compile + 2 steps. Sync via device_get of a scalar — on the
    # axon-tunneled TPU, block_until_ready on the arrays returns before the
    # remote execution finishes; fetching a value cannot.
    for _ in range(2):
        state, metrics = trainer.train_step(state, next(data))
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.train_step(state, next(data))
    # The final loss depends on the whole step chain (state threads through).
    float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0

    samples_per_sec = steps * global_batch / dt
    per_chip = samples_per_sec / n_chips
    tokens_per_sec = samples_per_sec * seq_len

    baseline_path = os.path.join(os.path.dirname(__file__), "bench_baseline.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                recorded = json.load(f).get(f"gpt-{size}", 0.0)
            if recorded > 0:
                vs_baseline = per_chip / recorded
        except (OSError, ValueError):
            pass

    print(
        json.dumps(
            {
                "metric": f"gpt-{size} seq{seq_len} samples/sec/chip ({platform}, {n_chips} chip)",
                "value": round(per_chip, 3),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs_baseline, 3),
                "tokens_per_sec": round(tokens_per_sec, 1),
                "step_time_s": round(dt / steps, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
