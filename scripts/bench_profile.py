#!/usr/bin/env python3
"""Record an XLA trace at the bench config and attribute step time.

VERDICT r3 weak 3: MFU sat at ~0.507 across rounds while the attack was
lever-guessing — this script replaces guesses with a measured breakdown.
It runs bench.py's exact flagship config (GPT-2 345M, seq 1024, bf16,
remat=dots, flash attention) for a few steady-state steps under
``jax.profiler.trace`` (utils/profiling.py), then parses the Chrome-trace
JSON the profiler writes and aggregates TPU-lane op time by category:
flash fwd/bwd custom-calls, matmul fusions, other fusions, collectives,
infeed/outfeed, and gaps (host-bound time between device ops).

Output: one JSON report (``--out``, default PROFILE.json) with per-category
totals per step and the top-N individual ops — the evidence that names the
binding term.

Usage: python scripts/bench_profile.py [--steps 3] [--out PROFILE.json]
(requires the TPU; on CPU it still runs the tiny smoke config)
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile
import time
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def categorize(name: str) -> str:
    n = name.lower()
    if "flash" in n or "custom-call" in n or "custom_call" in n:
        return "flash_attention_custom_call"
    if "all-reduce" in n or "all-gather" in n or "reduce-scatter" in n \
            or "collective" in n or "ppermute" in n or "all-to-all" in n:
        return "collectives"
    if n.startswith(("dot", "convolution")) or "gemm" in n or "einsum" in n:
        return "matmul"
    if "fusion" in n:
        # XLA fuses elementwise chains into the producing/consuming op;
        # matmul-rooted fusions usually keep 'dot' in the name
        return "matmul_fusion" if "dot" in n else "other_fusion"
    if "infeed" in n or "outfeed" in n or "copy" in n or "transpose" in n:
        return "data_movement"
    if "scan" in n or "while" in n:
        return "control_flow"
    return "other"


def parse_trace(logdir: str):
    paths = glob.glob(os.path.join(
        logdir, "plugins", "profile", "*", "*.trace.json.gz"))
    if not paths:
        raise FileNotFoundError(f"no trace under {logdir}")
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    # Find the TPU device lanes: process names like '/device:TPU:0' or
    # 'TPU:0'; XLA op events live on threads under those pids.
    device_pids = set()
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            label = e.get("args", {}).get("name", "")
            pid_names[e.get("pid")] = label
            if "TPU" in label.upper() or "/device" in label.lower():
                device_pids.add(e.get("pid"))
    if not device_pids:  # CPU fallback: everything is one lane
        device_pids = set(pid_names)
    per_op = defaultdict(float)
    lane_busy = defaultdict(float)  # (pid, tid) -> busy us
    lane_span = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        dur = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        per_op[name] += dur
        key = (e["pid"], e.get("tid"))
        lane_busy[key] += dur
        t0, t1 = float(e.get("ts", 0.0)), float(e.get("ts", 0.0)) + dur
        lo, hi = lane_span.get(key, (t0, t1))
        lane_span[key] = (min(lo, t0), max(hi, t1))
    return per_op, lane_busy, lane_span, pid_names


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(REPO, "PROFILE.json"))
    ap.add_argument("--logdir", default="")
    ap.add_argument("--top", type=int, default=15)
    args = ap.parse_args()

    import jax
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig, Trainer
    from easydl_tpu.models.registry import get_model
    from easydl_tpu.utils.profiling import trace

    platform = jax.default_backend()
    n_chips = jax.device_count()
    if platform == "tpu":
        size, seq_len = "345m", 1024
        grad_accum, global_batch = 32, 256 * n_chips
        bundle = get_model("gpt", size=size, seq_len=seq_len, remat=True,
                           remat_policy="dots", dtype="bfloat16",
                           fused_loss=False)
    else:
        size, seq_len, global_batch, grad_accum = "test", 128, 8, 2
        bundle = get_model("gpt", size=size, seq_len=seq_len, vocab=512)

    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adamw(2e-4, weight_decay=0.01),
        config=TrainConfig(global_batch=global_batch, grad_accum=grad_accum),
        mesh_spec=MeshSpec(dp=n_chips),
    )
    state = trainer.init_state()
    data = iter(bundle.make_data(global_batch))

    for _ in range(2):  # compile + warm
        state, metrics = trainer.train_step(state, next(data))
    float(jax.device_get(metrics["loss"]))

    logdir = args.logdir or tempfile.mkdtemp(prefix="bench-profile-")
    t0 = time.perf_counter()
    with trace(logdir):
        for _ in range(args.steps):
            state, metrics = trainer.train_step(state, next(data))
        float(jax.device_get(metrics["loss"]))
    wall = time.perf_counter() - t0

    per_op, lane_busy, lane_span, pid_names = parse_trace(logdir)
    cats = defaultdict(float)
    for name, dur in per_op.items():
        cats[categorize(name)] += dur
    total_op_us = sum(per_op.values())
    busiest = max(lane_busy.items(), key=lambda kv: kv[1]) if lane_busy else None
    span_us = 0.0
    if busiest:
        lo, hi = lane_span[busiest[0]]
        span_us = hi - lo
    top = sorted(per_op.items(), key=lambda kv: -kv[1])[: args.top]
    report = {
        "config": f"gpt-{size} seq{seq_len} b{global_batch}/a{grad_accum} "
                  f"({platform}, {n_chips} chip)",
        "profiled_steps": args.steps,
        "wall_s": round(wall, 3),
        "wall_per_step_s": round(wall / args.steps, 4),
        "device_op_time_per_step_s": round(total_op_us / 1e6 / args.steps, 4),
        "busiest_lane_busy_per_step_s": (
            round(busiest[1] / 1e6 / args.steps, 4) if busiest else None),
        "busiest_lane_span_per_step_s": round(span_us / 1e6 / args.steps, 4),
        "busiest_lane_gap_pct": (
            round(100 * (1 - busiest[1] / span_us), 2)
            if busiest and span_us else None),
        "category_us_per_step": {
            k: round(v / args.steps, 1)
            for k, v in sorted(cats.items(), key=lambda kv: -kv[1])
        },
        "top_ops_us_per_step": [
            {"op": name[:120], "us": round(dur / args.steps, 1),
             "pct_of_op_time": round(100 * dur / total_op_us, 2)}
            for name, dur in top
        ],
        "trace_logdir": logdir,
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
