"""easydl_tpu — a TPU-native elastic distributed training framework.

Re-implements the capability set of the EasyDL design (reference:
``/root/reference`` README.md:9-13 — ElasticTrainer + ElasticOperator + Brain)
as an idiomatic JAX/XLA/Pallas stack:

- ``easydl_tpu.api``      — job/resource contracts (≙ ElasticJob / JobResource CRDs)
- ``easydl_tpu.core``     — mesh, sharding, train loop, checkpointing, data
- ``easydl_tpu.elastic``  — master, agents, rendezvous, fault handling
- ``easydl_tpu.brain``    — autoscaling plan service (step-metric driven)
- ``easydl_tpu.operator`` — ResourcePlan → pod/slice reconciliation controller
- ``easydl_tpu.ps``       — host-side sparse-embedding parameter server
- ``easydl_tpu.models``   — model zoo (MLP, ResNet-50, BERT, GPT-2, DeepFM, ...)
- ``easydl_tpu.ops``      — Pallas TPU kernels (flash attention, ...)
- ``easydl_tpu.parallel`` — DP/FSDP/TP/SP machinery: ring attention, Ulysses, collectives
"""

__version__ = "0.1.0"

from easydl_tpu.api.job_spec import JobSpec, RoleSpec, ResourceSpec, TpuSpec  # noqa: F401
from easydl_tpu.api.resource_plan import ResourcePlan, RolePlan, ResourceUpdation  # noqa: F401
