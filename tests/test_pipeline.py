"""Pipeline parallelism (ops/pipeline.py): the GPipe schedule over the
``pp`` mesh axis must be a pure re-scheduling — identical loss and
gradients to the unpipelined model — and train end-to-end through the
standard Trainer. (SURVEY §2.2 listed pp as a reserved axis with no
schedule; this is the schedule.)"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from easydl_tpu.core import MeshSpec, Trainer, TrainConfig, build_mesh
from easydl_tpu.core.sharding import DEFAULT_RULES
from easydl_tpu.models import get_model
from easydl_tpu.ops.pipeline import make_pipeline, pipeline_rules


def bundles(mesh, microbatches=4):
    common = dict(size="test", seq_len=32, vocab=256, dtype="float32")
    plain = get_model("gpt", **common)
    piped = get_model(
        "gpt", **common,
        pipeline_fn=make_pipeline(mesh, microbatches=microbatches),
        pipeline_stages=mesh.shape["pp"],
    )
    return plain, piped


def test_pipeline_matches_plain_loss_and_grads(eight_devices):
    mesh = build_mesh(MeshSpec(dp=2, pp=2), devices=eight_devices[:4])
    plain, piped = bundles(mesh)
    params = plain.init_fn(jax.random.PRNGKey(0))
    batch = next(iter(plain.make_data(8, seed=1)))
    rng = jax.random.PRNGKey(1)

    def loss_of(bundle):
        def f(p):
            loss, _ = bundle.loss_fn(p, batch, rng)
            return loss
        return f

    with mesh:
        l_plain, g_plain = jax.jit(jax.value_and_grad(loss_of(plain)))(params)
        l_pipe, g_pipe = jax.jit(jax.value_and_grad(loss_of(piped)))(params)
    np.testing.assert_allclose(float(l_plain), float(l_pipe),
                               rtol=1e-5, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_pipeline_trains_through_trainer(eight_devices):
    """The full production path: pjit Trainer over a dp×pp mesh, stacked
    layer params sharded over pp by the pipeline rule table, several steps,
    finite decreasing loss."""
    mesh = build_mesh(MeshSpec(dp=4, pp=2))
    _, piped = bundles(mesh, microbatches=2)
    trainer = Trainer(
        init_fn=piped.init_fn,
        loss_fn=piped.loss_fn,
        optimizer=optax.adam(1e-3),
        config=TrainConfig(global_batch=16,
                           rules=pipeline_rules(DEFAULT_RULES)),
        mesh=mesh,
    )
    state = trainer.init_state()
    # the stacked block params really are stage-sharded over pp
    from easydl_tpu.core.sharding import unbox

    blocks = unbox(state.params)["blocks"]
    leaf = jax.tree.leaves(blocks)[0]
    specs = {str(d.sharding.spec) for d in (leaf,)}
    assert any("pp" in s for s in specs), specs

    before = np.asarray(jax.tree.leaves(unbox(state.params))[0])
    data = iter(piped.make_data(16, seed=0))
    losses = []
    for _ in range(4):
        state, metrics = trainer.train_step(state, next(data))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    after = np.asarray(jax.tree.leaves(unbox(state.params))[0])
    assert not np.allclose(before, after)  # the optimizer actually stepped
    assert float(metrics["grad_norm"]) > 0


def test_pipeline_config_validation(eight_devices):
    mesh = build_mesh(MeshSpec(dp=2, pp=2), devices=eight_devices[:4])
    piped = get_model(
        "gpt", size="test", seq_len=16, vocab=128,
        pipeline_fn=make_pipeline(mesh, microbatches=2),
        pipeline_stages=3,  # does not divide n_layers=2
    )
    params = piped.init_fn(jax.random.PRNGKey(0))
    batch = next(iter(piped.make_data(4)))
    with mesh, pytest.raises(ValueError, match="not divisible"):
        jax.jit(lambda p: piped.loss_fn(p, batch, jax.random.PRNGKey(0)))(
            params)
    with pytest.raises(ValueError, match="pp axis"):
        make_pipeline(build_mesh(MeshSpec(dp=8)), microbatches=2)


def test_pipeline_stage_mismatch_fails_loudly(eight_devices):
    mesh = build_mesh(MeshSpec(dp=2, pp=2), devices=eight_devices[:4])
    piped = get_model(
        "gpt", size="test", seq_len=16, vocab=128,
        pipeline_fn=make_pipeline(mesh, microbatches=2),
        pipeline_stages=1,  # != mesh pp size 2
    )
    params = piped.init_fn(jax.random.PRNGKey(0))
    batch = next(iter(piped.make_data(4)))
    with mesh, pytest.raises(ValueError, match="pp size"):
        jax.jit(lambda p: piped.loss_fn(p, batch, jax.random.PRNGKey(0)))(
            params)


def test_apply_pipeline_config_gates(eight_devices):
    """The entry-point helper: no-op without a pp axis; loud one-line error
    for pipeline-incapable models; kwargs+rules for capable ones."""
    from easydl_tpu.core.sharding import DEFAULT_RULES
    from easydl_tpu.ops.pipeline import apply_pipeline_config

    flat = build_mesh(MeshSpec(dp=8))
    kw, rules = apply_pipeline_config("mlp", {"features": [8]}, flat)
    assert kw == {"features": [8]} and rules == DEFAULT_RULES

    pp_mesh = build_mesh(MeshSpec(dp=4, pp=2))
    with pytest.raises(ValueError, match="does not support pipeline"):
        apply_pipeline_config("mlp", {}, pp_mesh)
    kw, rules = apply_pipeline_config("bert", {"size": "test"}, pp_mesh)
    assert kw["pipeline_stages"] == 2 and callable(kw["pipeline_fn"])
    assert dict(rules)["layers"] == "pp"


def test_pipeline_rejects_train_mode_dropout_loudly(eight_devices):
    """The stage apply passes no rngs, so dropout>0 + pipeline_fn in a
    NON-deterministic (train-mode) apply must fail with a clear error at
    trace time — not an opaque flax missing-rng error deep inside
    shard_map (advisor r4 low #2). Deterministic applies (eval, embedding
    extraction) need no rng and must keep working."""
    from easydl_tpu.models.transformer import Transformer, TransformerConfig

    mesh = build_mesh(MeshSpec(dp=2, pp=2), devices=eight_devices[:4])
    cfg = TransformerConfig(
        vocab=128, d_model=32, n_heads=2, n_layers=2, d_ff=64, max_seq=16,
        dropout=0.1,
        pipeline_fn=make_pipeline(mesh, microbatches=2), pipeline_stages=2,
    )
    model = Transformer(cfg)
    tokens = jnp.zeros((4, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    with mesh, pytest.raises(NotImplementedError, match="dropout"):
        model.apply({"params": params}, tokens, deterministic=False,
                    rngs={"dropout": jax.random.PRNGKey(1)})
    # deterministic apply: allowed (no dropout applied, no rng needed)
    with mesh:
        out = model.apply({"params": params}, tokens, deterministic=True)
    assert np.isfinite(np.asarray(out)).all()


def test_bubble_model_and_parity_across_microbatches(eight_devices):
    """The schedule's only bubble lever is the microbatch count (module
    docstring: a non-interleaved 1F1B reorder would not change the
    fraction). Check the analytic model and that parity holds at every m
    — the schedule is a pure re-ordering regardless of how deep the
    pipeline fill is."""
    from easydl_tpu.ops.pipeline import bubble_fraction, pipeline_ticks

    assert pipeline_ticks(4, 2) == 5
    assert pipeline_ticks(8, 4) == 11
    assert abs(bubble_fraction(4, 2) - 1 / 5) < 1e-9
    assert abs(bubble_fraction(8, 2) - 1 / 9) < 1e-9
    assert bubble_fraction(8, 2) < bubble_fraction(4, 2) < bubble_fraction(2, 2)

    mesh = build_mesh(MeshSpec(dp=2, pp=2), devices=eight_devices[:4])
    plain, _ = bundles(mesh)
    params = plain.init_fn(jax.random.PRNGKey(0))
    # per-dp-shard batch 8, so microbatches=8 still divides it
    batch = next(iter(plain.make_data(16, seed=3)))
    rng = jax.random.PRNGKey(1)
    with mesh:
        l_ref = float(jax.jit(
            lambda p: plain.loss_fn(p, batch, rng)[0])(params))
    for m in (2, 8):
        _, piped = bundles(mesh, microbatches=m)
        with mesh:
            l_m = float(jax.jit(
                lambda p: piped.loss_fn(p, batch, rng)[0])(params))
        np.testing.assert_allclose(l_ref, l_m, rtol=1e-5, atol=1e-5)
