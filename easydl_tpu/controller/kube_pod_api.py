"""Kubernetes pod backend for the elastic operator.

The reference IS a k8s operator ("a k8s controller to manage training Pods",
/root/reference/README.md:12; CRDs watched via the API server,
docs/design/elastic-training-operator.md:16-18,53-55). This backend
implements :class:`~easydl_tpu.controller.pod_api.PodApi` against the k8s
REST API so the same reconcile core that drives the in-memory fake and the
local-process backend drives a real cluster.

Implementation notes:
- stdlib HTTP only (urllib): the image has no ``kubernetes`` client package,
  and the pod API surface we need (POST/GET/DELETE on
  ``/api/v1/namespaces/{ns}/pods``) is small enough that a generated client
  buys nothing. In-cluster auth (service-account token + CA) is picked up
  from the conventional mount path; tests point ``base_url`` at a local
  fake API server over plain HTTP (tests/test_kube_pod_api.py).
- pods carry labels ``easydl.org/job|role|replaces`` so ``list_pods`` is one
  labelSelector GET and the reconcile core's replace-then-retire metadata
  round-trips through the cluster.
- ``TpuSpec`` maps to GKE TPU pod-slice scheduling: the
  ``google.com/tpu`` resource limit plus the
  ``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology`` node
  selectors (the GKE-documented contract for TPU slices).
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Any, Dict, List, Optional

from easydl_tpu.api.job_spec import ResourceSpec, TpuSpec
from easydl_tpu.controller.kube_http import SA_DIR, KubeApiError, KubeClient
from easydl_tpu.controller.pod_api import Pod, PodApi
from easydl_tpu.utils.logging import get_logger

log = get_logger("controller", "kubepods")

__all__ = [
    "KubePodApi", "KubeApiError", "pod_to_manifest", "manifest_to_pod",
    "SA_DIR", "GKE_TPU_ACCELERATOR",
]

#: accelerator family -> GKE gke-tpu-accelerator node-selector value
GKE_TPU_ACCELERATOR = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}

LABEL_JOB = "easydl.org/job"
LABEL_ROLE = "easydl.org/role"
LABEL_REPLACES = "easydl.org/replaces"
ANNOTATION_RESOURCE = "easydl.org/resource"


#: default in-container mount point of the job's shared volume — the k8s
#: equivalent of the process backend's workdir (master.json, the PS
#: registry, checkpoints all live here; pods must see one shared path).
DEFAULT_WORKDIR = "/workdir"


def pod_to_manifest(pod: Pod, namespace: str,
                    workdir: str = DEFAULT_WORKDIR,
                    workdir_volume: Optional[Dict[str, Any]] = None,
                    ) -> Dict[str, Any]:
    """Our Pod record -> a k8s V1Pod manifest.

    ``workdir`` is substituted into ``{workdir}`` command tokens and exported
    as EASYDL_WORKDIR (parity with the process backend — the PS registry,
    master.json and checkpoints all live under it). ``workdir_volume`` is an
    optional k8s volume SOURCE (e.g. ``{"persistentVolumeClaim":
    {"claimName": "train-shared"}}`` or ``{"nfs": {...}}``) mounted at that
    path in every pod; without a shared volume the pods see different
    filesystems and the file-based rendezvous cannot work."""
    requests: Dict[str, str] = {}
    limits: Dict[str, str] = {}
    if pod.resource.cpu:
        requests["cpu"] = str(pod.resource.cpu)
    if pod.resource.memory:
        requests["memory"] = f"{pod.resource.memory}Mi"
    if pod.resource.disk:
        requests["ephemeral-storage"] = f"{pod.resource.disk}Mi"
    if pod.resource.gpu:
        limits["nvidia.com/gpu"] = str(pod.resource.gpu)
    node_selector: Dict[str, str] = {}
    tpu = pod.resource.tpu
    if tpu is not None and tpu.chips:
        # GKE TPU pod slice: chips-per-pod via the google.com/tpu limit;
        # slice family/topology via node selectors.
        limits["google.com/tpu"] = str(tpu.chips)
        node_selector["cloud.google.com/gke-tpu-accelerator"] = (
            GKE_TPU_ACCELERATOR.get(tpu.type, tpu.type)
        )
        if tpu.topology:
            node_selector["cloud.google.com/gke-tpu-topology"] = tpu.topology
    container: Dict[str, Any] = {
        "name": pod.role.replace("_", "-"),
        "image": pod.image or "python:3.11-slim",
        # identity env, mirroring the process backend's EASYDL_POD_* exports
        "env": [
            {"name": "EASYDL_POD_NAME", "value": pod.name},
            {"name": "EASYDL_POD_ROLE", "value": pod.role},
            {"name": "EASYDL_JOB", "value": pod.job},
            {"name": "EASYDL_REPLACES", "value": pod.replaces or ""},
            {"name": "EASYDL_WORKDIR", "value": workdir},
        ],
    }
    if pod.command:
        cmd = pod.command
        if "{workdir}" in cmd and workdir_volume is None:
            # Without a shared volume, {workdir} resolves to a path on each
            # container's OWN filesystem — master.json, the PS registry, and
            # ready files would never be visible across pods and every
            # discover()/rendezvous would hang until timeout with no hint.
            # Warn at create time, where the misconfiguration is actionable.
            log.warning(
                "pod %s: command uses {workdir} but no --workdir-volume is "
                "configured — %s will be container-local and cross-pod "
                "file rendezvous will hang", pod.name, workdir,
            )
        for token, value in (("{name}", pod.name), ("{role}", pod.role),
                             ("{job}", pod.job), ("{workdir}", workdir)):
            cmd = cmd.replace(token, value)
        if "{ready_file}" in cmd:
            # Readiness-gated command (the process backend's {ready_file}
            # convention): emit a real readinessProbe so replace-then-retire
            # orders the old pod's retirement after the handoff on k8s too —
            # without a probe, kubelet reports Ready at container start and
            # the drain window would race the retirement.
            ready_path = "/tmp/easydl-ready"
            cmd = cmd.replace("{ready_file}", ready_path)
            container["readinessProbe"] = {
                "exec": {"command": ["cat", ready_path]},
                "initialDelaySeconds": 1,
                "periodSeconds": 2,
            }
        container["command"] = ["/bin/sh", "-c", cmd]
    if requests or limits:
        container["resources"] = {}
        if requests:
            container["resources"]["requests"] = requests
        if limits:
            container["resources"]["limits"] = limits
    manifest: Dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod.name,
            "namespace": namespace,
            "labels": {
                LABEL_JOB: pod.job,
                LABEL_ROLE: pod.role,
                **({LABEL_REPLACES: pod.replaces} if pod.replaces else {}),
            },
            # Full resource doc as an annotation so list_pods can rebuild
            # the exact ResourceSpec (and its signature) without lossy
            # quantity parsing.
            "annotations": {
                ANNOTATION_RESOURCE: json.dumps(pod.resource.to_dict()),
            },
        },
        "spec": {
            "restartPolicy": "Never",  # the operator owns restarts
            **({"nodeSelector": node_selector} if node_selector else {}),
            "containers": [container],
        },
    }
    if workdir_volume is not None:
        container.setdefault("volumeMounts", []).append(
            {"name": "easydl-workdir", "mountPath": workdir}
        )
        if "name" in workdir_volume:
            # A full k8s volume (not a bare source) was pasted: its own name
            # would desync from the volumeMount's — ours wins.
            log.warning("workdir_volume 'name' %r ignored (mount uses "
                        "'easydl-workdir')", workdir_volume["name"])
        manifest["spec"]["volumes"] = [
            {**workdir_volume, "name": "easydl-workdir"}
        ]
    return manifest


def manifest_to_pod(doc: Dict[str, Any]) -> Pod:
    meta = doc.get("metadata", {})
    labels = meta.get("labels", {}) or {}
    annotations = meta.get("annotations", {}) or {}
    try:
        resource = ResourceSpec.from_dict(
            json.loads(annotations.get(ANNOTATION_RESOURCE, "{}"))
        )
    except (ValueError, TypeError):
        resource = ResourceSpec()
    status = doc.get("status", {}) or {}
    phase = status.get("phase", "Pending")
    # k8s keeps phase Running during graceful deletion; our reconcile core
    # models that window as Terminating (replace-then-retire relies on it).
    if meta.get("deletionTimestamp") and phase in ("Pending", "Running"):
        phase = "Terminating"
    # Running-but-not-Ready reads as Pending: replace-then-retire must not
    # retire the old pod while its replacement's readiness probe (e.g. the
    # PS handoff) is still failing.
    if phase == "Running":
        for cond in status.get("conditions") or []:
            if cond.get("type") == "Ready" and cond.get("status") != "True":
                phase = "Pending"
                break
    spec = doc.get("spec", {}) or {}
    containers = spec.get("containers") or [{}]
    command = containers[0].get("command") or []
    return Pod(
        name=meta.get("name", ""),
        job=labels.get(LABEL_JOB, ""),
        role=labels.get(LABEL_ROLE, ""),
        resource=resource,
        phase=phase,
        replaces=labels.get(LABEL_REPLACES, ""),
        command=command[-1] if command else "",
        image=containers[0].get("image", ""),
    )


class KubePodApi(PodApi):
    """PodApi over the k8s REST API (stdlib HTTP; in-cluster or explicit)."""

    def __init__(
        self,
        base_url: str = "",
        namespace: str = "",
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        timeout: float = 10.0,
        client: Optional[KubeClient] = None,
        workdir: str = DEFAULT_WORKDIR,
        workdir_volume: Optional[Dict[str, Any]] = None,
    ):
        self._client = client or KubeClient(
            base_url=base_url, namespace=namespace, token=token,
            ca_file=ca_file, timeout=timeout,
        )
        self.base_url = self._client.base_url
        self.namespace = self._client.namespace
        self.workdir = workdir
        self.workdir_volume = workdir_volume

    # ------------------------------------------------------------------ http
    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return self._client.request(method, path, body)

    # ---------------------------------------------------------------- PodApi
    def create_pod(self, pod: Pod) -> None:
        manifest = pod_to_manifest(pod, self.namespace, workdir=self.workdir,
                                   workdir_volume=self.workdir_volume)
        # A known template token surviving substitution would reach the
        # container as a literal brace string and crash-loop the pod with a
        # baffling error; fail loudly here instead. ({ready_file} is
        # substituted by the readiness-probe block; arbitrary braces — JSON
        # model args — are legitimate and pass through.)
        cmd = manifest["spec"]["containers"][0].get("command")
        if cmd:
            leftover = [t for t in ("{name}", "{role}", "{job}", "{workdir}",
                                    "{ready_file}") if t in cmd[-1]]
            if leftover:
                raise ValueError(
                    f"pod {pod.name!r}: unsubstituted command tokens "
                    f"{leftover} in {cmd[-1]!r}"
                )
        path = f"/api/v1/namespaces/{self.namespace}/pods"
        try:
            self._request("POST", path, manifest)
        except KubeApiError as e:
            if e.code == 409:  # AlreadyExists — reconcile is level-triggered
                log.warning("pod %s already exists", pod.name)
                return
            raise
        log.info("created pod %s (%s)", pod.name, pod.role)

    def delete_pod(self, name: str) -> None:
        path = f"/api/v1/namespaces/{self.namespace}/pods/{name}"
        try:
            self._request("DELETE", path)
        except KubeApiError as e:
            if e.code == 404:  # idempotent, like k8s delete of a gone pod
                return
            raise
        log.info("deleted pod %s", name)

    def list_pods(self, job: Optional[str] = None) -> List[Pod]:
        selector = f"{LABEL_JOB}={job}" if job else LABEL_JOB
        path = (
            f"/api/v1/namespaces/{self.namespace}/pods"
            f"?labelSelector={urllib.parse.quote(selector)}"
        )
        doc = self._request("GET", path)
        pods = [manifest_to_pod(item) for item in doc.get("items", [])]
        return sorted(pods, key=lambda p: p.name)
