"""``python -m easydl_tpu.models.run`` — the model-zoo entrypoint.

This is the command a job's pods execute (the reference quickstart runs
``python -m model_zoo.iris.dnn_estimator``,
docs/design/elastic-training-operator.md:37; our manifests point here).
Roles:

- ``--role trainer`` (default): single-process training loop with periodic
  checkpointing — the path worker pods run under the elastic runtime too
  (the agent sets the distributed env; see easydl_tpu/elastic/worker.py).
- ``--role evaluator``: checkpoint-following side evaluation
  (easydl_tpu/core/evaluator.py).

Data is synthetic per model bundle, so any config runs hermetically.
"""

from __future__ import annotations

import argparse
import contextlib
import json


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description="easydl_tpu model zoo runner")
    ap.add_argument("--model", required=True, help="registry name (mlp, resnet, bert, gpt, deepfm, widedeep)")
    ap.add_argument("--role", choices=["trainer", "evaluator"], default="trainer")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--ckpt-every", type=int, default=50)
    ap.add_argument("--dp", type=int, default=0, help="data-parallel size (0 = all devices)")
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline stages (GPipe over the pp mesh axis; "
                         "transformer models only)")
    ap.add_argument("--pp-microbatches", type=int, default=2)
    ap.add_argument("--eval-polls", type=int, default=0, help="evaluator: stop after N evals (0 = forever)")
    ap.add_argument("--model-arg", action="append", default=[],
                    help="k=v forwarded to the model factory (repeatable)")
    ap.add_argument("--profile-dir", default="",
                    help="capture an XLA trace of 3 steady-state steps here")
    ap.add_argument("--data-dir", default="",
                    help="file-backed data: a dir of tokens-*.npy shards "
                         "(LM models) or images.npy/labels.npy "
                         "(classification). Default: the model bundle's "
                         "synthetic stream")
    ap.add_argument("--seq-len", type=int, default=0,
                    help="sequence length for --data-dir token shards "
                         "(default: the model's seq_len model-arg or 128)")
    ap.add_argument("--val-fraction", type=float, default=0.0,
                    help="deterministic held-out fraction of --data-dir "
                         "token windows; trainers read the rest, the "
                         "evaluator reads the holdout")
    return ap


def file_data(args, bundle, rank: int = 0, world: int = 1,
              batch: int = 0, seed_offset: int = 0, split: str = "train"):
    """--data-dir -> a dataset matching the model's input contract.

    seq_len comes from the bundle's own data stream (the model's actual
    config) unless --seq-len overrides it — a hardcoded fallback would
    silently train a long-context model on short windows."""
    import os

    from easydl_tpu.data import (
        ArrayImageDataset,
        ClickLogDataset,
        TokenFileDataset,
    )

    batch = batch or args.batch
    if os.path.exists(os.path.join(args.data_dir, "images.npy")):
        return ArrayImageDataset(args.data_dir, batch_size=batch,
                                 rank=rank, world=world, seed=seed_offset,
                                 split=split,
                                 val_fraction=args.val_fraction)
    if os.path.exists(os.path.join(args.data_dir, "sparse.npy")):
        return ClickLogDataset(args.data_dir, batch_size=batch,
                               rank=rank, world=world, seed=seed_offset,
                               split=split,
                               val_fraction=args.val_fraction)
    seq_len = args.seq_len or getattr(bundle.make_data(1), "seq_len", 0)
    if not seq_len:
        raise SystemExit(
            f"cannot infer seq_len for model {bundle.name!r}; pass --seq-len"
        )
    return TokenFileDataset(args.data_dir, batch_size=batch,
                            seq_len=seq_len, rank=rank, world=world,
                            seed=seed_offset, split=split,
                            val_fraction=args.val_fraction)


def main() -> None:
    ap = build_parser()
    args = ap.parse_args()

    import jax

    from easydl_tpu.utils.env import pin_cpu_platform_if_requested

    pin_cpu_platform_if_requested()

    import optax

    from easydl_tpu.core.checkpoint import CheckpointManager
    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.metrics import MetricsRecorder
    from easydl_tpu.core.train_loop import TrainConfig, Trainer
    from easydl_tpu.models.registry import get_model
    from easydl_tpu.utils.logging import get_logger

    log = get_logger("models", "run")

    kwargs = {}
    for kv in args.model_arg:
        k, _, v = kv.partition("=")
        try:
            kwargs[k] = json.loads(v)
        except json.JSONDecodeError:
            kwargs[k] = v

    from easydl_tpu.core.mesh import build_mesh
    from easydl_tpu.ops.pipeline import apply_pipeline_config

    pp = max(args.pp, 1)
    n_dev = jax.device_count()
    if pp > 1 and (n_dev < pp or n_dev % pp):
        # fail here with the cause, not later with an empty/truncated mesh
        ap.error(f"--pp {pp} needs a device count divisible by it "
                 f"(have {n_dev})")
    dp = args.dp or (n_dev // pp)
    mesh = build_mesh(MeshSpec(dp=dp, pp=pp))
    kwargs, rules = apply_pipeline_config(
        args.model, kwargs, mesh, microbatches=args.pp_microbatches)
    bundle = get_model(args.model, **kwargs)

    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adamw(args.lr),
        config=TrainConfig(global_batch=args.batch, rules=rules),
        mesh=mesh,
    )
    ckpt = CheckpointManager(args.ckpt_dir) if args.ckpt_dir else None

    if args.role == "evaluator":
        if ckpt is None:
            ap.error("--role evaluator requires --ckpt-dir")
        from easydl_tpu.core.evaluator import Evaluator

        if args.data_dir:
            # --val-fraction: a real held-out split; otherwise fall back to
            # a different shuffle order than training (seed_offset=1)
            split = "val" if args.val_fraction else "train"
            eval_data = iter(file_data(args, bundle, seed_offset=1,
                                       split=split))
        else:
            eval_data = iter(bundle.make_data(args.batch, seed=1))
        ev = Evaluator(trainer, ckpt, eval_data, eval_fn=bundle.eval_fn)
        ev.run(poll_interval_s=2.0, max_evals=args.eval_polls or None)
        return

    state = trainer.init_state()
    if ckpt is not None and ckpt.latest_step() is not None:
        state = trainer.restore_from(ckpt)
        log.info("resumed from step %d", state.int_step)
    source = None
    if args.data_dir:
        source = file_data(args, bundle)
        if ckpt is not None and state.int_step > 0:
            # resume the data cursor alongside the model: without this a
            # restored run replays epoch 0 from the start
            data_state = ckpt.metadata(state.int_step).get(
                "metadata", {}).get("data_state")
            if data_state:
                source.restore_state(data_state)
                log.info("data cursor resumed: %s", data_state)
        log.info("file-backed data: %s (%d batches/epoch)",
                 args.data_dir, source.batches_per_epoch)
        data = iter(source)
    else:
        data = iter(bundle.make_data(args.batch, seed=0))
    recorder = MetricsRecorder(args.batch, world_size=dp)
    profiler = None
    if args.profile_dir:
        from easydl_tpu.utils.profiling import StepProfiler, step_annotation

        # Window relative to the (possibly resumed) first step, so the
        # recompile-after-restore step is skipped just like a cold start's.
        profiler = StepProfiler(
            args.profile_dir, start_step=state.int_step + 3, num_steps=3
        )
    try:
        while state.int_step < args.steps:
            step = state.int_step
            if profiler is not None:
                profiler.maybe_start(step)
            annotation = (
                step_annotation("train", step) if profiler is not None
                else contextlib.nullcontext()
            )
            recorder.start_step()
            with annotation:
                state, metrics = trainer.train_step(state, next(data))
            step = state.int_step
            rec = recorder.end_step(step, float(metrics["loss"]))
            if profiler is not None:
                profiler.maybe_stop(step - 1)
            if step % 10 == 0 or step == args.steps:
                log.info("step %d loss %.4f (%.1f samples/s)", step, rec.loss,
                         rec.samples_per_sec)
            if ckpt is not None and (step % args.ckpt_every == 0 or step == args.steps):
                ckpt.save(step, state, metadata=(
                    {"data_state": source.state()} if source is not None
                    else None))
            if ckpt is not None:
                # Complete any deferred multi-process commit at the step
                # boundary (collectives on this main thread); no-op otherwise.
                ckpt.finalize()
    finally:
        # Flush an in-flight trace even on a crash — the traced steps are
        # exactly the ones worth inspecting afterwards.
        if profiler is not None:
            profiler.close()
    if ckpt is not None:
        ckpt.wait()


if __name__ == "__main__":
    main()
