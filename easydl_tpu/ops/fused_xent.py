"""Chunked, fused softmax cross-entropy for large-vocab LM heads.

The naive LM loss materializes the full logits tensor ``[B, S, V]`` in f32
(GPT-2 345M at microbatch 8, seq 1024: 8·1024·50304·4B ≈ 1.6 GB — the
compile-time OOM recorded in bench.py's r2 evidence, which capped the
microbatch at 8 and MFU at ~0.50). This op never builds it: the head matmul,
log-sum-exp and target-pick run chunk-by-chunk over the sequence inside a
``lax.scan`` whose body is ``jax.checkpoint``-ed, so

- forward peak is one ``[B, chunk, V]`` f32 buffer instead of ``[B, S, V]``;
- backward *recomputes* each chunk's logits from the (bf16) hidden states
  and head — without the checkpoint, scan would stash every chunk's logits
  as residuals and the memory win would vanish;
- the matmul itself runs in the input dtype (bf16 on TPU) with f32
  accumulation via ``preferred_element_type`` — MXU-native, no f32 copy of
  activations or head.

Numerics are identical to ``optax.softmax_cross_entropy_with_integer_labels``
(loss = lse(logits) − logits[target], f32 accumulation throughout); the op
is differentiable w.r.t. both ``hidden`` and ``head``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def fused_softmax_xent(
    hidden: jax.Array,
    head: jax.Array,
    targets: jax.Array,
    *,
    ignore_id: int = -1,
    chunk_size: int = 128,
):
    """Mean next-token cross-entropy from final hidden states.

    Args:
      hidden: ``[B, S, D]`` final (post-LN) hidden states, any float dtype.
      head: ``[V, D]`` output head in *embedding layout* (the tied-head
        ``tok_emb.embedding``; pass ``kernel.T`` for an untied ``[D, V]``
        head).
      targets: ``[B, S]`` int token ids; positions equal to ``ignore_id``
        contribute nothing to loss or denominator.
      chunk_size: sequence positions per scan step. Peak memory is
        ``B · chunk_size · V`` f32; 128 ≈ 1/8 the naive peak at seq 1024.

    Returns:
      ``(loss, denom)`` — mean f32 loss over unmasked positions and the
      (f32) count of them, matching ``models.gpt.lm_loss``'s contract.
    """
    if hidden.ndim != 3:
        raise ValueError(f"hidden must be [B,S,D], got {hidden.shape}")
    if head.ndim != 2 or head.shape[1] != hidden.shape[2]:
        raise ValueError(
            f"head must be [V,D] with D={hidden.shape[2]}, got {head.shape}"
        )
    seq = hidden.shape[1]
    chunk_size = min(chunk_size, seq)
    pad = (-seq) % chunk_size
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)),
                          constant_values=ignore_id)
    n_chunks = hidden.shape[1] // chunk_size

    def body(carry, i):
        h = lax.dynamic_slice_in_dim(hidden, i * chunk_size, chunk_size, 1)
        t = lax.dynamic_slice_in_dim(targets, i * chunk_size, chunk_size, 1)
        mask = (t != ignore_id).astype(jnp.float32)
        t_safe = jnp.maximum(t, 0)
        # [B, C, V] — f32 accumulation on the MXU, inputs stay bf16
        logits = lax.dot_general(
            h, head,
            dimension_numbers=(((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_safe[..., None], axis=-1)[..., 0]
        total, count = carry
        total = total + ((lse - tgt) * mask).sum()
        count = count + mask.sum()
        return (total, count), None

    # checkpoint: scan must NOT keep each chunk's logits as bwd residuals
    (total, denom), _ = lax.scan(
        jax.checkpoint(body),
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        jnp.arange(n_chunks),
    )
    denom = jnp.maximum(denom, 1.0)
    return total / denom, denom
