"""``python -m easydl_tpu.ps`` — the parameter-server pod entrypoint.

This is what the operator actually launches for the ``parameter_server``
role, and the piece that turns the operator's generic replace-then-retire
into the reference's zero-lost-updates vertical scaling
(docs/design/elastic-training-operator.md:86-101):

- **fresh pod** (initial creation): the trailing index of the pod name
  (``job-parameter_server-3`` → shard 3) is a HINT, checked against the
  registry: if some shard's latest publication is dead (its pod crashed and
  the reconciler levelled THIS pod in under a fresh name with no
  ``replaces``), the fresh pod adopts that orphaned shard instead —
  claiming it via an O_EXCL file so concurrent rescues can't collide — and
  restores its rows from the last complete ``ps-ckpt`` save. Then serve,
  publish to the registry, touch the ready file.
- **replacement pod** (``resource_updation`` → the operator created it with
  ``replaces=<old>``): inherit the OLD pod's shard index from the registry,
  then run the handoff — Drain the old pod (its pushes gate + rows save),
  Restore those rows here, publish (clients reroute on their next retried
  push), and only THEN touch the ready file. The operator retires the old
  pod when the replacement looks Running-and-ready, so retirement is
  ordered strictly after the handoff — the window in which an acked update
  could be lost never exists.

The pod name / replaces / workdir arrive via argv or the EASYDL_POD_*
environment the pod backend exports.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Optional, Tuple

from easydl_tpu.ps import registry
from easydl_tpu.ps.server import PS_SERVICE, PsShard
from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.rpc import RpcClient

log = get_logger("ps", "main")


def shard_index_from_name(name: str) -> Optional[int]:
    tail = name.rsplit("-", 1)[-1]
    return int(tail) if tail.isdigit() else None


def probe_alive(address: str, timeout: float = 2.0) -> bool:
    """Is a PS actually serving at this registry address? Registry entries
    outlive their pods (a crashed shard's file stays on disk), so liveness
    is decided by the socket, not the file."""
    from easydl_tpu.proto import easydl_pb2 as pb

    client = RpcClient(PS_SERVICE, address, timeout=timeout)
    try:
        client.Stats(pb.PsStatsRequest())
        return True
    except Exception:
        return False
    finally:
        client.close()


def claim_orphan_shard(workdir: str, pod: str, orphans,
                       stale_s: float = 30.0) -> Tuple[Optional[int],
                                                       Optional[str]]:
    """Claim one orphaned shard via an O_EXCL claim file so two concurrent
    failure replacements can't adopt the same shard. A claim older than
    ``stale_s`` whose shard is still unserved is presumed abandoned (the
    claimant crashed mid-rescue) and stolen; the original claimant notices
    at publish time (claim ownership is re-checked) and exits."""
    claim_dir = os.path.join(workdir, registry.REG_DIR)
    os.makedirs(claim_dir, exist_ok=True)
    doc = json.dumps({"pod": pod, "t": time.time()})
    for s in orphans:
        path = os.path.join(claim_dir, f"claim-shard-{s}.json")
        try:
            with open(path, "x") as f:
                f.write(doc)
            return s, path
        except FileExistsError:
            try:
                with open(path) as f:
                    age = time.time() - float(json.load(f).get("t", 0))
            except (OSError, ValueError):
                age = stale_s + 1  # torn claim: treat as stale
            if age > stale_s:
                tmp = f"{path}.steal-{pod}"
                with open(tmp, "w") as f:
                    f.write(doc)
                os.replace(tmp, path)
                return s, path
    return None, None


def resolve_fresh_shard(workdir: str, pod: str,
                        num_shards: int) -> Tuple[int, bool, Optional[str]]:
    """Decide which shard a fresh (non-replacement) PS pod serves.

    The pod name's trailing index is only a HINT: the reconciler replaces a
    Failed pod via replica levelling under a fresh name with no ``replaces``
    (reconciler.py), so ``job-parameter_server-2`` may well be the rescue of
    crashed shard 0. The registry decides: a shard whose latest publication
    no longer answers is orphaned, and an orphan outranks the name. Returns
    (shard index, rescued — a dead prior publication exists, claim path)."""
    smap = registry.shard_map(workdir)
    live, dead = set(), set()
    for s, doc in smap.items():
        if 0 <= s < num_shards:
            (live if probe_alive(doc["address"]) else dead).add(s)
    name_idx = shard_index_from_name(pod)
    if (name_idx is not None and 0 <= name_idx < num_shards
            and name_idx not in live and not dead - {name_idx}):
        # The normal initial-creation path (and in-place restart): the name
        # is a valid unserved shard and no OTHER shard needs rescue.
        return name_idx, name_idx in dead, None
    orphans = [s for s in range(num_shards) if s not in live]
    # Prefer the name's own shard when it is among the orphans (less churn).
    orphans.sort(key=lambda s: (s != name_idx, s))
    if not orphans:
        raise SystemExit(
            f"pod {pod!r}: every shard 0..{num_shards - 1} is already "
            "served; nothing to do (scale-down should delete this pod)"
        )
    s, claim = claim_orphan_shard(workdir, pod, orphans)
    if s is None:
        raise SystemExit(
            f"pod {pod!r}: shards {orphans} unserved but all freshly "
            "claimed by other pods"
        )
    log.info("pod %s adopting orphaned shard %d (name suggested %s)",
             pod, s, name_idx)
    return s, s in dead, claim


def wait_registry_entry(workdir: str, pod: str, wait_s: float = 60.0) -> dict:
    deadline = time.monotonic() + wait_s
    doc = registry.entry_for_pod(workdir, pod)
    while doc is None and time.monotonic() < deadline:
        time.sleep(0.2)
        doc = registry.entry_for_pod(workdir, pod)
    if doc is None:
        raise SystemExit(
            f"replaces={pod!r} but it never published to the registry"
        )
    return doc


def run_handoff(old: dict, workdir: str, shard: PsShard) -> None:
    """Drain the predecessor into a handoff dir, restore its rows here."""
    old_pod = old["pod"]
    handoff_dir = os.path.join(workdir, "ps-handoff", old_pod)
    client = RpcClient(PS_SERVICE, old["address"], timeout=120.0)
    try:
        from easydl_tpu.proto import easydl_pb2 as pb

        ack = client.Drain(pb.PsSaveRequest(directory=handoff_dir, step=0))
        if not ack.ok:
            raise SystemExit(f"drain of {old_pod} failed: {ack.message}")
    finally:
        client.close()
    shard.restore(handoff_dir, step=0)
    log.info("handoff from %s complete: shard %d restored from %s",
             old_pod, shard.shard_index, handoff_dir)


def main() -> None:
    ap = argparse.ArgumentParser(description="easydl_tpu PS pod")
    ap.add_argument("--name", default=os.environ.get("EASYDL_POD_NAME", ""))
    ap.add_argument("--workdir", default=os.environ.get("EASYDL_WORKDIR", ""))
    ap.add_argument("--num-shards", type=int, required=True)
    ap.add_argument("--shard-index", type=int, default=-1,
                    help="default: trailing index of the pod name (fresh "
                         "pods) or inherited from the replaced pod")
    ap.add_argument("--replaces",
                    default=os.environ.get("EASYDL_REPLACES", ""))
    ap.add_argument("--ready-file", default="",
                    help="touched once serving (and any handoff) is "
                         "complete — the pod backend's readiness gate")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    if not args.name or not args.workdir:
        ap.error("--name and --workdir (or EASYDL_POD_NAME/EASYDL_WORKDIR) "
                 "are required")

    old = None
    rescued, claim_path = False, None
    if args.replaces:
        # The shard identity is inherited from the pod being replaced — the
        # operator names replacements with a fresh trailing index, so the
        # name is NOT the shard.
        old = wait_registry_entry(args.workdir, args.replaces)
        index, num_shards = int(old["shard"]), int(old["num_shards"])
    else:
        num_shards = args.num_shards
        if args.shard_index >= 0:
            index = args.shard_index
        else:
            index, rescued, claim_path = resolve_fresh_shard(
                args.workdir, args.name, num_shards
            )
    shard = PsShard(shard_index=index, num_shards=num_shards)
    server = shard.serve(port=args.port)
    log.info("ps pod %s serving shard %d/%d on %s",
             args.name, shard.shard_index, num_shards, server.address)

    if old is not None:
        run_handoff(old, args.workdir, shard)
    elif rescued:
        # Failure rescue: the shard's previous server died without a drain,
        # so recover its rows from the last complete PS checkpoint (workers
        # save the PS tier alongside dense checkpoints; restore() keeps only
        # this shard's ids). Updates since that checkpoint are lost — same
        # bound as the dense state after a crash.
        ckpt_dir = os.path.join(args.workdir, "ps-ckpt")
        try:
            step = shard.restore(ckpt_dir)
            log.info("rescued shard %d from %s at step %d",
                     index, ckpt_dir, step)
        except FileNotFoundError:
            log.warning("no complete PS checkpoint under %s; rescued shard "
                        "%d starts empty", ckpt_dir, index)

    if claim_path is not None:
        # A stale-claim thief may have taken the shard while we restored;
        # the registry must not see two publications racing for it.
        try:
            with open(claim_path) as f:
                owner = json.load(f).get("pod")
        except (OSError, ValueError):
            owner = None
        if owner != args.name:
            raise SystemExit(
                f"claim on shard {index} taken over by {owner!r}; exiting"
            )
    registry.publish(args.workdir, args.name, shard.shard_index,
                     num_shards, server.address)
    if args.ready_file:
        with open(args.ready_file, "w") as f:
            f.write(server.address)

    stop = {"flag": False}

    def on_term(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    server.stop()
    log.info("ps pod %s exiting", args.name)
    sys.exit(0)


if __name__ == "__main__":
    main()
