"""Pipeline parallelism: a GPipe fill–drain schedule over the ``pp`` axis.

The reference has no pipeline parallelism (SURVEY §2.2 — the ``pp`` mesh
axis was reserved with no dedicated schedule); this module supplies the
schedule, TPU-first: the transformer's blocks are ALREADY an ``nn.scan``
over a stacked ``layers`` parameter axis, so stage sharding is just mapping
``layers → pp`` in the rule table — each pp rank then physically holds its
``n_layers/pp`` consecutive layers, and :func:`pipeline_blocks` runs the
classic GPipe schedule inside one ``shard_map``:

- the stage-local activation hops to the next stage over ``ppermute``
  (neighbour ICI traffic — exactly what pipeline parallelism exists to
  exploit);
- ``lax.scan`` over ``M + pp - 1`` ticks (static trip count: XLA-friendly
  control flow); the first ``pp-1`` and last ``pp-1`` ticks are the usual
  GPipe bubble;
- microbatching splits only the *forward pathway* inside the pipeline;
  loss/optimizer see the reassembled full batch, so training math is
  identical to the unpipelined model (the parity test asserts this).

Embedding, final LN, head and loss stay OUTSIDE the shard_map under plain
GSPMD; the pipeline output is replicated over ``pp`` via a masked psum of
the last stage's result.

Scope (v1): stage-local weights are unsharded inside the pipeline (no
tp/fsdp of a stage's own matrices — :func:`pipeline_rules` maps the weight
axes to None); dropout-free paths; dense FFNs (no MoE inside the
pipeline).

On 1F1B (why there is no ``schedule="1f1b"`` flag): under jax autodiff
the user writes only the FORWARD schedule; the backward is the transpose
XLA derives — for this scan-over-ticks + ppermute formulation that
transpose is itself a reverse-order pipeline, i.e. the backward is
already pipelined. Non-interleaved 1F1B has the SAME bubble fraction as
GPipe, ``(pp-1)/(m+pp-1)`` (see :func:`bubble_fraction`); what it buys in
a hand-scheduled framework is peak activation memory O(pp) instead of
O(m), and here ``jax.checkpoint`` around the stage apply already bounds
the stored state to the per-tick boundary activations. The variant that
genuinely cuts the bubble — the circular/interleaved schedule (v chunks
per rank, bubble ``(pp-1)/(v·m+pp-1)``) — needs chunk c resident on rank
``c mod pp``, i.e. a STRIDED layer placement; with the stacked
``[n_layers, ...]`` parameter layout this round's checkpoints use, that
means either relaying out saved states or an every-step weight all-to-all
inside the pipeline. Deliberately deferred rather than shipped as a flag
whose measured effect would be nil (the honest lever exposed instead:
raise ``microbatches`` — the bubble amortizes as 1/m, and the parity
tests hold at any m).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from easydl_tpu.ops._compat import shard_map


def pipeline_ticks(microbatches: int, pp: int) -> int:
    """Static trip count of the schedule's scan: ``m`` work ticks plus the
    ``pp-1`` fill/drain ticks (the GPipe bubble)."""
    return microbatches + pp - 1


def bubble_fraction(microbatches: int, pp: int) -> float:
    """Idle fraction of the fill–drain schedule: ``(pp-1)/(m+pp-1)``.

    The knob that shrinks it is ``microbatches`` (1/m amortization); a
    non-interleaved 1F1B reordering would NOT change this number (see the
    module docstring)."""
    return (pp - 1) / pipeline_ticks(microbatches, pp)


def pipeline_rules(base) -> tuple:
    """Rule table for a pipelined model: stage-shard the stacked ``layers``
    axis over ``pp``; un-shard the weight/activation feature axes (the
    stage-local weights live whole on their stage in v1)."""
    drop = {"embed", "mlp", "heads", "kv", "qkv", "vocab", "seq"}
    out = []
    for name, target in base:
        if name == "layers":
            out.append((name, "pp"))
        elif name in drop:
            out.append((name, None))
        else:
            out.append((name, target))
    return tuple(out)


#: model families whose factories accept the pipeline (they share the
#: nn.scan transformer stack). gpt_moe is excluded: MoE inside the
#: pipeline is a NotImplementedError in the model.
PIPELINE_CAPABLE = ("gpt", "bert")


def apply_pipeline_config(model: str, model_kwargs: dict, mesh: Mesh,
                          microbatches: int = 2):
    """Entry-point helper: when ``mesh`` has a real ``pp`` axis, extend the
    model kwargs with the pipeline (``pipeline_fn`` closes over the mesh,
    so it can't travel through a serialized job config — the zoo runner and
    the elastic worker both call this after building their mesh).

    No-op (returning the kwargs and the default rules unchanged) when the
    mesh has no pp axis. A pp axis with a model family that can't pipeline
    raises a one-line config error — the alternative is an unexplained
    ``TypeError`` from the model factory deep in a worker crash-loop.

    Returns ``(model_kwargs, rules)`` — the rule table switches to
    :func:`pipeline_rules` so the stacked layer params stage-shard."""
    from easydl_tpu.core.sharding import DEFAULT_RULES

    pp = mesh.shape.get("pp", 1)
    if pp < 2:
        return model_kwargs, DEFAULT_RULES
    if model not in PIPELINE_CAPABLE:
        raise ValueError(
            f"mesh has pp={pp} but model {model!r} does not support "
            f"pipeline parallelism (capable: {', '.join(PIPELINE_CAPABLE)})"
        )
    out = dict(model_kwargs)
    out.setdefault("pipeline_fn", make_pipeline(mesh, microbatches))
    out.setdefault("pipeline_stages", pp)
    return out, pipeline_rules(DEFAULT_RULES)


def make_pipeline(mesh: Mesh, microbatches: int,
                  remat: Optional[bool] = None) -> Callable:
    """Build the ``pipeline_fn`` a :class:`TransformerConfig` carries
    (mirroring the ``attention_fn`` pattern): closes over the mesh so the
    model stays mesh-agnostic.

    Returns ``fn(apply_stage, stage_params, x, block_remat=False) -> y``
    where ``stage_params`` is the stacked ``[n_layers, ...]`` block tree
    (sharded ``layers → pp``) and ``x`` is the embedded activation
    ``[B, S, D]``. ``fn.stages`` carries the mesh's pp size so the model
    can validate its ``pipeline_stages`` against it.

    ``remat`` default (None) is automatic: the stage apply is wrapped in
    ``jax.checkpoint`` only when the caller says the blocks are NOT already
    remat-wrapped (``block_remat``) — stacking both would recompute the
    whole stage forward twice in the backward pass.
    """
    pp = mesh.shape["pp"]
    if pp < 2:
        raise ValueError(f"pipeline needs a pp axis of ≥2 (mesh has {pp})")

    def fn(apply_stage: Callable, stage_params: Any, x: jax.Array,
           block_remat: bool = False):
        outer_remat = remat if remat is not None else not block_remat
        return pipeline_blocks(mesh, apply_stage, stage_params, x,
                               microbatches=microbatches, remat=outer_remat)

    fn.stages = pp
    return fn


def pipeline_blocks(mesh: Mesh, apply_stage: Callable, stage_params: Any,
                    x: jax.Array, microbatches: int,
                    remat: bool = True) -> jax.Array:
    """Run ``apply_stage`` as a ``pp``-stage GPipe pipeline over ``x``.

    ``apply_stage(local_params, h) -> h`` applies one stage's layer chunk
    (the caller builds it from an ``nn.scan`` of length ``n_layers/pp``).
    ``stage_params`` leaves carry the stacked layer axis first and must be
    sharded over ``pp`` on that axis; ``x`` is batch-sharded over
    ``(dp, fsdp)`` and replicated over ``pp``.
    """
    pp = mesh.shape["pp"]
    batch_spec = P(("dp", "fsdp"))
    param_spec = jax.tree.map(lambda _: P("pp"), stage_params)
    stage_apply = jax.checkpoint(apply_stage) if remat else apply_stage

    @partial(
        shard_map, mesh=mesh,
        in_specs=(param_spec, batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )
    def run(p_local, x_local):
        import flax.linen as nn

        stage = jax.lax.axis_index("pp")
        batch = x_local.shape[0]
        if batch % microbatches:
            raise ValueError(
                f"per-shard batch {batch} not divisible by "
                f"microbatches={microbatches}"
            )
        mb = batch // microbatches
        xs = x_local.reshape((microbatches, mb) + x_local.shape[1:])
        ticks = microbatches + pp - 1

        def tick(carry, t):
            buf, out = carry
            # stage 0 ingests microbatch t (clamped past the drain phase);
            # later stages consume what the previous tick handed them
            mb_in = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, microbatches - 1), 0, keepdims=False
            )
            inp = jnp.where(stage == 0, mb_in, buf)
            with nn.logical_axis_rules(()):
                # inside shard_map the model's logical constraints must be
                # no-ops (there is no GSPMD context here); empty rules make
                # with_logical_constraint the identity
                y = stage_apply(p_local, inp)
            # hand the activation to the next stage (ring: the wrap-around
            # edge feeds stage 0, which ignores it — it reads xs instead)
            nxt = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)]
            )
            # the last stage emits microbatch t-(pp-1) once it's real
            oidx = t - (pp - 1)
            valid = (stage == pp - 1) & (oidx >= 0)
            out = jnp.where(
                valid,
                jax.lax.dynamic_update_index_in_dim(
                    out, y, jnp.clip(oidx, 0, microbatches - 1), 0
                ),
                out,
            )
            return (nxt, out), None

        (_, outs), _ = jax.lax.scan(
            tick, (jnp.zeros_like(xs[0]), jnp.zeros_like(xs)),
            jnp.arange(ticks),
        )
        y = outs.reshape(x_local.shape)
        # replicate the last stage's assembled output to every pp rank so
        # the head/loss outside the shard_map see one consistent value
        return jax.lax.psum(
            jnp.where(stage == pp - 1, y, jnp.zeros_like(y)), "pp"
        )

    return run(stage_params, x)
