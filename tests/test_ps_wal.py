"""PS push write-ahead log + shard-epoch fencing tests (zero-loss rescue).

Covers the ISSUE-6 tentpole surface: record framing and torn-tail/checksum
truncation, segment rotation and snapshot-commit retirement, rescue replay
bit-parity (snapshot + WAL == never-crashed table, optimizer state
included), replay-vs-retry dedupe, the epoch fence (stale route rejection,
zombie self-fencing, proof-of-successor), registry epoch bookkeeping and
the startup sweep, and the AsyncPusher drain error contract.
"""

import json
import os
import struct
import subprocess
import sys
import time
import zlib

import numpy as np
import pytest

from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.ps import registry, wal
from easydl_tpu.ps.client import ShardedPsClient
from easydl_tpu.ps.server import DRAINING, STALE_EPOCH, PsShard
from easydl_tpu.ps.table import TableSpec
from easydl_tpu.ps.trainer import AsyncPusher


def spec(**kw):
    base = dict(name="emb", dim=8, init_std=0.01, seed=7,
                optimizer="adagrad", lr=0.1)
    base.update(kw)
    return TableSpec(**base)


def push_req(table, ids, grads, scale=1.0, epoch=0):
    return pb.PushRequest(
        table=table, raw_ids=np.ascontiguousarray(ids, "<i8").tobytes(),
        grads=np.ascontiguousarray(grads, np.float32).tobytes(),
        scale=scale, epoch=epoch,
    )


def stream(n=6, ids_n=16, dim=8, seed=3):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, 50, ids_n).astype(np.int64),
         rng.standard_normal((ids_n, dim)).astype(np.float32))
        for _ in range(n)
    ]


# ---------------------------------------------------------------- framing


def test_push_record_roundtrip():
    ids = np.array([3, -7, 2**40], np.int64)
    grads = np.arange(24, dtype=np.float32).reshape(3, 8)
    payload = wal.encode_push("emb", ids, grads, 0.25)
    assert wal.record_kind(payload) == wal.REC_PUSH
    table, rids, rgrads, scale = wal.decode_push(payload)
    assert table == "emb" and scale == 0.25
    np.testing.assert_array_equal(rids, ids)
    np.testing.assert_array_equal(rgrads, grads)


def test_create_record_roundtrip():
    payload = wal.encode_create('{"name": "emb"}')
    assert wal.record_kind(payload) == wal.REC_CREATE
    assert wal.decode_create(payload) == '{"name": "emb"}'


def test_read_segment_stops_at_torn_tail(tmp_path):
    seg = str(tmp_path / "seg-00000001.wal")
    frames = [wal.frame(wal.encode_create(f'{{"n": {i}}}')) for i in range(3)]
    with open(seg, "wb") as f:
        f.write(b"".join(frames))
        f.write(frames[0][: len(frames[0]) // 2])  # killed mid-append
    payloads, consumed, clean = wal.read_segment(seg)
    assert len(payloads) == 3 and not clean
    assert consumed == sum(len(fr) for fr in frames)


def test_read_segment_stops_at_checksum_mismatch(tmp_path):
    seg = str(tmp_path / "seg-00000001.wal")
    frames = [wal.frame(wal.encode_create(f'{{"n": {i}}}')) for i in range(3)]
    data = bytearray(b"".join(frames))
    # flip one payload byte of the SECOND record: its crc fails and nothing
    # at or past it may ever be applied
    off = len(frames[0]) + struct.calcsize("<II") + 2
    data[off] ^= 0xFF
    with open(seg, "wb") as f:
        f.write(bytes(data))
    payloads, consumed, clean = wal.read_segment(seg)
    assert len(payloads) == 1 and not clean
    assert consumed == len(frames[0])
    assert wal.decode_create(payloads[0]) == '{"n": 0}'


def test_read_segment_respects_replay_cap(tmp_path):
    seg = str(tmp_path / "seg-00000001.wal")
    frames = [wal.frame(wal.encode_create(f'{{"n": {i}}}')) for i in range(3)]
    with open(seg, "wb") as f:
        f.write(b"".join(frames))
    cap = len(frames[0]) + len(frames[1])
    payloads, consumed, _clean = wal.read_segment(seg, limit=cap)
    assert len(payloads) == 2 and consumed == cap


def test_wal_rotates_segments(tmp_path):
    w = wal.PsWal(str(tmp_path), segment_bytes=64, sync_s=-1)
    for i in range(5):
        w.append(wal.encode_create(json.dumps({"n": i, "pad": "x" * 40})))
    w.close()
    segs = sorted(p for p in os.listdir(tmp_path) if p.endswith(".wal"))
    assert len(segs) >= 5  # 64-byte threshold: every append rotates
    got = [
        wal.decode_create(p)
        for s in segs
        for p in wal.read_segment(str(tmp_path / s))[0]
    ]
    assert [json.loads(g)["n"] for g in got] == list(range(5))


def test_wal_rollback_truncates_last_frame(tmp_path):
    w = wal.PsWal(str(tmp_path), sync_s=-1)
    w.append(wal.encode_create('{"n": 1}'))
    n = w.append(wal.encode_create('{"n": 2}'))
    w.rollback(n)
    w.append(wal.encode_create('{"n": 3}'))
    w.close()
    payloads, _consumed, clean = wal.read_segment(w.path)
    assert clean
    assert [json.loads(wal.decode_create(p))["n"] for p in payloads] == [1, 3]


def test_failed_store_apply_rolls_back_wal_record(tmp_path, monkeypatch):
    """WAL-then-apply with the apply raising: the client saw an error, so
    the durably framed record must come back OFF the log — a rescue
    replaying it would recover a table the acked history never produced.
    The log must stay appendable afterwards (the rollback is a truncate,
    not a brick)."""
    shard = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    shard.create_table(spec())
    ids, grads = np.arange(4), np.ones((4, 8), np.float32)
    assert shard.Push(push_req("emb", ids, grads), None).ok

    t = shard.table("emb")
    real_push = t.push
    monkeypatch.setattr(
        t, "push",
        lambda *a, **kw: (_ for _ in ()).throw(MemoryError("arena growth")))
    with pytest.raises(MemoryError):
        shard.Push(push_req("emb", ids, 2 * grads), None)
    monkeypatch.setattr(t, "push", real_push)
    assert shard.Push(push_req("emb", ids, 3 * grads), None).ok
    shard._wal.sync()  # crash: no close, just stop using it

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    rescuer.replay_wal()
    reference = PsShard()
    reference.create_table(spec())
    reference.table("emb").push(ids, grads)
    reference.table("emb").push(ids, 3 * grads)
    np.testing.assert_array_equal(rescuer.table("emb").pull(ids),
                                  reference.table("emb").pull(ids))


def test_wal_broken_append_raises_wal_error(tmp_path):
    w = wal.PsWal(str(tmp_path), sync_s=-1)
    os.close(w._fd)  # simulate the volume dying under the log
    w._fd = os.open("/dev/full", os.O_WRONLY)
    with pytest.raises(wal.WalError):
        w.append(b"x" * 64)
    with pytest.raises(wal.WalError):  # stays broken: durability is gone
        w.append(b"y")


# ------------------------------------------------------------ rescue replay


def wal_root(tmp_path, shard=0):
    return str(tmp_path / "ps-wal" / f"shard-{shard}")


def test_rescue_replay_is_bit_identical(tmp_path):
    """Snapshot mid-stream + crash + replay == the table that never died —
    embedding AND adagrad accumulator rows, bit for bit."""
    batches = stream(8)
    victim = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    reference = PsShard()
    for s in (victim, reference):
        s.create_table(spec())
    ckpt = str(tmp_path / "ps-ckpt")
    for i, (ids, grads) in enumerate(batches):
        if i == 4:
            victim.save(ckpt, step=i)  # retires the covered segments
        victim.table("emb").push(ids, grads, scale=0.5)
        victim._wal.append(wal.encode_push("emb", ids, grads, 0.5))
        reference.table("emb").push(ids, grads, scale=0.5)
    victim._wal.sync()  # crash: no close, just stop using it

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    rescuer.restore(ckpt)
    stats = rescuer.replay_wal()
    # the create record died with the retired pre-snapshot segment; the
    # table itself came back through restore()'s snapshot spec
    assert stats["pushes"] == 4 and stats["torn"] == 0
    probe = np.arange(50)
    np.testing.assert_array_equal(
        rescuer.table("emb").pull(probe), reference.table("emb").pull(probe))
    ids_r, rows_r = rescuer.table("emb").export_rows()
    ids_f, rows_f = reference.table("emb").export_rows()
    np.testing.assert_array_equal(np.sort(ids_r), np.sort(ids_f))
    np.testing.assert_array_equal(
        rows_r[np.argsort(ids_r, kind="stable")],
        rows_f[np.argsort(ids_f, kind="stable")])


def test_rescue_replay_truncates_torn_tail(tmp_path):
    """A SIGKILL mid-append leaves a half-written record: replay applies
    everything before it and equals a reference that never saw the lost
    push."""
    batches = stream(5)
    victim = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    reference = PsShard()
    for s in (victim, reference):
        s.create_table(spec())
    for i, (ids, grads) in enumerate(batches):
        victim.table("emb").push(ids, grads, scale=0.5)
        victim._wal.append(wal.encode_push("emb", ids, grads, 0.5))
        if i < len(batches) - 1:  # the final push never made the reference
            reference.table("emb").push(ids, grads, scale=0.5)
    victim._wal.sync()
    seg = victim._wal.path
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:  # tear the last record in half
        f.truncate(size - 40)

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    stats = rescuer.replay_wal()
    assert stats["torn"] == 1 and stats["pushes"] == len(batches) - 1
    probe = np.arange(50)
    np.testing.assert_array_equal(
        rescuer.table("emb").pull(probe), reference.table("emb").pull(probe))


def test_rescue_replay_stops_at_corrupt_record(tmp_path):
    """Bit-rot inside a record body: the crc catches it and replay stops
    THERE — later (possibly fine) records must not apply out of order."""
    victim = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    victim.create_table(spec())
    reference = PsShard()
    reference.create_table(spec())
    batches = stream(4)
    offsets = []  # byte offset of each record in the open segment
    for ids, grads in batches:
        victim.table("emb").push(ids, grads, scale=0.5)
        offsets.append(os.path.getsize(victim._wal.path))
        victim._wal.append(wal.encode_push("emb", ids, grads, 0.5))
    victim._wal.sync()
    # reference sees only the pushes before the corrupt record (the 3rd)
    for ids, grads in batches[:2]:
        reference.table("emb").push(ids, grads, scale=0.5)
    seg = victim._wal.path
    with open(seg, "r+b") as f:  # corrupt one byte INSIDE record 3's body
        f.seek(offsets[2] + struct.calcsize("<II") + 8)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    stats = rescuer.replay_wal()
    assert stats["torn"] == 1 and stats["pushes"] == 2
    probe = np.arange(50)
    np.testing.assert_array_equal(
        rescuer.table("emb").pull(probe), reference.table("emb").pull(probe))


def test_replay_dedupes_retried_push(tmp_path):
    """A push the dead shard applied-and-logged but never acked comes back
    as a client retry: the rescuer recognises the payload and acks WITHOUT
    applying twice."""
    ids = np.arange(8)
    grads = np.ones((8, 8), np.float32)
    victim = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    victim.create_table(spec())
    victim.table("emb").push(ids, grads, scale=1.0)
    victim._wal.append(wal.encode_push("emb", ids, grads, 1.0))
    victim._wal.sync()

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    rescuer.replay_wal()
    before = rescuer.table("emb").pull(ids).copy()
    ack = rescuer.Push(push_req("emb", ids, grads, epoch=2), None)
    assert ack.ok and "dedup" in ack.message
    np.testing.assert_array_equal(rescuer.table("emb").pull(ids), before)
    # the SAME bytes again are a genuinely new push now (dedupe is one-shot)
    ack2 = rescuer.Push(push_req("emb", ids, grads, epoch=2), None)
    assert ack2.ok and "dedup" not in ack2.message
    assert not np.array_equal(rescuer.table("emb").pull(ids), before)


def test_replay_markers_freeze_zombie_appends(tmp_path):
    """Appends a zombie makes AFTER a rescue consumed its segments must be
    invisible to any LATER rescue — the rescuer re-acked those retries
    itself (or fenced them)."""
    ids = np.arange(4)
    grads = np.ones((4, 8), np.float32)
    zombie = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    zombie.create_table(spec())
    zombie.table("emb").push(ids, grads, scale=1.0)
    zombie._wal.append(wal.encode_push("emb", ids, grads, 1.0))
    zombie._wal.sync()

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    rescuer.replay_wal()
    # zombie wakes up and logs one more (unfenced local append)
    zombie._wal.append(wal.encode_push("emb", ids, grads * 9, 1.0))
    zombie._wal.sync()

    second = PsShard(epoch=3, wal_root=wal_root(tmp_path))
    stats = second.replay_wal()
    # epoch-1 replay capped at the marker (1 push), epoch-2 wal had the
    # create + nothing else
    assert stats["pushes"] == 1
    probe = np.arange(4)
    np.testing.assert_array_equal(second.table("emb").pull(probe),
                                  rescuer.table("emb").pull(probe))


def test_save_retires_segments_and_predecessor_dirs(tmp_path):
    batches = stream(3)
    victim = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    victim.create_table(spec())
    for ids, grads in batches:
        victim.table("emb").push(ids, grads, scale=0.5)
        victim._wal.append(wal.encode_push("emb", ids, grads, 0.5))
    victim._wal.sync()

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    rescuer.replay_wal()
    epoch_dirs = [d for _e, d in wal.epoch_dirs(wal_root(tmp_path))]
    assert len(epoch_dirs) == 2
    # snapshot commit: the predecessor's whole incarnation dir dies with
    # the covered segments — everything in it is in this snapshot
    rescuer.save(str(tmp_path / "ps-ckpt"), step=10)
    left = wal.epoch_dirs(wal_root(tmp_path))
    assert [e for e, _d in left] == [2]
    segs = [n for n in os.listdir(left[0][1]) if n.endswith(".wal")]
    assert len(segs) == 1  # only the freshly-cut open segment remains
    payloads, _c, _ok = wal.read_segment(os.path.join(left[0][1], segs[0]))
    assert payloads == []


def test_drain_save_keeps_wal(tmp_path):
    """The drain/handoff snapshot must NOT retire the log: it lands in a
    handoff dir a failure rescue never reads."""
    ids = np.arange(4)
    grads = np.ones((4, 8), np.float32)
    shard = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    shard.create_table(spec())
    shard.table("emb").push(ids, grads, scale=1.0)
    shard._wal.append(wal.encode_push("emb", ids, grads, 1.0))
    shard.save(str(tmp_path / "handoff"), step=0, marker_expected=1,
               retire_wal=False)
    d = wal.epoch_dirs(wal_root(tmp_path))[0][1]
    recs = [
        p for n in sorted(os.listdir(d)) if n.endswith(".wal")
        for p in wal.read_segment(os.path.join(d, n))[0]
    ]
    assert sum(1 for p in recs if wal.record_kind(p) == wal.REC_PUSH) == 1


def test_save_outside_rescue_dir_keeps_wal(tmp_path):
    """A snapshot committed anywhere but the rescue lineage (verify dumps,
    ad-hoc Save RPCs) must not retire segments: a failure rescue never
    reads it, so retiring against it would silently lose those pushes."""
    batches = stream(6)
    ck = str(tmp_path / "ps-ckpt")
    victim = PsShard(epoch=1, wal_root=wal_root(tmp_path), rescue_dir=ck)
    reference = PsShard()
    for s in (victim, reference):
        s.create_table(spec())
    for i, (ids, grads) in enumerate(batches):
        victim.Push(push_req("emb", ids, grads, scale=0.5), None)
        reference.table("emb").push(ids, grads, scale=0.5)
        if i == 2:
            victim.save(str(tmp_path / "ps-verify"), step=0)
    victim._wal.sync()

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path), rescue_dir=ck)
    with pytest.raises(FileNotFoundError):
        rescuer.restore(ck)  # the verify save is not a rescue point
    stats = rescuer.replay_wal()
    assert stats["pushes"] == len(batches)  # nothing was retired
    probe = np.arange(50)
    np.testing.assert_array_equal(
        rescuer.table("emb").pull(probe), reference.table("emb").pull(probe))


def test_torn_multi_shard_save_defers_retirement(tmp_path):
    """A save whose sibling shard dies before its done marker is not
    restorable, so it must keep the log; and once the step DOES complete,
    a rescue restoring it must not double-apply the records the snapshot
    already holds (the cut marker is the boundary)."""
    from easydl_tpu.ps.table import shard_of

    ck = str(tmp_path / "ps-ckpt")
    victim = PsShard(shard_index=0, num_shards=2, epoch=1,
                     wal_root=wal_root(tmp_path), rescue_dir=ck)
    reference = PsShard(shard_index=0, num_shards=2)
    for s in (victim, reference):
        s.create_table(spec())
    rng = np.random.default_rng(11)
    batches = []
    for _ in range(6):
        ids = rng.integers(0, 200, 64).astype(np.int64)
        ids = np.unique(ids[shard_of(ids, 2) == 0])[:16]
        grads = rng.standard_normal((len(ids), 8)).astype(np.float32)
        batches.append((ids, grads))
    epoch_dir = None
    pre_save_segs: set = set()
    for i, (ids, grads) in enumerate(batches):
        victim.Push(push_req("emb", ids, grads, scale=0.5), None)
        reference.table("emb").push(ids, grads, scale=0.5)
        if i == 2:
            epoch_dir = wal.epoch_dirs(wal_root(tmp_path))[0][1]
            pre_save_segs = set(os.listdir(epoch_dir))
            victim.save(ck, step=7)  # shard 1 never writes its marker
            assert pre_save_segs <= set(os.listdir(epoch_dir))
    victim._wal.sync()
    step_dir = os.path.join(ck, "step_0000000007")
    assert not PsShard.saved_steps(ck)  # torn: invisible to restore
    # the sibling commits its marker AFTER the victim died
    with open(os.path.join(step_dir, ".done-1"), "w") as f:
        f.write("2")

    rescuer = PsShard(shard_index=0, num_shards=2, epoch=2,
                      wal_root=wal_root(tmp_path), rescue_dir=ck)
    assert rescuer.restore(ck) == 7
    stats = rescuer.replay_wal()
    assert stats["pushes"] == 3  # only the post-snapshot pushes
    probe = np.arange(200)
    probe = probe[shard_of(probe, 2) == 0]
    np.testing.assert_array_equal(
        rescuer.table("emb").pull(probe), reference.table("emb").pull(probe))


def test_pull_rejected_when_fenced(tmp_path):
    """A superseded zombie must stop answering READS too: pulls are not
    epoch-stamped and never fail on a responsive server, so the fence
    aborts them with UNAVAILABLE — the one status the pull retry loop
    reroutes on."""
    import grpc

    workdir = str(tmp_path)
    shard = PsShard(epoch=1, wal_root=wal_root(tmp_path), workdir=workdir)
    shard.create_table(spec())
    ids = np.arange(4)
    grads = np.ones((4, 8), np.float32)
    # proof of successor: a newer-stamped push forces the registry check,
    # and the registry confirms the higher-epoch publication
    registry.publish(workdir, "rescuer", 0, 1, "localhost:2", epoch=2)
    ack = shard.Push(push_req("emb", ids, grads, epoch=2), None)
    assert not ack.ok and ack.message.startswith(STALE_EPOCH)

    class Abort(Exception):
        pass

    class Ctx:
        def abort(self, code, details):
            raise Abort(code, details)

    with pytest.raises(Abort) as ei:
        shard.Pull(pb.PullRequest(
            table="emb", raw_ids=ids.astype("<i8").tobytes()), Ctx())
    code, details = ei.value.args
    assert code == grpc.StatusCode.UNAVAILABLE
    assert STALE_EPOCH in details


def test_replay_dedupe_window_closes_at_snapshot_commit(tmp_path):
    """Replay digests exist to absorb the post-rescue retry storm; a
    snapshot commit ends that window, after which byte-identical pushes
    are genuinely new updates and must apply."""
    ids = np.arange(8)
    grads = np.ones((8, 8), np.float32)
    victim = PsShard(epoch=1, wal_root=wal_root(tmp_path))
    victim.create_table(spec())
    victim.table("emb").push(ids, grads, scale=1.0)
    victim._wal.append(wal.encode_push("emb", ids, grads, 1.0))
    victim._wal.sync()

    rescuer = PsShard(epoch=2, wal_root=wal_root(tmp_path))
    rescuer.replay_wal()
    rescuer.save(str(tmp_path / "ps-ckpt"), step=1)
    before = rescuer.table("emb").pull(ids).copy()
    ack = rescuer.Push(push_req("emb", ids, grads, epoch=2), None)
    assert ack.ok and "dedup" not in ack.message
    assert not np.array_equal(rescuer.table("emb").pull(ids), before)


def test_background_sync_survives_concurrent_cuts(tmp_path):
    """The background fsync races segment rotation: an fsync landing on
    the fd cut() just closed used to EBADF and permanently brick the log
    via _broken. Hammer the pair and prove the WAL stays appendable."""
    w = wal.PsWal(str(tmp_path), segment_bytes=1 << 30, sync_s=0.002)
    deadline = time.monotonic() + 0.5
    while time.monotonic() < deadline:
        w.append(wal.encode_create('{"n": 1}'))
        w.cut()
    assert w._broken is None
    w.append(wal.encode_create('{"n": 2}'))
    w.close()


# ----------------------------------------------------------------- fencing


def test_stale_epoch_push_rejected_retriably():
    shard = PsShard(epoch=3)
    shard.create_table(spec())
    ids, grads = np.arange(4), np.ones((4, 8), np.float32)
    before = shard.table("emb").pull(ids).copy()
    ack = shard.Push(push_req("emb", ids, grads, epoch=2), None)
    assert not ack.ok and ack.message.startswith(STALE_EPOCH)
    np.testing.assert_array_equal(shard.table("emb").pull(ids), before)
    # matching stamp applies; unstamped (legacy) is always accepted
    assert shard.Push(push_req("emb", ids, grads, epoch=3), None).ok
    assert shard.Push(push_req("emb", ids, grads, epoch=0), None).ok


def test_newer_epoch_push_fences_permanently(tmp_path, monkeypatch):
    """A push stamped with a NEWER epoch forces an unthrottled registry
    check; with the successor's publication confirmed there, the shard
    fences for good — even correctly-stamped pushes are now rejected (the
    zombie may not diverge from the successor). The huge throttle proves
    the FORCED check fenced us, not the periodic one."""
    monkeypatch.setenv("EASYDL_PS_FENCE_CHECK_S", "3600")
    workdir = str(tmp_path)
    shard = PsShard(epoch=3, workdir=workdir)
    shard.create_table(spec())
    registry.publish(workdir, "successor", 0, 1, "localhost:2", epoch=4)
    ids, grads = np.arange(4), np.ones((4, 8), np.float32)
    ack = shard.Push(push_req("emb", ids, grads, epoch=4), None)
    assert not ack.ok and ack.message.startswith(STALE_EPOCH)
    ack2 = shard.Push(push_req("emb", ids, grads, epoch=3), None)
    assert not ack2.ok and ack2.message.startswith(STALE_EPOCH)
    assert shard._fenced


def test_bogus_newer_stamp_does_not_fence_healthy_shard(tmp_path,
                                                        monkeypatch):
    """The registry is the only authority that can fence permanently: a
    push carrying a bogus higher epoch (client bug, corrupt field) against
    a shard the registry still shows as current is rejected retriably and
    must NOT disable the shard — correctly-stamped traffic keeps
    applying."""
    monkeypatch.setenv("EASYDL_PS_FENCE_CHECK_S", "0.0")
    workdir = str(tmp_path)
    shard = PsShard(epoch=3, workdir=workdir)
    shard.create_table(spec())
    registry.publish(workdir, "me", 0, 1, "localhost:1", epoch=3)
    ids, grads = np.arange(4), np.ones((4, 8), np.float32)
    ack = shard.Push(push_req("emb", ids, grads, epoch=7), None)
    assert not ack.ok and ack.message.startswith(STALE_EPOCH)
    assert not shard._fenced
    assert shard.Push(push_req("emb", ids, grads, epoch=3), None).ok


def test_fenced_shard_reads_dead_to_liveness_probe(tmp_path, monkeypatch):
    """A fenced zombie must FAIL the Stats liveness probe: probe_alive
    decides rescue-discovery liveness via Stats, and a fenced shard that
    kept answering would be adopted as live after its rescuer died —
    permanently blocking the next rescue while rejecting all traffic."""
    from easydl_tpu.ps.__main__ import probe_alive

    monkeypatch.setenv("EASYDL_PS_FENCE_CHECK_S", "0.0")
    monkeypatch.setenv("EASYDL_PS_PROBE_TIMEOUT_S", "2.0")
    workdir = str(tmp_path)
    shard = PsShard(epoch=1, workdir=workdir)
    shard.create_table(spec())
    srv = shard.serve(port=0)
    try:
        registry.publish(workdir, "me", 0, 1, srv.address, epoch=1)
        assert probe_alive(srv.address, attempts=1)
        registry.publish(workdir, "rescuer", 0, 1, "localhost:2", epoch=2)
        ids, grads = np.arange(4), np.ones((4, 8), np.float32)
        ack = shard.Push(push_req("emb", ids, grads, epoch=1), None)
        assert not ack.ok and shard._fenced
        assert not probe_alive(srv.address, attempts=1)
    finally:
        srv.stop()
        shard.stop()


def test_zombie_self_fences_via_registry(tmp_path, monkeypatch):
    """The resumed-zombie path: every client is stale (all stamp the OLD
    epoch), so only the shard's own throttled registry check can catch the
    takeover."""
    monkeypatch.setenv("EASYDL_PS_FENCE_CHECK_S", "0.0")
    workdir = str(tmp_path)
    shard = PsShard(epoch=1, workdir=workdir)
    shard.create_table(spec())
    ids, grads = np.arange(4), np.ones((4, 8), np.float32)
    registry.publish(workdir, "me", 0, 1, "localhost:1", epoch=1)
    assert shard.Push(push_req("emb", ids, grads, epoch=1), None).ok
    # ... SIGSTOP here, a rescuer takes over, SIGCONT ...
    registry.publish(workdir, "rescuer", 0, 1, "localhost:2", epoch=2)
    ack = shard.Push(push_req("emb", ids, grads, epoch=1), None)
    assert not ack.ok and ack.message.startswith(STALE_EPOCH)
    assert shard._fenced


def test_fence_rejection_reroutes_client_to_successor(tmp_path, monkeypatch):
    """The full convergence loop over real gRPC: a client with a stale
    route+epoch pushes at the superseded server, gets the retriable fence
    Ack, refreshes from the registry, and the push lands on the successor
    — bit-identical to a never-rerouted reference."""
    # No throttle on the registry self-check: the superseded server must
    # notice the takeover on its very next push (a real zombie has been
    # SIGSTOPped past the throttle anyway by the time it wakes).
    monkeypatch.setenv("EASYDL_PS_FENCE_CHECK_S", "0.0")
    workdir = str(tmp_path)
    old = PsShard(epoch=registry.bump_epoch(workdir, 0),
                  wal_root=wal_root(tmp_path), workdir=workdir)
    old_srv = old.serve(port=0)
    registry.publish(workdir, "old", 0, 1, old_srv.address, epoch=old.epoch)
    client = ShardedPsClient.from_registry(workdir, 1, timeout=10.0,
                                           drain_retry_s=30.0)
    reference = PsShard()
    reference.create_table(spec())
    batches = stream(4)
    probe = np.arange(50)
    try:
        client.create_table(spec())
        for ids, grads in batches[:2]:
            client.push("emb", ids, grads, scale=0.5)
            reference.table("emb").push(ids, grads, scale=0.5)
        old_state = old.table("emb").pull(probe).copy()

        # successor levels in: WAL-only recovery, higher epoch, republish
        new = PsShard(epoch=registry.bump_epoch(workdir, 0),
                      wal_root=wal_root(tmp_path), workdir=workdir)
        new.replay_wal()
        new_srv = new.serve(port=0)
        registry.publish(workdir, "new", 0, 1, new_srv.address,
                         epoch=new.epoch)
        try:
            # client still points at `old`; the fence bounces it across
            for ids, grads in batches[2:]:
                client.push("emb", ids, grads, scale=0.5)
                reference.table("emb").push(ids, grads, scale=0.5)
            assert client.addresses[0] == new_srv.address
            assert client._epochs[0] == new.epoch
            np.testing.assert_array_equal(
                new.table("emb").pull(probe),
                reference.table("emb").pull(probe))
            # the zombie fenced itself and applied nothing post-takeover
            assert old._fenced
            np.testing.assert_array_equal(old.table("emb").pull(probe),
                                          old_state)
        finally:
            new_srv.stop()
            new.stop()
    finally:
        old_srv.stop()
        old.stop()
        client.close()


# ---------------------------------------------------------------- registry


def test_bump_epoch_monotonic(tmp_path):
    w = str(tmp_path)
    assert registry.bump_epoch(w, 0) == 1
    assert registry.bump_epoch(w, 0) == 2
    assert registry.bump_epoch(w, 1) == 1  # per-shard counters
    assert registry.shard_epoch(w, 0) == 2
    assert registry.shard_epoch(w, 5) == 0  # never bumped


def test_shard_map_prefers_highest_epoch(tmp_path):
    w = str(tmp_path)
    registry.publish(w, "a", 0, 1, "localhost:1", epoch=2)
    time.sleep(0.01)
    # later publish, LOWER epoch (a zombie re-publishing): must not win
    registry.publish(w, "b", 0, 1, "localhost:2", epoch=1)
    assert registry.shard_map(w)[0]["address"] == "localhost:1"
    registry.publish(w, "c", 0, 1, "localhost:3", epoch=3)
    assert registry.shard_map(w)[0]["address"] == "localhost:3"


def test_sweep_stale_removes_dead_pid_entries(tmp_path):
    w = str(tmp_path)
    alive = subprocess.Popen([sys.executable, "-c",
                              "import time; time.sleep(60)"])
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait()
    try:
        registry.publish(w, "alive", 0, 2, "localhost:1", epoch=1)
        registry.publish(w, "dead", 1, 2, "localhost:2", epoch=1)
        registry.publish(w, "remote", 1, 2, "otherhost:3", epoch=1)
        # rewrite pids: publish() stamps os.getpid()
        for pod, pid in (("alive", alive.pid), ("dead", dead.pid),
                         ("remote", dead.pid)):
            p = os.path.join(w, "ps", f"ps-{pod}.json")
            with open(p) as f:
                doc = json.load(f)
            doc["pid"] = pid
            with open(p, "w") as f:
                json.dump(doc, f)
        assert registry.sweep_stale(w) == 1
        left = set(registry.entries(w))
        # dead localhost entry swept; live pid and other-host entries stay
        assert left == {"alive", "remote"}
        # the epoch counters outlive the sweep (fencing history)
        assert registry.bump_epoch(w, 1) == 1
    finally:
        alive.kill()
        alive.wait()


# ------------------------------------------------------------- async pusher


def test_drain_pushes_raises_promptly_when_no_reroute(tmp_path):
    """A shard stuck DRAINING with no replacement ever published: the
    bounded drain window must RAISE (naming the shard and the last Ack),
    not hang — and the raise must surface through AsyncPusher.drain with
    the failing push named."""
    shard = PsShard()
    srv = shard.serve(port=0)
    client = ShardedPsClient([srv.address], timeout=10.0, drain_retry_s=1.0)
    pusher = AsyncPusher(client, depth=2)
    try:
        client.create_table(spec())
        shard._draining = True  # migration started; nobody ever finishes it
        t0 = time.monotonic()
        pusher.submit("emb", np.arange(4), np.ones((4, 8), np.float32), 1.0)
        with pytest.raises(RuntimeError) as ei:
            pusher.drain()
        elapsed = time.monotonic() - t0
        assert elapsed < 10.0  # bounded by drain_retry_s, not a hang
        msg = str(ei.value)
        assert "emb" in msg  # the wrapper names the push
        cause = str(ei.value.__cause__)
        assert "shard 0" in cause and DRAINING in cause  # id + last ack
    finally:
        pusher.close()
        srv.stop()
        shard.stop()
        client.close()
