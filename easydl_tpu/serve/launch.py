"""Spawn serving-replica subprocesses and wait for their discovery files.

The ONE copy of the launch-and-wait idiom shared by the fleet bench
(``scripts/bench_serve.py --fleet``) and the chaos fleet drill
(``serve_replica_death_mid_flood``): both start N
``python -m easydl_tpu.serve`` processes against a job workdir and block
until every replica has published ``<workdir>/serve/<name>.json`` — the
same files the router's discovery scans. A CLI-flag or discovery-
convention change lands here once, not in two drifting copies.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def spawn_replicas(n: int, workdir: str, table: str, fields: int,
                   device_ms: float = 0.0, max_batch: int = 256,
                   max_wait_ms: float = 2.0, max_pending: int = 2048,
                   cache_mb: int = 32,
                   extra_env: Optional[Dict[str, str]] = None,
                   wait_s: float = 90.0,
                   name_prefix: str = "serve-") -> Dict[str, object]:
    """Launch ``n`` replica processes; returns {name: Popen} once every
    one has published its discovery file (kills them all and raises on
    timeout)."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               **(extra_env or {}))
    procs: Dict[str, object] = {}
    for i in range(n):
        name = f"{name_prefix}{i}"
        procs[name] = subprocess.Popen(
            [sys.executable, "-m", "easydl_tpu.serve",
             "--workdir", workdir, "--name", name,
             "--table", table, "--fields", str(int(fields)),
             "--max-batch", str(int(max_batch)),
             "--max-wait-ms", str(float(max_wait_ms)),
             "--max-pending", str(int(max_pending)),
             "--cache-mb", str(int(cache_mb)),
             "--device-ms", str(float(device_ms))],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    serve_dir = os.path.join(workdir, "serve")
    deadline = time.monotonic() + wait_s
    want = set(procs)
    while time.monotonic() < deadline:
        seen = ({os.path.splitext(f)[0] for f in os.listdir(serve_dir)
                 if f.endswith(".json")}
                if os.path.isdir(serve_dir) else set())
        if want <= seen:
            return procs
        time.sleep(0.1)
    for p in procs.values():
        p.kill()
    raise TimeoutError("serve replicas never published discovery files")
