"""Deterministic virtual-clock control-plane simulator.

The tentpole of ROADMAP item 3: scaling/drain/eviction policy used to be
exercisable only by live multi-process chaos drills (seconds-to-minutes
each, wall-clock-jittered); this engine replays a recorded or synthetic
signal timeline (sim/timeline.py) through the **real** policy objects —

- the real :class:`easydl_tpu.elastic.membership.Rendezvous` (the FSM is
  constructed with an injected virtual clock; every transition rule,
  including the preemption short-window and the straggler exclusion, is
  the production code path),
- the real :class:`easydl_tpu.brain.straggler.StragglerDetector` actuated
  through the same :func:`~easydl_tpu.brain.straggler.actuate_eviction`
  helper the live master's tick loop calls,
- the real :class:`easydl_tpu.brain.policy.Autoscaler` (``force_python``
  so verdicts are byte-identical with or without the native toolchain),

— under a discrete-event loop that models only what the control plane
cannot see: workers stepping at the recorded durations, heartbeats at the
agent cadence, checkpoints at the job cadence, faults at their scheduled
virtual timestamps. A multi-minute incident replays in milliseconds, with
NO subprocesses, NO sleeps, NO wall-clock reads — same timeline + same
policy ⇒ byte-identical verdict (asserted by chaos_smoke.sh running every
committed fixture twice).

The worker model is deliberately coarse (steps, checkpoints, drain at a
step boundary, fixed restart delay): the subject under test is the
*decision* layer, and every decision input it sees — step-time skew,
preemption flags, member loss, heartbeat gaps — is faithful to the
timeline. Invariants over the result live in sim/invariants.py.
"""

from __future__ import annotations

import itertools
import statistics
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

from easydl_tpu.brain.mesh_policy import MeshPolicyConfig, MeshShapePolicy
from easydl_tpu.brain.policy import Autoscaler, AutoscalerConfig
from easydl_tpu.brain.straggler import (
    StragglerConfig, StragglerDetector, actuate_eviction,
)
from easydl_tpu.core.mesh_shapes import MeshConstraints
from easydl_tpu.elastic.membership import JobPhase, Rendezvous
from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.utils.logging import get_logger

log = get_logger("sim", "simulator")


@dataclass
class MeshSimConfig:
    """Mesh-shape mode: replay the REAL MeshShapePolicy — candidates from
    the real enumeration, probes/adoption actuated through the real
    ``Rendezvous.request_mesh_reshape`` path. The timeline's
    ``meta.shape_profile`` supplies per-(world, shape) step time /
    throughput, the simulated analogue of the ``easydl_worker_mfu``
    signal the live policy consumes."""

    constraints: MeshConstraints = field(default_factory=MeshConstraints)
    policy: MeshPolicyConfig = field(default_factory=MeshPolicyConfig)
    #: operator pin (the runbook override / the negative control's
    #: deliberately pathological shape)
    pinned: str = ""


@dataclass
class SimPolicy:
    """The control-plane configuration under test — the simulator's
    equivalent of the live Master's constructor knobs."""

    desired_workers: int = 1
    min_workers: int = 1
    heartbeat_interval: float = 0.3
    heartbeat_timeout: float = 5.0
    tick_interval: float = 0.2
    #: agents register this far apart (mirrors the harness stagger: a0
    #: first, so single-member worlds deterministically pick it)
    register_stagger_s: float = 0.25
    #: RUN directive → first post-restore step (process spawn + restore +
    #: compile, collapsed into one constant)
    restart_delay_s: float = 1.0
    prepare_timeout_s: float = 0.0
    preempt_prepare_timeout_s: float = 20.0
    straggler: StragglerConfig = field(default_factory=StragglerConfig)
    #: feed the real Autoscaler and actuate its decisions as desired-worker
    #: changes when set (None = hold desired_workers fixed)
    autoscaler: Optional[AutoscalerConfig] = None
    #: feed the real MeshShapePolicy and actuate its probes/adoptions as
    #: mesh-shape reshapes when set (None = static mesh, the legacy path)
    mesh: Optional[MeshSimConfig] = None


@dataclass
class _SimAgent:
    agent_id: str
    stream: List[List[float]]
    tail_dt: float
    registered: bool = False
    alive: bool = True
    preempting: bool = False
    state: str = "idle"
    generation: int = -1
    coordinator: str = ""
    step: int = 0
    idx: int = 0          # next stream sample to consume
    next_hb_t: float = 0.0
    step_done_t: Optional[float] = None
    quiesce_pending: bool = False
    #: latest completed sample [dt, rate, world] — what the next heartbeat
    #: reports (the live agent reads only the metrics-JSONL tail too)
    last_sample: Optional[List[float]] = None
    last_observed_step: int = -1
    #: the applied RUN directive's decided mesh shape + world (mesh mode)
    mesh: str = ""
    world: int = 0


def _median(vals: List[float]) -> float:
    return float(statistics.median(vals)) if vals else 0.0


class ControlPlaneSimulator:
    """Single-use: build with a timeline + policy, call :meth:`run`."""

    #: dispatch priority at equal timestamps (then agent id): faults hit
    #: before anything reacts, steps land before the heartbeat that would
    #: report them, the master tick observes last.
    _PRIO = {"fault": 0, "step": 1, "hb": 2, "tick": 3}

    def __init__(self, timeline: Mapping[str, Any],
                 policy: Optional[SimPolicy] = None):
        self.timeline = timeline
        self.policy = policy or SimPolicy()
        self.now = 0.0
        p = self.policy
        ports = itertools.count(50000)
        self.mesh_policy: Optional[MeshShapePolicy] = (
            MeshShapePolicy(p.mesh.constraints, p.mesh.policy,
                            pinned=p.mesh.pinned)
            if p.mesh is not None else None
        )
        self.rdv = Rendezvous(
            desired_workers=p.desired_workers,
            min_workers=p.min_workers,
            heartbeat_timeout=p.heartbeat_timeout,
            port_alloc=lambda: next(ports),
            prepare_timeout_s=p.prepare_timeout_s,
            prepare_min_uptime_s=0.0,
            preempt_prepare_timeout_s=p.preempt_prepare_timeout_s,
            clock=lambda: self.now,
            mesh_select=(self.mesh_policy.decide
                         if self.mesh_policy is not None else None),
        )
        self.detector = StragglerDetector(p.straggler)
        self.autoscaler = (
            Autoscaler(p.autoscaler, clock=lambda: self.now,
                       force_python=True)
            if p.autoscaler is not None else None
        )
        meta = dict(timeline.get("meta", {}))
        self.total_steps = int(meta.get("total_steps", 0) or 0)
        self.ckpt_interval = int(meta.get("ckpt_interval", 100) or 100)
        self.world_profile: Dict[str, List[float]] = dict(
            meta.get("world_profile", {}))
        #: world -> shape key -> [step_time_s, global samples_per_sec]:
        #: the per-factorization performance surface mesh-mode agents step
        #: at (what the fleet would measure on real chips)
        self.shape_profile: Dict[str, Dict[str, List[float]]] = {
            str(w): dict(shapes)
            for w, shapes in dict(meta.get("shape_profile", {})).items()
        }
        self.agents: Dict[str, _SimAgent] = {}
        for i, (aid, stream) in enumerate(
                sorted(timeline.get("agents", {}).items())):
            # Exhausted-stream extrapolation: the recording's FINAL regime
            # continues. A recording cut mid-straggle (the live policy
            # mitigated and the worker stopped) must keep looking slow —
            # the median of the last 16 would erase a short recorded
            # straggle and a stricter replay policy would run out of
            # signal it is entitled to.
            tail = _median([s[0] for s in stream[-8:]]) or 0.05
            self.agents[aid] = _SimAgent(
                agent_id=aid, stream=[list(s) for s in stream],
                tail_dt=tail, next_hb_t=i * p.register_stagger_s,
            )
        self.faults: List[Dict[str, Any]] = [
            dict(f) for f in timeline.get("faults", [])
        ]
        self._fault_i = 0
        self._next_tick = 0.0
        self._active_stragglers: List[Dict[str, Any]] = []
        self.job_ckpt_step = 0
        self._gen_max_step: Dict[int, int] = {}
        self._gen_seen: set = set()
        self._as_last_fed: Tuple[int, int] = (-1, -1)
        self._mesh_last_fed: Tuple[int, int] = (-1, -1)
        self.mesh_reshapes: List[Dict[str, Any]] = []
        # ---- evidence the invariants judge
        self.evictions: List[Dict[str, Any]] = []
        self.switches: List[Dict[str, Any]] = []
        self.drains: List[Dict[str, Any]] = []
        self.kills: List[Dict[str, Any]] = []
        self.preempts: List[Dict[str, Any]] = []
        self.scale_decisions: List[Dict[str, Any]] = []
        self.events_simulated = 0
        meta_dur = float(meta.get("duration_s", 0.0) or 0.0)
        longest = max(
            (sum(s[0] for s in a.stream) for a in self.agents.values()),
            default=0.0,
        )
        self.horizon = meta_dur if meta_dur > 0 else (longest * 2.0 + 60.0)

    # ---------------------------------------------------------------- run
    def run(self) -> Dict[str, Any]:
        guard = 0
        while self.now <= self.horizon:
            guard += 1
            if guard > 2_000_000:
                raise RuntimeError("simulator event-count guard tripped")
            nxt = self._next_event()
            if nxt is None or nxt[0] > self.horizon:
                break
            t, _prio, _key, kind, payload = nxt
            self.now = t
            self.events_simulated += 1
            if kind == "fault":
                self._dispatch_fault(payload)
            elif kind == "step":
                self._complete_step(payload)
            elif kind == "hb":
                self._heartbeat(payload)
            elif kind == "tick":
                self._tick()
            if self.rdv.phase == JobPhase.DONE:
                break
        return self._result()

    def _next_event(self):
        best = None
        if self._fault_i < len(self.faults):
            f = self.faults[self._fault_i]
            best = self._consider(best, float(f["t"]), "fault", "", f)
        for aid in sorted(self.agents):
            a = self.agents[aid]
            if a.alive:
                best = self._consider(best, a.next_hb_t, "hb", aid, a)
            if a.step_done_t is not None:
                best = self._consider(best, a.step_done_t, "step", aid, a)
        best = self._consider(best, self._next_tick, "tick", "", None)
        return best

    def _consider(self, best, t: float, kind: str, key: str, payload):
        cand = (t, self._PRIO[kind], key, kind, payload)
        return cand if best is None or cand[:3] < best[:3] else best

    # ------------------------------------------------------------- faults
    def _dispatch_fault(self, f: Dict[str, Any]) -> None:
        self._fault_i += 1
        kind = f["kind"]
        aid = str(f.get("agent", ""))
        a = self.agents.get(aid)
        if kind == "straggler":
            if f.get("inject", True):
                self._active_stragglers.append(f)
        elif kind == "preempt_notice":
            if a is not None:
                a.preempting = True
            self.preempts.append({"t": self.now, "agent": aid})
        elif kind == "kill":
            worker_alive = a is not None and a.state == "running"
            if a is not None:
                if a.state == "running":
                    a.state = "idle"
                a.step_done_t = None
                a.quiesce_pending = False
                if dict(f.get("params", {})).get("vm_dies"):
                    a.alive = False
            self.kills.append({
                "t": self.now, "agent": aid,
                "worker_alive": worker_alive,
                "step": a.step if a is not None else 0,
            })
        elif kind == "agent_down":
            if a is not None:
                a.alive = False
                if a.state == "running":
                    a.state = "idle"
                a.step_done_t = None

    def _dt_for(self, a: _SimAgent) -> Tuple[float, float, int]:
        shaped = (
            self.shape_profile.get(str(a.world), {}).get(a.mesh)
            if a.mesh else None
        )
        profile = self.world_profile.get(str(len(self.rdv.members)))
        if shaped is not None:
            # Mesh mode: the agent steps at the (world, factorization)
            # cell of the performance surface its applied RUN decided.
            dt, rate = float(shaped[0]), float(shaped[1])
            world = a.world
        elif profile is not None:
            dt, rate = float(profile[0]), float(profile[1])
            world = len(self.rdv.members)
        elif a.idx < len(a.stream):
            dt, rate, world = a.stream[a.idx]
        else:
            dt, rate, world = a.tail_dt, 0.0, 1
        for f in self._active_stragglers:
            if f.get("agent") != a.agent_id:
                continue
            if self.now < float(f["t"]) or self.now >= float(
                    f.get("end_t", float("inf"))):
                continue
            params = dict(f.get("params", {}))
            if "factor" in params:
                dt *= float(params["factor"])
            if "sleep_s" in params:
                dt += float(params["sleep_s"])
        return float(dt), float(rate), int(world)

    # -------------------------------------------------------------- steps
    def _complete_step(self, a: _SimAgent) -> None:
        dt, rate, world = self._dt_for(a)
        a.step += 1
        a.idx += 1
        a.last_sample = [dt, rate, world]
        if a.agent_id in self.rdv.members:
            g = self.rdv.generation
            self._gen_max_step[g] = max(self._gen_max_step.get(g, 0),
                                        a.step)
            if self.ckpt_interval > 0 and a.step % self.ckpt_interval == 0:
                self.job_ckpt_step = max(self.job_ckpt_step, a.step)
        if a.quiesce_pending:
            a.quiesce_pending = False
            a.state = "quiesced"
            a.step_done_t = None
            self.job_ckpt_step = max(self.job_ckpt_step, a.step)
            self.drains.append({"t": self.now, "agent": a.agent_id,
                                "step": a.step})
            return
        if self.total_steps and a.step >= self.total_steps:
            a.state = "done"
            a.step_done_t = None
            return
        ndt, _, _ = self._dt_for(a)
        a.step_done_t = self.now + ndt

    # ---------------------------------------------------------- heartbeats
    def _heartbeat(self, a: _SimAgent) -> None:
        a.next_hb_t = self.now + self.policy.heartbeat_interval
        self._master_intake(a)
        if not a.registered:
            d = self.rdv.register(a.agent_id, host=a.agent_id, slots=1,
                                  preempting=a.preempting)
            a.registered = True
        else:
            d = self.rdv.heartbeat(
                a.agent_id, a.generation, a.state, step=a.step,
                preempting=a.preempting,
            )
        self._apply_directive(a, d)

    def _master_intake(self, a: _SimAgent) -> None:
        """What the live master does with a heartbeat's metrics payload:
        feed the straggler detector (members only, step-deduped inside)
        and the autoscaler (one aggregate per advanced job step)."""
        if a.last_sample is None or a.agent_id not in self.rdv.members:
            return
        dt, rate, world = a.last_sample
        if a.step > a.last_observed_step:
            a.last_observed_step = a.step
            self.detector.observe(a.agent_id, dt, a.step, self.now,
                                  generation=self.rdv.generation)
        if self.autoscaler is not None and rate > 0 \
                and a.agent_id == (self.rdv.members or [""])[0]:
            gen = self.rdv.generation
            if (gen, a.step) > self._as_last_fed:
                self._as_last_fed = (gen, a.step)
                self.autoscaler.observe(pb.StepMetrics(
                    step=a.step, step_time_s=dt, samples_per_sec=rate,
                    world_size=max(world, 1),
                ))
        # Mesh-shape intake mirrors the live master's: the CURRENT
        # generation's decided shape, per advanced (generation, step),
        # one reporting member (the aggregate the live master forwards).
        if self.mesh_policy is not None and rate > 0 \
                and self.rdv.mesh and a.mesh == self.rdv.mesh \
                and a.agent_id == (self.rdv.members or [""])[0]:
            gen = self.rdv.generation
            if (gen, a.step) > self._mesh_last_fed:
                self._mesh_last_fed = (gen, a.step)
                self.mesh_policy.observe(max(world, 1), self.rdv.mesh,
                                         rate)

    def _apply_directive(self, a: _SimAgent, d) -> None:
        if d.kind == "run":
            if (d.generation, d.coordinator) == (a.generation,
                                                 a.coordinator):
                return
            a.generation = d.generation
            a.coordinator = d.coordinator
            a.mesh = d.mesh
            a.world = d.world_size
            a.state = "running"
            a.quiesce_pending = False
            if d.generation not in self._gen_seen:
                self._gen_seen.add(d.generation)
                prev_max = max(
                    (s for g, s in self._gen_max_step.items()
                     if g < d.generation), default=0)
                self.switches.append({
                    "t": self.now, "generation": d.generation,
                    "members": list(d.hosts),
                    "mesh": d.mesh,
                    "resumed_from_step": self.job_ckpt_step,
                    "steps_lost": max(0, prev_max - self.job_ckpt_step),
                })
            a.step = self.job_ckpt_step
            ndt, _, _ = self._dt_for(a)
            a.step_done_t = self.now + self.policy.restart_delay_s + ndt
        elif d.kind == "quiesce":
            if a.state == "running":
                a.quiesce_pending = True
        elif d.kind == "kill":
            if a.state == "running":
                a.state = "idle"
            a.step_done_t = None
            a.quiesce_pending = False
        elif d.kind == "shutdown":
            a.state = "done"
            a.step_done_t = None

    # --------------------------------------------------------------- tick
    def _tick(self) -> None:
        self._next_tick = self.now + self.policy.tick_interval
        self.rdv.tick(self.now)
        cand = actuate_eviction(self.detector, self.rdv, self.now)
        if cand is not None:
            self.evictions.append({
                "t": self.now, "agent": cand,
                "holddown_s": self.detector.config.holddown_s,
            })
        if self.autoscaler is not None \
                and self.rdv.phase == JobPhase.STABLE and self.rdv.members:
            world = len(self.rdv.members)
            target = self.autoscaler.decide(world)
            if target != world and target != self.rdv.desired_workers:
                self.scale_decisions.append({
                    "t": self.now, "from_workers": world,
                    "to_workers": target,
                })
                self.rdv.set_desired_workers(target)
        # Mesh-shape refinement, actuated exactly like the live master's
        # tick: only over a fully-running STABLE generation, through the
        # real request_mesh_reshape path.
        if (
            self.mesh_policy is not None
            and self.rdv.phase == JobPhase.STABLE and self.rdv.members
            and all(
                self.agents[m].state == "running"
                and self.agents[m].generation == self.rdv.generation
                for m in self.rdv.members if m in self.agents
            )
        ):
            world = len(self.rdv.members)
            if self.mesh_policy.want_reshape(world, self.now):
                if self.rdv.request_mesh_reshape():
                    self.mesh_policy.note_reshape(self.now)
                    self.mesh_reshapes.append({
                        "t": self.now, "world": world,
                        "from_mesh": self.rdv.mesh,
                    })

    # ------------------------------------------------------------- result
    def _result(self) -> Dict[str, Any]:
        def r6(x: float) -> float:
            return round(float(x), 6)

        def stamp(entries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
            out = []
            for e in entries:
                e = dict(e)
                for k, v in e.items():
                    if isinstance(v, float):
                        e[k] = r6(v)
                out.append(e)
            return out

        pol = asdict(self.policy)
        mesh_doc = None
        if self.mesh_policy is not None:
            mesh_doc = {
                "final_shape": self.rdv.mesh,
                "final_world": len(self.rdv.members),
                "log": stamp([
                    {k: v for k, v in e.items()} for e in self.rdv.mesh_log
                ]),
                "reshapes": stamp(self.mesh_reshapes),
                "policy": self.mesh_policy.status(),
            }
        det = self.detector.status()
        hu = det.get("holddown_until")
        det["holddown_until"] = None if hu is None else r6(float(hu))
        det["evictions"] = stamp(det["evictions"])
        return {
            "name": str(self.timeline.get("name", "")),
            "source": str(self.timeline.get("source", "")),
            "policy": pol,
            "final": {
                "phase": self.rdv.phase.value,
                "generation": self.rdv.generation,
                "members": list(self.rdv.members),
                "desired_workers": self.rdv.desired_workers,
                "steps": {aid: a.step
                          for aid, a in sorted(self.agents.items())},
                "excluded": sorted(
                    aid for aid, v in self.rdv.agents.items()
                    if v.excluded_until > self.now),
                "max_step": max(
                    (a.step for a in self.agents.values()), default=0),
            },
            "reshapes": stamp(self.rdv.reshape_log),
            "evictions": stamp(self.evictions),
            "switches": stamp(self.switches),
            "drains": stamp(self.drains),
            "kills": stamp(self.kills),
            "preempts": stamp(self.preempts),
            "scale_decisions": stamp(self.scale_decisions),
            "mesh": mesh_doc,
            "detector": det,
            "events_simulated": self.events_simulated,
            "sim_end_t": r6(self.now),
        }


def simulate(timeline: Mapping[str, Any],
             policy: Optional[SimPolicy] = None,
             expect: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
    """Run one simulation and (when ``expect`` is given) attach the
    invariant verdict — the one-call entry scripts/policy_replay.py and
    the tier-1 tests use."""
    result = ControlPlaneSimulator(timeline, policy).run()
    if expect is not None:
        from easydl_tpu.sim import invariants

        verdict = invariants.check(result, dict(expect), timeline)
        result["expect"] = dict(expect)
        result["invariants"] = verdict
        result["passed"] = verdict["passed"]
    return result
