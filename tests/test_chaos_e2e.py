"""Chaos drills end-to-end: each test runs one catalog scenario through
ChaosHarness — a real gRPC master, real agents, real jax.distributed worker
subprocesses (and real PS pods where the scenario needs them) — injects the
seed-deterministic fault schedule, and requires EVERY recovery invariant to
hold.

Tier-1 runs only the fastest drill (worker SIGKILL). The rest are
``slow`` + ``chaos`` (see pyproject.toml markers): run the whole catalog
with ``pytest -m chaos`` or ``python scripts/chaos_run.py``.
"""

import json
import os
import subprocess
import sys

import pytest

from easydl_tpu.chaos.harness import run_scenario

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(name, tmp_path):
    verdict = run_scenario(name, workdir=str(tmp_path))
    assert verdict["passed"], json.dumps(verdict["invariants"], indent=2)
    # the cross-check wiring really saw injected faults where declared
    if verdict["expect"].get("min_faults"):
        assert verdict["faults_injected"], verdict
    return verdict


@pytest.mark.chaos  # no `slow`: this one rides tier-1 AND `-m chaos`
def test_chaos_worker_kill_scenario(tmp_path):
    """The tier-1 drill: SIGKILL the member's worker, no notice. The job
    must reach its target step with ≤ ckpt_interval steps lost, generation
    monotonic, the world converged, and no reshape churn — and the
    min_final_generation invariant proves a recovery actually happened."""
    verdict = _run("worker_kill", tmp_path)
    assert verdict["faults_injected"].get("worker_kill", 0) >= 1
    assert verdict["final_status"]["generation"] >= 2


@pytest.mark.chaos  # no `slow`: the fast FAILOVER drill also rides tier-1
def test_chaos_master_crash_scenario(tmp_path):
    """Control-plane failover: the master dies at steady state and a fresh
    one restores the membership journal over the same workdir. Zero
    reshapes after the failover, training progress recorded INSIDE the
    outage window, generation monotonic, job reaches its target step."""
    verdict = _run("master_crash", tmp_path)
    assert verdict["faults_injected"].get("master_crash", 0) >= 1
    checks = verdict["invariants"]["checks"]
    assert checks["no_spurious_reshape_after_failover"]["ok"]
    assert checks["training_progress_during_outage"]["ok"]
    assert verdict["outages"] and "t_up" in verdict["outages"][0]
    # the failover really went through the journal-restore path
    assert checks["no_spurious_reshape_after_failover"]["failovers"] >= 1

    # ISSUE 4 acceptance: the completed drill's workdir exports to a
    # Perfetto-loadable trace.json with ≥1 generation-switch span tree
    # whose worker-side child spans share the master's trace_id, and the
    # injected fault present as an instant event.
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "trace_export.py"),
         "--workdir", str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout
    doc = json.loads((tmp_path / "trace.json").read_text())
    events = doc["traceEvents"]
    proc_names = {e["pid"]: e["args"]["name"] for e in events
                  if e.get("ph") == "M" and e["name"] == "process_name"}
    switches = [e for e in events
                if e["name"] == "generation_switch" and e["ph"] == "X"]
    assert switches, "no generation_switch span tree in the merged trace"
    switch_traces = {e["args"]["trace"] for e in switches}
    worker_spans = [
        e for e in events
        if str(proc_names.get(e.get("pid"), "")).startswith("worker-")
        and e.get("args", {}).get("trace") in switch_traces
    ]
    assert worker_spans, "no worker-side span shares a switch trace_id"
    faults = [e for e in events if e["name"].startswith("fault:")]
    assert any(e["name"] == "fault:master_crash" for e in faults), faults


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_master_restart_mid_drain_scenario(tmp_path):
    """Master crash DURING a notice-driven drain: the restarted master
    resumes the in-flight drain from the journal (or adopts its completed
    result) — at most one reshape after the failover, never two."""
    verdict = _run("master_restart_mid_drain", tmp_path)
    assert verdict["faults_injected"].get("master_crash", 0) >= 1
    assert verdict["faults_injected"].get("preempt_notice", 0) >= 1
    assert verdict["final_status"]["generation"] >= 2


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_heartbeat_loss_scenario(tmp_path):
    """Agent hang past the eviction threshold: evicted, survivors reshape,
    then the agent returns and the world converges back to plan."""
    _run("heartbeat_loss", tmp_path)


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_rpc_burst_scenario(tmp_path):
    """Drop/delay burst on agent→master RPCs below the eviction threshold:
    the retry/backoff path must ride it out with zero reshapes."""
    verdict = _run("rpc_burst", tmp_path)
    assert verdict["faults_injected"].get("rpc_drop", 0) >= 1


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_ps_shard_crash_scenario(tmp_path):
    """SIGKILL a live PS shard pod mid-job; a rescue pod claims the orphan
    and the worker's pull/push retry + registry reroute ride the outage
    without a worker generation switch."""
    _run("ps_shard_crash", tmp_path)
    # the registry's authoritative server for the killed shard is the rescue
    from easydl_tpu.ps import registry

    owner = registry.shard_map(str(tmp_path))[1]["pod"]
    assert "rescue" in owner, owner


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_ckpt_corrupt_scenario(tmp_path):
    """Corrupt the newest committed checkpoint then SIGKILL the worker: the
    restore must quarantine the damaged step and fall back to the previous
    one instead of crash-looping."""
    verdict = _run("ckpt_corrupt", tmp_path)
    assert verdict["faults_injected"].get("corrupt_latest_ckpt", 0) >= 1
    # The fallback really fired: more than one ckpt_interval of steps was
    # lost, which only happens when the restore skipped the corrupted
    # latest commit for the previous one. (The CORRUPT marker itself is
    # ephemeral — the recovered worker re-trains through the quarantined
    # step and re-saves over it, clearing the debris.)
    worst = verdict["invariants"]["checks"]["steps_lost_bounded"]["worst"]
    assert worst > 1000, verdict["invariants"]["checks"]["steps_lost_bounded"]


@pytest.mark.chaos  # no `slow`: the zero-loss certification rides tier-1
def test_chaos_ps_zero_loss_scenario(tmp_path):
    """ISSUE 6 acceptance: SIGKILL a PS shard mid-push-storm (after a
    snapshot commit) — the rescue restores the snapshot, replays the push
    WAL, and the surviving tier's tables digest-match a fault-free
    in-process replay of the exact same stream, optimizer rows included.
    The verdict must show the log was actually consumed."""
    verdict = _run("ps_shard_crash_zero_loss", tmp_path)
    assert verdict["faults_injected"].get("ps_kill", 0) >= 1
    checks = verdict["invariants"]["checks"]
    assert checks["ps_zero_loss_bit_identical"]["ok"]
    assert checks["ps_wal_replayed"]["wal_replayed_records"] >= 1
    assert verdict["zero_loss"]["digests_match"]
    # the evidence artifact is on disk for post-incident reading
    assert (tmp_path / "ps-zero-loss.json").exists()


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_serve_replica_death_mid_flood_scenario(tmp_path):
    """ISSUE 14 acceptance: a serving replica is SIGKILLed mid-flash-crowd
    behind the fleet router — ejection + hold-down, ≥1 hedge fired AND
    won/rescued, zero hard failures, a bounded p99 spike, every served
    score bit-exact vs a cache-bypassing wire client across acked
    pushes, and ≥1 shm pull observed (the anti-vacuous gates live in the
    serve_fleet_resilient invariant)."""
    verdict = _run("serve_replica_death_mid_flood", tmp_path)
    assert verdict["faults_injected"].get("serve_replica_kill", 0) >= 1
    checks = verdict["invariants"]["checks"]
    fleet = checks["serve_fleet_resilient"]
    assert fleet["ok"]
    assert fleet["hard_failures"] == 0
    assert fleet["ejections"] >= 1
    assert fleet["hedges_fired"] >= 1
    assert fleet["stale_check"]["mismatches"] == 0
    assert (tmp_path / "fleet-evidence.json").exists()


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_ps_zombie_writer_scenario(tmp_path):
    """The partition variant: SIGSTOP the shard's pod, rescue with a
    higher epoch, SIGCONT — the resumed zombie must fence itself (reject
    an old-epoch push) and apply zero stale-epoch pushes, and digest
    parity must still hold."""
    verdict = _run("ps_zombie_writer", tmp_path)
    assert verdict["faults_injected"].get("ps_pause", 0) >= 1
    checks = verdict["invariants"]["checks"]
    assert checks["ps_zero_loss_bit_identical"]["ok"]
    assert checks["ps_zombie_fenced"]["ok"]
    z = verdict["zero_loss"]["zombie"]
    assert z["probe_rejected_stale_epoch"] and z["excess_wal_bytes"] == 0


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_straggler_mitigation_scenario(tmp_path):
    """ISSUE 8 acceptance: the member's worker turns ~100x slower; the
    master's skew detector must evict it via a planned reshape that
    excludes the host (within the declared budget of the straggler
    window's start), the standby takes over, and ZERO further reshapes
    happen inside the hold-down window. The injector count is recovered
    from the worker's trace flight recorder — anti-vacuous."""
    verdict = _run("straggler_mitigation", tmp_path)
    assert verdict["faults_injected"].get("straggler", 0) >= 1
    checks = verdict["invariants"]["checks"]
    assert checks["straggler_mitigated"]["ok"]
    assert checks["holddown_quiet"]["ok"]
    assert "a0" not in verdict["final_status"]["members"]
    # the reshape was counted under its cause
    events = [e for e in _events(tmp_path) if e.get("kind") == "reshape"]
    assert any(e.get("reason") == "straggler" for e in events), events


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_preempt_race_scenario(tmp_path):
    """ISSUE 8 acceptance: preemption notice at t, SIGKILL at t+grace —
    the drain checkpoint (the worker's own quiesce_exit record) must land
    strictly before the kill timestamp, with the kill finding no live
    worker. Reactive recovery after the kill fails the drill."""
    verdict = _run("preempt_race", tmp_path)
    assert verdict["faults_injected"].get("preempt_notice", 0) >= 1
    race = verdict["invariants"]["checks"]["proactive_drain_before_kill"]
    assert race["ok"] and race["races"][0]["margin_s"] > 0
    assert race["races"][0]["worker_alive_at_kill"] is False
    events = [e for e in _events(tmp_path) if e.get("kind") == "reshape"]
    assert any(e.get("reason") == "preemption" for e in events), events


def _events(tmp_path):
    out = []
    with open(os.path.join(str(tmp_path), "events.jsonl")) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
