"""Online serving tier: stateless inference frontends over the live PS.

The training side of this repo writes embedding tables through the PS
push path; this package is the read side the north star promises
("serve heavy traffic from millions of users"): a batched, jitted
forward pass whose sparse rows are pulled READ-ONLY from the live PS
tier through :class:`easydl_tpu.ps.read_client.PsReadClient` — the same
pull code path the trainer rides, so every wire win (raw_ids, fp16,
chunked concurrent transfers, stale-route handling) is inherited, never
reimplemented.

- :mod:`easydl_tpu.serve.cache` — the hot-id client-side embedding
  cache (byte-bounded LRU, version/generation invalidated).
- :mod:`easydl_tpu.serve.frontend` — micro-batching request queue with
  deadline-based admission control, the jitted forward, the
  ``easydl.Serve`` gRPC service, and the ``easydl_serve_*`` telemetry.
- :mod:`easydl_tpu.serve.routing` / :mod:`easydl_tpu.serve.router` —
  the fleet layer: pure least-loaded + session-affinity dispatch policy,
  and the router that actuates it over every discovered replica with
  request hedging, ejection + hold-down, and fleet-wide load gauges.
"""

from easydl_tpu.serve.cache import HotIdCache  # noqa: F401
from easydl_tpu.serve.frontend import (  # noqa: F401
    SERVE_SERVICE,
    InferResult,
    ServeConfig,
    ServeFrontend,
)
from easydl_tpu.serve.router import ServeRouter  # noqa: F401
from easydl_tpu.serve.routing import ReplicaView, route_decision  # noqa: F401
