"""Perf exploration sweep for the flagship bench config (run on real TPU).

Times several (remat, batch, dtype, attention) variants in one process and
prints a line per config — the evidence base for bench.py's chosen settings.
Usage: python scripts/bench_sweep.py [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import time


def mesh_table(paths) -> None:
    """Aggregate per-shape MFU cells (``bench.py --mesh-sweep`` output,
    MULTICHIP_r06-style docs) into one table: devices x shape -> MFU /
    samples/s/chip. Multiple docs merge (e.g. a CPU sweep + a later real-
    TPU sweep); later files win on (devices, mesh) collisions."""
    cells = {}
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        for c in doc.get("cells", []):
            cells[(int(c.get("n_chips", 0)), str(c.get("mesh", "")))] = c
    if not cells:
        raise SystemExit("no mesh MFU cells in the given files")
    print(f"{'devices':>7}  {'mesh':24s} {'mfu':>12} "
          f"{'samples/s/chip':>15} {'step_ms':>9}")
    best = {}
    for (n, mesh), c in sorted(cells.items()):
        best.setdefault(n, (0.0, ""))
        if c.get("mfu", 0.0) > best[n][0]:
            best[n] = (c["mfu"], mesh)
        print(f"{n:>7}  {mesh:24s} {c.get('mfu', 0.0):>12.8f} "
              f"{c.get('value', 0.0):>15.3f} "
              f"{1000 * c.get('step_time_s', 0.0):>9.1f}")
    for n, (m, mesh) in sorted(best.items()):
        print(f"BEST {n}dev: {mesh} (mfu {m:.8f})")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--mesh-table", nargs="+", metavar="JSON",
                    help="aggregate bench.py --mesh-sweep docs into one "
                         "per-shape MFU table and exit (no jax import)")
    args = ap.parse_args()
    if args.mesh_table:
        mesh_table(args.mesh_table)
        return

    import jax
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig, Trainer
    from easydl_tpu.models.registry import get_model

    n_chips = jax.device_count()
    bf16_dots = dict(remat=True, remat_policy="dots", dtype="bfloat16")
    # r2 sweep (kept for the record): f32 b8 27.6 / bf16 b8 37.9 / bf16
    # no-remat and mb>8 OOMed on the f32 logits buffer; b64/a8 39.9,
    # b128/a16 40.1. r3 removes the logits buffer (fused chunked LM loss),
    # so this sweep explores the unlocked microbatch/chunk frontier.
    configs = [
        # (label, model kwargs, per-chip batch, grad_accum)
        ("plain  b64/a8  mb8 (r2 best)",
         dict(fused_loss=False, **bf16_dots), 64, 8),
        ("fused c128 b64/a8  mb8",
         dict(fused_loss=True, loss_chunk=128, **bf16_dots), 64, 8),
        ("fused c128 b128/a16 mb8",
         dict(fused_loss=True, loss_chunk=128, **bf16_dots), 128, 16),
        ("fused c128 b128/a8  mb16",
         dict(fused_loss=True, loss_chunk=128, **bf16_dots), 128, 8),
        ("fused c256 b128/a8  mb16",
         dict(fused_loss=True, loss_chunk=256, **bf16_dots), 128, 8),
        ("fused c512 b128/a8  mb16",
         dict(fused_loss=True, loss_chunk=512, **bf16_dots), 128, 8),
        ("fused c128 b256/a8  mb32",
         dict(fused_loss=True, loss_chunk=128, **bf16_dots), 256, 8),
        ("fused c128 b128/a4  mb32",
         dict(fused_loss=True, loss_chunk=128, **bf16_dots), 128, 4),
        ("fused c128 no-remat b128/a8 mb16",
         dict(fused_loss=True, loss_chunk=128, dtype="bfloat16"), 128, 8),
        # accum_unroll hypothesis: lax.scan unroll lets XLA fuse the
        # accumulation carry update across microbatches. (The r4 trace
        # numbers once cited here are RETRACTED — that parser was
        # incoherent; see PROFILE.json r4_attribution_superseded. The
        # rewritten invariant-checked attribution re-records first.)
        # UNMEASURED on TPU so far (tunnel down through r4 and r5);
        # still the first lever to sweep on a live chip.
        ("plain  b256/a32 u1 (r4 bench)",
         dict(fused_loss=False, **bf16_dots), 256, 32, 1),
        ("plain  b256/a32 u2",
         dict(fused_loss=False, **bf16_dots), 256, 32, 2),
        ("plain  b256/a32 u4",
         dict(fused_loss=False, **bf16_dots), 256, 32, 4),
        ("plain  b256/a32 u8",
         dict(fused_loss=False, **bf16_dots), 256, 32, 8),
    ]
    for label, kwargs, per_chip_batch, grad_accum, *rest in configs:
        accum_unroll = rest[0] if rest else 1
        global_batch = per_chip_batch * n_chips
        try:
            bundle = get_model("gpt", size="345m", seq_len=args.seq, **kwargs)
            trainer = Trainer(
                init_fn=bundle.init_fn,
                loss_fn=bundle.loss_fn,
                optimizer=optax.adamw(2e-4, weight_decay=0.01),
                config=TrainConfig(global_batch=global_batch,
                                   grad_accum=grad_accum,
                                   accum_unroll=accum_unroll),
                mesh_spec=MeshSpec(dp=n_chips),
            )
            state = trainer.init_state()
            data = iter(bundle.make_data(global_batch))
            for _ in range(2):
                state, metrics = trainer.train_step(state, next(data))
            float(jax.device_get(metrics["loss"]))
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, metrics = trainer.train_step(state, next(data))
            float(jax.device_get(metrics["loss"]))
            dt = time.perf_counter() - t0
            sps = args.steps * global_batch / dt / n_chips
            print(f"RESULT {label:28s} {sps:8.2f} samples/s/chip  "
                  f"step {dt / args.steps * 1000:7.1f} ms", flush=True)
            del state, trainer
        except Exception as e:  # OOM etc: report and keep sweeping
            print(f"RESULT {label:28s} FAILED: {type(e).__name__}: {e}",
                  flush=True)


if __name__ == "__main__":
    main()
