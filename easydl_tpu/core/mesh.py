"""Device-mesh construction — the TPU-native substrate for every parallelism.

The reference scales by adding/removing PS and worker *pods*
(README.md:31-35); here the unit of scale is a chip in a
``jax.sharding.Mesh``. One mesh with named axes expresses every strategy the
framework supports — data (``dp``), fully-sharded data (``fsdp``), tensor
(``tp``), sequence/context (``sp``), expert (``ep``) and pipeline (``pp``)
parallelism — and GSPMD inserts the matching ICI/DCN collectives.

Axis order puts ``tp``/``sp`` innermost so their collectives ride
nearest-neighbour ICI links on real TPU topologies
(``mesh_utils.create_device_mesh`` does the physical assignment).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# The logical-shape algebra (MeshSpec, constraints, enumeration) lives in
# the jax-free twin module so the membership FSM / Brain policy / offline
# simulator can import it without dragging jax in; re-exported here so
# `from easydl_tpu.core.mesh import MeshSpec` keeps working.
from easydl_tpu.core.mesh_shapes import (  # noqa: F401
    AXES,
    BATCH_AXES,
    MeshConstraints,
    MeshSpec,
    enumerate_shapes,
    validate_shape,
)


def build_mesh(
    spec: MeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
    num_slices: int = 1,
) -> Mesh:
    """Materialise a :class:`MeshSpec` over real (or forced-CPU) devices.

    On TPU, ``mesh_utils.create_device_mesh`` maps logical axes onto the
    physical torus so innermost axes get contiguous ICI neighbours; elsewhere
    (CPU tests) a plain reshape suffices.

    ``num_slices > 1`` builds a **hybrid ICI+DCN mesh** for multi-slice
    jobs (the scaling-book recipe): the slice dimension becomes the MAJOR
    stride of the ``dp`` axis — gradient all-reduce then decomposes into a
    fast per-slice ICI reduce plus one cross-slice DCN exchange per step
    (XLA's hierarchical collectives), while model axes (fsdp/tp/sp/ep/pp)
    stay entirely within a slice. Requires ``spec.dp % num_slices == 0``;
    slice membership comes from ``device.slice_index`` when the platform
    reports it, else devices are chunked evenly in order (tests).
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    n = spec.size
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    devices = devices[:n]
    shape = spec.axis_sizes()
    if num_slices > 1:
        return _build_hybrid_mesh(spec, devices, num_slices)
    if devices[0].platform == "tpu":
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, AssertionError):
            dev_array = np.asarray(devices).reshape(shape)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def _build_hybrid_mesh(
    spec: MeshSpec, devices: Sequence[jax.Device], num_slices: int
) -> Mesh:
    if spec.dp % num_slices:
        raise ValueError(
            f"dp={spec.dp} must be divisible by num_slices={num_slices} "
            "(dp is the only axis that may cross DCN)"
        )
    per_slice = len(devices) // num_slices
    by_slice: dict = {}
    for i, d in enumerate(devices):
        key = getattr(d, "slice_index", i // per_slice)
        by_slice.setdefault(key, []).append(d)
    if len(by_slice) != num_slices or any(
        len(v) != per_slice for v in by_slice.values()
    ):
        raise ValueError(
            f"devices don't form {num_slices} equal slices: "
            f"{ {k: len(v) for k, v in by_slice.items()} }"
        )
    # Per-slice ICI mesh with the slice's dp share, then stack slices as the
    # major dp dimension.
    slice_spec = MeshSpec(
        dp=spec.dp // num_slices, fsdp=spec.fsdp, tp=spec.tp,
        sp=spec.sp, ep=spec.ep, pp=spec.pp,
    )
    slice_shape = slice_spec.axis_sizes()
    stacks = []
    for key in sorted(by_slice):
        devs = by_slice[key]
        if devs[0].platform == "tpu":
            try:
                arr = mesh_utils.create_device_mesh(slice_shape, devices=devs)
            except (ValueError, AssertionError):
                arr = np.asarray(devs).reshape(slice_shape)
        else:
            arr = np.asarray(devs).reshape(slice_shape)
        stacks.append(arr)
    dp_axis = AXES.index("dp")
    dev_array = np.concatenate(stacks, axis=dp_axis)
    return Mesh(dev_array, AXES)


def batch_divisor(mesh: Mesh) -> int:
    """Number of ways the global batch is split (product of batch axes)."""
    return math.prod(mesh.shape[a] for a in BATCH_AXES)
