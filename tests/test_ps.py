"""Sparse PS tier tests: native/numpy store parity, optimizer math, shard
routing, gRPC pull/push, reshard-on-restore, and the jit-visible lookup
(SURVEY.md §7 step 5; BASELINE config 5)."""

import numpy as np
import pytest

from easydl_tpu.ps import (
    LocalPsClient,
    PsShard,
    ShardedPsClient,
    TableSpec,
    shard_of,
)
from easydl_tpu.ps.build import load_native
from easydl_tpu.ps.table import EmbeddingTable


def spec(**kw):
    base = dict(name="emb", dim=8, init_std=0.01, seed=7, optimizer="sgd", lr=0.5)
    base.update(kw)
    return TableSpec(**base)


# ------------------------------------------------------------------- table


def test_native_store_builds():
    assert load_native() is not None, "C++ embedding store must compile in CI"


def test_pull_is_deterministic_and_lazy():
    t = EmbeddingTable(spec())
    ids = np.array([[3, 5], [3, 9]])
    v1 = t.pull(ids)
    v2 = t.pull(ids)
    assert v1.shape == (2, 2, 8)
    np.testing.assert_array_equal(v1, v2)
    # same id -> same row wherever it appears
    np.testing.assert_array_equal(v1[0, 0], v1[1, 0])
    assert t.rows == 3  # lazy: only touched ids exist
    # init statistics: uniform(-a, a), a = std*sqrt(3)
    big = t.pull(np.arange(10_000))
    assert abs(big.std() - 0.01) < 1e-3
    assert abs(big.mean()) < 1e-3


def test_native_numpy_bit_parity():
    if load_native() is None:
        pytest.skip("no g++")
    ids = np.array([0, 1, 42, -7, 2**40, 12345])
    grads = np.random.default_rng(0).standard_normal((len(ids), 8)).astype(np.float32)
    for opt in ("sgd", "adagrad"):
        nat = EmbeddingTable(spec(optimizer=opt), backend="native")
        ref = EmbeddingTable(spec(optimizer=opt), backend="numpy")
        np.testing.assert_array_equal(nat.pull(ids), ref.pull(ids))
        for _ in range(3):
            nat.push(ids, grads, scale=0.5)
            ref.push(ids, grads, scale=0.5)
        np.testing.assert_allclose(nat.pull(ids), ref.pull(ids), rtol=1e-6)


def test_sgd_push_matches_dense_update():
    t = EmbeddingTable(spec(lr=0.1))
    ids = np.array([1, 2, 1])  # duplicate id 1: grads must accumulate
    before = t.pull(np.array([1, 2]))
    g = np.ones((3, 8), np.float32)
    t.push(ids, g, scale=2.0)
    after = t.pull(np.array([1, 2]))
    np.testing.assert_allclose(before[0] - 0.1 * 2.0 * 2.0, after[0], rtol=1e-6)
    np.testing.assert_allclose(before[1] - 0.1 * 2.0 * 1.0, after[1], rtol=1e-6)


def test_adagrad_push():
    t = EmbeddingTable(spec(optimizer="adagrad", lr=0.1, eps=0.0))
    ids = np.array([5])
    w0 = t.pull(ids).copy()
    g = np.full((1, 8), 2.0, np.float32)
    t.push(ids, g)
    # slot = 4, update = lr * 2/sqrt(4) = 0.1
    np.testing.assert_allclose(t.pull(ids), w0 - 0.1, rtol=1e-5)
    t.push(ids, g)
    # slot = 8, update = lr * 2/sqrt(8)
    np.testing.assert_allclose(
        t.pull(ids), w0 - 0.1 - 0.1 * 2 / np.sqrt(8), rtol=1e-5
    )


def test_export_import_roundtrip():
    t = EmbeddingTable(spec(optimizer="adagrad"))
    ids = np.arange(100)
    t.push(ids, np.ones((100, 8), np.float32))
    exp_ids, rows = t.export_rows()
    assert rows.shape == (100, 16)  # dim + adagrad slot
    t2 = EmbeddingTable(spec(optimizer="adagrad", seed=999))  # different seed
    t2.import_rows(exp_ids, rows)
    np.testing.assert_array_equal(t.pull(ids), t2.pull(ids))
    # and further pushes continue from imported optimizer slots
    t.push(ids, np.ones((100, 8), np.float32))
    t2.push(ids, np.ones((100, 8), np.float32))
    np.testing.assert_allclose(t.pull(ids), t2.pull(ids), rtol=1e-6)


# ------------------------------------------------------------------ routing


def test_shard_of_balances():
    owners = shard_of(np.arange(100_000), 4)
    counts = np.bincount(owners, minlength=4)
    assert counts.min() > 20_000  # ~25k each


def test_local_cluster_matches_single_table():
    single = EmbeddingTable(spec())
    cluster = LocalPsClient(num_shards=4)
    cluster.create_table(spec())
    ids = np.random.default_rng(1).integers(0, 1000, (64, 3))
    np.testing.assert_array_equal(cluster.pull("emb", ids), single.pull(ids))
    g = np.random.default_rng(2).standard_normal((64, 3, 8)).astype(np.float32)
    cluster.push("emb", ids, g)
    single.push(ids, g)
    np.testing.assert_allclose(cluster.pull("emb", ids), single.pull(ids), rtol=1e-6)
    assert cluster.total_rows("emb") == single.rows


# --------------------------------------------------------------------- grpc


def test_grpc_ps_cluster(tmp_path):
    shards = [PsShard(shard_index=i, num_shards=2) for i in range(2)]
    servers = [s.serve() for s in shards]
    try:
        client = ShardedPsClient([sv.address for sv in servers])
        client.create_table(spec())
        ids = np.arange(200).reshape(50, 4)
        local = EmbeddingTable(spec())
        np.testing.assert_array_equal(client.pull("emb", ids), local.pull(ids))
        g = np.ones((50, 4, 8), np.float32)
        client.push("emb", ids, g, scale=0.25)
        local.push(ids, g, scale=0.25)
        np.testing.assert_allclose(client.pull("emb", ids), local.pull(ids), rtol=1e-6)
        # save from 2 shards…
        client.save(str(tmp_path), step=3)
        stats = client.stats()
        assert sum(t.rows for st in stats for t in st.tables) == 200
        client.close()
    finally:
        for sv in servers:
            sv.stop()
    # …restore into 3 shards (reshard-on-restore)
    new_shards = [PsShard(shard_index=i, num_shards=3) for i in range(3)]
    for s in new_shards:
        s.restore(str(tmp_path))
    restored = LocalPsClient(num_shards=3)
    restored.shards = new_shards
    np.testing.assert_allclose(
        restored.pull("emb", ids), local.pull(ids), rtol=1e-6
    )
    assert restored.total_rows("emb") == 200


def test_live_shard_migration_zero_lost_rows(tmp_path):
    """Vertical-scaling handoff (resource_updation replace-then-retire on a
    PS pod, docs/design/elastic-training-operator.md:86-101): replace a LIVE
    shard mid-training — drain gates pushes, the replacement restores the
    drained save, the client reroutes, and gated pushes retry onto the
    replacement. Zero lost updates: final rows must bit-match a cluster that
    never migrated."""
    import threading

    shards = [PsShard(shard_index=i, num_shards=2) for i in range(2)]
    servers = [s.serve() for s in shards]
    replacement = PsShard(shard_index=1, num_shards=2)  # the "new pod"
    repl_server = replacement.serve()
    client = ShardedPsClient([sv.address for sv in servers])
    reference = LocalPsClient(num_shards=2)
    try:
        client.create_table(spec())
        reference.create_table(spec())
        ids = np.arange(400)
        g = np.full((400, 8), 1.0, np.float32)

        # steady-state training before the migration
        for _ in range(3):
            client.push("emb", ids, g, scale=0.1)
            reference.push("emb", ids, g, scale=0.1)

        # a concurrent pusher keeps training DURING the migration
        errors = []

        def pusher():
            try:
                for _ in range(4):
                    client.push("emb", ids, g, scale=0.1)
            except Exception as e:  # surfaced below
                errors.append(e)

        t = threading.Thread(target=pusher)
        t.start()
        client.migrate_shard(
            1, repl_server.address, str(tmp_path / "migrate-1"), step=3
        )
        t.join(60)
        assert not t.is_alive() and not errors, errors
        for _ in range(4):
            reference.push("emb", ids, g, scale=0.1)

        # post-migration training continues on the replacement
        client.push("emb", ids, g, scale=0.1)
        reference.push("emb", ids, g, scale=0.1)

        np.testing.assert_allclose(
            client.pull("emb", ids), reference.pull("emb", ids), rtol=1e-6
        )
        # old shard 1 is gated; the replacement serves its rows
        assert shards[1]._draining
        assert replacement.table("emb").rows == shards[1].table("emb").rows
        client.close()
    finally:
        for sv in servers:
            sv.stop()
        repl_server.stop()


def test_torn_save_is_invisible(tmp_path):
    """A save that only completed on some shards must not be restorable —
    otherwise the missing shard's ids silently re-init to fresh values."""
    shards = [PsShard(shard_index=i, num_shards=2) for i in range(2)]
    ids = np.arange(100)
    for s in shards:
        s.create_table(spec())
        mine = shard_of(ids, 2) == s.shard_index
        s.table("emb").pull(ids[mine])
    shards[0].save(str(tmp_path), step=7)  # shard 1 "crashed" before saving
    assert PsShard.saved_steps(str(tmp_path)) == []
    with pytest.raises(FileNotFoundError):
        PsShard(shard_index=0, num_shards=2).restore(str(tmp_path))
    shards[1].save(str(tmp_path), step=7)  # now complete
    assert PsShard.saved_steps(str(tmp_path)) == [7]


def test_restore_clears_warm_rows(tmp_path):
    """Restoring onto a warm shard must not keep post-checkpoint rows: ids
    first touched after the save re-init lazily, same as on a fresh shard."""
    s = PsShard()
    s.create_table(spec(lr=1.0))
    s.table("emb").pull(np.arange(10))
    s.save(str(tmp_path), step=1)
    # train past the checkpoint: update old ids, touch new ones
    s.table("emb").push(np.arange(20), np.ones((20, 8), np.float32))
    s.restore(str(tmp_path), step=1)
    fresh = PsShard()
    fresh.restore(str(tmp_path), step=1)
    np.testing.assert_array_equal(
        s.table("emb").pull(np.arange(30)), fresh.table("emb").pull(np.arange(30))
    )


# ------------------------------------------------------------- jit lookup


def test_ps_lookup_custom_vjp():
    import jax
    import jax.numpy as jnp

    from easydl_tpu.ps import register_lookup
    from easydl_tpu.ps.client import ps_lookup

    client = LocalPsClient(num_shards=2)
    client.create_table(spec(lr=1.0))
    handle = register_lookup(client, "emb", dim=8)

    ids = np.array([[1, 2], [3, 1]])
    w = jnp.ones((8,), jnp.float32)
    anchor = jnp.zeros((), jnp.float32)
    before = client.pull("emb", ids).copy()

    @jax.jit
    def loss(w, anchor, ids):
        emb = ps_lookup(handle, ids, anchor)
        return (emb * w).sum()

    val, (gw, _) = jax.value_and_grad(loss, argnums=(0, 1))(w, anchor, ids)
    np.testing.assert_allclose(val, before.sum(), rtol=1e-5)
    np.testing.assert_allclose(gw, before.sum(axis=(0, 1)), rtol=1e-5)
    # the backward pushed d(loss)/d(emb) = w = ones; sgd lr=1 ⇒ row -= count(id)
    after = client.pull("emb", np.array([1, 2, 3]))
    b = {1: before[0, 0], 2: before[0, 1], 3: before[1, 0]}
    np.testing.assert_allclose(after[0], b[1] - 2.0, rtol=1e-5)  # id 1 twice
    np.testing.assert_allclose(after[1], b[2] - 1.0, rtol=1e-5)
    np.testing.assert_allclose(after[2], b[3] - 1.0, rtol=1e-5)


# ------------------------------------------------------- end-to-end deepfm


def test_make_ps_model_inside_jit_step():
    """The convenience path: pull/push as host callbacks inside the compiled
    step, driven through the unmodified core Trainer."""
    import jax
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig, Trainer
    from easydl_tpu.models.registry import get_model
    from easydl_tpu.ps import register_lookup
    from easydl_tpu.ps.trainer import make_ps_model

    dim = 8
    bundle = get_model(
        "deepfm", vocab=2000, dim=dim, hidden=(16,), embedding="ps",
        num_sparse=4, num_dense=3,
    )
    client = LocalPsClient(num_shards=2)
    client.create_table(TableSpec(name="emb", dim=dim, optimizer="sgd", lr=0.1))
    handle = register_lookup(client, "emb", dim=dim)
    init2, loss2 = make_ps_model(bundle.init_fn, bundle.loss_fn, handle)
    trainer = Trainer(
        init_fn=init2,
        loss_fn=loss2,
        optimizer=optax.adam(1e-2),
        config=TrainConfig(global_batch=16, compute_dtype=jax.numpy.float32),
        mesh_spec=MeshSpec(dp=1),
    )
    state = trainer.init_state()
    data = iter(bundle.make_data(16, seed=9))
    for _ in range(3):
        state, metrics = trainer.train_step(state, next(data))
    jax.block_until_ready(metrics["loss"])
    assert client.total_rows("emb") > 0  # backward pushes materialised rows


def test_ps_pipelined_steps_learn():
    """The prefetch-pipelined loop (pull overlaps device step) still
    learns; one-step staleness is benign."""
    import jax
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig
    from easydl_tpu.models.registry import get_model
    from easydl_tpu.ps.trainer import PsTrainer

    bundle = get_model("deepfm", vocab=2000, dim=8, hidden=(32,),
                       embedding="ps", num_sparse=5, num_dense=4)
    client = LocalPsClient(num_shards=2)
    trainer = PsTrainer(
        init_fn=bundle.init_fn, loss_fn=bundle.loss_fn,
        optimizer=optax.adam(3e-3),
        config=TrainConfig(global_batch=32, compute_dtype=jax.numpy.float32),
        client=client,
        table=TableSpec(name="emb", dim=8, optimizer="adagrad"),
        mesh_spec=MeshSpec(dp=4),
    )
    state = trainer.init_state()
    data = iter(bundle.make_data(32, seed=11))
    seen = []
    state, metrics = trainer.train_steps(
        state, data, 25, on_metrics=lambda m: seen.append(float(m["loss"]))
    )
    assert len(seen) == 25 and state.int_step == 25
    assert np.mean(seen[-5:]) < np.mean(seen[:5])


def test_deepfm_ps_training_learns(tmp_path):
    import jax
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig
    from easydl_tpu.models.registry import get_model
    from easydl_tpu.ps.trainer import PsTrainer

    dim = 8
    bundle = get_model(
        "deepfm", vocab=5000, dim=dim, hidden=(32, 32), embedding="ps",
        num_sparse=6, num_dense=4,
    )
    client = LocalPsClient(num_shards=2)
    trainer = PsTrainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adam(1e-2),
        config=TrainConfig(global_batch=32, compute_dtype=jax.numpy.float32),
        client=client,
        table=TableSpec(name="emb", dim=dim, optimizer="adagrad", lr=0.05, seed=3),
        mesh_spec=MeshSpec(dp=4),
    )
    state = trainer.init_state()
    data = iter(bundle.make_data(32, seed=5))
    losses = []
    for _ in range(30):
        state, metrics = trainer.train_step(state, next(data))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], f"no learning: {losses[0]:.3f} -> {losses[-1]:.3f}"
    assert client.total_rows("emb") > 0


def test_drain_waits_for_inflight_push(tmp_path):
    """Push/Drain race: a push that passed the draining gate but is still
    applying when Drain arrives must land in the drained snapshot — the
    server acked it ok=True, so losing it would break the zero-lost-updates
    handoff contract."""
    import threading
    import time

    from easydl_tpu.proto import easydl_pb2 as pb

    shard = PsShard(shard_index=0, num_shards=1)
    shard.create_table(spec())
    ids = np.arange(50)
    g = np.ones((50, 8), np.float32)
    shard.Push(
        pb.PushRequest(table="emb", ids=ids.tolist(), grads=g.tobytes(),
                       scale=0.1),
        None,
    )

    # Make the apply slow so Drain provably arrives mid-push.
    t = shard.table("emb")
    orig_push = t.push
    started = threading.Event()

    def slow_push(ids, grads, scale=1.0):
        started.set()
        time.sleep(0.4)
        return orig_push(ids, grads, scale=scale)

    t.push = slow_push
    acks = []
    th = threading.Thread(
        target=lambda: acks.append(
            shard.Push(
                pb.PushRequest(table="emb", ids=ids.tolist(),
                               grads=g.tobytes(), scale=0.1),
                None,
            )
        )
    )
    th.start()
    assert started.wait(5)
    shard.drain(str(tmp_path), step=1)  # must block until the push applied
    th.join(10)
    assert acks and acks[0].ok

    repl = PsShard(shard_index=0, num_shards=1)
    repl.restore(str(tmp_path))
    np.testing.assert_array_equal(
        repl.table("emb").pull(ids), shard.table("emb").pull(ids)
    )


def test_push_survives_reroute_closing_old_transport(tmp_path):
    """A draining push retry must treat transport failures as retriable:
    reroute() closes the old RpcClient while the retry loop may be mid-Push
    on it, and the old pod may already be gone — the push the handoff exists
    to preserve has to ride that out and land on the replacement."""
    import threading
    import time

    shards = [PsShard(shard_index=0, num_shards=1)]
    server = shards[0].serve()
    repl = PsShard(shard_index=0, num_shards=1)
    repl_server = repl.serve()
    client = ShardedPsClient([server.address], drain_retry_s=30.0)
    try:
        client.create_table(spec())
        ids = np.arange(20)
        g = np.ones((20, 8), np.float32)
        client.push("emb", ids, g, scale=0.1)

        # Gate the old shard and hand its rows to the replacement.
        shards[0].drain(str(tmp_path / "mig"), step=0)
        repl.restore(str(tmp_path / "mig"))

        done, errors = [], []

        def run():
            try:
                client.push("emb", ids, g, scale=0.1)
                done.append(1)
            except Exception as e:  # surfaced below
                errors.append(e)

        th = threading.Thread(target=run)
        th.start()
        time.sleep(0.2)  # let the push enter its DRAINING retry loop
        # Simulate reroute's close racing the in-flight retry, with the old
        # pod retired (server stopped) before the new address is swapped in.
        old = client._clients[0]
        old.close()
        server.stop()
        time.sleep(0.2)
        client.reroute(0, repl_server.address)
        th.join(30)
        assert done and not errors, errors
        # Both pushes (pre-drain on old, retried on replacement) applied.
        expected_delta = 2 * 0.1 * 0.5  # 2 pushes x scale x sgd lr
        base = PsShard(shard_index=0, num_shards=1)
        base.create_table(spec())
        fresh = base.table("emb").pull(ids)
        np.testing.assert_allclose(
            client.pull("emb", ids), fresh - expected_delta, rtol=1e-5
        )
        client.close()
    finally:
        server.stop()
        repl_server.stop()


def test_claim_lock_protocol(tmp_path):
    """The rescue-claim file protocol (ps/__main__.py): O_EXCL creation,
    atomic flock-serialized steal of stale claims only, and a heartbeat
    that stands down (never resurrects ownership) after a steal — the
    round-4 review's split-brain interleavings."""
    import threading
    import time as _time

    from easydl_tpu.ps.__main__ import (
        _locked_claim,
        claim_heartbeat,
        claim_orphan_shard,
        claim_owner,
    )

    wd = str(tmp_path)
    s, path = claim_orphan_shard(wd, "podA", [0])
    assert s == 0 and claim_owner(path) == "podA"
    # a FRESH claim cannot be stolen
    s2, _ = claim_orphan_shard(wd, "podB", [0])
    assert s2 is None
    # a STALE claim is stolen (age re-checked under the lock)
    _locked_claim(path, lambda d: {"pod": "podA", "t": _time.time() - 60})
    s3, p3 = claim_orphan_shard(wd, "podB", [0], stale_s=30)
    assert s3 == 0 and p3 == path and claim_owner(path) == "podB"
    # podA's resumed heartbeat must observe the steal and stand down
    stop = threading.Event()
    t = threading.Thread(target=claim_heartbeat,
                         args=(path, "podA", stop, 0.01), daemon=True)
    t.start()
    t.join(timeout=5)
    assert not t.is_alive(), "heartbeat kept running after losing the claim"
    assert claim_owner(path) == "podB"
    stop.set()


def test_rescue_requires_claim_even_for_own_name(tmp_path):
    """An in-place same-name restart whose shard is DEAD must go through the
    claim (a levelled-in fresh pod can race it for the same shard); only a
    never-published shard skips it."""
    from easydl_tpu.ps import registry as reg
    from easydl_tpu.ps.__main__ import claim_owner, resolve_fresh_shard

    wd = str(tmp_path)
    # never-published: name path, no claim
    idx, rescued, claim = resolve_fresh_shard(wd, "j-parameter_server-0", 2)
    assert (idx, rescued, claim) == (0, False, None)
    # a dead publication for shard 0 (nothing listens on the port)
    reg.publish(wd, "j-parameter_server-0", 0, 2, "127.0.0.1:1")
    idx, rescued, claim = resolve_fresh_shard(wd, "j-parameter_server-0", 2)
    assert idx == 0 and rescued and claim is not None
    assert claim_owner(claim) == "j-parameter_server-0"
