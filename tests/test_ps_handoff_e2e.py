"""Controller-driven PS vertical scaling, end-to-end (VERDICT r2 item 7;
docs/design/elastic-training-operator.md:86-101).

The full reference flow with REAL processes: a JobResource
``resource_updation`` on a live PS pod makes the operator create a
replacement (replace-then-retire); the replacement pod's own entrypoint
drains the old shard, restores its rows, publishes to the registry and only
then reports ready — so the operator retires the old pod strictly after the
handoff. A training client keeps pushing through the whole window and must
lose nothing (bit-match against a never-migrated reference cluster).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np
import pytest

from easydl_tpu.api.job_spec import JobSpec, ResourceSpec, RoleSpec
from easydl_tpu.api.resource_plan import ResourcePlan, ResourceUpdation, RolePlan
from easydl_tpu.controller import CrStore, ElasticJobController
from easydl_tpu.controller.process_pod_api import LocalProcessPodApi
from easydl_tpu.ps import registry
from easydl_tpu.ps.client import LocalPsClient, ShardedPsClient
from easydl_tpu.ps.table import TableSpec

PS_CMD = (
    f"{sys.executable} -m easydl_tpu.ps --name {{name}} "
    "--workdir {workdir} --num-shards 2 --ready-file {ready_file}"
)


def spec(**kw):
    kw.setdefault("name", "emb")
    kw.setdefault("dim", 8)
    kw.setdefault("optimizer", "sgd")
    kw.setdefault("lr", 1.0)
    return TableSpec(**kw)


def wait_for(cond, timeout, desc):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {desc}")


def test_controller_driven_ps_vertical_handoff(tmp_path):
    workdir = str(tmp_path)
    store = CrStore()
    pods = LocalProcessPodApi(workdir)
    ctl = ElasticJobController(store, pods)
    ctl.start(resync_s=0.3)
    client = None
    try:
        store.submit_job(JobSpec(
            name="hj",
            command="python -m easydl_tpu.models.run --model mlp",
            roles={
                # inert trainer: this test drives the plan itself
                "trainer": RoleSpec(command="sleep 600"),
                "parameter_server": RoleSpec(command=PS_CMD),
            },
        ))
        store.apply_plan(ResourcePlan(
            job_name="hj", version=1,
            roles={"parameter_server": RolePlan(
                replicas=2, resource=ResourceSpec(cpu=1))},
        ))
        # both PS pods publish + become ready
        addrs = registry.addresses(workdir, 2, timeout=60)
        assert len(set(addrs)) == 2

        client = ShardedPsClient.from_registry(workdir, 2)
        reference = LocalPsClient(num_shards=2)
        client.create_table(spec())
        reference.create_table(spec())
        ids = np.arange(300)
        g = np.full((300, 8), 1.0, np.float32)
        for _ in range(3):
            client.push("emb", ids, g, scale=0.1)
            reference.push("emb", ids, g, scale=0.1)

        # training continues THROUGH the migration: push until the old pod
        # has actually been retired, so pushes demonstrably span the drain
        # window and the gated-retry/reroute path runs
        errors: list = []
        stop_push = threading.Event()
        pushed = {"n": 0}

        def pusher():
            try:
                while not stop_push.is_set():
                    client.push("emb", ids, g, scale=0.1)
                    pushed["n"] += 1
                    time.sleep(0.05)
            except Exception as e:  # surfaced below
                errors.append(e)

        old_pod = "hj-parameter_server-0"
        old_addr = registry.entry_for_pod(workdir, old_pod)["address"]
        t = threading.Thread(target=pusher)
        t.start()
        # the reference flow: resource_updation on the live PS pod
        store.apply_plan(ResourcePlan(
            job_name="hj", version=2,
            roles={"parameter_server": RolePlan(
                replicas=2, resource=ResourceSpec(cpu=1))},
            resource_updation=[ResourceUpdation(
                name=old_pod, resource=ResourceSpec(cpu=2, memory=4096),
            )],
        ))
        # replace-then-retire completed: old pod gone, replacement serving
        try:
            wait_for(
                lambda: old_pod not in [p.name for p in pods.list_pods("hj")],
                120, "old PS pod retired",
            )
        finally:
            stop_push.set()
        t.join(120)
        assert not t.is_alive() and not errors, errors
        assert pushed["n"] >= 3  # pushes really spanned the window
        for _ in range(pushed["n"]):
            reference.push("emb", ids, g, scale=0.1)
        live_ps = [p for p in pods.list_pods("hj")
                   if p.role == "parameter_server"
                   and p.phase in ("Pending", "Running")]
        assert sorted(p.name for p in live_ps) == [
            "hj-parameter_server-1", "hj-parameter_server-2"]
        repl = next(p for p in live_ps if p.name == "hj-parameter_server-2")
        assert repl.replaces == old_pod
        assert repl.resource.cpu == 2  # the vertical scale actually applied

        # the client followed the replacement via the registry
        assert client.addresses[0] != old_addr
        assert client.addresses[0] == registry.shard_map(workdir)[0]["address"]

        # post-migration training still works and NOTHING was lost
        client.push("emb", ids, g, scale=0.1)
        reference.push("emb", ids, g, scale=0.1)
        np.testing.assert_allclose(
            client.pull("emb", ids), reference.pull("emb", ids), rtol=1e-6
        )
    finally:
        if client is not None:
            client.close()
        ctl.stop()
        pods.shutdown()


def test_registry_latest_publication_wins(tmp_path):
    wd = str(tmp_path)
    registry.publish(wd, "p0", shard=0, num_shards=2, address="a:1")
    registry.publish(wd, "p1", shard=1, num_shards=2, address="a:2")
    assert registry.addresses(wd, 2) == ("a:1", "a:2")
    time.sleep(0.02)
    registry.publish(wd, "p2", shard=0, num_shards=2, address="a:3")
    assert registry.shard_map(wd)[0]["pod"] == "p2"
    assert registry.addresses(wd, 2) == ("a:3", "a:2")
    with pytest.raises(TimeoutError):
        registry.addresses(wd, 3, timeout=0.2)


def test_ready_file_gates_running(tmp_path):
    """A pod whose command uses {ready_file} stays Pending until the file
    exists — the ordering lever replace-then-retire relies on."""
    from easydl_tpu.controller.pod_api import Pod

    pods = LocalProcessPodApi(str(tmp_path))
    try:
        pods.create_pod(Pod(
            name="gated", job="j", role="parameter_server",
            command="sh -c 'sleep 1; touch {ready_file}; sleep 60'",
        ))
        pods.poll()
        assert [p.phase for p in pods.list_pods("j")] == ["Pending"]
        wait_for(
            lambda: [p.phase for p in pods.list_pods("j")] == ["Running"],
            15, "ready file appears",
        )
        # ungated pods run immediately
        pods.create_pod(Pod(name="plain", job="j", role="worker",
                            command="sleep 60"))
        wait_for(
            lambda: {p.name: p.phase for p in pods.list_pods("j")}["plain"]
            == "Running", 5, "ungated pod running",
        )
    finally:
        pods.shutdown()


def test_ps_trainer_against_real_ps_pods(tmp_path, eight_devices):
    """Config 5 in its DEPLOYED topology: the device-mesh PsTrainer trains
    widedeep against real PS pod processes (python -m easydl_tpu.ps)
    discovered through the shard registry — the same pods the operator
    launches — not an in-process client."""
    import optax
    import subprocess

    from easydl_tpu.core import MeshSpec, TrainConfig
    from easydl_tpu.models import get_model
    from easydl_tpu.ps import TableSpec
    from easydl_tpu.ps.client import ShardedPsClient
    from easydl_tpu.ps.trainer import PsTrainer

    wd = str(tmp_path)
    pods = []
    logs = []
    client = None
    try:
        for i in range(2):
            logf = open(os.path.join(wd, f"cfg5-ps-{i}.log"), "w+")
            logs.append(logf)
            pods.append(subprocess.Popen(
                [sys.executable, "-m", "easydl_tpu.ps",
                 "--name", f"cfg5-ps-{i}", "--workdir", wd,
                 "--num-shards", "2", "--shard-index", str(i)],
                stdout=logf, stderr=subprocess.STDOUT,
            ))
        try:
            client = ShardedPsClient.from_registry(wd, 2, wait_s=60)
        except TimeoutError:
            for i, logf in enumerate(logs):
                logf.seek(0)
                print(f"--- cfg5-ps-{i} log ---\n{logf.read()}")
            raise

        import jax.numpy as jnp

        bundle = get_model("widedeep", vocab=2000, dim=8, hidden=(32,),
                           embedding="ps", num_sparse=5, num_dense=4)
        trainer = PsTrainer(
            init_fn=bundle.init_fn, loss_fn=bundle.loss_fn,
            optimizer=optax.adam(3e-3),
            config=TrainConfig(global_batch=32,
                               compute_dtype=jnp.float32),
            client=client,
            table=TableSpec(name="emb", dim=8, optimizer="adagrad"),
            mesh_spec=MeshSpec(dp=8),
        )
        state = trainer.init_state()
        data = iter(bundle.make_data(32, seed=2))
        losses = []
        for _ in range(20):
            state, metrics = trainer.train_step(state, next(data))
            losses.append(float(metrics["loss"]))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])  # it learns
        # the rows genuinely live on the remote shards, split between them
        per_shard = [
            sum(t.rows for t in st.tables if t.name == "emb")
            for st in client.stats()
        ]
        assert len(per_shard) == 2 and all(r > 0 for r in per_shard), per_shard
    finally:
        if client is not None:
            client.close()
        for p in pods:
            p.terminate()
        for p in pods:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()  # a wedged pod must not mask the real failure
                p.wait()
        for logf in logs:
            logf.close()


def test_crashed_ps_shard_rescued_by_fresh_replacement(tmp_path):
    """Advisor r3 medium: a Failed PS pod is replaced via replica levelling
    under a FRESH name with no `replaces` — the replacement must adopt the
    crashed pod's shard (not trust its own name's trailing index) and
    restore that shard's rows from the last ps-ckpt save."""
    workdir = str(tmp_path)
    store = CrStore()
    pods = LocalProcessPodApi(workdir)
    ctl = ElasticJobController(store, pods)
    ctl.start(resync_s=0.3)
    client = None
    try:
        store.submit_job(JobSpec(
            name="rj",
            command="python -m easydl_tpu.models.run --model mlp",
            roles={
                "trainer": RoleSpec(command="sleep 600"),
                "parameter_server": RoleSpec(command=PS_CMD),
            },
        ))
        store.apply_plan(ResourcePlan(
            job_name="rj", version=1,
            roles={"parameter_server": RolePlan(
                replicas=2, resource=ResourceSpec(cpu=1))},
        ))
        registry.addresses(workdir, 2, timeout=60)
        client = ShardedPsClient.from_registry(workdir, 2)
        client.create_table(spec())
        ids = np.arange(200)
        g = np.full((200, 8), 1.0, np.float32)
        client.push("emb", ids, g, scale=0.1)
        expected = client.pull("emb", ids)
        # checkpoint the PS tier (what workers do every ckpt interval)
        client.save(os.path.join(workdir, "ps-ckpt"), step=7)

        # SIGKILL shard 0's pod: exits nonzero -> Failed -> reconciler
        # levels a replacement under a fresh name, replaces=""
        victim = "rj-parameter_server-0"
        shard0_addr = registry.entry_for_pod(workdir, victim)["address"]
        entry = pods._procs[victim]
        entry.proc.kill()
        wait_for(
            lambda: any(
                p.name == "rj-parameter_server-2"
                and p.phase in ("Pending", "Running")
                for p in pods.list_pods("rj")
            ),
            60, "fresh-named replacement created",
        )
        # the replacement adopts SHARD 0 (not shard 2) and re-publishes it
        wait_for(
            lambda: registry.shard_map(workdir).get(0, {}).get("address")
            not in (None, shard0_addr),
            60, "replacement published shard 0 under a new address",
        )
        smap = registry.shard_map(workdir)
        assert smap[0]["pod"] == "rj-parameter_server-2", smap
        assert 2 not in smap  # it did NOT serve a bogus shard 2
        # registry remains complete: clients can discover both shards
        n, addrs = registry.discover(workdir, timeout=30)
        assert n == 2 and len(set(addrs)) == 2

        # and the rescued shard serves the CHECKPOINTED rows, not an empty
        # table (pull through a fresh client to pick up the new address)
        client.close()
        client = ShardedPsClient.from_registry(workdir, 2)
        client.create_table(spec())
        np.testing.assert_allclose(
            client.pull("emb", ids), expected, rtol=1e-6
        )
    finally:
        if client is not None:
            client.close()
        ctl.stop()
        pods.shutdown()
