"""Standard-dataset importers → the ``images.npy``/``labels.npy`` format.

The BASELINE configs name their datasets (config 1 "MNIST", config 2
"ImageNet" — BASELINE.md), but :class:`~easydl_tpu.data.datasets.
ArrayImageDataset` reads only the framework's own array layout. This module
closes the gap (VERDICT r3 missing 3) with two importers that emit that
layout, so the named datasets feed in as downloaded — no hand conversion:

- **MNIST IDX**: :func:`read_idx` parses the IDX file format (the
  magic-number encoding from Yann LeCun's distribution: 2 zero bytes, a
  dtype code, a rank byte, big-endian dims, row-major data), transparently
  gunzipping ``.gz`` files; :func:`convert_mnist` pairs the
  ``{train,t10k}-images-idx3-ubyte`` / ``-labels-idx1-ubyte`` files.
- **Image folder**: :func:`import_image_folder` walks the standard
  class-per-subdirectory layout (the ImageNet/torchvision convention),
  decodes with PIL, resizes, and writes uint8 arrays plus a
  ``classes.json`` index.

CLI: ``python -m easydl_tpu.data.images mnist|folder ...``.
Images are stored uint8 (ArrayImageDataset normalizes to float32 at read
time), so an imported dataset costs the same disk as the raw pixels.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import List, Optional, Tuple

import numpy as np

from easydl_tpu.utils.logging import get_logger

log = get_logger("data", "images")

#: IDX dtype codes → numpy dtypes (all multi-byte types are big-endian)
_IDX_DTYPES = {
    0x08: np.dtype(np.uint8),
    0x09: np.dtype(np.int8),
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (``.gz`` handled transparently) into an ndarray."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    if len(data) < 4 or data[0] != 0 or data[1] != 0:
        raise ValueError(f"{path}: not an IDX file (bad magic {data[:4]!r})")
    dtype = _IDX_DTYPES.get(data[2])
    if dtype is None:
        raise ValueError(f"{path}: unknown IDX dtype code 0x{data[2]:02x}")
    ndim = data[3]
    header = 4 + 4 * ndim
    dims = [int.from_bytes(data[4 + 4 * i:8 + 4 * i], "big")
            for i in range(ndim)]
    count = int(np.prod(dims)) if dims else 0
    body = np.frombuffer(data, dtype=dtype, count=count, offset=header)
    if body.size != count:
        raise ValueError(f"{path}: truncated IDX body "
                         f"({body.size} of {count} items)")
    # native byte order out: downstream code never sees the BE dtypes
    return body.reshape(dims).astype(dtype.newbyteorder("="), copy=False)


def _find_one(src_dir: str, stem: str) -> str:
    """The MNIST distribution names files ``train-images-idx3-ubyte`` but
    mirrors also ship ``train-images.idx3-ubyte`` and ``.gz`` variants —
    accept all four spellings."""
    for sep in ("-", "."):
        for suffix in ("", ".gz"):
            cands = glob.glob(os.path.join(src_dir,
                                           stem.replace("#", sep) + suffix))
            if cands:
                return sorted(cands)[0]
    raise FileNotFoundError(
        f"no {stem.replace('#', '-')}[.gz] under {src_dir}")


def convert_mnist(src_dir: str, out_dir: str, prefix: str = "train") -> int:
    """``{prefix}-images-idx3-ubyte(.gz)`` + labels → images.npy/labels.npy.

    Images come out ``[N, 28, 28, 1]`` uint8 (the trailing channel axis is
    what the model zoo's conv/MLP input shapes expect); returns N."""
    images = read_idx(_find_one(src_dir, f"{prefix}-images#idx3-ubyte"))
    labels = read_idx(_find_one(src_dir, f"{prefix}-labels#idx1-ubyte"))
    if images.ndim != 3:
        raise ValueError(f"expected rank-3 image IDX, got {images.shape}")
    if labels.ndim != 1 or len(labels) != len(images):
        raise ValueError(
            f"labels {labels.shape} don't match images {images.shape}")
    os.makedirs(out_dir, exist_ok=True)
    np.save(os.path.join(out_dir, "images.npy"), images[..., None])
    np.save(os.path.join(out_dir, "labels.npy"), labels.astype(np.int64))
    log.info("mnist: %d examples %s -> %s", len(images), images.shape[1:],
             out_dir)
    return len(images)


_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".pgm", ".webp")


def import_image_folder(src_dir: str, out_dir: str,
                        size: Tuple[int, int] = (224, 224),
                        classes: Optional[List[str]] = None) -> Tuple[int, List[str]]:
    """Class-per-subdirectory image tree → images.npy/labels.npy.

    The torchvision ``ImageFolder`` convention (ImageNet's layout): every
    immediate subdirectory of ``src_dir`` is a class, sorted name order
    fixes the label index (persisted to ``classes.json`` so training and
    evaluation agree across machines). Images are decoded with PIL,
    converted to RGB, and bilinear-resized to ``size``; returns
    ``(N, class_names)``.

    Memory stays O(1 image): decoded pixels stream straight into a
    memory-mapped ``images.npy`` (ImageNet at 224² is ~190 GB — holding it
    in RAM and stacking would OOM any realistic host). The file is sized by
    the candidate count up front and truncated to the decoded count at the
    end, so undecodable files cost nothing but a warning."""
    from PIL import Image

    if classes is None:
        classes = sorted(
            d for d in os.listdir(src_dir)
            if os.path.isdir(os.path.join(src_dir, d)))
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {src_dir}")
    h, w = size
    candidates: List[tuple] = []  # (path, label)
    for label, cls in enumerate(classes):
        for name in sorted(os.listdir(os.path.join(src_dir, cls))):
            if name.lower().endswith(_IMAGE_EXTS):
                candidates.append((os.path.join(src_dir, cls, name), label))
    if not candidates:
        raise FileNotFoundError(f"no image files under {src_dir}")
    os.makedirs(out_dir, exist_ok=True)
    images_path = os.path.join(out_dir, "images.npy")
    out = np.lib.format.open_memmap(
        images_path, mode="w+", dtype=np.uint8,
        shape=(len(candidates), h, w, 3))
    labels: List[int] = []
    skipped = 0
    n = 0
    for path, label in candidates:
        try:
            with Image.open(path) as im:
                out[n] = np.asarray(
                    im.convert("RGB").resize((w, h), Image.BILINEAR),
                    np.uint8)
        except (OSError, ValueError) as e:
            skipped += 1
            log.warning("skipping undecodable %s: %s", path, e)
            continue
        labels.append(label)
        n += 1
    del out
    if n == 0:
        os.remove(images_path)
        raise FileNotFoundError(f"no decodable images under {src_dir}")
    if skipped:
        log.warning("image folder import: skipped %d undecodable file(s)",
                    skipped)
        # Shrink to the decoded count with a streaming memmap→memmap copy
        # (only paid when something was skipped; never a full-size RAM copy)
        src = np.load(images_path, mmap_mode="r")
        tmp_path = images_path + ".tmp.npy"
        dst = np.lib.format.open_memmap(
            tmp_path, mode="w+", dtype=np.uint8, shape=(n, h, w, 3))
        step = max(1, (64 << 20) // (h * w * 3))  # ~64MB batches
        for lo in range(0, n, step):
            hi = min(lo + step, n)  # src is still the over-sized file
            dst[lo:hi] = src[lo:hi]
        del src, dst
        os.replace(tmp_path, images_path)
    np.save(os.path.join(out_dir, "labels.npy"),
            np.asarray(labels, np.int64))
    with open(os.path.join(out_dir, "classes.json"), "w") as f:
        json.dump(classes, f)
    log.info("image folder: %d examples, %d classes -> %s",
             n, len(classes), out_dir)
    return n, classes


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="standard datasets -> images.npy/labels.npy")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("mnist", help="MNIST/Fashion-MNIST IDX files")
    mp.add_argument("src", help="dir holding *-images-idx3-ubyte(.gz) files")
    mp.add_argument("--out", required=True)
    mp.add_argument("--prefix", default="train", choices=("train", "t10k"))
    fp = sub.add_parser("folder", help="class-per-subdirectory image tree")
    fp.add_argument("src")
    fp.add_argument("--out", required=True)
    fp.add_argument("--size", type=int, nargs=2, default=(224, 224),
                    metavar=("H", "W"))
    args = ap.parse_args()

    if args.cmd == "mnist":
        n = convert_mnist(args.src, args.out, prefix=args.prefix)
        print(f"mnist: {n} examples -> {args.out}")
    else:
        n, classes = import_image_folder(args.src, args.out,
                                         size=tuple(args.size))
        print(f"folder: {n} examples, {len(classes)} classes -> {args.out}")


if __name__ == "__main__":
    main()
