"""slo-metric-refs: every series an SLO or the alerting stack names
must exist in the metric registry.

The failure mode this closes (ISSUE 19): an SLO spec referencing a
misspelled or since-renamed family is silently vacuous — ``bound``
objectives read absent-series-as-healthy by design, so the alert never
fires and nobody notices until the drill that needed it. The registry
in ``analysis/rules/metric_names.py`` (``REGISTERED_METRICS``, kept in
sync with the registration sites by AST scan in tests/test_easylint.py)
is the source of truth; this rule resolves against it in two places:

* **the SLO catalog** — when the anchor module
  (``easydl_tpu/obs/slo.py``) is analyzed, every ``slos/*.yaml`` is
  loaded through the validating loader and each selector's family must
  be registered (``_bucket``/``_sum``/``_count`` suffixes resolve to
  their histogram base). easylint only collects ``.py`` files, so the
  YAML catalog rides the anchor: the finding's path is the YAML file;
* **the alerting modules** — string literals in ``obs/slo.py``,
  ``obs/alerts.py`` and ``brain/alert_policy.py`` that parse as a
  metric family (``easydl_<component>_<metric>``) must be registered,
  so a hardcoded series name in the evaluator cannot drift either.
"""

from __future__ import annotations

import ast
import os
import re
from typing import FrozenSet, List, Optional

from easydl_tpu.analysis.core import Finding, Rule
from easydl_tpu.analysis.rules.metric_names import REGISTERED_METRICS

#: The module whose analysis triggers the YAML-catalog half.
ANCHOR = "easydl_tpu/obs/slo.py"

#: Modules whose string literals are checked against the registry.
LITERAL_PATHS = (
    "easydl_tpu/obs/slo.py",
    "easydl_tpu/obs/alerts.py",
    "easydl_tpu/brain/alert_policy.py",
)

#: Suffixes that resolve to a histogram's base family.
_DERIVED_SUFFIXES = ("_bucket", "_sum", "_count")

_FAMILY_RE = re.compile(r"^easydl(_[a-z0-9]+){2,}$")


def _registered(name: str, registry: FrozenSet[str]) -> bool:
    if name in registry:
        return True
    for suffix in _DERIVED_SUFFIXES:
        if name.endswith(suffix) and name[: -len(suffix)] in registry:
            return True
    return False


class SloMetricRefs(Rule):
    name = "slo-metric-refs"
    invariant = ("Every series referenced by an SLO spec (slos/*.yaml) or "
                 "named literally in the alerting modules resolves to a "
                 "family in REGISTERED_METRICS — a misspelled selector is "
                 "a lint failure, not a silently-vacuous alert.")

    def __init__(self, slos_dir: Optional[str] = None,
                 registry: Optional[FrozenSet[str]] = None) -> None:
        #: override points for the fixture tests; defaults are the repo
        #: catalog and the live registry
        self.slos_dir = slos_dir
        self.registry = registry if registry is not None else REGISTERED_METRICS

    # -- the YAML-catalog half -------------------------------------------

    def _check_catalog(self, findings: List[Finding]) -> None:
        from easydl_tpu.obs import slo as slo_mod

        d = self.slos_dir if self.slos_dir is not None else slo_mod.SLOS_DIR
        if not os.path.isdir(d):
            # a repo without a catalog has nothing to resolve; the
            # anti-vacuous guarantee lives in the fixture tests
            return
        for path in slo_mod.list_slo_files(d):
            rel = os.path.join("slos", os.path.basename(path))
            try:
                spec = slo_mod.load_slo_file(path)
            except slo_mod.SloSpecError as e:
                findings.append(Finding(
                    rule=self.name, path=rel, line=1, scope="<slo>",
                    detail=f"invalid-slo:{os.path.basename(path)}",
                    message=f"spec fails the validating loader: {e}"))
                continue
            for series in slo_mod.referenced_series(spec):
                family = series.split("{", 1)[0]
                if not _registered(family, self.registry):
                    findings.append(Finding(
                        rule=self.name, path=rel, line=1,
                        scope=str(spec.get("name", "<slo>")),
                        detail=f"unknown-series:{family}",
                        message=(f"selector {series!r} names a family not "
                                 f"in REGISTERED_METRICS — a typo here is "
                                 f"a silently-vacuous alert")))

    # -- the literal half ------------------------------------------------

    def _check_literals(self, path: str, tree: ast.Module,
                        findings: List[Finding]) -> None:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            text = node.value
            family = text.split("{", 1)[0]
            if not _FAMILY_RE.match(family):
                continue
            if not _registered(family, self.registry):
                findings.append(Finding(
                    rule=self.name, path=path,
                    line=getattr(node, "lineno", 1), scope="<literal>",
                    detail=f"unknown-series:{family}",
                    message=(f"literal {text!r} names a metric family not "
                             f"in REGISTERED_METRICS")))

    def check(self, path: str, tree: ast.Module,
              source: str) -> List[Finding]:
        findings: List[Finding] = []
        if path in LITERAL_PATHS:
            self._check_literals(path, tree, findings)
        if path == ANCHOR:
            self._check_catalog(findings)
        return findings
