"""Known-bad fixture for slo-metric-refs: literals in an alerting
module naming families the registry has never heard of."""

# a plain misspelling (extra 's') — the classic silently-vacuous alert
SERIES = "easydl_serve_router_request_total"

# a selector literal whose family is made up entirely
SELECTOR = "easydl_made_up_family_total{shard=\"0\"}"


def relevant():
    # registered name is fine; the derived _bucket suffix resolves too
    return ["easydl_alert_active", "easydl_rpc_client_latency_seconds_bucket",
            SERIES, SELECTOR]
