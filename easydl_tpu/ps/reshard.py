"""Online PS resharding N→M: the migration coordinator.

Composes the PR-6 primitives — per-shard WAL with a foreign-id replay
filter, monotonic shard epochs, registry-confirmed fencing, retriable
Acks — into a zero-loss live migration of a serving PS tier to a new
shard count, without stopping the push stream. The protocol, in the
order :func:`run_reshard` drives it:

1. **plan** — claim the single reshard slot in the registry's routing
   table (:func:`registry.begin_reshard`): generation ``committed+1``,
   target shard count, owner. A second coordinator gets ``None`` back; a
   plan whose owner died is stolen after ``stale_s``.
2. **export** — every *source* shard cuts a snapshot + WAL boundary
   under its ordering lock (``ReshardExport``) and writes its rows into
   ``<workdir>/ps-reshard/gen-<g>``. Pushes KEEP flowing: everything
   after the cut lands in the WAL tail.
3. **destinations** — the new shard set (fresh pods, ``--reshard-dest``)
   publishes under the PLAN's generation, invisible to clients
   (``registry.shard_map`` filters to the committed generation).
4. **restore** — each destination restores the export; the existing
   reshard-on-restore filter keeps only ids that hash to it under the
   NEW count.
5. **cutover** — each source gates pushes for good (``ReshardCutover``,
   retriable ``stale-route`` Acks) and fsyncs its WAL: the tail is now
   final. An update that passed the gate was WAL'd and acked before the
   cutover returned, so it is part of the tail.
6. **replay** — each destination replays every source's tail (the
   records past its export cut marker) through the foreign-id filter
   (``ReshardReplay``): pushes acked mid-migration land exactly once,
   and the final state is bit-identical to a never-resharded reference.
7. **commit** — the routing table atomically switches to the plan's
   generation (:func:`registry.commit_reshard`). Clients bouncing off
   ``stale-route`` rebuild their whole routing on the next refresh and
   re-partition the rejected chunks onto the new shard set.
8. **checkpoint** — each destination saves into the rescue lineage
   (``ps-ckpt``) at a fresh step, so a destination crash recovers
   through the normal snapshot+WAL rescue (and the sources' now-covered
   WAL epochs are garbage-collected by that save).

Failure matrix (the chaos drill injects the first two):

- **Source SIGKILLed mid-migration** — its registry entry vanishes
  (dead-pid filter), a rescue pod recovers it from snapshot + WAL at a
  higher epoch, and every per-shard RPC here re-resolves the address
  from the registry per attempt, so the retried export/cutover lands on
  the rescuer. The destinations' tail replay iterates ALL epochs past
  the cut, so a rescued source's records are covered either way. A pod
  that comes up while a plan is active starts push-GATED
  (ps/__main__.py): a rescuer accepting pushes after a destination
  already replayed its tail would lose them — gating turns that window
  into bounded retriable Acks instead.
- **Destination SIGSTOPped mid-migration** — its restore/replay RPC
  stalls; the per-phase retry loop keeps re-issuing until the pod
  resumes or the phase deadline aborts the migration.
- **Coordinator dies mid-migration** — the plan goes stale and is
  stolen by the next :func:`run_reshard` call; sources re-export (a
  fresh cut supersedes the old markers), destinations re-restore. The
  committed routing never moved, so clients never saw the torn attempt.
- **Abort** — any phase failing past its deadline rolls back: sources
  get ``ReshardResume`` (the push gate lifts), the plan is dropped, and
  the committed routing is untouched — clients never left the source
  set. Destinations replayed into tables no client ever read; the pods
  are torn down by the caller.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.ps import registry
from easydl_tpu.ps.server import PS_SERVICE, PsShard
from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.rpc import GRPC_MSG_OPTIONS, RpcClient

log = get_logger("ps", "reshard")

#: Where migration exports land: one dir per routing generation, so a
#: stolen/retried plan at the same generation overwrites (never mixes
#: with) the torn attempt, and operators can inspect a migration's
#: artifacts after the fact.
RESHARD_DIR = "ps-reshard"


class ReshardError(RuntimeError):
    """A migration phase failed past its deadline (after rollback)."""


class ReshardInProgress(ReshardError):
    """Another coordinator's plan is active (and not stale)."""


def export_dir(workdir: str, generation: int) -> str:
    return os.path.join(workdir, RESHARD_DIR, f"gen-{int(generation)}")


def _rpc(address: str, timeout: float) -> RpcClient:
    return RpcClient(PS_SERVICE, address, timeout=timeout,
                     options=GRPC_MSG_OPTIONS)


def _committed_shards(workdir: str) -> int:
    """The serving tier's current shard count: the routing table's when
    one exists, else the committed publications'."""
    n = int(registry.routing_table(workdir).get("num_shards", 0))
    if n > 0:
        return n
    m = registry.shard_map(workdir)
    if not m:
        raise ReshardError(f"no PS publications under {workdir}")
    return max(int(d["num_shards"]) for d in m.values())


class _Phase:
    """One retriable per-shard RPC phase: re-resolves the target address
    from the registry on EVERY attempt (a SIGKILLed source's rescuer
    publishes a fresh address; a SIGSTOPped destination keeps its old
    one and simply times out until it resumes)."""

    def __init__(self, workdir: str, generation: Optional[int],
                 rpc_timeout: float, deadline: float):
        self.workdir = workdir
        self.generation = generation  # None = committed (source side)
        self.rpc_timeout = rpc_timeout
        self.deadline = deadline

    def _address(self, shard: int) -> Optional[str]:
        entry = registry.shard_map(self.workdir,
                                   generation=self.generation).get(shard)
        return entry["address"] if entry else None

    def call(self, shard: int, method: str, req, describe: str):
        """Issue ``method(req)`` against whoever currently serves
        ``shard``, retrying transport failures and not-ok Acks until the
        phase deadline. Returns the ok Ack."""
        last = "no publication for the shard yet"
        while True:
            addr = self._address(shard)
            if addr is not None:
                client = _rpc(addr, self.rpc_timeout)
                try:
                    ack = getattr(client, method)(req)
                    if ack.ok:
                        return ack
                    last = f"ack: {ack.message}"
                except Exception as e:  # transport loss or stalled pod
                    last = repr(e)
                finally:
                    client.close()
            if time.monotonic() > self.deadline:
                raise ReshardError(
                    f"{describe} (shard {shard}) failed past the phase "
                    f"deadline; last: {last}")
            time.sleep(0.2)


def run_reshard(
    workdir: str,
    to_shards: int,
    owner: str,
    *,
    ensure_destinations: Optional[Callable[[Dict[str, Any]], None]] = None,
    on_phase: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    rpc_timeout: float = 15.0,
    phase_timeout_s: float = 180.0,
    dest_wait_s: float = 120.0,
    plan_stale_s: float = 600.0,
) -> Dict[str, Any]:
    """Drive one complete online reshard to ``to_shards``; returns the
    migration summary (plan, per-destination replay stats, wall times).

    ``ensure_destinations(plan)`` is called once after the export phase
    to bring up the destination shard set (spawn ``--reshard-dest``
    pods); without it the coordinator simply waits for destinations to
    appear in the registry under the plan's generation.
    ``on_phase(name, info)`` fires at every phase boundary — the chaos
    drill hooks its faults there, so "SIGKILL a source mid-migration"
    means *after export, before cutover* deterministically rather than
    by wall-clock luck. A hook that raises aborts (and rolls back) the
    migration like any phase failure."""
    t_start = time.monotonic()
    from_shards = _committed_shards(workdir)
    plan = registry.begin_reshard(workdir, from_shards, to_shards, owner,
                                  stale_s=plan_stale_s)
    if plan is None:
        raise ReshardInProgress(
            f"a reshard plan is already active under {workdir}")
    gen = int(plan["generation"])
    from_shards = int(plan["from_shards"])  # authoritative (plan steal)
    directory = export_dir(workdir, gen)
    step = gen  # the step dir inside the export dir is the generation
    summary: Dict[str, Any] = {
        "plan": dict(plan),
        "export_dir": directory,
        "phases": {},
    }

    def phase(name: str, **info) -> None:
        summary["phases"][name] = {
            "t_s": round(time.monotonic() - t_start, 3), **info}
        log.info("reshard gen %d phase %s (%.2fs)%s", gen, name,
                 time.monotonic() - t_start,
                 f" {info}" if info else "")
        # Plan heartbeat: every phase boundary refreshes the plan's
        # timestamp so a LIVE migration can never look stale — each
        # individual phase is bounded well under plan_stale_s, but their
        # sum is not, and a steal mid-migration would let the loser's
        # rollback un-gate sources the thief already cut over.
        registry.touch_reshard(workdir, owner)
        if on_phase is not None:
            on_phase(name, dict(plan))

    committed = False
    try:
        phase("planned")
        # -------------------------------------------------------- export
        src = _Phase(workdir, None, rpc_timeout,
                     time.monotonic() + phase_timeout_s)
        for s in range(from_shards):
            src.call(s, "ReshardExport",
                     pb.PsSaveRequest(directory=directory, step=step),
                     "reshard export")
        phase("exported")
        # -------------------------------------------------- destinations
        if ensure_destinations is not None:
            ensure_destinations(dict(plan))
        deadline = time.monotonic() + dest_wait_s
        while True:
            m = registry.shard_map(workdir, generation=gen)
            if all(d in m for d in range(to_shards)):
                break
            if time.monotonic() > deadline:
                missing = [d for d in range(to_shards) if d not in m]
                raise ReshardError(
                    f"destination shards {missing} never published under "
                    f"generation {gen}")
            time.sleep(0.2)
        phase("destinations_ready")
        # ------------------------------------------------------- restore
        dst = _Phase(workdir, gen, rpc_timeout,
                     time.monotonic() + phase_timeout_s)
        for d in range(to_shards):
            dst.call(d, "Restore",
                     pb.PsRestoreRequest(directory=directory, step=step),
                     "reshard destination restore")
        phase("restored")
        # ------------------------------------------------------- cutover
        # Addresses re-resolve inside the phase: a source SIGKILLed after
        # export answers here through its rescuer (which came up
        # push-gated — see module docstring — so no push can slip past
        # the tail between its birth and this cutover).
        cut = _Phase(workdir, None, rpc_timeout,
                     time.monotonic() + phase_timeout_s)
        for s in range(from_shards):
            cut.call(s, "ReshardCutover", pb.PsSaveRequest(),
                     "reshard cutover")
        phase("cutover")
        # -------------------------------------------------------- replay
        replays: List[Dict[str, Any]] = []
        rep = _Phase(workdir, gen, rpc_timeout,
                     time.monotonic() + phase_timeout_s)
        for d in range(to_shards):
            ack = rep.call(d, "ReshardReplay",
                           pb.PsSaveRequest(directory=directory, step=step),
                           "reshard tail replay")
            try:
                replays.append(json.loads(ack.message))
            except ValueError:
                replays.append({})
        summary["replays"] = replays
        summary["rows_migrated"] = int(sum(
            r.get("rows_migrated", 0) for r in replays))
        summary["tail_pushes_replayed"] = int(sum(
            r.get("pushes", 0) for r in replays))
        summary["tail_foreign_ids_filtered"] = int(sum(
            r.get("foreign_ids", 0) for r in replays))
        phase("replayed",
              rows_migrated=summary["rows_migrated"],
              tail_pushes=summary["tail_pushes_replayed"])
        # -------------------------------------------------------- commit
        summary["committed_routing"] = registry.commit_reshard(workdir,
                                                               owner)
        committed = True
        phase("committed")
        # -------------------------------------- rescue-lineage checkpoint
        # A destination that crashes after commit must recover through
        # the normal snapshot+WAL rescue; its first rescue-dir save both
        # anchors that (cut marker under the NEW count) and retires the
        # sources' now-covered WAL epochs under its shard root.
        ckpt = os.path.join(workdir, "ps-ckpt")
        steps = PsShard.saved_steps(ckpt)
        save_step = (max(steps) + 1) if steps else 0
        sv = _Phase(workdir, gen, rpc_timeout,
                    time.monotonic() + phase_timeout_s)
        for d in range(to_shards):
            sv.call(d, "Save",
                    pb.PsSaveRequest(directory=ckpt, step=save_step),
                    "post-commit checkpoint")
        summary["post_commit_ckpt_step"] = save_step
        phase("saved")
    except BaseException:
        if not committed:
            _rollback(workdir, owner, from_shards, rpc_timeout)
        raise
    summary["wall_s"] = round(time.monotonic() - t_start, 3)
    log.info("reshard %d->%d committed as generation %d in %.2fs "
             "(%d rows migrated, %d tail pushes replayed)",
             from_shards, to_shards, gen, summary["wall_s"],
             summary["rows_migrated"], summary["tail_pushes_replayed"])
    return summary


def _rollback(workdir: str, owner: str, from_shards: int,
              rpc_timeout: float) -> None:
    """Best-effort abort: un-gate every source (a cutover source would
    otherwise bounce pushes forever against a routing that will never
    move), then drop the plan. The committed routing never changed, so
    clients never left the source set; whatever the destinations
    restored/replayed was never read by anyone."""
    log.warning("reshard under %s aborting: resuming %d source shard(s) "
                "and dropping the plan", workdir, from_shards)
    for s in range(from_shards):
        entry = registry.shard_map(workdir).get(s)
        if entry is None:
            continue
        client = _rpc(entry["address"], rpc_timeout)
        try:
            client.ReshardResume(pb.PsSaveRequest())
        except Exception as e:  # the abort path must never mask the cause
            log.warning("reshard rollback: resume of shard %d failed: %s",
                        s, e)
        finally:
            client.close()
    registry.abort_reshard(workdir, owner)
