"""Sparse parameter-server tier: host-resident embedding tables for
recommender models (reference PS role,
docs/design/elastic-training-operator.md:39-40; BASELINE config 5).

C++ core (native/embedding_store.cc) + gRPC shards (server) + sharded client
and jit-visible lookup (client) + the async-PS worker loop (trainer).
"""

from easydl_tpu.ps.client import (  # noqa: F401
    LocalPsClient,
    ShardedPsClient,
    ps_lookup,
    register_lookup,
)
from easydl_tpu.ps.server import PS_SERVICE, PsShard  # noqa: F401
from easydl_tpu.ps.table import EmbeddingTable, TableSpec, shard_of  # noqa: F401
from easydl_tpu.ps.trainer import PsTrainer, make_ps_model  # noqa: F401
