"""Contract tests for JobSpec (≙ ElasticJob) and ResourcePlan (≙ JobResource).

The YAML fixtures below are transcriptions of the reference's CRD examples
(docs/design/elastic-training-operator.md:31-45 and :57-95) — round-tripping
them proves manifest compatibility.
"""

import pytest

from easydl_tpu.api import (
    JobSpec,
    ResourcePlan,
    ResourceSpec,
    RolePlan,
    TpuSpec,
)
from easydl_tpu.api.job_spec import SpecError

ELASTIC_JOB_YAML = """
apiVersion: elastic.easydl.org/v1alpha1
kind: ElasticJob
metadata:
  name: deepctr
spec:
  image: elasticdl:iris_estimator
  command: python -m model_zoo.iris.dnn_estimator
  parameter_server:
    image: elasticdl:iris_estimator
  worker:
    image: elasticdl:iris_estimator
  evaluator:
    image: elasticdl:iris_estimator
"""

JOB_RESOURCE_YAML = """
apiVersion: elastic.easydl.org/v1alpha1
kind: JobResource
metadata:
  name: deepctr-resource
spec:
  selector:
    name: deepctr
  parameter_server:
    replicas: 1
    resource:
      cpu: 4
      memory: 4096
  worker:
    replicas: 2
    resource:
      cpu: 4
      memory: 4096
  evaluator:
    replicas: 1
    resource:
      cpu: 4
      memory: 4096
  resource_updation:
    - name: deepctr-ps-0
      resource:
        cpu: 8
        memory: 8192
"""


def test_elastic_job_round_trip():
    job = JobSpec.from_yaml(ELASTIC_JOB_YAML)
    assert job.name == "deepctr"
    assert job.command == "python -m model_zoo.iris.dnn_estimator"
    assert set(job.roles) == {"parameter_server", "worker", "evaluator"}
    assert job.role_image("worker") == "elasticdl:iris_estimator"
    # role command falls back to the shared top-level command
    assert job.role_command("worker") == job.command
    again = JobSpec.from_yaml(job.to_yaml())
    assert again == job


def test_job_resource_round_trip_and_updation():
    plan = ResourcePlan.from_yaml(JOB_RESOURCE_YAML)
    assert plan.job_name == "deepctr"
    assert plan.replicas("worker") == 2
    assert plan.replicas("parameter_server") == 1
    assert plan.roles["worker"].resource.cpu == 4
    assert len(plan.resource_updation) == 1
    upd = plan.resource_updation[0]
    assert upd.name == "deepctr-ps-0"
    assert upd.resource.memory == 8192
    again = ResourcePlan.from_yaml(plan.to_yaml())
    assert again == plan


def test_tpu_resource_extension():
    plan = ResourcePlan(
        job_name="bert",
        roles={
            "worker": RolePlan(
                replicas=4,
                resource=ResourceSpec(tpu=TpuSpec(type="v4", chips=8, topology="2x2x2")),
            )
        },
    )
    plan.validate()
    assert plan.total_tpu_chips == 32
    again = ResourcePlan.from_yaml(plan.to_yaml())
    assert again.roles["worker"].resource.tpu.topology == "2x2x2"


def test_topology_chip_mismatch_rejected():
    with pytest.raises(SpecError):
        TpuSpec(type="v4", chips=16, topology="2x2x2").validate()


def test_job_requires_command():
    with pytest.raises(SpecError):
        JobSpec(name="x").validate()


def test_plan_diff_scale_and_replace():
    p1 = ResourcePlan.from_yaml(JOB_RESOURCE_YAML)
    p2 = p1.with_role("worker", 5)
    delta = p1.diff(p2)
    assert delta["scale"] == {"worker": (2, 5)}
    assert p2.version == p1.version + 1


def test_vertical_merge():
    base = ResourceSpec(cpu=4, memory=4096)
    upd = ResourceSpec(cpu=8)
    merged = upd.merged_over(base)
    assert merged.cpu == 8 and merged.memory == 4096


def test_evaluator_role_default_command():
    """A bare `evaluator: {}` role must run the checkpoint-following
    evaluator entrypoint, NOT inherit the training command (which would
    make the evaluator pod train)."""
    from easydl_tpu.api.job_spec import JobSpec, RoleSpec

    job = JobSpec(name="j", command="python -m easydl_tpu.models.run --model mlp",
                  roles={"evaluator": RoleSpec(), "worker": RoleSpec()})
    assert "evaluator_main" in job.role_command("evaluator")
    assert job.role_command("worker") == job.command  # workers still inherit
    # an explicit evaluator command still wins
    job2 = JobSpec(name="j", command="c",
                   roles={"evaluator": RoleSpec(command="custom eval")})
    assert job2.role_command("evaluator") == "custom eval"
