"""Model-zoo tests: every family initialises, shards per the rule table, and
takes a real compiled train step on the forced 8-device CPU mesh
(SURVEY.md §4 item 3) — across DP, FSDP and TP mesh layouts for the
transformer, proving the logical-axis annotations actually retarget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from easydl_tpu.core.mesh import MeshSpec
from easydl_tpu.core.train_loop import TrainConfig, Trainer
from easydl_tpu.models.registry import get_model, list_models


def one_step(bundle, mesh_spec, global_batch=8, grad_accum=1):
    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adam(1e-3),
        config=TrainConfig(global_batch=global_batch, grad_accum=grad_accum),
        mesh_spec=mesh_spec,
    )
    state = trainer.init_state()
    batch = next(iter(bundle.make_data(global_batch)))
    state, metrics = trainer.train_step(state, batch)
    state, metrics = trainer.train_step(state, batch)
    return trainer, state, jax.device_get(metrics)


def test_registry_lists_all_families():
    models = list_models()
    for name in ("mlp", "resnet", "bert", "gpt", "deepfm", "widedeep"):
        assert name in models, models


def test_gpt_tiny_dp():
    bundle = get_model("gpt", size="test", seq_len=64, vocab=256)
    _, state, metrics = one_step(bundle, MeshSpec(dp=8))
    assert np.isfinite(metrics["loss"])
    assert metrics["perplexity"] > 1.0
    assert state.int_step == 2


def test_gpt_tiny_fsdp_tp():
    bundle = get_model("gpt", size="test", seq_len=64, vocab=256)
    trainer, state, metrics = one_step(bundle, MeshSpec(fsdp=2, tp=2, dp=2))
    assert np.isfinite(metrics["loss"])
    # TP actually sharded the MLP kernel over tp axis.
    up = state.params["blocks"]["up"]["kernel"]
    spec = getattr(up, "names", None)
    flat = jax.tree.leaves(
        jax.tree.map(lambda x: x, trainer.state_shardings())
    )
    assert any("tp" in str(s.spec) for s in flat), "no parameter sharded over tp"
    assert any("fsdp" in str(s.spec) for s in flat), "no parameter sharded over fsdp"


def test_gpt_grad_accum_matches_single(tmp_path):
    bundle = get_model("gpt", size="test", seq_len=32, vocab=128)
    _, _, m1 = one_step(bundle, MeshSpec(dp=4), global_batch=8, grad_accum=1)
    _, _, m2 = one_step(bundle, MeshSpec(dp=4), global_batch=8, grad_accum=2)
    assert abs(m1["loss"] - m2["loss"]) < 5e-2


def test_gpt_remat_matches_no_remat():
    b1 = get_model("gpt", size="test", seq_len=32, vocab=128, remat=False)
    b2 = get_model("gpt", size="test", seq_len=32, vocab=128, remat=True)
    _, _, m1 = one_step(b1, MeshSpec(dp=2))
    _, _, m2 = one_step(b2, MeshSpec(dp=2))
    np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=1e-4)


def test_bert_tiny_mlm():
    bundle = get_model("bert", size="test", seq_len=64, vocab=256)
    _, state, metrics = one_step(bundle, MeshSpec(dp=8))
    assert np.isfinite(metrics["loss"])
    assert 0.0 <= metrics["mlm_accuracy"] <= 1.0


def test_resnet_tiny():
    bundle = get_model("resnet", size="test", classes=10, image_size=32)
    _, state, metrics = one_step(bundle, MeshSpec(dp=8))
    assert np.isfinite(metrics["loss"])


def test_resnet50_builds_abstractly():
    bundle = get_model("resnet", size="50")
    abstract = jax.eval_shape(bundle.init_fn, jax.random.PRNGKey(0))
    import flax.linen as nn

    n = sum(x.size for x in jax.tree.leaves(nn.meta.unbox(abstract)))
    assert 23_000_000 < n < 28_000_000, n  # ~25.6M params


def test_gpt_345m_param_count_abstract():
    bundle = get_model("gpt", size="345m", seq_len=1024)
    abstract = jax.eval_shape(bundle.init_fn, jax.random.PRNGKey(0))
    import flax.linen as nn

    n = sum(x.size for x in jax.tree.leaves(nn.meta.unbox(abstract)))
    # GPT-2 medium: ~354M with padded vocab + positions
    assert 330_000_000 < n < 380_000_000, n


def test_deepfm_device_embedding():
    bundle = get_model("deepfm", vocab=1000, dim=8, hidden=(32, 32))
    _, state, metrics = one_step(bundle, MeshSpec(dp=4, fsdp=2))
    assert np.isfinite(metrics["loss"])
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_widedeep_no_fm():
    bundle = get_model("widedeep", vocab=1000, dim=8, hidden=(32,))
    _, _, metrics = one_step(bundle, MeshSpec(dp=8))
    assert np.isfinite(metrics["loss"])


def test_deepfm_ps_mode_uses_batch_embeddings():
    bundle = get_model("deepfm", vocab=1000, dim=8, hidden=(32,), embedding="ps")

    def with_emb(batch):
        rng = np.random.default_rng(0)
        batch = dict(batch)
        batch["sparse_emb"] = rng.standard_normal(
            (batch["sparse_ids"].shape[0], batch["sparse_ids"].shape[1], 8)
        ).astype(np.float32)
        return batch

    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adam(1e-3),
        config=TrainConfig(global_batch=8),
        mesh_spec=MeshSpec(dp=8),
    )
    state = trainer.init_state()
    batch = with_emb(next(iter(bundle.make_data(8))))
    state, metrics = trainer.train_step(state, batch)
    assert np.isfinite(jax.device_get(metrics)["loss"])
    # No embedding table in device params in PS mode.
    assert "embedding" not in state.params
