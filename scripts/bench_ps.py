#!/usr/bin/env python
"""PS hot-path microbenchmark: pull/push round-trips against REAL out-of-
process gRPC shards (plus an in-process Local run), uniform vs Zipf id
streams, pre-PR baseline vs the coalesced/raw-wire/vectorized path.

Baseline = the pre-PR data path, reconstructed exactly: strict per-position
wire rows (no dedup), varint ``repeated int64 ids`` encoding, boolean-mask
shard partition, one unary message per shard per op, synchronous push, and
the per-id python-loop numpy store (``EASYDL_PS_STORE_LOOP=1``). Optimized
= the defaults after this PR: ``np.unique`` coalescing with
scatter-on-return, client-side duplicate-grad accumulation, argsort
partition, zero-copy ``raw_ids`` bytes, ~1MB chunked concurrent transfers,
write-behind async push (drained inside the timed region), and the
batched-gather/scatter store.

The default store backend is ``numpy`` — the store this PR vectorized, so
the sharded cells measure the complete pre/post delta (and what any
deployment without a C++ toolchain runs). ``--backend auto``/``native``
swaps in the C++ store, which is byte-identical pre/post PR, isolating the
client+wire portion of the win.

Shard servers run as SUBPROCESSES (like production pods) so the client and
servers don't share a GIL; wire bytes are the shards' own
``easydl_ps_{pull,push}_bytes_total`` counters, scraped from their /metrics
exporters. The Local transport stays in-process (that IS its deployment
shape) and uses the numpy backend so the store vectorization is visible.

JSON lands next to the other bench artifacts::

    python scripts/bench_ps.py --out BENCH_PS.json
    python scripts/bench_ps.py --smoke          # seconds, CI-sized
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from easydl_tpu.ps.client import LocalPsClient, ShardedPsClient  # noqa: E402
from easydl_tpu.ps.table import TableSpec  # noqa: E402
from easydl_tpu.ps.trainer import AsyncPusher  # noqa: E402

TABLE = "bench"

_SERVE_SHARD = r"""
import sys, time
from easydl_tpu.ps.server import PsShard
idx, n, backend, addr_file, obs_dir = sys.argv[1:6]
wal_root = sys.argv[6] if len(sys.argv) > 6 else ""
shard = PsShard(shard_index=int(idx), num_shards=int(n), backend=backend,
                epoch=1 if wal_root else 0, wal_root=wal_root or None)
server = shard.serve(obs_workdir=obs_dir or None)
with open(addr_file + ".tmp", "w") as f:
    f.write(server.address)
import os as _os
_os.replace(addr_file + ".tmp", addr_file)
while True:
    time.sleep(1)
"""


def make_stream(kind: str, steps: int, batch: int, vocab: int,
                zipf_a: float, seed: int = 0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        if kind == "zipf":
            ids = (rng.zipf(zipf_a, batch) % vocab).astype(np.int64)
        else:
            ids = rng.integers(0, vocab, batch).astype(np.int64)
        out.append(ids)
    return out


def _spawn_shards(n: int, backend: str, workdir: str, store_loop: bool,
                  wal: bool = False):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("EASYDL_PS_STORE_LOOP", None)
    if store_loop:
        env["EASYDL_PS_STORE_LOOP"] = "1"
    procs, addr_files = [], []
    for i in range(n):
        addr_file = os.path.join(workdir, f"shard-{i}.addr")
        addr_files.append(addr_file)
        wal_root = (os.path.join(workdir, "ps-wal", f"shard-{i}")
                    if wal else "")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _SERVE_SHARD, str(i), str(n), backend,
             addr_file, workdir, wal_root],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ))
    addrs = []
    deadline = time.monotonic() + 60
    for path in addr_files:
        while not os.path.exists(path):
            if time.monotonic() > deadline:
                for p in procs:
                    p.kill()
                raise TimeoutError("ps shard subprocess never published "
                                   f"{path}")
            time.sleep(0.05)
        with open(path) as f:
            addrs.append(f.read().strip())
    return procs, addrs


def _scrape_wire_bytes(workdir: str) -> float:
    from easydl_tpu.obs.scrape import merge_snapshot

    merged = merge_snapshot(workdir=workdir).get("merged", {})
    return sum(v for k, v in merged.items()
               if k.startswith("easydl_ps_pull_bytes_total")
               or k.startswith("easydl_ps_push_bytes_total"))


def _scrape_wal_counters(workdir: str) -> dict:
    from easydl_tpu.obs.scrape import merge_snapshot

    merged = merge_snapshot(workdir=workdir).get("merged", {})

    def total(name: str) -> float:
        return sum(v for k, v in merged.items() if k.startswith(name))

    return {
        "appends": int(total("easydl_ps_wal_appends_total")),
        "bytes": int(total("easydl_ps_wal_bytes_total")),
    }


def _pass(client, stream, grads, scale: float = 0.125,
          async_push: bool = False) -> float:
    """One pull+push round trip per batch. ``async_push`` runs the pushes
    through the write-behind queue exactly as the pipelined training loop
    does (ps/trainer.py train_steps); the queue is fully DRAINED inside the
    timed region, so every measured pass ends with all updates applied."""
    pusher = AsyncPusher(client, depth=2) if async_push else None
    t0 = time.perf_counter()
    try:
        for ids in stream:
            client.pull(TABLE, ids)
            if pusher is not None:
                pusher.submit(TABLE, ids, grads, scale)
            else:
                client.push(TABLE, ids, grads, scale)
        if pusher is not None:
            pusher.drain()
        return time.perf_counter() - t0
    finally:
        if pusher is not None:
            pusher.close()


def _result(elapsed: float, stream, wire: float) -> dict:
    n_ids = sum(len(s) for s in stream)
    return {
        "elapsed_s": round(elapsed, 4),
        "roundtrips_per_s": round(len(stream) / elapsed, 2),
        "ids_per_s": round(n_ids / elapsed, 1),
        "wire_bytes": int(wire),
        "wire_bytes_per_roundtrip": int(wire / len(stream)),
    }


def run_sharded(optimized: bool, stream, dim: int, shards: int,
                backend: str, fp16: bool = False,
                async_push: bool = False, repeats: int = 3,
                wal: bool = False) -> dict:
    spec = TableSpec(name=TABLE, dim=dim, optimizer="adagrad", seed=11)
    with tempfile.TemporaryDirectory(prefix="bench_ps_") as workdir:
        procs, addrs = _spawn_shards(shards, backend, workdir,
                                     store_loop=not optimized, wal=wal)
        client = None
        try:
            client = ShardedPsClient(addrs, coalesce=optimized,
                                     raw_ids=optimized, pull_fp16=fp16,
                                     chunk_bytes=None if optimized else 0)
            client.create_table(spec)
            grads = np.ones((len(stream[0]), dim), np.float32)
            # Untimed warm pass: channels, pools, lazy row init — one-time
            # table-population costs a real job amortises away. The timed
            # passes are the steady state a training step actually pays;
            # best-of-N filters scheduler noise (this box is small).
            _pass(client, stream, grads)
            b0 = _scrape_wire_bytes(workdir)
            elapsed = min(_pass(client, stream, grads, async_push=async_push)
                          for _ in range(repeats))
            wire = (_scrape_wire_bytes(workdir) - b0) / repeats
            out = _result(elapsed, stream, wire)
            if wal:
                out["wal"] = _scrape_wal_counters(workdir)
            return out
        finally:
            if client is not None:
                client.close()
            for p in procs:
                p.kill()
            for p in procs:
                p.wait()


def run_local(optimized: bool, stream, dim: int, shards: int,
              backend: str, repeats: int = 3) -> dict:
    os.environ.pop("EASYDL_PS_STORE_LOOP", None)
    if not optimized:
        os.environ["EASYDL_PS_STORE_LOOP"] = "1"
    try:
        client = LocalPsClient(num_shards=shards, backend=backend)
        client.create_table(
            TableSpec(name=TABLE, dim=dim, optimizer="adagrad", seed=11)
        )
        grads = np.ones((len(stream[0]), dim), np.float32)
        _pass(client, stream, grads)  # warm: lazy row init off the clock
        elapsed = min(_pass(client, stream, grads) for _ in range(repeats))
        return _result(elapsed, stream, 0.0)
    finally:
        os.environ.pop("EASYDL_PS_STORE_LOOP", None)


def run_wal_mode(args) -> int:
    """WAL-overhead mode: the full post-PR sharded hot path (coalesced raw
    wire, chunked transfers, async push) measured with the push WAL off vs
    on — the only delta is the log append + background fsync on every
    applied push. When a prior ``BENCH_PS.json`` exists its optimized
    round-trip rate is folded in as a cross-run reference (same machine,
    different boot: same-run wal_off is the honest denominator; the
    reference guards against the wal_off run itself having regressed)."""
    doc = {
        "bench": "ps_wal_overhead",
        "config": {
            "shards": args.shards, "dim": args.dim, "batch": args.batch,
            "steps": args.steps, "repeats": args.repeats,
            "vocab": args.vocab, "zipf_a": args.zipf_a,
            "backend": args.backend, "smoke": bool(args.smoke),
        },
        "machine": {
            "platform": platform.platform(),
            "python": platform.python_version(),
            "numpy": np.__version__,
        },
        "results": {},
    }
    reference = {}
    if args.reference:
        try:
            with open(args.reference) as f:
                reference = json.load(f)
        except (OSError, ValueError):
            print(f"note: no reference artifact at {args.reference}")
    for kind in args.streams.split(","):
        stream = make_stream(kind, args.steps, args.batch, args.vocab,
                             args.zipf_a)
        off = run_sharded(True, stream, args.dim, args.shards, args.backend,
                          async_push=True, repeats=args.repeats)
        on = run_sharded(True, stream, args.dim, args.shards, args.backend,
                         async_push=True, repeats=args.repeats, wal=True)
        cell = {
            "wal_off": off,
            "wal_on": on,
            # overhead = throughput lost to the log, as a fraction
            "overhead": round(
                1.0 - on["roundtrips_per_s"] / off["roundtrips_per_s"], 4),
            "wal_bytes_per_roundtrip": int(
                on.get("wal", {}).get("bytes", 0) / max(len(stream), 1)
                / max(args.repeats + 1, 1)),
        }
        ref_cell = (reference.get("results", {}).get("sharded", {})
                    .get(kind, {}).get("optimized"))
        if ref_cell:
            cell["reference_roundtrips_per_s"] = ref_cell["roundtrips_per_s"]
            cell["overhead_vs_reference"] = round(
                1.0 - on["roundtrips_per_s"] / ref_cell["roundtrips_per_s"],
                4)
        doc["results"][kind] = cell
        line = (f"wal/{kind:<8s} off {off['roundtrips_per_s']:8.1f} rt/s  "
                f"on {on['roundtrips_per_s']:8.1f} rt/s  "
                f"overhead {cell['overhead'] * 100:5.1f}%")
        if ref_cell:
            line += (f"  vs-ref {cell['overhead_vs_reference'] * 100:5.1f}%"
                     f" (ref {ref_cell['roundtrips_per_s']:.1f})")
        print(line)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0


def run_reshard_mode(args) -> int:
    """Reshard-dip mode: steady-state Zipf(1.1) pull/push throughput with
    a LIVE 2→``--reshard-to`` online split (ps/reshard.py) running under
    the stream. Unlike the other modes this spawns real registry-backed
    pods (``python -m easydl_tpu.ps``) — the reshard protocol needs the
    routing table, publications, WALs, and epoch fencing the bare bench
    shards don't have. The client is ``ShardedPsClient.from_registry``,
    so cutover-window pushes bounce off retriable `stale-route` Acks and
    re-route exactly as a training job's would.

    Reported: per-window (``--window-s``) round-trip rates, the dip depth
    (1 − worst migration window / pre-split baseline), the dip duration
    (time below 90% of baseline from migration start to recovery), the
    post-cutover steady rate, and the count of HARD client failures
    (exceptions escaping pull/push — the acceptance bar is zero: every
    rejection during migration must be a retriable Ack, never an error).
    Acceptance: hard_failures == 0 and post ≥ 95% of baseline."""
    import shutil
    import threading

    from easydl_tpu.ps import registry, reshard
    from easydl_tpu.ps.client import ShardedPsClient

    from_shards, to_shards = args.shards, args.reshard_to
    spec = TableSpec(name=TABLE, dim=args.dim, optimizer="adagrad", seed=11)
    stream = make_stream("zipf", max(args.steps, 8), args.batch, args.vocab,
                         args.zipf_a)
    workdir = tempfile.mkdtemp(prefix="bench_ps_reshard_")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = []

    def spawn_pod(name: str, num_shards: int, index: int,
                  dest: bool = False) -> None:
        cmd = [sys.executable, "-m", "easydl_tpu.ps", "--name", name,
               "--workdir", workdir, "--num-shards", str(num_shards),
               "--shard-index", str(index)]
        if dest:
            cmd.append("--reshard-dest")
        procs.append(subprocess.Popen(
            cmd, env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))

    def ensure_destinations(plan: dict) -> None:
        for d in range(int(plan["to_shards"])):
            spawn_pod(f"bench-g{plan['generation']}-{d}",
                      int(plan["to_shards"]), d, dest=True)

    migration: dict = {}
    t_mig = {"start": None, "commit": None}

    def run_migration() -> None:
        t_mig["start"] = time.perf_counter()

        def on_phase(name: str, _plan: dict) -> None:
            if name == "committed":
                t_mig["commit"] = time.perf_counter()

        try:
            migration.update(reshard.run_reshard(
                workdir, to_shards, owner="bench-reshard",
                ensure_destinations=ensure_destinations,
                on_phase=on_phase, rpc_timeout=10.0))
        except Exception as e:
            migration["error"] = repr(e)
            return
        # Post-commit the source set is superseded (gated, invisible to
        # routing) — tear it down like the operator would, so the post
        # window measures the new shard set, not CPU contention from
        # idle leftovers.
        for p in procs[:from_shards]:
            p.kill()

    client = None
    try:
        for i in range(from_shards):
            spawn_pod(f"bench-src-{i}", from_shards, i)
        registry.discover(workdir, timeout=60.0)
        client = ShardedPsClient.from_registry(workdir)
        client.create_table(spec)
        grads = np.ones((args.batch, args.dim), np.float32)
        for ids in stream:  # warm: row init, channels, plan caches
            client.pull(TABLE, ids)
            client.push(TABLE, ids, grads, 0.125)

        # One continuous timestamped stream across all three phases; the
        # migration thread starts after ``--pre-s`` of steady state.
        stamps: list = []
        hard_failures = 0
        mig_thread = threading.Thread(target=run_migration, daemon=True)
        t0 = time.perf_counter()
        i = 0
        while True:
            now = time.perf_counter()
            if not mig_thread.is_alive() and t_mig["start"] is None:
                if now - t0 >= args.pre_s:
                    mig_thread.start()
            elif not mig_thread.is_alive():
                if t_mig["commit"] is None:  # migration failed outright
                    break
                if now - t_mig["commit"] >= args.post_s:
                    break
            ids = stream[i % len(stream)]
            i += 1
            try:
                client.pull(TABLE, ids)
                client.push(TABLE, ids, grads, 0.125)
            except Exception:
                hard_failures += 1
            stamps.append(time.perf_counter())
        mig_thread.join(timeout=300.0)

        if "error" in migration or t_mig["commit"] is None:
            print(f"reshard migration FAILED: {migration.get('error')}")
            return 1

        # Steady-state rates come from the stamp SPANS of each phase slice
        # ((n-1)/elapsed — continuous resolution), not windowed counts: at
        # ~20 rt/s a 1s window resolves rate only to ±5%, the same order
        # as the acceptance bar. Windows are kept for dip detection only,
        # where per-window granularity is dwarfed by the dip itself.
        w = args.window_s
        t_start, t_commit = t_mig["start"], t_mig["commit"]

        def span_rate(ts: list) -> float:
            if len(ts) < 2:
                return 0.0
            return (len(ts) - 1) / (ts[-1] - ts[0])

        baseline = span_rate([t for t in stamps if t <= t_start])
        # Post-cutover steady state: the trailing half of the post window
        # (the first half is the settle — reroutes, capability
        # re-negotiation against the fresh pods — which the dip metrics
        # already account for).
        post_rate = span_rate(
            [t for t in stamps if t >= t_commit + args.post_s / 2]
        ) or span_rate([t for t in stamps if t >= t_commit])
        buckets: dict = {}
        for t in stamps:
            buckets.setdefault(int((t - t0) / w), 0)
            buckets[int((t - t0) / w)] += 1
        rate = {k: v / w for k, v in sorted(buckets.items())}
        mig = [r for k, r in rate.items()
               if t_start - t0 <= k * w < t_commit - t0]
        worst = min(mig) if mig else baseline
        # Dip duration: TOTAL time below 90% of baseline from migration
        # start on (a sum, not a first-to-last span — window quantization
        # puts the odd steady-state window a hair under the line, and a
        # span would stretch the dip to the last such straggler).
        low = [k for k, r in rate.items()
               if k * w >= t_start - t0 and r < 0.9 * baseline]
        dip_s = len(low) * w
        doc = {
            "bench": "ps_reshard_dip",
            "config": {
                "from_shards": from_shards, "to_shards": to_shards,
                "dim": args.dim, "batch": args.batch,
                "vocab": args.vocab, "zipf_a": args.zipf_a,
                "pre_s": args.pre_s, "post_s": args.post_s,
                "window_s": w, "smoke": bool(args.smoke),
            },
            "machine": {
                "platform": platform.platform(),
                "python": platform.python_version(),
                "numpy": np.__version__,
            },
            "results": {
                "baseline_rt_per_s": round(baseline, 2),
                "migration_worst_window_rt_per_s": round(worst, 2),
                "dip_depth": round(1.0 - worst / baseline, 4)
                             if baseline else None,
                "dip_duration_s": round(dip_s, 2),
                "post_cutover_rt_per_s": round(post_rate, 2),
                "post_over_baseline": round(post_rate / baseline, 4)
                                      if baseline else None,
                "migration_wall_s": migration.get("wall_s"),
                "rows_migrated": migration.get("rows_migrated"),
                "tail_pushes_replayed": migration.get(
                    "tail_pushes_replayed"),
                "hard_failures": hard_failures,
                "roundtrips_total": len(stamps),
            },
            "acceptance": {
                "no_hard_failures": hard_failures == 0,
                "post_within_5pct_of_baseline":
                    baseline > 0 and post_rate >= 0.95 * baseline,
            },
        }
        r = doc["results"]
        print(f"reshard {from_shards}->{to_shards}: baseline "
              f"{r['baseline_rt_per_s']:.1f} rt/s, dip "
              f"{(r['dip_depth'] or 0) * 100:.1f}% for "
              f"{r['dip_duration_s']:.2f}s, post "
              f"{r['post_cutover_rt_per_s']:.1f} rt/s "
              f"({(r['post_over_baseline'] or 0) * 100:.1f}% of baseline), "
              f"{r['hard_failures']} hard failure(s), migration "
              f"{r['migration_wall_s']}s")
        if args.out:
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
                f.write("\n")
            print(f"wrote {args.out}")
        ok = all(doc["acceptance"].values())
        if not ok:
            print(f"ACCEPTANCE FAILED: {doc['acceptance']}")
        return 0 if ok else 1
    finally:
        if client is not None:
            client.close()
        for p in procs:
            p.kill()
        for p in procs:
            p.wait()
        shutil.rmtree(workdir, ignore_errors=True)


def run_tiered_mode(args) -> int:
    """Two-tier store mode: the NATIVE store single-tier vs tiered with the
    hot arena budgeted to ~1/10 of the materialised table (so ≥10x of the
    table lives cold). In-process on purpose: the wire and client layers
    are identical either way, so this isolates what tiering costs where it
    could hurt — the store itself.

    Two measurements, one gate each way:

    * HOT PATH (the <10% gate): a Zipf stream restricted to the converged
      hot working set. This is the traffic the hot arena exists to serve;
      tiering must not tax it. A contamination gate (cold hits during the
      hot passes < 1% of ids) proves the gate measured hot-tier-served
      traffic, not a mislabeled mixed stream. Maintenance is background-
      cadence work (every EASYDL_PS_TIER_PROMOTE_INTERVAL_S seconds, not
      per step), so its steady-state tick is timed separately and reported
      as ``steady_tick_ms`` rather than smeared into per-step numbers a
      smoke-sized pass cannot amortise.
    * MIXED Zipf(1.1) over the full vocab (reported, not <10%-gated): with
      the hot arena at 1/10 of the table, ~a quarter of Zipf(1.1) accesses
      land cold by construction, and a cold access pays for 4K-paged
      file-backed mmap instead of the THP-backed arena (measured: the
      penalty is identical on tmpfs, so it is page-granularity, not
      writeback). That is the price of beyond-RAM capacity, reported as
      ``mixed_stream_regression`` with the cold-hit ratio that explains it.

    Reported: both round-trip rates, cold-hit ratios, promotion/demotion
    churn, and an export digest from each run. Acceptance (non-zero exit on
    violation): hot-path regression < 10%, hot-pass cold contamination
    < 1%, cold_rows > 0 at the end (a run where nothing spilled proves
    nothing), table ≥10x the hot arena, and export digest parity — the
    tiered table must hold bit-identical rows after the same update
    stream."""
    import hashlib

    from easydl_tpu.ps.table import EmbeddingTable

    spec = TableSpec(name=TABLE, dim=args.dim, optimizer="adagrad", seed=11)
    stream = make_stream("zipf", args.steps, args.batch, args.vocab,
                         args.zipf_a)
    grads = np.ones((args.batch, args.dim), np.float32)
    maintain_every = max(1, len(stream) // 4)
    n_ids = sum(len(s) for s in stream)

    def digest(table) -> str:
        ids, rows = table.export_rows()
        order = np.argsort(ids, kind="stable")
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(ids[order]).tobytes())
        h.update(np.ascontiguousarray(rows[order]).tobytes())
        return h.hexdigest()

    def hot_stream_for(hot_target: int):
        # Zipf draws folded into the hottest `hot_target` ids. Under
        # zipf%vocab the access frequency is decreasing in id, so these are
        # exactly the rows maintenance converges into the hot arena —
        # deterministic, and identical for both runs since `rows` is a
        # function of the shared mixed stream.
        rng = np.random.default_rng(2024)
        return [(rng.zipf(args.zipf_a, args.batch) % hot_target)
                .astype(np.int64) for _ in range(args.steps)]

    def timed_pass(table, ids_stream, ticks: bool, hot_target: int) -> float:
        t0 = time.perf_counter()
        for step, ids in enumerate(ids_stream):
            table.pull(ids)
            table.push(ids, grads, 0.125)
            if ticks and (step + 1) % maintain_every == 0:
                table.tier_maintain(decay=0.9, promote_min_freq=1.0,
                                    swap_margin=1.25,
                                    hot_target_rows=hot_target)
        return time.perf_counter() - t0

    def run(tiered: bool, workdir: str) -> dict:
        t = EmbeddingTable(spec, backend="native")
        for ids in stream:  # warm: row init off the clock, as elsewhere
            t.pull(ids)
            t.push(ids, grads, 0.125)
        rows = t.rows
        hot_target = max(1, rows // 10)
        if tiered:
            row_bytes = spec.row_width * 4
            ok = t.tier_enable(os.path.join(workdir, "bench.cold"),
                               hot_budget_bytes=hot_target * row_bytes,
                               cold_capacity_bytes=2 * rows * row_bytes)
            if not ok:
                raise RuntimeError("tier_enable failed")
            # converge to the budget before timing, like a shard that has
            # been up for a few maintenance intervals
            t.tier_maintain(decay=0.9, promote_min_freq=1.0,
                            swap_margin=1.25, hot_target_rows=hot_target)
            # steady-state tick cost, measured at its real granularity: a
            # whole background maintenance round on the converged table
            tick_t0 = time.perf_counter()
            t.tier_maintain(decay=0.9, promote_min_freq=1.0,
                            swap_margin=1.25, hot_target_rows=hot_target)
            steady_tick_ms = (time.perf_counter() - tick_t0) * 1e3
        cold_hits_0 = t.tier_stats()["cold_hits"] if tiered else 0
        mixed_s = min(timed_pass(t, stream, tiered, hot_target)
                      for _ in range(args.repeats))
        cold_hits_mixed = t.tier_stats()["cold_hits"] if tiered else 0
        # hot-path leg: warm the hot working set, run one maintenance round
        # so stragglers promote (both off the clock), then time the stream
        # the hot tier serves
        hstream = hot_stream_for(hot_target)
        timed_pass(t, hstream, False, hot_target)
        if tiered:
            t.tier_maintain(decay=0.9, promote_min_freq=1.0,
                            swap_margin=1.25, hot_target_rows=hot_target)
        cold_hits_1 = t.tier_stats()["cold_hits"] if tiered else 0
        hot_s = min(timed_pass(t, hstream, False, hot_target)
                    for _ in range(args.repeats))
        st = t.tier_stats()
        out = {
            "hot_elapsed_s": round(hot_s, 4),
            "hot_roundtrips_per_s": round(len(hstream) / hot_s, 2),
            "mixed_elapsed_s": round(mixed_s, 4),
            "mixed_roundtrips_per_s": round(len(stream) / mixed_s, 2),
            "mixed_ids_per_s": round(n_ids / mixed_s, 1),
            "rows": int(rows),
            "digest": digest(t),
        }
        if tiered:
            # every id is accessed twice per round trip (pull then push)
            h_acc = 2 * sum(len(s) for s in hstream) * args.repeats
            out.update({
                "hot_rows": int(st["hot_rows"]),
                "cold_rows": int(st["cold_rows"]),
                "table_over_hot_arena": round(rows / max(st["hot_cap_rows"],
                                                         1), 2),
                "steady_tick_ms": round(steady_tick_ms, 3),
                "cold_hit_ratio_mixed": round(
                    (cold_hits_mixed - cold_hits_0)
                    / max(2 * n_ids * args.repeats, 1), 4),
                "cold_hit_ratio_hot_passes": round(
                    (st["cold_hits"] - cold_hits_1) / max(h_acc, 1), 4),
                "promotions": int(st["promotions"]),
                "demotions": int(st["demotions"]),
                "promotion_churn_per_step": round(
                    st["promotions"] / max(len(stream) * args.repeats, 1), 3),
            })
        return out

    with tempfile.TemporaryDirectory(prefix="bench_ps_tier_") as workdir:
        single = run(False, workdir)
        tiered = run(True, workdir)
    hot_regression = 1.0 - (tiered["hot_roundtrips_per_s"]
                            / single["hot_roundtrips_per_s"])
    mixed_regression = 1.0 - (tiered["mixed_roundtrips_per_s"]
                              / single["mixed_roundtrips_per_s"])
    doc = {
        "bench": "ps_tiered_store",
        "config": {
            "dim": args.dim, "batch": args.batch, "steps": args.steps,
            "repeats": args.repeats, "vocab": args.vocab,
            "zipf_a": args.zipf_a, "maintain_every": maintain_every,
            "smoke": bool(args.smoke),
        },
        "machine": {
            "platform": platform.platform(),
            "python": platform.python_version(),
            "numpy": np.__version__,
        },
        "results": {
            "single_tier": single,
            "tiered": tiered,
            "hot_path_regression": round(hot_regression, 4),
            "mixed_stream_regression": round(mixed_regression, 4),
        },
        "acceptance": {
            "hot_path_regression_under_10pct": hot_regression < 0.10,
            "hot_passes_served_by_hot_tier":
                tiered["cold_hit_ratio_hot_passes"] < 0.01,
            "cold_rows_nonzero": tiered["cold_rows"] > 0,
            "table_at_least_10x_hot_arena":
                tiered["table_over_hot_arena"] >= 10.0,
            "export_digest_parity": single["digest"] == tiered["digest"],
        },
    }
    print(f"tiered hot path: single {single['hot_roundtrips_per_s']:8.1f} "
          f"rt/s  tiered {tiered['hot_roundtrips_per_s']:8.1f} rt/s  "
          f"regression {hot_regression * 100:5.1f}%  "
          f"(hot-pass cold-hit "
          f"{tiered['cold_hit_ratio_hot_passes'] * 100:.2f}%)")
    print(f"tiered mixed:    single {single['mixed_roundtrips_per_s']:8.1f} "
          f"rt/s  tiered {tiered['mixed_roundtrips_per_s']:8.1f} rt/s  "
          f"regression {mixed_regression * 100:5.1f}%  "
          f"cold {tiered['cold_rows']}/{tiered['rows']} rows  "
          f"cold-hit {tiered['cold_hit_ratio_mixed'] * 100:.2f}%  "
          f"churn {tiered['promotion_churn_per_step']}/step")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    ok = all(doc["acceptance"].values())
    if not ok:
        print(f"ACCEPTANCE FAILED: {doc['acceptance']}")
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description="PS pull/push microbenchmark")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed passes per mode; best is reported")
    ap.add_argument("--vocab", type=int, default=200_000)
    ap.add_argument("--zipf-a", type=float, default=1.1)
    ap.add_argument("--backend", default="numpy",
                    help="sharded-store backend: numpy (default — the "
                         "store this PR vectorized, i.e. the full pre/post "
                         "delta and what runs without a C++ toolchain) | "
                         "auto | native (C++ store, identical pre/post PR: "
                         "isolates the client+wire win alone)")
    ap.add_argument("--local-backend", default="numpy",
                    help="Local-transport store backend (numpy shows the "
                         "store vectorization; native is pre/post identical)")
    ap.add_argument("--transports", default="local,sharded")
    ap.add_argument("--streams", default="uniform,zipf")
    ap.add_argument("--fp16", action="store_true",
                    help="add an optimized+fp16-pull variant (sharded only)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized: runs in seconds on CPU")
    ap.add_argument("--wal", action="store_true",
                    help="WAL-overhead mode: the post-PR sharded hot path "
                         "with the push write-ahead log OFF vs ON (same "
                         "stream, same shards); compares against "
                         "BENCH_PS.json when present. Acceptance: ≤10%% "
                         "round-trip overhead on the Zipf(1.1) stream.")
    ap.add_argument("--reference", default=os.path.join(REPO, "BENCH_PS.json"),
                    help="--wal mode: prior bench artifact to compare "
                         "against ('' skips)")
    ap.add_argument("--reshard", action="store_true",
                    help="reshard-dip mode: steady-state Zipf throughput "
                         "while a live --shards→--reshard-to online split "
                         "(ps/reshard.py, real registry-backed pods) runs "
                         "under the stream; reports dip depth/duration and "
                         "post-cutover recovery. Acceptance: zero hard "
                         "client failures and post ≥95%% of baseline.")
    ap.add_argument("--tiered", action="store_true",
                    help="two-tier store mode: the native store's pull/push "
                         "hot path single-tier vs tiered (hot arena ~1/10 "
                         "of the table, maintenance ticks in the timed "
                         "region) on the Zipf(1.1) stream. Acceptance: "
                         "<10%% regression, nonzero cold tier, export "
                         "digest parity.")
    ap.add_argument("--reshard-to", type=int, default=4,
                    help="--reshard mode: destination shard count")
    ap.add_argument("--pre-s", type=float, default=6.0,
                    help="--reshard mode: steady-state seconds before the "
                         "split starts (the baseline window)")
    ap.add_argument("--post-s", type=float, default=6.0,
                    help="--reshard mode: seconds measured after commit")
    ap.add_argument("--window-s", type=float, default=0.5,
                    help="--reshard mode: throughput bucket width")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.smoke:
        args.shards, args.dim = 2, 8
        args.batch, args.steps, args.vocab = 1024, 4, 20_000
        args.repeats = 1
        args.pre_s, args.post_s = 2.0, 2.0
    if args.wal:
        return run_wal_mode(args)
    if args.reshard:
        return run_reshard_mode(args)
    if args.tiered:
        return run_tiered_mode(args)

    doc = {
        "bench": "ps_hot_path",
        "config": {
            "shards": args.shards, "dim": args.dim, "batch": args.batch,
            "steps": args.steps, "repeats": args.repeats,
            "vocab": args.vocab, "zipf_a": args.zipf_a,
            "backend": args.backend, "local_backend": args.local_backend,
            "smoke": bool(args.smoke),
        },
        "machine": {
            "platform": platform.platform(),
            "python": platform.python_version(),
            "numpy": np.__version__,
        },
        "results": {},
        "dedup_ratio": {},
    }
    for kind in args.streams.split(","):
        stream = make_stream(kind, args.steps, args.batch, args.vocab,
                             args.zipf_a)
        total = sum(len(s) for s in stream)
        uniq = sum(len(np.unique(s)) for s in stream)
        doc["dedup_ratio"][kind] = round(uniq / total, 4)
    for transport in args.transports.split(","):
        doc["results"][transport] = {}
        for kind in args.streams.split(","):
            stream = make_stream(kind, args.steps, args.batch, args.vocab,
                                 args.zipf_a)
            if transport == "sharded":
                # Baseline = the full pre-PR loop: strict per-position wire,
                # no chunking, synchronous push on the critical path.
                # Optimized = the full post-PR data path, async push
                # included (drained inside the timed region) — exactly what
                # the pipelined training loop runs. optimized_strict keeps
                # the push synchronous, isolating the wire/store win.
                base = run_sharded(False, stream, args.dim, args.shards,
                                   args.backend, repeats=args.repeats)
                opt_strict = run_sharded(True, stream, args.dim, args.shards,
                                         args.backend, repeats=args.repeats)
                opt = run_sharded(True, stream, args.dim, args.shards,
                                  args.backend, async_push=True,
                                  repeats=args.repeats)
            else:
                base = run_local(False, stream, args.dim, args.shards,
                                 args.local_backend, repeats=args.repeats)
                opt_strict = None
                opt = run_local(True, stream, args.dim, args.shards,
                                args.local_backend, repeats=args.repeats)
            cell = {
                "baseline": base,
                "optimized": opt,
                "speedup": round(opt["roundtrips_per_s"]
                                 / base["roundtrips_per_s"], 2),
                "wire_bytes_ratio": round(
                    opt["wire_bytes"] / max(base["wire_bytes"], 1), 4),
            }
            if opt_strict is not None:
                cell["optimized_strict"] = opt_strict
                cell["speedup_strict"] = round(
                    opt_strict["roundtrips_per_s"]
                    / base["roundtrips_per_s"], 2)
            if transport == "sharded" and args.fp16:
                cell["optimized_fp16"] = run_sharded(
                    True, stream, args.dim, args.shards, args.backend,
                    fp16=True, async_push=True, repeats=args.repeats,
                )
            doc["results"][transport][kind] = cell
            print(f"{transport:>8s}/{kind:<8s} "
                  f"base {base['roundtrips_per_s']:8.1f} rt/s  "
                  f"opt {opt['roundtrips_per_s']:8.1f} rt/s  "
                  f"speedup {cell['speedup']:5.2f}x  "
                  f"wire {cell['wire_bytes_ratio']:.3f}x")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
