"""GCE metadata-server maintenance/preemption watcher.

SIGTERM covers k8s eviction, but Cloud TPU VMs get an *earlier* warning
through the instance metadata server: the ``maintenance-event`` value flips
from ``NONE`` before the host is migrated/terminated, and preemptible/spot
VMs flip ``preempted`` to ``TRUE`` at the start of the ~30s grace window.
The reference's elasticity story leans on reacting to exactly this class of
notice (SURVEY.md §5.3/§7.3; /root/reference/README.md:25-29); watching the
metadata server converts "the host vanished mid-step" (restore from last
checkpoint, lose the window) into "drain at the next step boundary" (lose
nothing).

Protocol: hanging GET with ``?wait_for_change=true&timeout_sec=N`` and the
mandatory ``Metadata-Flavor: Google`` header — the server long-polls and
responds when the value changes (or the timeout elapses, returning the
current value; we re-poll). stdlib-only, one daemon thread, fires the
callback once. Tests point ``base_url`` at a local fake metadata server
(tests/test_gce_metadata.py).
"""

from __future__ import annotations

import threading
import urllib.error
import urllib.request
from typing import Callable, Optional

from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.env import knob_str

log = get_logger("elastic", "gce")

DEFAULT_BASE_URL = "http://metadata.google.internal"
_MAINT_PATH = (
    "/computeMetadata/v1/instance/maintenance-event"
    "?wait_for_change=true&timeout_sec={timeout}"
)
_PREEMPT_PATH = (
    "/computeMetadata/v1/instance/preempted"
    "?wait_for_change=true&timeout_sec={timeout}"
)
#: maintenance-event values that mean nothing is happening; the watcher
#: fires on anything NOT in this tuple (MIGRATE/TERMINATE_ON_HOST_MAINTENANCE)
_BENIGN = ("", "NONE")


class GceMaintenanceWatcher:
    """Fires ``on_notice(reason)`` once when the metadata server announces a
    maintenance event or preemption.

    ``available()`` probes for a metadata server first so non-GCE
    deployments (tests, on-prem, other clouds) skip the watcher entirely
    rather than log connection errors forever.
    """

    def __init__(
        self,
        on_notice: Callable[[str], None],
        base_url: str = DEFAULT_BASE_URL,
        wait_timeout_s: int = 60,
        retry_s: float = 5.0,
    ):
        self.on_notice = on_notice
        self.base_url = base_url.rstrip("/")
        self.wait_timeout_s = wait_timeout_s
        self.retry_s = retry_s
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------------ http
    def _get(self, path: str, timeout: float) -> str:
        req = urllib.request.Request(
            self.base_url + path, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode(errors="replace").strip()

    def available(self, probe_timeout: float = 1.0) -> bool:
        """True when a metadata server answers (i.e. we're on GCE)."""
        try:
            self._get("/computeMetadata/v1/instance/", probe_timeout)
            return True
        except (urllib.error.URLError, OSError, ValueError):
            return False

    # ------------------------------------------------------------------ loops
    def _fire(self, reason: str) -> None:
        if not self._fired.is_set():
            self._fired.set()
            log.warning("GCE notice: %s — signalling preemption", reason)
            try:
                self.on_notice(reason)
            except Exception:
                log.exception("preemption callback failed")

    def _watch(self, path_tpl: str, is_notice: Callable[[str], bool],
               label: str) -> None:
        path = path_tpl.format(timeout=self.wait_timeout_s)
        while not (self._stop.is_set() or self._fired.is_set()):
            try:
                value = self._get(path, self.wait_timeout_s + 15.0)
            except (urllib.error.URLError, OSError) as e:
                # metadata server unreachable: back off and retry — the VM
                # may be under the very disruption we're watching for
                log.debug("%s poll failed: %s", label, e)
                self._stop.wait(self.retry_s)
                continue
            if is_notice(value):
                self._fire(f"{label}={value}")
                return
            # benign value (NONE / FALSE): the hanging GET timed out or the
            # event cleared; immediately re-poll

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "GceMaintenanceWatcher":
        for path_tpl, is_notice, label in (
            (_MAINT_PATH, lambda v: v.upper() not in _BENIGN,
             "maintenance-event"),
            (_PREEMPT_PATH, lambda v: v.upper() == "TRUE", "preempted"),
        ):
            t = threading.Thread(
                target=self._watch, args=(path_tpl, is_notice, label),
                daemon=True, name=f"gce-{label}",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()

    @property
    def fired(self) -> bool:
        return self._fired.is_set()


def maybe_start_watcher(
    on_notice: Callable[[str], None],
    base_url: Optional[str] = None,
) -> Optional[GceMaintenanceWatcher]:
    """Start a watcher if a metadata server is reachable; None otherwise.

    ``base_url`` override (or the EASYDL_GCE_METADATA_URL env var) exists
    for tests and for metadata proxies.
    """
    url = base_url or knob_str("EASYDL_GCE_METADATA_URL") \
        or DEFAULT_BASE_URL
    w = GceMaintenanceWatcher(on_notice, base_url=url)
    if not w.available():
        log.info("no GCE metadata server at %s; watcher disabled", url)
        return None
    return w.start()
