"""Acceptance test for distributed tracing (ISSUE 4): on a live local job —
real gRPC master, real agent thread, real worker subprocess — the master's
generation-switch trace context crosses the gRPC hop (directive reply
metadata → agent) and the subprocess-env hop (EASYDL_TRACE_CONTEXT →
worker), so worker-side spans carry the MASTER's trace_id. Also pins the
disabled contract: an untraced job writes no span files."""

import os
import time

import pytest

from easydl_tpu.elastic.agent import Agent
from easydl_tpu.elastic.master import Master
from easydl_tpu.obs import tracing

JOB = "trace-e2e"
CFG = {
    "model": "mlp",
    "model_kwargs": {"input_shape": [8, 8, 1], "features": [32, 32]},
    "global_batch": 32,
    # Long enough that the job is still live while we read span files.
    "total_steps": 100_000,
    "ckpt_interval": 50,
    "lr": 0.01,
    "seed": 0,
}


def wait_for(cond, timeout=180.0, interval=0.2, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}")


def test_worker_step_span_carries_master_trace_id(tmp_path, monkeypatch):
    workdir = str(tmp_path)
    monkeypatch.setenv(tracing.TRACE_ENV, "1")
    monkeypatch.setenv("EASYDL_TRACE_STEP_EVERY", "5")
    master = Master(
        job_name=JOB, workdir=workdir, desired_workers=1, min_workers=1,
        worker_config=CFG,
    ).start()
    agent = Agent("a0", master.address, workdir, slots=1).start()
    try:
        wait_for(
            lambda: master.status()["agents"].get("a0", {}).get("step", 0)
            >= 10,
            desc="worker training past step 10",
        )

        def switch_closed():
            return any(
                r["ph"] == "X" and r["name"] == "generation_switch"
                for r in tracing.read_all(workdir)
            )
        wait_for(switch_closed, timeout=30,
                 desc="generation_switch span closed on the master")

        recs = tracing.read_all(workdir)
        switch = next(r for r in recs if r["ph"] == "X"
                      and r["name"] == "generation_switch")
        # the switch really formed generation 1 and saw its directives
        assert switch["attrs"]["generation"] >= 1
        assert any(e["name"] == "directive:run"
                   for e in switch.get("events", []))
        assert switch["proc"] == "master"

        # worker-side spans: same trace as the master's switch — the
        # context crossed gRPC (reply metadata) AND the subprocess env.
        worker = [r for r in recs if r["proc"] == "worker-a0"]
        assert worker, sorted({r["proc"] for r in recs})
        run = next(r for r in worker if r["name"] == "worker_run"
                   and r["ph"] == "B")
        assert run["trace"] == switch["trace"]
        wait_for(
            lambda: any(r["ph"] == "X" and r["name"] == "step"
                        for r in tracing.read_all(workdir)),
            timeout=30, desc="a sampled worker step span",
        )
        step = next(r for r in tracing.read_all(workdir)
                    if r["ph"] == "X" and r["name"] == "step")
        assert step["trace"] == switch["trace"]
        assert step["attrs"]["step"] % 5 == 0

        # the generic RPC server spans exist for the heartbeat stream
        assert any(r["name"] == "rpc:easydl.Master/Heartbeat"
                   for r in recs if r["proc"] == "master")
    finally:
        agent.stop()
        master.stop()


def test_untraced_job_writes_no_span_files(tmp_path, monkeypatch):
    monkeypatch.delenv(tracing.TRACE_ENV, raising=False)
    workdir = str(tmp_path)
    master = Master(
        job_name=JOB, workdir=workdir, desired_workers=1, min_workers=1,
        worker_config=dict(CFG, total_steps=30),
    ).start()
    agent = Agent("a0", master.address, workdir, slots=1).start()
    try:
        wait_for(lambda: master.done, desc="tiny job done")
    finally:
        agent.stop()
        master.stop()
    obs = os.path.join(workdir, "obs")
    if os.path.isdir(obs):
        spans = [n for n in os.listdir(obs) if n.startswith("spans-")]
        assert spans == [], spans
