#!/usr/bin/env python3
"""easylint CLI: run the repo-invariant analyzer and gate on the baseline.

Usage::

    python scripts/easylint.py                      # easydl_tpu/ scripts/
    python scripts/easylint.py easydl_tpu/ps        # a subtree
    python scripts/easylint.py --list-rules
    python scripts/easylint.py --update-baseline    # regenerate allowlist

Exit status is the gate: 0 when every finding is covered by the committed
baseline (scripts/codestyle/easylint_baseline.txt) and no baseline entry
still carries the TODO reason marker; 1 on any new finding, TODO-stamped
entry, or malformed baseline. Stale entries (allowlisted violations that
no longer exist) are reported as warnings here and rejected by the tier-1
gate (tests/test_easylint.py) so the baseline can only shrink silently,
never grow. ``--update-baseline`` rewrites the allowlist sorted/deduped,
preserving existing reasons and stamping new entries with a TODO the gate
refuses — baselining always requires a human-written reason
(docs/operations.md#easylint).
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from easydl_tpu.analysis import baseline as bl  # noqa: E402
from easydl_tpu.analysis.core import analyze_paths  # noqa: E402
from easydl_tpu.analysis.rules import all_rules  # noqa: E402

DEFAULT_PATHS = ("easydl_tpu", "scripts")
DEFAULT_BASELINE = os.path.join("scripts", "codestyle",
                                "easylint_baseline.txt")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="easylint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to analyze (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=REPO,
                    help="repo root paths are relative to (default: the "
                         "checkout containing this script)")
    ap.add_argument("--baseline", default=None,
                    help=f"allowlist file (default: <root>/{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the allowlist; report every finding")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the allowlist from current findings "
                         "(reasons preserved, new entries TODO-stamped)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name}: {r.invariant}")
        return 0

    root = os.path.abspath(args.root)
    paths = args.paths or list(DEFAULT_PATHS)
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)

    try:
        findings = analyze_paths(paths, rules, root=root)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1

    if args.update_baseline:
        entries = bl.load(baseline_path)
        merged = bl.updated(findings, entries)
        bl.save(baseline_path, merged)
        todo = sum(1 for e in merged if e.reason == bl.TODO_REASON)
        print(f"easylint: baseline rewritten: {len(merged)} entries "
              f"({todo} need a reason) -> {baseline_path}")
        if todo:
            print("easylint: replace every TODO reason before committing — "
                  "the gate rejects TODO-stamped entries")
        return 0

    if args.no_baseline:
        entries = []
    else:
        try:
            entries = bl.load(baseline_path)
        except ValueError as e:
            print(f"easylint: {e}", file=sys.stderr)
            return 1

    new, stale = bl.match(findings, entries)
    todo = [e for e in entries if e.reason == bl.TODO_REASON]

    for f in new:
        print(f.render())
    for e in stale:
        print(f"easylint: WARNING stale baseline entry (violation is gone "
              f"— delete the line or run --update-baseline): {e.render()}",
              file=sys.stderr)
    for e in todo:
        print(f"easylint: baseline entry lacks a reason: {e.render()}",
              file=sys.stderr)

    n_rules = len(rules)
    n_ok = len(findings) - len(new)
    print(f"easylint: {n_rules} rules, {len(findings)} findings "
          f"({n_ok} baselined, {len(new)} new, {len(stale)} stale, "
          f"{len(todo)} TODO)", file=sys.stderr)
    return 1 if (new or todo) else 0


if __name__ == "__main__":
    sys.exit(main())
