"""The EASYDL_* knob registry + process-environment recipes.

Every environment knob the fleet reads is DECLARED here — name, type,
default, one-line purpose — and read through the typed accessors
(:func:`knob_str` / :func:`knob_int` / :func:`knob_float` /
:func:`knob_bool` / :func:`knob_raw`). The declaration is load-bearing
three ways:

* easylint's ``knob-registry`` rule (analysis/rules/knobs.py) rejects any
  inline ``os.environ`` read of an ``EASYDL_*`` literal outside this
  module, and rejects accessor calls whose name is not declared — a
  typo'd knob fails in lint, not silently in production;
* the doc-sync test (tests/test_easylint.py) asserts the
  ``docs/operations.md`` knob table and ``KNOB_DECLS`` agree both ways,
  so the operator docs cannot rot;
* the accessors give every knob ONE parsing convention (booleans via the
  flag grammar below, numbers via int()/float()) and one default,
  instead of per-call-site drift.

``KNOB_DECLS`` is a pure literal tuple on purpose: the static analyzer
reads it with ``ast.literal_eval`` — no import side effects required. A
trailing ``*`` declares a name FAMILY (``EASYDL_METRICS_PORT_<COMP>``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Mapping, Optional

# --------------------------------------------------------------- registry
#: (name, type, default, help). type ∈ {str,int,float,bool}; default None
#: means REQUIRED — reading it when unset raises KeyError, matching the
#: old `env["EASYDL_RANK"]` behavior for the agent→worker IPC variables.
KNOB_DECLS = (
    # -- identity / job wiring (set by launchers, read by services) ------
    ("EASYDL_WORKDIR", "str", None,
     "Job working directory: journals, WAL roots, checkpoints, metrics "
     "files, timelines all live under it."),
    ("EASYDL_JOB", "str", "",
     "Job name a pod belongs to (controller process/kube backends)."),
    ("EASYDL_POD_NAME", "str", "",
     "Pod name injected by the controller backends (PS pod identity)."),
    ("EASYDL_POD_ROLE", "str", "",
     "Pod role (ps/master/agent/serve) injected by controller backends."),
    ("EASYDL_AGENT_ID", "str", "",
     "Agent identity passed to worker subprocesses (chaos windows, "
     "metrics file naming)."),
    ("EASYDL_REPLACES", "str", "",
     "Pod name a rescue PS shard replaces (claims its WAL + shard slot)."),
    ("EASYDL_RESHARD_DEST", "bool", False,
     "Marks a PS pod as a live-reshard destination (skips rescue probe)."),
    # -- agent -> worker IPC (required where read) -----------------------
    ("EASYDL_RANK", "int", None,
     "Worker rank within the generation (agent->worker spawn env)."),
    ("EASYDL_WORLD", "int", None,
     "World size of the generation (agent->worker spawn env)."),
    ("EASYDL_COORD", "str", None,
     "jax.distributed coordinator address (agent->worker spawn env)."),
    ("EASYDL_GEN", "int", None,
     "Membership generation the worker belongs to."),
    ("EASYDL_METRICS", "str", None,
     "Per-agent metrics JSONL path the worker appends step reports to."),
    ("EASYDL_MESH", "str", "",
     "Mesh shape key ('dp=2,fsdp=2,tp=2') the elastic master decided for "
     "this generation; '' = take the static job-config mesh."),
    ("EASYDL_TIMELINE", "str", "",
     "Recovery-timeline JSONL path (phase boundary events)."),
    ("EASYDL_GO_FILE", "str", "",
     "Rendezvous gate file: worker blocks until it appears."),
    ("EASYDL_WARM_FILE", "str", "",
     "Warm-standby gate file: standby imports+compiles, then blocks."),
    ("EASYDL_MASTER_WAIT_S", "float", 600.0,
     "How long an agent waits for a master before giving up."),
    # -- logging / metrics exporter --------------------------------------
    ("EASYDL_LOG_LEVEL", "str", "INFO",
     "Root logger level for every easydl_tpu process."),
    ("EASYDL_METRICS_HOST", "str", "",
     "Bind host for /metrics exporters (default localhost)."),
    ("EASYDL_METRICS_PORT", "int", 0,
     "Exporter port for all components; 0 picks a free port; "
     "off/disabled/negative disables."),
    ("EASYDL_METRICS_PORT_*", "int", 0,
     "Per-component exporter port override; wins over "
     "EASYDL_METRICS_PORT."),
    ("EASYDL_METRICS_PORT_MASTER", "int", 0,
     "Exporter port for the elastic master."),
    ("EASYDL_METRICS_PORT_AGENT", "int", 0,
     "Exporter port for the elastic agent."),
    ("EASYDL_METRICS_PORT_PS", "int", 0,
     "Exporter port for PS shard pods."),
    ("EASYDL_METRICS_PORT_BRAIN", "int", 0,
     "Exporter port for the Brain service."),
    ("EASYDL_METRICS_PORT_CONTROLLER", "int", 0,
     "Exporter port for the controller/operator."),
    ("EASYDL_METRICS_PORT_SERVE", "int", 0,
     "Exporter port for serving replicas."),
    # -- tracing ----------------------------------------------------------
    ("EASYDL_TRACE", "str", "",
     "Arms distributed tracing; ''/0/off/false/no/disabled/none = off."),
    ("EASYDL_TRACE_CONTEXT", "str", "",
     "Injected parent span context (subprocess hop of propagation)."),
    ("EASYDL_TRACE_PROC", "str", "",
     "Process name override for the flight recorder."),
    ("EASYDL_TRACE_MAX_BYTES", "int", 8_388_608,  # 8 MiB
     "Flight-recorder ring size per process."),
    ("EASYDL_TRACE_STEP_EVERY", "int", 25,
     "Worker traces every Nth train step."),
    # -- parameter server -------------------------------------------------
    ("EASYDL_PS_WAL", "bool", True,
     "Push write-ahead log on/off (zero-loss recovery, PR 6)."),
    ("EASYDL_PS_WAL_SEGMENT_BYTES", "int", 33_554_432,  # 32 MiB
     "WAL segment roll size."),
    ("EASYDL_PS_WAL_SYNC_S", "float", 0.2,
     "WAL fsync cadence; 0 = fsync every append."),
    ("EASYDL_PS_FENCE_CHECK_S", "float", 0.5,
     "Zombie self-check cadence against the registry epoch."),
    ("EASYDL_PS_PROBE_TIMEOUT_S", "float", 5.0,
     "Rescue probe per-attempt timeout."),
    ("EASYDL_PS_PROBE_RETRIES", "int", 2,
     "Rescue probe attempts before declaring a shard dead."),
    ("EASYDL_PS_CHUNK_BYTES", "int", 1_048_576,  # 1 MiB
     "Client-side pull/push chunking target."),
    ("EASYDL_PS_COALESCE", "bool", True,
     "Duplicate-id coalescing on pull (trainer path defaults off)."),
    ("EASYDL_PS_RAW_IDS", "bool", True,
     "Zero-copy raw-bytes id wire format (falls back per shard)."),
    ("EASYDL_PS_PULL_FP16", "bool", False,
     "Negotiate fp16 pull payloads (halves the wire)."),
    ("EASYDL_PS_PULL_I8", "bool", False,
     "Negotiate int8 pull payloads (per-row symmetric quantization, "
     "~0.25x the f32 wire; serving replicas only — the trainer keeps "
     "f32)."),
    ("EASYDL_PS_SHM", "bool", False,
     "Zero-copy shared-memory pull transport: shards mirror tables into "
     "named shm segments, co-located clients gather rows directly "
     "(seqlock-validated) and fall back to gRPC on any mismatch."),
    ("EASYDL_PS_SHM_MAX_MB", "int", 256,
     "Per-table shm mirror capacity cap; a table outgrowing it revokes "
     "the mirror (clients fall back to the wire)."),
    ("EASYDL_PS_STORE_LOOP", "bool", False,
     "Force the python reference row-apply loop (bench comparisons)."),
    ("EASYDL_PS_TIER_HOT_MB", "int", 0,
     "Hot-tier byte budget per shard for the two-tier native store; 0 = "
     "single-tier (no cold spill)."),
    ("EASYDL_PS_TIER_COLD_MB", "int", 4096,
     "Cold-tier mmap file capacity per table (under the shard workdir)."),
    ("EASYDL_PS_TIER_PROMOTE_INTERVAL_S", "float", 2.0,
     "Tier maintenance cadence: decay frequencies, demote cold hot rows, "
     "promote warm cold rows."),
    ("EASYDL_PS_TIER_DECAY", "float", 0.9,
     "Per-tick multiplicative access-frequency decay (ages out "
     "yesterday's hot set)."),
    # -- cross-cell failover (cell/) --------------------------------------
    ("EASYDL_CELL_STANDBY_WORKDIR", "str", "",
     "Standby cell workdir the WAL shipper replicates into; '' = no "
     "standby configured."),
    ("EASYDL_CELL_SHIP_INTERVAL_S", "float", 0.5,
     "Cross-cell ship pass cadence (bounds the async-replication RPO)."),
    ("EASYDL_CELL_LAG_SLO_BYTES", "int", 4_194_304,  # 4 MiB
     "Replication-lag SLO the promotion decision records breaches "
     "against (easydl_cell_replication_lag gauge)."),
    ("EASYDL_CELL_RTO_BUDGET_S", "float", 60.0,
     "Promotion RTO budget: fence -> standby tier serving scores."),
    ("EASYDL_PS_SPLIT_HOT_RATIO", "float", 1.5,
     "Hot-shard split trigger: shard rows vs mean ratio."),
    ("EASYDL_PS_SPLIT_MIN_ROWS", "float", 100_000.0,
     "Minimum total rows before split decisions engage."),
    ("EASYDL_PS_SPLIT_MAX_SHARDS", "int", 64,
     "Upper bound on PS shard fan-out from auto-splits."),
    ("EASYDL_PS_SPLIT_ACCESS_RATIO", "float", 2.0,
     "Max/mean per-shard access ratio that counts as hot-working-set "
     "skew (the two-tier split trigger)."),
    # -- serving ----------------------------------------------------------
    ("EASYDL_SERVE_TARGET_QPS", "float", 500.0,
     "Per-replica QPS target for the autoscale policy."),
    ("EASYDL_SERVE_P99_BUDGET_S", "float", 0.050,
     "p99 latency budget for the autoscale policy."),
    ("EASYDL_SERVE_MIN_REPLICAS", "int", 1,
     "Autoscale floor for serving replicas."),
    ("EASYDL_SERVE_MAX_REPLICAS", "int", 64,
     "Autoscale ceiling for serving replicas."),
    # -- serve fleet router ------------------------------------------------
    ("EASYDL_SERVE_HEDGE_BUDGET", "float", 0.1,
     "Hedged-request budget: max fraction of recent routed requests that "
     "may carry a hedge (a sick fleet must not double its own load); "
     "<= 0 disables hedging."),
    ("EASYDL_SERVE_HEDGE_MIN_MS", "float", 5.0,
     "Floor for the p95-derived hedge delay."),
    ("EASYDL_SERVE_HEDGE_MAX_MS", "float", 200.0,
     "Ceiling for the p95-derived hedge delay."),
    ("EASYDL_SERVE_ROUTER_HOLDDOWN_S", "float", 2.0,
     "Hold-down before an ejected replica is re-probed for rotation."),
    ("EASYDL_SERVE_ROUTER_EJECT_FAILS", "int", 3,
     "Consecutive transport failures (or hard sheds) that eject a "
     "replica from rotation."),
    ("EASYDL_SERVE_ROUTER_REFRESH_S", "float", 1.0,
     "Replica discovery refresh cadence (workdir serve/ registry scan)."),
    # -- production loop: feedback stream + rollout -----------------------
    ("EASYDL_FEEDBACK_SPOOL_BYTES", "int", 268_435_456,  # 256 MiB
     "Per-replica feedback spool byte bound; past it (after retiring "
     "trainer-consumed segments) new events DROP with a count — the "
     "spool never blocks or fails a serve request."),
    ("EASYDL_FEEDBACK_SEGMENT_BYTES", "int", 8_388_608,  # 8 MiB
     "Feedback spool segment roll size."),
    ("EASYDL_FEEDBACK_SYNC_S", "float", 0.2,
     "Feedback spool fsync cadence; 0 = every append, negative = never."),
    ("EASYDL_FEEDBACK_POLL_S", "float", 0.2,
     "Continuous-trainer poll cadence on an exhausted spool "
     "(block-with-timeout, never terminate)."),
    ("EASYDL_FEEDBACK_LABEL_HORIZON_S", "float", 60.0,
     "Delayed-label join horizon: a serve event unlabeled past it trains "
     "with the implicit negative label."),
    ("EASYDL_ROLLOUT_POLL_S", "float", 0.5,
     "Serve-side model-publication watcher poll cadence."),
    ("EASYDL_ROLLOUT_KEEP", "int", 4,
     "Committed model versions the publisher keeps on disk."),
    ("EASYDL_ROLLOUT_CANARY_FRACTION", "float", 0.1,
     "Session-hash fraction routed to the canary arm while one is "
     "active (sessions without an id always serve control)."),
    ("EASYDL_ROLLOUT_SALT", "str", "",
     "Session->arm hash salt; rotate to reshuffle the A/B population."),
    # -- retrieval: two-tower + ANN index ---------------------------------
    ("EASYDL_RETRIEVAL_USER_TABLE", "str", "tt_user",
     "PS table holding the user-tower context embeddings."),
    ("EASYDL_RETRIEVAL_ITEM_TABLE", "str", "tt_item",
     "PS table holding the item-tower embeddings; pushes to it are what "
     "the index builder tails into retrievability."),
    ("EASYDL_RETRIEVAL_K", "int", 10,
     "Default candidate count a Retrieve request gets when it asks for "
     "k<=0."),
    ("EASYDL_RETRIEVAL_NLIST", "int", 16,
     "ANN index bucket count (k-means centroids) once clustered."),
    ("EASYDL_RETRIEVAL_NPROBE", "int", 8,
     "Centroid buckets probed per query; >= nlist degenerates to exact "
     "brute force."),
    ("EASYDL_RETRIEVAL_POLL_S", "float", 0.05,
     "Index-builder WAL tail poll cadence on an exhausted log."),
    ("EASYDL_RETRIEVAL_CKPT_EVERY", "int", 8,
     "Applied incremental updates between index snapshot publications "
     "(snapshot first, cursor second — the exactly-once boundary)."),
    ("EASYDL_RETRIEVAL_FRESHNESS_SLO_S", "float", 5.0,
     "Push->retrievable freshness SLO the bench gates p99 against."),
    ("EASYDL_RETRIEVAL_TEMPERATURE", "float", 0.05,
     "In-batch sampled-softmax temperature for two-tower training."),
    ("EASYDL_RETRIEVAL_REBUILD_MIN_ROWS", "int", 64,
     "Rows before the flat index first clusters; below it brute force is "
     "exact and cheap."),
    # -- mesh-shape policy / MFU ------------------------------------------
    ("EASYDL_MESH_PIN", "str", "",
     "Operator override: pin the elastic mesh-shape policy to this shape "
     "key ('dp=8'); invalid-for-world pins fall back to the policy."),
    ("EASYDL_CHIP_PEAK_TFLOPS", "float", 0.0,
     "MFU denominator override: this chip's peak dense TFLOP/s (wins over "
     "the built-in device-kind table; unset+unknown chip = loud v4 "
     "fallback)."),
    # -- storage / caches -------------------------------------------------
    ("EASYDL_COMPILE_CACHE", "str", "",
     "Persistent XLA compile cache dir; off disables; '' = workdir "
     "default."),
    ("EASYDL_CHUNK_CACHE", "str", "",
     "Dataset chunk cache: 0/off disables, a path overrides the root."),
    ("EASYDL_GCS_ENDPOINT", "str", "https://storage.googleapis.com",
     "GCS base URL override (fake server / proxy)."),
    ("EASYDL_GCE_METADATA_URL", "str", "",
     "GCE metadata server override (tests, proxies)."),
    # -- SLOs / alerting (obs/slo.py, obs/alerts.py) ----------------------
    ("EASYDL_SLO_DIR", "str", "",
     "SLO spec directory the alert evaluator loads; '' = the repo's "
     "slos/."),
    ("EASYDL_ALERT_EVAL_INTERVAL_S", "float", 0.5,
     "Alert evaluator cadence: one fleet snapshot + one pure burn-rate "
     "decision per tick."),
    ("EASYDL_ALERT_LEDGER_SEGMENT_BYTES", "int", 4_194_304,  # 4 MiB
     "Alert-decision ledger (spool-framed JSONL) segment roll size."),
    ("EASYDL_ALERT_TTD_BUDGET_S", "float", 15.0,
     "Default time-to-detect budget a drill's expected alert must fire "
     "within (per-scenario expect.detect.ttd_budget_s overrides)."),
    ("EASYDL_ALERT_DRILL_RECORD", "bool", True,
     "Chaos harness records the alert timeline during every drill "
     "(detected_and_cleared evidence); off skips the recorder thread."),
    ("EASYDL_ALERT_SETTLE_S", "float", 12.0,
     "Max seconds teardown waits for a drill's expected alert to clear "
     "before stopping the recorder (the clear half of "
     "detected_and_cleared needs one clean long window)."),
    ("EASYDL_SCRAPE_POOL", "int", 8,
     "Bounded worker pool for concurrent fleet scrapes "
     "(obs.scrape.scrape_fleet)."),
    # -- chaos / harness child markers ------------------------------------
    ("EASYDL_CHAOS_SPEC", "str", "",
     "Armed chaos scenario spec path; unset = every hook is one dict "
     "lookup."),
    ("EASYDL_CHAOS_CHILD", "str", "",
     "Marks the re-exec'd forced-CPU chaos_run child ('1')."),
    ("EASYDL_RECOVERY_CHILD", "str", "",
     "Marks the re-exec'd measure_recovery child ('1')."),
    ("EASYDL_PIPEBENCH_CHILD", "str", "",
     "Marks the re-exec'd bench_pipeline child ('1')."),
)


@dataclass(frozen=True)
class Knob:
    name: str
    type: str
    default: object
    help: str


KNOBS: Dict[str, Knob] = {d[0]: Knob(*d) for d in KNOB_DECLS}

_UNSET = object()


def _declared(name: str) -> Knob:
    k = KNOBS.get(name)
    if k is not None:
        return k
    for fam, kf in KNOBS.items():  # family declarations: trailing *
        if fam.endswith("*") and name.startswith(fam[:-1]):
            return kf
    raise KeyError(
        f"{name} is not declared in easydl_tpu.utils.env.KNOB_DECLS — "
        "declare it (name, type, default, help) and add it to the "
        "docs/operations.md knob table")


def knob_raw(name: str, env: Optional[Mapping[str, str]] = None,
             ) -> Optional[str]:
    """The declared-but-untyped read: raw value or None when unset. For
    save/restore idioms and presence checks; typed reads use knob_*."""
    _declared(name)
    return (env if env is not None else os.environ).get(name)


def _resolve(name: str, default, env) -> Optional[str]:
    knob = _declared(name)
    v = (env if env is not None else os.environ).get(name)
    if v is not None:
        return v
    d = knob.default if default is _UNSET else default
    if d is None:
        raise KeyError(f"required knob {name} is not set")
    return d


def knob_str(name: str, default=_UNSET,
             env: Optional[Mapping[str, str]] = None) -> str:
    return str(_resolve(name, default, env))


def knob_int(name: str, default=_UNSET,
             env: Optional[Mapping[str, str]] = None) -> int:
    return int(_resolve(name, default, env))


def knob_float(name: str, default=_UNSET,
               env: Optional[Mapping[str, str]] = None) -> float:
    return float(_resolve(name, default, env))


def knob_bool(name: str, default=_UNSET,
              env: Optional[Mapping[str, str]] = None) -> bool:
    v = _resolve(name, default, env)
    if isinstance(v, bool):
        return v
    return v not in ("", "0", "false", "False")


def env_flag(name: str, default: bool) -> bool:
    """Boolean EASYDL_* knob convention: unset → ``default``; ``"0"``,
    ``"false"``/``"False"`` and empty mean off; anything else means on.
    (Deliberately lenient about undeclared names — tests mint throwaway
    flags; the knob-registry lint still checks literal in-tree uses.)"""
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("", "0", "false", "False")


def obs_port_from_env(component: str, default: int = 0):
    """Resolve a service's metrics-exporter port from the environment.

    Precedence: ``EASYDL_METRICS_PORT_<COMPONENT>`` (component upper-cased,
    non-alnum → ``_``) > ``EASYDL_METRICS_PORT`` > ``default`` (0 = pick a
    free port). ``off``/``disabled``/negative disables the exporter —
    returns None. Unparseable values fall back to the default rather than
    killing the service: observability must never be load-bearing."""
    key = "EASYDL_METRICS_PORT_" + "".join(
        c if c.isalnum() else "_" for c in component
    ).upper()
    raw = os.environ.get(key) or os.environ.get("EASYDL_METRICS_PORT")
    if raw is None:
        return default
    raw = raw.strip().lower()
    if raw in ("off", "disabled", "none", "false"):
        return None
    try:
        port = int(raw)
    except ValueError:
        return default
    if port < 0:
        return None
    if port > 65535:  # a typo'd port must not take the service down
        return default
    return port


def cpu_subprocess_env(
    n_devices: int, base: Optional[Mapping[str, str]] = None
) -> Dict[str, str]:
    """Environment for a subprocess that must initialise JAX on a forced
    ``n_devices``-device CPU platform.

    Neutralises the image's TPU tunnel plugin (PALLAS_AXON_POOL_IPS) so the
    child cannot re-attach to the chip — the single authoritative copy of the
    recipe used by the elastic agent's worker spawns and the driver's
    ``dryrun_multichip`` bootstrap.
    """
    env = dict(base if base is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    return env


def join_rank_processes(procs, timeout: float = 900.0, poll_s: float = 0.25):
    """Join coordinated rank subprocesses (stdout/stderr PIPEd), fail-fast.

    A crashed rank leaves its peers blocked in a collective; waiting out the
    full timeout hides the root cause for minutes and then discards the
    failing rank's stderr. Poll instead: the moment any rank exits non-zero
    (or the deadline passes) kill the stragglers, then harvest every rank's
    output. Pipes are drained CONCURRENTLY by reader threads — draining
    only after exit would deadlock any child whose chatter exceeds the OS
    pipe buffer (it blocks in write(), never exits, and a passing run turns
    into a full-timeout kill). Returns ``[(returncode, stdout, stderr)]``
    in rank order — killed stragglers report negative returncodes; the
    caller should report the *non-signal* failures first.
    """
    import threading
    import time

    def drain(stream, sink):
        if stream is None:
            return
        while True:  # empty-chunk EOF test works for text AND binary pipes
            chunk = stream.read(8192)
            if not chunk:
                return
            sink.append(chunk)

    buffers = []
    readers = []
    for p in procs:
        out_buf, err_buf = [], []
        buffers.append((out_buf, err_buf))
        for stream, sink in ((p.stdout, out_buf), (p.stderr, err_buf)):
            t = threading.Thread(target=drain, args=(stream, sink),
                                 daemon=True)
            t.start()
            readers.append(t)

    deadline = time.monotonic() + timeout
    try:
        while True:
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                break
            if any(c not in (None, 0) for c in codes):
                break  # a rank failed: don't wait for the blocked peers
            if time.monotonic() > deadline:
                break
            time.sleep(poll_s)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p in procs:
        p.wait()
    for t in readers:
        t.join(timeout=10.0)
    def joined(buf):
        return (b"" if buf and isinstance(buf[0], bytes) else "").join(buf)

    return [
        (p.returncode, joined(out_buf), joined(err_buf))
        for p, (out_buf, err_buf) in zip(procs, buffers)
    ]


def run_cpu_rank_fleet(argvs, n_local_devices: int, timeout: float = 900.0,
                       cwd=None):
    """Spawn one forced-CPU jax subprocess per argv (a coordinated rank
    fleet), join with fail-fast, and surface failures.

    The single authoritative copy of the spawn/report idiom shared by
    ``dryrun_multichip``'s multi-process leg and the measurement scripts:
    per-rank ``cpu_subprocess_env`` + repo PYTHONPATH, concurrent pipe
    drains via :func:`join_rank_processes`, stdouts replayed in rank order,
    and failures reported with *real* (non-signal) exits first — a killed
    straggler's -9 must not mask the rank whose stderr holds the root
    cause. Raises RuntimeError naming the failing rank; returns the list
    of rank stdouts on success."""
    import os
    import subprocess
    import sys

    root = cwd or os.getcwd()
    procs = []
    for argv in argvs:
        env = cpu_subprocess_env(n_local_devices)
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            argv, env=env, cwd=root,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = join_rank_processes(procs, timeout=timeout)
    for rc, out, err in results:
        sys.stdout.write(out)
    for rank, (rc, out, err) in sorted(
            enumerate(results), key=lambda kv: kv[1][0] >= 0, reverse=True):
        if rc != 0:
            sys.stderr.write(err)
            raise RuntimeError(f"rank {rank} failed rc={rc}")
    return [out for _, out, _ in results]

def pin_cpu_platform_if_requested() -> None:
    """Honor ``JAX_PLATFORMS=cpu`` even where a sitecustomize pins an
    accelerator plugin via jax.config (which outranks env vars).

    The in-process half of the forced-CPU recipe — the single copy every
    entrypoint (zoo runner, elastic worker, warm standby, evaluator pod)
    calls right after importing jax. Without it, a CPU-deployed process
    attaches to the accelerator plugin and hangs or fails whenever that
    backend is unreachable."""
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
