"""Pod lifecycle interface + the in-memory fake used by tests and the
simulated-distributed runtime.

The reference operator talks to the real k8s pod API; the framework keeps
that behind :class:`PodApi` so the reconciler is testable against an
in-memory cluster (SURVEY.md §4 item 4: "reconcile logic against an
in-memory k8s API fake") and portable to a real cluster client later.

Phases follow k8s: Pending → Running → Succeeded/Failed (+ Terminating
while a delete is in flight). :class:`InMemoryPodApi` adds the test levers:
``tick()`` advances Pending pods to Running, ``fail()`` injects a crash,
and every mutation lands on an event list the controller can watch.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from easydl_tpu.api.job_spec import ResourceSpec
from easydl_tpu.utils.logging import get_logger

log = get_logger("controller", "pods")

PHASES = ("Pending", "Running", "Succeeded", "Failed", "Terminating")


@dataclass
class Pod:
    name: str
    job: str
    role: str
    resource: ResourceSpec = field(default_factory=ResourceSpec)
    phase: str = "Pending"
    #: name of the pod this one replaces (resource_updation replace-then-retire,
    #: docs/design/elastic-training-operator.md:99-101); "" if none.
    replaces: str = ""
    command: str = ""
    image: str = ""
    created_at: float = field(default_factory=time.time)


class PodApi:
    """The operator's view of the cluster."""

    def create_pod(self, pod: Pod) -> None:
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def list_pods(self, job: Optional[str] = None) -> List[Pod]:
        raise NotImplementedError

    def get_pod(self, name: str) -> Optional[Pod]:
        for p in self.list_pods():
            if p.name == name:
                return p
        return None


class InMemoryPodApi(PodApi):
    """Fake cluster: pods are records; deletes are immediate (no grace
    period) unless ``graceful`` — then they linger Terminating until tick."""

    def __init__(self, graceful: bool = False):
        self._pods: Dict[str, Pod] = {}
        self._lock = threading.RLock()
        self._graceful = graceful
        self.events: List[tuple] = []  # (verb, pod_name)
        self._watchers: List[Callable[[str, str], None]] = []

    def _emit(self, verb: str, name: str) -> None:
        self.events.append((verb, name))
        for w in list(self._watchers):
            w(verb, name)

    def watch(self, fn: Callable[[str, str], None]) -> None:
        """Register fn(verb, pod_name); called under the api lock — keep it
        cheap (the controller just pokes its reconcile queue)."""
        self._watchers.append(fn)

    # ----------------------------------------------------------------- PodApi
    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            if pod.name in self._pods:
                raise ValueError(f"pod {pod.name!r} already exists")
            self._pods[pod.name] = pod
            self._emit("create", pod.name)
            log.debug("created pod %s (%s, replaces=%r)", pod.name, pod.role,
                      pod.replaces)

    def delete_pod(self, name: str) -> None:
        with self._lock:
            pod = self._pods.get(name)
            if pod is None:
                return  # idempotent, like k8s delete of a gone pod
            if self._graceful and pod.phase in ("Pending", "Running"):
                pod.phase = "Terminating"
            else:
                del self._pods[name]
            self._emit("delete", name)

    def list_pods(self, job: Optional[str] = None) -> List[Pod]:
        with self._lock:
            pods = [p for p in self._pods.values() if job is None or p.job == job]
            return sorted(pods, key=lambda p: p.name)

    # ------------------------------------------------------------ test levers
    def tick(self) -> None:
        """Advance the fake cluster: Pending → Running, Terminating → gone."""
        with self._lock:
            for name in list(self._pods):
                p = self._pods[name]
                if p.phase == "Pending":
                    p.phase = "Running"
                    self._emit("running", name)
                elif p.phase == "Terminating":
                    del self._pods[name]
                    self._emit("gone", name)

    def fail(self, name: str) -> None:
        """Inject a crash (preemption, OOM): phase → Failed."""
        with self._lock:
            if name in self._pods:
                self._pods[name].phase = "Failed"
                self._emit("failed", name)

    def set_phase(self, name: str, phase: str) -> None:
        assert phase in PHASES, phase
        with self._lock:
            if name in self._pods:
                self._pods[name].phase = phase
                self._emit(phase.lower(), name)
