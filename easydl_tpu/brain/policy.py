"""Brain decision logic: startup plans and the autoscaling policy.

Pure functions/objects with an injectable clock — no IO, no gRPC — so the
scale-decision loop is unit-testable and replayable (SURVEY.md §5.2). The
service layer (brain/service.py) wires this to the wire protocol.

The reference promises: "EasyDL can automatically configure the resources"
at startup and "monitor the performance of a training job and dynamically
adjust the resources" during it (README.md:19-23); the trainer queries
startup resources once and new plans periodically
(docs/design/elastic-training-operator.md:106-112). Plan quality — avoiding
oscillation — is SURVEY.md §7 hard part 5; the damping here (cooldown,
hysteresis band, remembered bad sizes, marginal-efficiency test) is the
answer.
"""

from __future__ import annotations

import ctypes
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from easydl_tpu.api.job_spec import ResourceSpec, TpuSpec
from easydl_tpu.api.resource_plan import ResourcePlan, RolePlan
from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.native import load_native

log = get_logger("brain", "policy")

# The native Brain core (SURVEY §2.1 item 2): startup sizing + the damped
# autoscale step as C functions over a line wire format. Python twins below
# are pinned to it by randomized parity tests (tests/test_brain.py) — the
# same architecture as the operator's reconciler core.
_SOURCE = os.path.join(os.path.dirname(__file__), "native", "brain_core.cc")


def _bind(lib: ctypes.CDLL) -> None:
    for fn in (lib.edb_startup, lib.edb_decide):
        fn.argtypes = [ctypes.c_char_p]
        fn.restype = ctypes.c_void_p  # manual free via edb_free
    lib.edb_free.argtypes = [ctypes.c_void_p]


def _native_call(fn_name: str, text: str) -> Optional[str]:
    lib = load_native(_SOURCE, _bind)
    if lib is None:
        return None
    ptr = getattr(lib, fn_name)(text.encode())
    if not ptr:
        return None
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        lib.edb_free(ptr)


#: Every character Python's str.splitlines treats as a terminator, plus the
#: field separator. The C++ core splits on '\n' only, so ANY terminator the
#: twin's splitlines honors must be sanitized or the two would desync (e.g.
#: '\r' from a CRLF-edited job spec).
_WIRE_UNSAFE = "|\n\r\v\f\x1c\x1d\x1e\x85\u2028\u2029"


def _wire_str(s: str) -> str:
    """Field sanitizer: the wire is line/pipe delimited."""
    out = s or ""
    for ch in _WIRE_UNSAFE:
        if ch in out:
            out = out.replace(ch, "_")
    return out


# ---------------------------------------------------------------------------
# Startup plans (docs/design/elastic-training-operator.md:106-107)
# ---------------------------------------------------------------------------

#: Per model family: (startup worker replicas, chips per worker, PS replicas).
#: Families match JobFeatures.model_family; sized for the five BASELINE
#: configs (BASELINE.md) — e.g. the MNIST quickstart is 1 PS + 2 workers.
_FAMILY_DEFAULTS: Dict[str, Tuple[int, int, int]] = {
    "mlp": (2, 0, 1),       # quickstart: CPU workers + 1 PS
    "resnet": (8, 1, 0),    # static 8-worker all-reduce DDP
    "bert": (8, 1, 0),      # elastic DP on a v4 slice
    "gpt": (8, 1, 0),       # starts at 8 chips; Brain may grow it to 32
    "deepfm": (4, 1, 2),    # async PS for sparse tables + dense TPU workers
    "widedeep": (4, 1, 2),
}
_DEFAULT = (2, 1, 0)

#: Parameter-count escalation: huge models start wider regardless of family.
_PARAMS_TO_MIN_WORKERS = (
    (5_000_000_000, 32),
    (1_000_000_000, 16),
    (200_000_000, 8),
)


def encode_features(features: pb.JobFeatures) -> str:
    """Wire-encode JobFeatures for the startup-sizing core. The family is
    pre-lowercased here so core and twin match on identical bytes."""
    return (
        f"F|{_wire_str(features.model_family).lower()}"
        f"|{int(features.model_params)}"
        f"|{1 if features.uses_ps else 0}"
        f"|{1 if features.uses_evaluator else 0}"
        f"|{_wire_str(features.accelerator.type)}"
        f"|{int(features.accelerator.chips)}\n"
    )


def _py_startup_sizing(wire: str) -> str:
    """Python twin of the native ``edb_startup`` (same wire in/out)."""
    for line in wire.splitlines():
        f = line.split("|")
        if not f or f[0] != "F" or len(f) < 7:
            continue
        family, params = f[1], int(f[2])
        uses_ps, uses_eval = f[3] == "1", f[4] == "1"
        tpu_type = f[5] or "v5e"
        acc_chips = int(f[6])
        workers, chips, ps = _FAMILY_DEFAULTS.get(family, _DEFAULT)
        if uses_ps and ps == 0:
            ps = 1
        if not uses_ps:
            ps = 0
        for threshold, min_workers in _PARAMS_TO_MIN_WORKERS:
            if params >= threshold:
                workers = max(workers, min_workers)
                break
        if acc_chips:
            chips = max(chips, acc_chips)
        return f"P|{workers}|{chips}|{ps}|{1 if uses_eval else 0}|{tpu_type}\n"
    return ""


def startup_sizing_wire(wire: str, force_python: bool = False) -> str:
    """Run the startup sizing through the native core (Python twin when no
    toolchain / forced)."""
    if not force_python:
        out = _native_call("edb_startup", wire)
        if out:
            return out
    return _py_startup_sizing(wire)


def startup_plan(features: pb.JobFeatures, version: int = 1,
                 force_python: bool = False) -> ResourcePlan:
    """First resource plan from extracted job features.

    Mirrors the trainer flow the reference specifies: "extracts features from
    the job, and queries the startup resources from EasyDL Brain"
    (docs/design/elastic-training-operator.md:106-107). Sizing numbers come
    from the native core (brain_core.cc) with the Python twin as fallback;
    this function materialises them into a ResourcePlan.
    """
    out = startup_sizing_wire(encode_features(features),
                              force_python=force_python)
    fields = (out.strip().split("|") + [""] * 6)[:6]
    if fields[0] != "P":
        raise ValueError(f"bad sizing result {out!r}")
    workers, chips, ps = int(fields[1]), int(fields[2]), int(fields[3])
    tpu_type = fields[5] or "v5e"

    roles = {
        "worker": RolePlan(
            replicas=workers,
            resource=ResourceSpec(
                cpu=4.0,
                memory=16384,
                tpu=TpuSpec(type=tpu_type, chips=chips) if chips else None,
            ),
        ),
    }
    if ps:
        roles["parameter_server"] = RolePlan(
            replicas=ps, resource=ResourceSpec(cpu=8.0, memory=32768)
        )
    if features.uses_evaluator:
        roles["evaluator"] = RolePlan(
            replicas=1, resource=ResourceSpec(cpu=4.0, memory=8192)
        )
    plan = ResourcePlan(
        name=f"{features.job_name}-plan",
        job_name=features.job_name,
        roles=roles,
        version=version,
    )
    plan.validate()
    return plan


# ---------------------------------------------------------------------------
# Autoscaler (docs/design/elastic-training-operator.md:110-112)
# ---------------------------------------------------------------------------


@dataclass
class AutoscalerConfig:
    """Damped scale policy knobs.

    The decision loop doubles the worker count while scaling stays efficient
    and retreats when marginal efficiency collapses — the north-star shape
    (8→32 chips with <5% throughput loss) climbs 8→16→32.
    """

    min_workers: int = 1
    max_workers: int = 32
    #: samples needed at the current size before any decision
    min_samples: int = 5
    #: seconds between scale decisions (cooldown against oscillation)
    cooldown_s: float = 30.0
    #: scale up only if measured efficiency at the current size is above this
    #: (perfect linear scaling = 1.0)
    scaleup_efficiency_floor: float = 0.80
    #: after a scale-up, demand at least this marginal efficiency — otherwise
    #: revert and remember the size as bad
    marginal_efficiency_floor: float = 0.60
    #: scale down when per-chip throughput is this far below the best seen
    #: (the job shrank or stalled; fewer chips waste less)
    scaledown_throughput_ratio: float = 0.35
    #: growth factor per decision (2 ⇒ 8→16→32)
    growth: int = 2
    #: sliding window per world size
    window: int = 20


@dataclass
class _SizeStats:
    samples: Deque[float] = field(default_factory=lambda: deque(maxlen=64))

    def add(self, samples_per_sec: float, window: int) -> None:
        if self.samples.maxlen != window:
            self.samples = deque(self.samples, maxlen=window)
        self.samples.append(samples_per_sec)

    @property
    def count(self) -> int:
        return len(self.samples)

    @property
    def throughput(self) -> float:
        if not self.samples:
            return 0.0
        return sum(self.samples) / len(self.samples)


class Autoscaler:
    """Per-job damped scale decider.

    Feed it :class:`pb.StepMetrics` via :meth:`observe`; ask :meth:`decide`
    for a target worker count. Deterministic given the metric stream and the
    injected ``clock``.
    """

    def __init__(
        self,
        config: Optional[AutoscalerConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        force_python: bool = False,
    ):
        self.config = config or AutoscalerConfig()
        self._clock = clock
        self._force_py = force_python
        self._per_size: Dict[int, _SizeStats] = {}
        self._last_decision_t: float = -1e18
        self._last_size: int = 0
        #: best windowed per-chip rate ever observed (collapse detector baseline)
        self._best_per_chip: float = 0.0
        #: sizes that failed the marginal-efficiency test (don't retry them)
        self._bad_sizes: set = set()
        #: (from_size, to_size) of the last scale-up, for the marginal check
        self._pending_check: Optional[Tuple[int, int]] = None

    # ------------------------------------------------------------------ intake
    def observe(self, m: pb.StepMetrics) -> None:
        import math

        size = max(int(m.world_size), 1)
        # Reject non-finite rates at the source: a NaN admitted into a
        # window would make "efficiency" NaN, where the native core's
        # NaN-encodes-None convention and the twin's is-not-None check
        # would legitimately diverge (review r5 finding #1).
        if not math.isfinite(m.samples_per_sec) or m.samples_per_sec <= 0:
            return
        stats = self._per_size.setdefault(size, _SizeStats())
        stats.add(m.samples_per_sec, self.config.window)
        self._last_size = size
        if stats.count >= self.config.min_samples:
            self._best_per_chip = max(self._best_per_chip, stats.throughput / size)

    # ---------------------------------------------------------------- decision
    def encode_state(self, current_workers: int, now: float) -> str:
        """Wire-encode the full decision input for the native core (and its
        Python twin). Floats as ``repr`` — shortest round-trip decimal, so
        C++ strtod reconstructs the identical double."""
        cfg = self.config
        lines = [
            f"C|{cfg.min_workers}|{cfg.max_workers}|{cfg.min_samples}"
            f"|{cfg.cooldown_s!r}|{cfg.scaleup_efficiency_floor!r}"
            f"|{cfg.marginal_efficiency_floor!r}"
            f"|{cfg.scaledown_throughput_ratio!r}|{cfg.growth}",
            f"T|{now!r}|{self._last_decision_t!r}|{max(current_workers, 1)}",
            f"B|{self._best_per_chip!r}",
        ]
        for s in sorted(self._bad_sizes):
            lines.append(f"X|{s}")
        if self._pending_check:
            lines.append(f"K|{self._pending_check[0]}|{self._pending_check[1]}")
        for s, st in sorted(self._per_size.items()):
            lines.append(f"S|{s}|" + ",".join(repr(float(v)) for v in st.samples))
        return "\n".join(lines) + "\n"

    def decide(self, current_workers: int) -> int:
        """Target worker count (== current to hold steady).

        The decision itself runs in the native core (brain_core.cc), with
        :func:`_py_decide_wire` as the toolchain-free twin; this method
        owns state: it encodes the snapshot, applies the returned effects
        (cooldown stamp, bad-size memory, pending audit), and logs."""
        now = self._clock()
        cur = max(current_workers, 1)
        wire = self.encode_state(cur, now)
        out = None
        if not self._force_py:
            out = _native_call("edb_decide", wire)
        if not out:
            out = _py_decide_wire(wire)
        f = (out.strip().split("|") + ["-1"] * 7)[:7]
        if f[0] != "D":
            raise ValueError(f"bad decision result {out!r}")
        target, decided = int(f[1]), f[2] == "1"
        bad, clear_pending = int(f[3]), f[4] == "1"
        pend_from, pend_to = int(f[5]), int(f[6])
        if clear_pending:
            self._pending_check = None
        if bad >= 0:
            self._bad_sizes.add(bad)
            log.warning(
                "scale-up %d→%d inefficient; reverting and remembering %d "
                "as bad", target, bad, bad,
            )
        if pend_from >= 0:
            self._pending_check = (pend_from, pend_to)
        if decided:
            self._last_decision_t = now
            if bad < 0 and target != cur:
                log.info("scaling %s %d→%d",
                         "up" if target > cur else "down", cur, target)
        return target

    # ------------------------------------------------------------- durability
    def to_state(self) -> Dict[str, object]:
        """JSON-serializable snapshot of everything :meth:`restore_state`
        needs to continue deciding as if the process never died: the per-size
        windows, the bad-size memory, the pending marginal audit, and the
        cooldown *as elapsed time* (the raw ``_last_decision_t`` is a
        monotonic-clock reading, meaningless in a new process)."""
        if self._last_decision_t > -1e17:
            cooldown_elapsed = min(
                max(self._clock() - self._last_decision_t, 0.0),
                self.config.cooldown_s,
            )
        else:
            cooldown_elapsed = None  # never decided: no cooldown in force
        return {
            "per_size": {
                str(s): [round(x, 4) for x in st.samples]
                for s, st in self._per_size.items()
            },
            "bad_sizes": sorted(self._bad_sizes),
            "best_per_chip": self._best_per_chip,
            "last_size": self._last_size,
            "pending_check": (
                list(self._pending_check) if self._pending_check else None
            ),
            "cooldown_elapsed_s": cooldown_elapsed,
        }

    def restore_state(self, doc: Dict[str, object]) -> None:
        """Restore a :meth:`to_state` snapshot. NEVER raises: the doc comes
        off disk, and a Brain pod crashed mid-journal-write leaves a torn /
        partial / garbage document behind — a replacement that dies on its
        own state file can never come back. Anything unusable degrades to
        fresh state with a logged warning; autoscaling then re-learns its
        windows instead of staying down for the rest of the job."""
        import math

        def reset() -> None:
            self._per_size = {}
            self._bad_sizes = set()
            self._best_per_chip = 0.0
            self._last_size = 0
            self._pending_check = None
            self._last_decision_t = -1e18

        try:
            if not isinstance(doc, dict):
                raise TypeError(f"state doc is {type(doc).__name__}, "
                                "not dict")
            per_size: Dict[int, _SizeStats] = {}
            for s, vals in (doc.get("per_size") or {}).items():
                stats = _SizeStats()
                for v in vals:
                    v = float(v)
                    if math.isfinite(v) and v > 0:
                        stats.add(v, self.config.window)
                per_size[int(s)] = stats
            bad_sizes = {int(b) for b in doc.get("bad_sizes") or []}
            best = float(doc.get("best_per_chip") or 0.0)
            best = best if math.isfinite(best) else 0.0
            last_size = int(doc.get("last_size") or 0)
            pending = doc.get("pending_check")
            pending_check = (
                (int(pending[0]), int(pending[1])) if pending else None
            )
            elapsed = doc.get("cooldown_elapsed_s")
            last_decision_t = (
                -1e18 if elapsed is None
                else self._clock() - float(elapsed)
            )
        except Exception as e:
            log.warning(
                "corrupt autoscaler state doc (%s); degrading to fresh "
                "state — windows will re-learn", e,
            )
            reset()
            return
        # Every field validated: install atomically (a raise above leaves
        # the autoscaler untouched until reset()).
        self._per_size = per_size
        self._bad_sizes = bad_sizes
        self._best_per_chip = best
        self._last_size = last_size
        self._pending_check = pending_check
        self._last_decision_t = last_decision_t

    # ------------------------------------------------------------------ status
    def status(self) -> Dict[str, object]:
        return {
            "sizes": {
                s: {"n": st.count, "samples_per_sec": round(st.throughput, 2)}
                for s, st in sorted(self._per_size.items())
            },
            "bad_sizes": sorted(self._bad_sizes),
            "last_size": self._last_size,
        }


# ------------------------------------------------------------- decision twin


def _py_decide_wire(text: str) -> str:
    """Python twin of the native ``edb_decide``: same wire in, same wire
    out, bit-identical arithmetic (both sides left-fold the same decimal
    literals as IEEE doubles). Pinned to the core by the randomized parity
    test in tests/test_brain.py."""
    cfg = {"min_w": 1, "max_w": 32, "min_samples": 5, "growth": 2,
           "cooldown": 30.0, "up_floor": 0.80, "marg_floor": 0.60,
           "down_ratio": 0.35}
    now, last_t, cur = 0.0, -1e18, 1
    best_per_chip = 0.0
    bad_sizes: set = set()
    pending: Optional[Tuple[int, int]] = None
    per_size: Dict[int, List[float]] = {}
    for line in text.splitlines():
        f = line.split("|")
        if not f or not f[0]:
            continue
        if f[0] == "C" and len(f) >= 9:
            cfg = {"min_w": int(f[1]), "max_w": int(f[2]),
                   "min_samples": int(f[3]), "cooldown": float(f[4]),
                   "up_floor": float(f[5]), "marg_floor": float(f[6]),
                   "down_ratio": float(f[7]), "growth": int(f[8])}
        elif f[0] == "T" and len(f) >= 4:
            now, last_t, cur = float(f[1]), float(f[2]), max(int(f[3]), 1)
        elif f[0] == "B" and len(f) >= 2:
            best_per_chip = float(f[1])
        elif f[0] == "X" and len(f) >= 2:
            bad_sizes.add(int(f[1]))
        elif f[0] == "K" and len(f) >= 3:
            pending = (int(f[1]), int(f[2]))
        elif f[0] == "S" and len(f) >= 3:
            per_size[int(f[1])] = [float(v) for v in f[2].split(",") if v]

    def throughput(samples: List[float]) -> float:
        return sum(samples, 0.0) / len(samples) if samples else 0.0

    def efficiency(size: int) -> Optional[float]:
        samples = per_size.get(size)
        if samples is None or len(samples) < cfg["min_samples"]:
            return None
        base = [
            throughput(vals) / s
            for s, vals in per_size.items()
            if s < size and len(vals) >= cfg["min_samples"]
        ]
        if not base:
            return None
        best_pc = max(base)
        if best_pc <= 0:
            return None
        return throughput(samples) / (size * best_pc)

    target, decided, bad, clear_pending = cur, False, -1, False
    pend_from = pend_to = -1

    def emit() -> str:
        return (f"D|{target}|{1 if decided else 0}|{bad}"
                f"|{1 if clear_pending else 0}|{pend_from}|{pend_to}\n")

    samples = per_size.get(cur)
    if samples is None or len(samples) < cfg["min_samples"]:
        return emit()
    if now - last_t < cfg["cooldown"]:
        return emit()

    # 1. Marginal-efficiency audit of the last scale-up.
    if pending and pending[1] == cur:
        eff = efficiency(cur)
        if eff is not None:
            clear_pending = True
            if eff < cfg["marg_floor"]:
                bad, decided, target = pending[1], True, pending[0]
                return emit()

    # 2. Scale down if far off the best per-chip rate ever seen.
    per_chip = throughput(samples) / cur
    if (cur > cfg["min_w"] and best_per_chip > 0
            and per_chip < cfg["down_ratio"] * best_per_chip):
        down = max(cfg["min_w"], cur // cfg["growth"])
        if down != cur:
            decided, target = True, down
            return emit()

    # 3. Scale up while efficient.
    up = min(cur * cfg["growth"], cfg["max_w"])
    if up > cur and up not in bad_sizes:
        eff = efficiency(cur)
        if eff is None:
            # At the smallest measured size there is no baseline: treat as
            # efficient (the north-star run must leave 8 chips somehow) —
            # provided the current rate is healthy vs the best ever seen.
            smaller = [s for s in per_size if s < cur]
            if not smaller and per_chip >= cfg["up_floor"] * best_per_chip:
                eff = 1.0
        if eff is not None and eff >= cfg["up_floor"]:
            decided, target = True, up
            pend_from, pend_to = cur, up
            return emit()
    return emit()


# ---------------------------------------------------------------------------
# Plan evolution
# ---------------------------------------------------------------------------


def replan(
    prev: ResourcePlan,
    target_workers: int,
) -> Optional[ResourcePlan]:
    """New plan if the target differs from ``prev`` (else None)."""
    if prev.replicas("worker") == target_workers:
        return None
    return prev.with_role("worker", target_workers)
