"""A fake Kubernetes API server for controller tests.

Speaks the exact surface the operator uses, over real localhost HTTP:

- pods: POST/GET(labelSelector)/DELETE on ``/api/v1/namespaces/{ns}/pods``
  (kube_pod_api.py);
- custom resources: CRUD + LIST + WATCH on
  ``/apis/elastic.easydl.org/v1alpha1/namespaces/{ns}/{elasticjobs,
  jobresources}`` (kube_cr_source.py), with per-write resourceVersions, the
  chunked line-delimited watch stream, watch ``timeoutSeconds``, and
  history compaction that produces the 410-Gone / ERROR-event resync path.

Shared by test_kube_pod_api.py and test_kube_cr_source.py so the full
controller loop — CRs in via the API server, pods out via the API server —
runs against one consistent "cluster".
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

CR_PREFIX = "/apis/elastic.easydl.org/v1alpha1/namespaces/"
CR_PLURALS = ("elasticjobs", "jobresources")


class FakeKubeApiServer:
    """In-memory pod + CR store behind a real HTTP server."""

    def __init__(self, max_watch_s: float = 10.0, port: int = 0):
        self.pods = {}  # name -> manifest dict
        self.crs = {p: {} for p in CR_PLURALS}  # plural -> name -> doc
        self.events = {p: [] for p in CR_PLURALS}  # plural -> [(rv, type, doc)]
        self.rv = 0
        self.compacted_below = 0  # watch rvs older than this get 410
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.auth_seen = []
        self.watch_connects = {p: 0 for p in CR_PLURALS}
        self.max_watch_s = max_watch_s
        store = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            # ------------------------------------------------------ CR verbs
            def _cr_parts(self):
                # /apis/G/V/namespaces/{ns}/{plural}[/{name}]
                rest = self.path[len(CR_PREFIX):]
                parsed = urllib.parse.urlparse(rest)
                parts = parsed.path.strip("/").split("/")
                q = urllib.parse.parse_qs(parsed.query)
                plural = parts[1] if len(parts) > 1 else ""
                name = parts[2] if len(parts) > 2 else ""
                return plural, name, q

            def _cr_write(self, etype):
                plural, name, _ = self._cr_parts()
                if plural not in CR_PLURALS:
                    self._send(404, {"reason": "NotFound"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                doc_name = doc.get("metadata", {}).get("name", name)
                with store.cond:
                    exists = doc_name in store.crs[plural]
                    if etype == "ADDED" and exists:
                        self._send(409, {"reason": "AlreadyExists"})
                        return
                    if etype == "MODIFIED" and not exists:
                        self._send(404, {"reason": "NotFound"})
                        return
                    store.rv += 1
                    doc.setdefault("metadata", {})["resourceVersion"] = str(
                        store.rv
                    )
                    store.crs[plural][doc_name] = doc
                    store.events[plural].append((store.rv, etype, doc))
                    store.cond.notify_all()
                self._send(201 if etype == "ADDED" else 200, doc)

            def _cr_delete(self):
                plural, name, _ = self._cr_parts()
                with store.cond:
                    doc = store.crs.get(plural, {}).pop(name, None)
                    if doc is None:
                        self._send(404, {"reason": "NotFound"})
                        return
                    store.rv += 1
                    doc = dict(doc)
                    doc.setdefault("metadata", {})["resourceVersion"] = str(
                        store.rv
                    )
                    store.events[plural].append((store.rv, "DELETED", doc))
                    store.cond.notify_all()
                self._send(200, doc)

            def _cr_get(self):
                plural, name, q = self._cr_parts()
                if plural not in CR_PLURALS:
                    self._send(404, {"reason": "NotFound"})
                    return
                if q.get("watch", ["false"])[0] == "true":
                    self._cr_watch(plural, q)
                    return
                with store.lock:
                    if name:
                        doc = store.crs[plural].get(name)
                        if doc is None:
                            self._send(404, {"reason": "NotFound"})
                        else:
                            self._send(200, doc)
                        return
                    items = sorted(
                        store.crs[plural].values(),
                        key=lambda d: d["metadata"]["name"],
                    )
                    rv = store.rv
                self._send(200, {
                    "kind": "List", "items": items,
                    "metadata": {"resourceVersion": str(rv)},
                })

            def _cr_watch(self, plural, q):
                rv_from = int(q.get("resourceVersion", ["0"])[0])
                timeout_s = min(
                    float(q.get("timeoutSeconds", ["10"])[0]),
                    store.max_watch_s,
                )
                with store.lock:
                    store.watch_connects[plural] += 1
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                # no Content-Length: body ends when the connection closes
                self.end_headers()

                def emit(etype, obj):
                    line = json.dumps({"type": etype, "object": obj}) + "\n"
                    self.wfile.write(line.encode())
                    self.wfile.flush()

                if rv_from and rv_from < store.compacted_below:
                    # Expired rv: the ERROR-event form of 410 Gone.
                    emit("ERROR", {
                        "kind": "Status", "code": 410, "reason": "Expired",
                    })
                    return
                deadline = time.monotonic() + timeout_s
                last = rv_from
                try:
                    while time.monotonic() < deadline:
                        with store.cond:
                            evs = [e for e in store.events[plural]
                                   if e[0] > last]
                            if not evs:
                                # clamp: a negative acquire timeout means
                                # "infinite" to threading, not "immediate"
                                store.cond.wait(timeout=max(0.0, min(
                                    0.2, deadline - time.monotonic())))
                                continue
                        for rv, etype, doc in evs:
                            emit(etype, doc)
                            last = rv
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream

            # ----------------------------------------------------- pod verbs
            def do_POST(self):
                store.auth_seen.append(self.headers.get("Authorization"))
                if self.path.startswith(CR_PREFIX):
                    self._cr_write("ADDED")
                    return
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                name = doc["metadata"]["name"]
                with store.lock:
                    if name in store.pods:
                        self._send(409, {"reason": "AlreadyExists"})
                        return
                    doc.setdefault("status", {})["phase"] = "Pending"
                    store.pods[name] = doc
                self._send(201, doc)

            def do_PUT(self):
                if self.path.startswith(CR_PREFIX):
                    self._cr_write("MODIFIED")
                    return
                self._send(405, {"reason": "MethodNotAllowed"})

            def do_PATCH(self):
                # merge-PATCH on the /status subresource (the operator's
                # ElasticJob.status write-back).
                if not self.path.startswith(CR_PREFIX):
                    self._send(405, {"reason": "MethodNotAllowed"})
                    return
                rest = self.path[len(CR_PREFIX):]
                parts = urllib.parse.urlparse(rest).path.strip("/").split("/")
                plural = parts[1] if len(parts) > 1 else ""
                name = parts[2] if len(parts) > 2 else ""
                sub = parts[3] if len(parts) > 3 else ""
                if plural not in CR_PLURALS or sub != "status":
                    self._send(404, {"reason": "NotFound"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                patch = json.loads(self.rfile.read(n))
                with store.cond:
                    doc = store.crs[plural].get(name)
                    if doc is None:
                        self._send(404, {"reason": "NotFound"})
                        return
                    doc = dict(doc)
                    doc["status"] = patch.get("status", {})
                    store.rv += 1
                    doc.setdefault("metadata", {})["resourceVersion"] = str(
                        store.rv
                    )
                    store.crs[plural][name] = doc
                    store.events[plural].append((store.rv, "MODIFIED", doc))
                    store.cond.notify_all()
                self._send(200, doc)

            def do_GET(self):
                if self.path.startswith(CR_PREFIX):
                    self._cr_get()
                    return
                parsed = urllib.parse.urlparse(self.path)
                q = urllib.parse.parse_qs(parsed.query)
                selector = q.get("labelSelector", [""])[0]
                want = None
                if "=" in selector:
                    k, v = selector.split("=", 1)
                    want = (k, v)
                with store.lock:
                    items = []
                    for doc in store.pods.values():
                        labels = doc["metadata"].get("labels", {})
                        if want is None or labels.get(want[0]) == want[1]:
                            items.append(doc)
                self._send(200, {"kind": "PodList", "items": items})

            def do_DELETE(self):
                if self.path.startswith(CR_PREFIX):
                    self._cr_delete()
                    return
                name = self.path.rsplit("/", 1)[-1]
                with store.lock:
                    if name not in store.pods:
                        self._send(404, {"reason": "NotFound"})
                        return
                    doc = store.pods.pop(name)
                self._send(200, doc)

        # explicit port supports "API server restarts at the same address"
        # tests (allow_reuse_address lets a successor rebind immediately)
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    # ---------------------------------------------------- test levers: pods
    def set_phase(self, name: str, phase: str) -> None:
        with self.lock:
            self.pods[name]["status"]["phase"] = phase

    def tick(self) -> None:
        with self.lock:
            for doc in self.pods.values():
                if doc["status"]["phase"] == "Pending":
                    doc["status"]["phase"] = "Running"

    # ----------------------------------------------------- test levers: CRs
    def put_cr(self, plural: str, doc: dict) -> None:
        """Create-or-update a CR as kubectl apply would."""
        name = doc["metadata"]["name"]
        with self.cond:
            etype = "MODIFIED" if name in self.crs[plural] else "ADDED"
            self.rv += 1
            doc = dict(doc)
            doc.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            self.crs[plural][name] = doc
            self.events[plural].append((self.rv, etype, doc))
            self.cond.notify_all()

    def delete_cr(self, plural: str, name: str) -> None:
        with self.cond:
            doc = self.crs[plural].pop(name)
            self.rv += 1
            self.events[plural].append((self.rv, "DELETED", doc))
            self.cond.notify_all()

    def compact(self) -> None:
        """Drop watch history: older-rv watches now get an ERROR/410."""
        with self.cond:
            self.compacted_below = self.rv + 1
            for p in CR_PLURALS:
                self.events[p].clear()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
