"""A stand-in ``jax`` that simulates the TPU tunnel's HANG failure mode.

The attached-TPU tunnel on the build image has two observed failure modes:
erroring ("Unable to initialize backend") and *hanging* — a process that
imports jax (or makes its first backend call) simply never returns. The
second mode is the one that killed round 4's driver artifacts, and it
cannot be simulated by raising an exception — it has to actually block.

Placed first on a subprocess's PYTHONPATH, this package:

- **blocks forever on import** when the process is NOT pinned to CPU —
  exactly what a half-dead tunnel does to any process that attaches; and
- **transparently defers to the real jax** when ``JAX_PLATFORMS=cpu``,
  using the documented replace-self-in-``sys.modules`` idiom — so
  forced-CPU children (the path the evidence entrypoints must take)
  work normally.

Used by tests/test_driver_entrypoints.py to prove that ``bench.py`` and
``__graft_entry__.dryrun_multichip`` produce their artifacts even when the
ambient backend hangs.
"""

import os
import sys

if os.environ.get("JAX_PLATFORMS") == "cpu":
    _pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path = [p for p in sys.path if os.path.abspath(p) != _pkg_root]
    del sys.modules["jax"]
    import jax as _real_jax  # resolves to the real package now

    sys.modules["jax"] = _real_jax
else:
    import time

    while True:  # the tunnel's hang mode: block, don't raise
        time.sleep(3600)
