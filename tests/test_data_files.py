"""File-backed data path (VERDICT r2 missing item 6): byte-BPE tokenizer,
token shards, array image files — rank-disjoint sharding, exact decode,
checkpointable cursors, and training end-to-end from files."""

from __future__ import annotations

import subprocess
import sys

import numpy as np
import pytest

from easydl_tpu.data import (
    ArrayImageDataset,
    ByteBpeTokenizer,
    TokenFileDataset,
    write_token_shards,
)

CORPUS = (
    "the quick brown fox jumps over the lazy dog\n"
    "the quick brown cat sleeps under the warm sun\n"
    "a lazy dog and a quick cat share the brown rug\n"
) * 20


# ---------------------------------------------------------------- tokenizer

def test_tokenizer_roundtrip_exact():
    tok = ByteBpeTokenizer.train([CORPUS], vocab_size=300)
    for text in (CORPUS, "unseen words étoile 漢字!  double  spaced",
                 " leading space", "tabs\tand\nnewlines"):
        assert tok.decode(tok.encode(text)) == text


def test_tokenizer_compresses_and_persists(tmp_path):
    tok = ByteBpeTokenizer.train([CORPUS], vocab_size=400)
    ids = tok.encode(CORPUS)
    assert len(ids) < len(CORPUS.encode())  # merges actually fired
    assert max(ids) >= 258  # some merged tokens in use
    path = str(tmp_path / "tok.json")
    tok.save(path)
    tok2 = ByteBpeTokenizer.load(path)
    assert tok2.vocab_size == tok.vocab_size
    assert tok2.encode(CORPUS) == ids
    assert tok2.decode(ids) == CORPUS


def test_tokenizer_eos_and_specials():
    tok = ByteBpeTokenizer.train([CORPUS], vocab_size=280)
    ids = tok.encode("hello", append_eos=True)
    assert ids[-1] == tok.eos_id
    assert tok.decode(ids) == "hello"  # specials render as nothing
    assert tok.pad_id != tok.eos_id


# ------------------------------------------------------------ token dataset

def test_token_dataset_shards_disjoint_and_exhaustive(tmp_path):
    ids = np.arange(4096)
    write_token_shards(ids, str(tmp_path), shard_size=1000)  # multi-shard
    seen = []
    for rank in range(2):
        ds = TokenFileDataset(str(tmp_path), batch_size=2, seq_len=15,
                              rank=rank, world=2, seed=7, loop=False)
        for batch in ds:
            assert batch["inputs"].shape == (2, 15)
            # targets are inputs shifted by one
            np.testing.assert_array_equal(batch["inputs"][:, 1:],
                                          batch["targets"][:, :-1])
            seen.extend(batch["inputs"][:, 0].tolist())
    # every window consumed exactly once across ranks (4096 tokens /
    # 16-token windows = 256 windows, all covered, none duplicated)
    assert len(seen) == len(set(seen)) == 256


def test_token_dataset_windows_cross_shard_boundaries(tmp_path):
    ids = np.arange(1000)
    write_token_shards(ids, str(tmp_path), shard_size=333)
    ds = TokenFileDataset(str(tmp_path), batch_size=1, seq_len=99,
                          seed=0, loop=False)
    for batch in ds:
        row = batch["inputs"][0]
        # windows are contiguous runs of the original stream even when they
        # span shard files
        np.testing.assert_array_equal(row, np.arange(row[0], row[0] + 100)[:-1])


def test_token_dataset_cursor_resume(tmp_path):
    write_token_shards(np.arange(8192), str(tmp_path))
    ds1 = TokenFileDataset(str(tmp_path), batch_size=2, seq_len=31, seed=3)
    it1 = iter(ds1)
    got = [next(it1) for _ in range(5)]
    state = ds1.state()
    ds2 = TokenFileDataset(str(tmp_path), batch_size=2, seq_len=31, seed=3)
    ds2.restore_state(state)
    a, b = next(iter(ds2)), next(it1)
    np.testing.assert_array_equal(a["inputs"], b["inputs"])
    assert state == {"epoch": 0, "cursor": 5, "world": 1, "batch": 2}
    del got


def test_token_dataset_cursor_rescales_across_reshape(tmp_path):
    """A cursor saved at world=2 restores onto world=4 at the same GLOBAL
    position (elastic scale event between checkpoint and resume)."""
    write_token_shards(np.arange(1 << 14), str(tmp_path))
    ds2 = TokenFileDataset(str(tmp_path), batch_size=4, seq_len=31,
                           rank=0, world=2)
    ds2.cursor = 10  # 10 batches x 4 x world 2 = 80 global windows consumed
    ds4 = TokenFileDataset(str(tmp_path), batch_size=4, seq_len=31,
                           rank=1, world=4)
    ds4.restore_state(ds2.state())
    assert ds4.cursor == 80 // (4 * 4)  # same global position, new shape


def test_token_dataset_epochs_reshuffle(tmp_path):
    write_token_shards(np.arange(2048), str(tmp_path))
    ds = TokenFileDataset(str(tmp_path), batch_size=4, seq_len=15, seed=1,
                          loop=False)
    first_epoch = [b["inputs"][:, 0].tolist() for b in ds]
    ds2 = TokenFileDataset(str(tmp_path), batch_size=4, seq_len=15, seed=1)
    it = iter(ds2)
    second_epoch = []
    for _ in range(2 * ds2.batches_per_epoch):
        b = next(it)
        if ds2.epoch >= 1 or len(second_epoch) < ds2.batches_per_epoch:
            second_epoch.append(b["inputs"][:, 0].tolist())
    assert second_epoch[:ds2.batches_per_epoch] == first_epoch
    assert second_epoch[ds2.batches_per_epoch:] != first_epoch  # reshuffled


# ------------------------------------------------------------ image dataset

def test_image_dataset_shapes_and_sharding(tmp_path):
    np.save(tmp_path / "images.npy",
            np.random.randint(0, 256, (64, 8, 8, 1)).astype(np.uint8))
    np.save(tmp_path / "labels.npy", np.arange(64) % 10)
    seen = []
    for rank in range(2):
        ds = ArrayImageDataset(str(tmp_path), batch_size=4, rank=rank,
                               world=2, loop=False)
        for batch in ds:
            assert batch["image"].shape == (4, 8, 8, 1)
            assert batch["image"].dtype == np.float32
            assert batch["image"].max() <= 1.0  # normalized
            seen.extend(batch["label"].tolist())
    assert len(seen) == 64


# ------------------------------------------------------------- end-to-end

def test_encode_cli_and_training_from_files(tmp_path, eight_devices):
    """Full path: corpus -> trained tokenizer -> shards -> gpt trains on it
    through the zoo runner's --data-dir."""
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(CORPUS)
    tok_path = tmp_path / "tok.json"
    shards = tmp_path / "shards"
    for cmd in (
        [sys.executable, "-m", "easydl_tpu.data.encode", str(corpus),
         "--tokenizer", str(tok_path), "--train-tokenizer",
         "--vocab-size", "384"],
        [sys.executable, "-m", "easydl_tpu.data.encode", str(corpus),
         "--tokenizer", str(tok_path), "--out", str(shards)],
    ):
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        assert res.returncode == 0, res.stderr

    ds = TokenFileDataset(str(shards), batch_size=4, seq_len=32)
    batch = next(iter(ds))
    tok = ByteBpeTokenizer.load(str(tok_path))
    assert batch["inputs"].max() < tok.vocab_size

    # the zoo runner trains a tiny gpt from these files
    from easydl_tpu.models.run import main as run_main

    argv = sys.argv
    sys.argv = [
        "run", "--model", "gpt", "--steps", "4", "--batch", "8",
        "--data-dir", str(shards), "--seq-len", "32",
        "--model-arg", "size=test", "--model-arg", "seq_len=32",
        "--model-arg", f"vocab={tok.vocab_size}",
    ]
    try:
        run_main()
    finally:
        sys.argv = argv


def _idx_bytes(arr: np.ndarray) -> bytes:
    """Serialize an array into the IDX wire format (ubyte payload)."""
    header = bytes([0, 0, 0x08, arr.ndim])
    for d in arr.shape:
        header += int(d).to_bytes(4, "big")
    return header + arr.astype(np.uint8).tobytes()


def test_mnist_idx_import_and_training(tmp_path, eight_devices):
    """BASELINE config 1 from the wire format it actually ships in: generate
    MNIST IDX bytes (images gzipped, labels plain — both spellings occur in
    the wild), import via the CLI, train the MLP from the output
    (VERDICT r3 missing 3)."""
    import gzip

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (64, 28, 28), dtype=np.uint8)
    labels = (np.arange(64) % 10).astype(np.uint8)
    src = tmp_path / "raw"
    src.mkdir()
    with gzip.open(src / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(_idx_bytes(images))
    (src / "train-labels-idx1-ubyte").write_bytes(_idx_bytes(labels))

    out = tmp_path / "mnist"
    res = subprocess.run(
        [sys.executable, "-m", "easydl_tpu.data.images", "mnist", str(src),
         "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr

    got = np.load(out / "images.npy")
    assert got.shape == (64, 28, 28, 1) and got.dtype == np.uint8
    np.testing.assert_array_equal(got[..., 0], images)
    np.testing.assert_array_equal(np.load(out / "labels.npy"), labels)

    from easydl_tpu.models.run import main as run_main

    argv = sys.argv
    sys.argv = [
        "run", "--model", "mlp", "--steps", "3", "--batch", "8",
        "--data-dir", str(out),
        "--model-arg", "input_shape=[28,28,1]",
        "--model-arg", "features=[32,32]",
    ]
    try:
        run_main()
    finally:
        sys.argv = argv


def test_image_folder_import(tmp_path):
    """Class-per-subdirectory tree -> arrays + stable classes.json; junk
    files are skipped, not fatal."""
    from PIL import Image

    from easydl_tpu.data import import_image_folder

    src = tmp_path / "tree"
    for cls, color in (("cat", (255, 0, 0)), ("dog", (0, 0, 255))):
        (src / cls).mkdir(parents=True)
        for i in range(3):
            Image.new("RGB", (10 + i, 12), color).save(
                src / cls / f"im{i}.png")
    (src / "cat" / "notes.txt").write_text("not an image")
    (src / "dog" / "broken.png").write_bytes(b"\x89PNG junk")

    n, classes = import_image_folder(str(src), str(tmp_path / "out"),
                                     size=(8, 8))
    assert classes == ["cat", "dog"]
    assert n == 6  # broken.png skipped, notes.txt ignored
    images = np.load(tmp_path / "out" / "images.npy")
    labels = np.load(tmp_path / "out" / "labels.npy")
    assert images.shape == (6, 8, 8, 3)
    # red images labelled cat(0), blue dog(1)
    assert [int(x) for x in labels] == [0, 0, 0, 1, 1, 1]
    assert images[0, 0, 0, 0] > 200 and images[-1, 0, 0, 2] > 200

    ds = ArrayImageDataset(str(tmp_path / "out"), batch_size=2, loop=False)
    batch = next(iter(ds))
    assert batch["image"].shape == (2, 8, 8, 3)


def test_elastic_cfg_forwards_data_dir():
    """--data-dir must survive the trainer's command parse (the elastic
    workers read it from the worker config, not argv)."""
    from easydl_tpu.elastic.trainer_main import parse_runner_command

    ns, _ = parse_runner_command(
        "python -m easydl_tpu.models.run --model gpt "
        "--data-dir /data/tok --seq-len 64"
    )
    assert ns.data_dir == "/data/tok" and ns.seq_len == 64


def test_token_dataset_val_split_disjoint_and_stable(tmp_path):
    """--val-fraction holdout: train and val windows are disjoint, cover
    everything, and the assignment is stable across seeds/epochs (no leak)."""
    write_token_shards(np.arange(1 << 14), str(tmp_path))
    train = TokenFileDataset(str(tmp_path), batch_size=4, seq_len=31,
                             seed=0, val_fraction=0.25, split="train")
    val = TokenFileDataset(str(tmp_path), batch_size=4, seq_len=31,
                           seed=99, val_fraction=0.25, split="val")
    t, v = set(train._windows.tolist()), set(val._windows.tolist())
    assert not (t & v)
    assert len(t | v) == train.num_windows
    assert 0.15 < len(v) / train.num_windows < 0.35
    # different seed, same assignment (the split hash ignores the seed)
    val2 = TokenFileDataset(str(tmp_path), batch_size=4, seq_len=31,
                            seed=0, val_fraction=0.25, split="val")
    assert set(val2._windows.tolist()) == v
    with pytest.raises(ValueError):
        TokenFileDataset(str(tmp_path), batch_size=4, seq_len=31,
                         split="val")  # val requires a fraction


# ------------------------------------------------------------- click logs

def _write_click_tsv(path, n=64, num_dense=13, num_sparse=26):
    rng = np.random.RandomState(5)
    with open(path, "w") as f:
        for i in range(n):
            dense = [str(rng.randint(0, 100)) if rng.rand() > 0.1 else ""
                     for _ in range(num_dense)]
            cats = ["%08x" % rng.randint(0, 1 << 30) if rng.rand() > 0.1
                    else "" for _ in range(num_sparse)]
            f.write("\t".join([str(i % 2)] + dense + cats) + "\n")


def test_click_tsv_encode_and_dataset(tmp_path):
    from easydl_tpu.data import ClickLogDataset, encode_click_tsv

    tsv = tmp_path / "clicks.tsv"
    _write_click_tsv(str(tsv))
    n = encode_click_tsv([str(tsv)], str(tmp_path / "enc"))
    assert n == 64
    ds = ClickLogDataset(str(tmp_path / "enc"), batch_size=8, loop=False)
    total = 0
    for batch in ds:
        assert batch["sparse_ids"].shape == (8, 26)
        assert batch["sparse_ids"].dtype == np.int64
        assert batch["dense"].shape == (8, 13)
        assert (batch["dense"] >= 0).all()  # log1p of clamped counts
        assert set(np.unique(batch["label"])) <= {0.0, 1.0}
        total += 8
    assert total == 64
    # missing/malformed tokens mapped deterministically: re-encode matches
    encode_click_tsv([str(tsv)], str(tmp_path / "enc2"))
    np.testing.assert_array_equal(
        np.load(tmp_path / "enc" / "sparse.npy"),
        np.load(tmp_path / "enc2" / "sparse.npy"))


def test_click_dataset_trains_deepfm_through_runner(tmp_path, eight_devices):
    from easydl_tpu.data import encode_click_tsv

    tsv = tmp_path / "clicks.tsv"
    _write_click_tsv(str(tsv), n=128)
    encode_click_tsv([str(tsv)], str(tmp_path / "enc"))

    from easydl_tpu.models.run import main as run_main

    argv = sys.argv
    sys.argv = [
        "run", "--model", "deepfm", "--steps", "3", "--batch", "16",
        "--data-dir", str(tmp_path / "enc"),
        "--model-arg", "vocab=1024", "--model-arg", "dim=4",
    ]
    try:
        run_main()
    finally:
        sys.argv = argv


def test_bert_trains_from_token_shards(tmp_path, eight_devices):
    """BERT's masked-LM loss reads only batch['inputs'] (masking happens
    inside the jitted loss), so the same token shards feed it unchanged —
    every LM family consumes the one file format."""
    write_token_shards(np.arange(4096) % 300, str(tmp_path))

    from easydl_tpu.models.run import main as run_main

    argv = sys.argv
    sys.argv = [
        "run", "--model", "bert", "--steps", "3", "--batch", "8",
        "--data-dir", str(tmp_path), "--seq-len", "32",
        "--model-arg", "size=test", "--model-arg", "seq_len=32",
        "--model-arg", "vocab=384",
    ]
    try:
        run_main()
    finally:
        sys.argv = argv
