"""Structured per-component logging.

The reference has no logging subsystem (lint-only CI); Brain's inputs imply one
(README.md:21-23 performance monitoring). Every easydl_tpu process logs through
here so component/role/host are always attached.
"""

from __future__ import annotations

import logging
import sys
import time
from easydl_tpu.utils.env import knob_str
from typing import Optional

_FORMAT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"
_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    root = logging.getLogger("easydl_tpu")
    root.addHandler(handler)
    level = knob_str("EASYDL_LOG_LEVEL").upper()
    if level not in ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"):
        level = "INFO"
    root.setLevel(level)
    root.propagate = False
    _configured = True


def get_logger(component: str, role: Optional[str] = None) -> logging.Logger:
    """Logger named ``easydl_tpu.<component>[.<role>]``."""
    _configure_root()
    name = f"easydl_tpu.{component}" + (f".{role}" if role else "")
    return logging.getLogger(name)


class StepTimer:
    """Cheap wall-clock step timer used by the trainer's metrics loop."""

    def __init__(self) -> None:
        self._t0 = time.perf_counter()

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        return dt
