"""Reconcile decision function: (ResourcePlan, observed pods) → pod ops.

The C++ core (native/reconciler_core.cc) is the production decision engine;
:func:`_py_reconcile` is its pure-Python twin (same wire format, same rules)
used when no toolchain exists — and pinned to the core by a parity test
(tests/test_controller.py) so the two can't drift.

Semantics implemented (all from the reference design doc):
- failed pods are retired and their slots recreated (README.md:26-29);
- ``resource_updation`` entries replace-then-retire: new pod first, old pod
  deleted only when the replacement is Running
  (docs/design/elastic-training-operator.md:99-101);
- per-role replica counts are levelled, creating fresh names / deleting the
  highest indices (:53-55, :97-98).
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import math
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from easydl_tpu.api.job_spec import ResourceSpec
from easydl_tpu.api.resource_plan import ResourcePlan
from easydl_tpu.controller.pod_api import Pod
from easydl_tpu.utils.native import load_native
from easydl_tpu.utils.env import knob_float, knob_int
from easydl_tpu.obs.errors import count_swallowed

_SOURCE = os.path.join(os.path.dirname(__file__), "native", "reconciler_core.cc")


def _bind(lib: ctypes.CDLL) -> None:
    lib.edr_reconcile.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.edr_reconcile.restype = ctypes.c_void_p  # manual free via edr_free
    lib.edr_free.argtypes = [ctypes.c_void_p]


def resource_sig(resource: ResourceSpec) -> str:
    """Deterministic short signature identifying a resource shape.

    Used to materialise CREATE ops back into full specs and to *detect* (not
    act on) role-level resource drift: per the reference, a changed role
    resource applies to newly created pods only — existing pods are resized
    exclusively through explicit ``resource_updation`` replace-then-retire
    entries (docs/design/elastic-training-operator.md:86-101). The operator
    logs drift so users know a resource_updation is needed."""
    blob = json.dumps(resource.to_dict(), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


@dataclass(frozen=True)
class PodOp:
    verb: str  # "CREATE" | "DELETE"
    name: str
    role: str = ""
    resource_sig: str = ""
    replaces: str = ""
    reason: str = ""


def encode_desired(job: str, plan: ResourcePlan) -> Tuple[str, Dict[str, ResourceSpec]]:
    """Wire-encode the plan; also return sig→ResourceSpec so ops can be
    materialised back into full pod specs."""
    sigs: Dict[str, ResourceSpec] = {}
    lines = [f"J|{job}"]
    for role, rp in plan.roles.items():
        sig = resource_sig(rp.resource)
        sigs[sig] = rp.resource
        lines.append(f"R|{role}|{rp.replicas}|{sig}")
    for u in plan.resource_updation:
        sig = resource_sig(u.resource)
        sigs[sig] = u.resource
        lines.append(f"U|{u.name}|{sig}")
    return "\n".join(lines) + "\n", sigs


def encode_observed(pods: List[Pod]) -> str:
    return "".join(
        f"P|{p.name}|{p.role}|{p.phase}|{resource_sig(p.resource)}|{p.replaces}\n"
        for p in pods
    )


def decode_ops(text: str) -> List[PodOp]:
    ops: List[PodOp] = []
    for line in text.splitlines():
        if not line:
            continue
        f = line.split("|")
        if f[0] == "CREATE":
            ops.append(PodOp("CREATE", f[1], role=f[2], resource_sig=f[3],
                             replaces=f[4] if len(f) > 4 else ""))
        elif f[0] == "DELETE":
            ops.append(PodOp("DELETE", f[1], reason=f[2] if len(f) > 2 else ""))
    return ops


# --------------------------------------------------------------- python twin


def _trailing_index(name: str) -> int:
    head, _, tail = name.rpartition("-")
    return int(tail) if head and tail.isdigit() else -1


def _py_reconcile(desired: str, observed: str) -> str:
    job, roles, updations, pods = "", {}, [], []
    frozen_roles = set()  # malformed replicas: don't level this pass
    for line in desired.splitlines():
        f = line.split("|")
        if f[0] == "J" and len(f) >= 2:
            job = f[1]
        elif f[0] == "R" and len(f) >= 4:
            # ASCII-digits-only, max 7 digits — matching the C++ core's
            # validation exactly (not int(): that accepts "+3"/" 3"/unicode
            # digits and unbounded magnitudes the core rejects). A malformed
            # count freezes the role — falling through to the
            # absent-role-means-0 fallback would delete every healthy pod.
            if f[2] and len(f[2]) <= 7 and all("0" <= c <= "9" for c in f[2]):
                roles[f[1]] = (int(f[2]), f[3])
            else:
                frozen_roles.add(f[1])
        elif f[0] == "U" and len(f) >= 3:
            updations.append((f[1], f[2]))
    for line in observed.splitlines():
        f = line.split("|")
        if f[0] == "P" and len(f) >= 6:
            pods.append(
                {"name": f[1], "role": f[2], "phase": f[3], "sig": f[4],
                 "replaces": f[5], "index": _trailing_index(f[1])}
            )

    next_index: Dict[str, int] = {}
    for p in pods:
        next_index[p["role"]] = max(next_index.get(p["role"], 0), p["index"] + 1)

    def next_name(role: str) -> str:
        n = next_index[role] = next_index.get(role, 0)
        next_index[role] = n + 1
        return f"{job}-{role}-{n}"

    ops: List[str] = []
    gone = set()
    for p in pods:
        if p["phase"] == "Failed":
            ops.append(f"DELETE|{p['name']}|failed")
            gone.add(p["name"])

    by_name = {p["name"]: p for p in pods if p["name"] not in gone}
    replacement_of = {
        p["replaces"]: p
        for p in pods
        if p["name"] not in gone and p["replaces"] and p["replaces"] in by_name
    }

    for name, sig in updations:
        old = by_name.get(name)
        # Succeeded pods completed their work: resizing one is meaningless
        # and replacing it would re-run finished work (the completion loop).
        if old is None or old["phase"] in ("Terminating", "Succeeded"):
            continue
        rep = replacement_of.get(name)
        if rep is not None:
            if rep["phase"] == "Running":
                ops.append(f"DELETE|{name}|replaced")
                gone.add(name)
        else:
            ops.append(f"CREATE|{next_name(old['role'])}|{old['role']}|{sig}|{name}")

    # Roles with pods but absent from the plan mean replicas 0 (omission must
    # not orphan pods); trainer is operator-owned, never levelled here.
    for p in pods:
        if (p["role"] != "trainer" and p["role"] not in roles
                and p["role"] not in frozen_roles):
            roles[p["role"]] = (0, "")

    def replacement_in_flight(p) -> bool:
        # Excluded from the count only while the pod it replaces still serves.
        if not p["replaces"] or p["replaces"] in gone:
            return False
        old = by_name.get(p["replaces"])
        return old is not None and old["phase"] in ("Pending", "Running")

    for role in sorted(roles):  # C++ core iterates a std::map: sorted
        want, sig = roles[role]
        # Succeeded pods fill their slot permanently (k8s Job semantics): a
        # worker only exits 0 when its work is COMPLETE, so the slot must not
        # be refilled — recreating it re-runs "job done" forever (the round-3
        # completion loop). Succeeded pods are retained, never scale_down'd;
        # any job-end GC is an explicit operator action, not a levelling one.
        done = sum(
            1 for p in pods
            if p["role"] == role and p["name"] not in gone
            and p["phase"] == "Succeeded"
        )
        need = max(0, want - done)
        active = [
            p for p in pods
            if p["role"] == role and p["name"] not in gone
            and p["phase"] in ("Pending", "Running")
            and not replacement_in_flight(p)
        ]
        for _ in range(max(0, need - len(active))):
            ops.append(f"CREATE|{next_name(role)}|{role}|{sig}|")
        if len(active) > need:
            for p in sorted(active, key=lambda p: -p["index"])[: len(active) - need]:
                ops.append(f"DELETE|{p['name']}|scale_down")
                gone.add(p["name"])
    return "".join(op + "\n" for op in ops)


def reconcile_wire(desired: str, observed: str, force_python: bool = False) -> str:
    """Run the decision function on wire-format inputs."""
    lib = None if force_python else load_native(_SOURCE, _bind)
    if lib is None:
        return _py_reconcile(desired, observed)
    ptr = lib.edr_reconcile(desired.encode(), observed.encode())
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        lib.edr_free(ptr)


def reconcile(job: str, plan: ResourcePlan, pods: List[Pod],
              force_python: bool = False) -> Tuple[List[PodOp], Dict[str, ResourceSpec]]:
    """High-level entry: returns (ops, sig→ResourceSpec)."""
    desired, sigs = encode_desired(job, plan)
    observed = encode_observed(pods)
    return decode_ops(reconcile_wire(desired, observed, force_python)), sigs


# ----------------------------------------------------- PS hot-shard split

#: Skew triggers (env-overridable where maybe_split_ps is wired into a
#: loop): a split pays a full table migration, so it needs BOTH a
#: genuinely hot shard (skew the hash layout cannot fix by itself) and a
#: tier big enough that the imbalance matters. The ratio is against the
#: MEAN, whose max is the shard count itself (one shard holding all
#: rows) — 1.5 fires on real Zipf skew while a ratio ≥ the shard count
#: could never fire at all.
PS_SPLIT_HOT_RATIO = 1.5
PS_SPLIT_MIN_ROWS = 100_000
PS_SPLIT_MAX_SHARDS = 64
#: Access skew fires later than row skew (2.0 vs 1.5): pull traffic is
#: noisier than resident rows (a batch of lookups against one shard
#: spikes the counter without meaning sustained heat), so the trigger
#: demands a wider margin before paying a migration for it.
PS_SPLIT_ACCESS_RATIO = 2.0


def ps_split_decision(shard_rows: Dict[int, float], num_shards: int,
                      hot_ratio: float = PS_SPLIT_HOT_RATIO,
                      min_total_rows: float = PS_SPLIT_MIN_ROWS,
                      max_shards: int = PS_SPLIT_MAX_SHARDS,
                      shard_access: Optional[Dict[int, float]] = None,
                      access_ratio: float = PS_SPLIT_ACCESS_RATIO,
                      ) -> Optional[int]:
    """Pure decision: observed per-shard row counts (and optionally
    per-shard access counts) → target shard count for an online split
    (ps/reshard.py), or None.

    Doubles the shard count when the hottest shard holds ≥ ``hot_ratio``
    × the mean row count (static hash-sharding concentrating a Zipf id
    stream), OR — when ``shard_access`` is supplied — when one shard
    serves ≥ ``access_ratio`` × the mean access count. The second
    trigger exists for the two-tier store: a shard can be balanced by
    resident ROWS yet concentrate the hot WORKING SET, burning its hot
    arena on traffic the hash layout cannot spread. Both triggers share
    the ``min_total_rows`` floor (a small table never pays a migration,
    however skewed its traffic) and the ``max_shards`` cap. Callers
    that pass no access counts get the legacy row-count-only verdict,
    bit for bit. Deliberately the same shape as the reconcile core:
    pure inputs → pure verdict, so policy is unit-testable without a
    live tier."""
    if num_shards <= 0 or not shard_rows:
        return None
    total = float(sum(shard_rows.values()))
    if total < float(min_total_rows):
        return None
    target = num_shards * 2
    if target > max_shards:
        return None
    hottest = max(shard_rows.values())
    if hottest >= hot_ratio * (total / num_shards):
        return target
    if shard_access:
        atotal = float(sum(shard_access.values()))
        if atotal > 0.0:
            ahot = max(shard_access.values())
            if ahot >= access_ratio * (atotal / num_shards):
                return target
    return None


def maybe_split_ps(workdir: str,
                   hot_ratio: Optional[float] = None,
                   min_total_rows: Optional[float] = None,
                   max_shards: Optional[int] = None) -> Optional[int]:
    """Scrape the live PS tier's ``easydl_ps_table_rows`` gauges (the
    PR-1 per-shard telemetry) from the job workdir's exporters and run
    :func:`ps_split_decision` over them. Returns the recommended target
    shard count, or None.

    Recommendation only — it never writes a migration plan: a plan in
    the routing table gates freshly-rescued source pods (ps/__main__.py),
    so claiming one without a coordinator ready to execute it would
    degrade the tier for nothing. The caller hands the verdict to
    ``ps.reshard.run_reshard``, which claims the plan itself. Skipped
    (None) while a plan is already in flight.

    The thresholds default from the environment
    (``EASYDL_PS_SPLIT_HOT_RATIO`` / ``EASYDL_PS_SPLIT_MIN_ROWS`` /
    ``EASYDL_PS_SPLIT_MAX_SHARDS`` / ``EASYDL_PS_SPLIT_ACCESS_RATIO``)
    so a deployed operator loop is tunable without a rollout; explicit
    args win."""
    import re as _re

    if hot_ratio is None:
        hot_ratio = knob_float("EASYDL_PS_SPLIT_HOT_RATIO",
                               PS_SPLIT_HOT_RATIO)
    if min_total_rows is None:
        min_total_rows = knob_float("EASYDL_PS_SPLIT_MIN_ROWS",
                                    PS_SPLIT_MIN_ROWS)
    if max_shards is None:
        max_shards = knob_int("EASYDL_PS_SPLIT_MAX_SHARDS",
                              PS_SPLIT_MAX_SHARDS)
    access_ratio = knob_float("EASYDL_PS_SPLIT_ACCESS_RATIO",
                              PS_SPLIT_ACCESS_RATIO)

    from easydl_tpu.obs.scrape import merge_snapshot
    from easydl_tpu.ps import registry as ps_registry

    rt = ps_registry.routing_table(workdir)
    if rt.get("plan"):
        return None
    smap = ps_registry.shard_map(workdir)
    num_shards = int(rt.get("num_shards", 0))
    if num_shards <= 0:
        if not smap:
            return None
        num_shards = max(int(d["num_shards"]) for d in smap.values())
    try:
        snap = merge_snapshot(workdir=workdir)
    except Exception as e:
        count_swallowed("controller.split_snapshot", e)
        return None
    # Per-service, filtered to the COMMITTED generation's pods — not the
    # blind merge: after a reshard the superseded sources are gated but
    # alive, still exporting easydl_ps_table_rows under the same shard
    # labels, and last-write-wins across exporters would hand the
    # decision phantom (pre-split) counts.
    committed = {f"ps-{d['pod']}" for d in smap.values() if d.get("pod")}
    rows_re = _re.compile(r'^easydl_ps_table_rows\{.*shard="(\d+)"')
    # Access signal for the two-tier store: a shard balanced by resident
    # rows can still concentrate the hot working set. Cumulative served-id
    # counters are a coarse proxy for that heat — good enough here because
    # the decision only compares shards against each other and the
    # counters all started at the same reshard generation.
    pulls_re = _re.compile(r'^easydl_ps_pull_ids_total\{.*shard="(\d+)"')
    shard_rows: Dict[int, float] = {}
    shard_access: Dict[int, float] = {}
    for component, svc in (snap.get("services") or {}).items():
        if component not in committed:
            continue
        for series, value in (svc.get("metrics") or {}).items():
            m2 = rows_re.match(series)
            if m2:
                s = int(m2.group(1))
                shard_rows[s] = shard_rows.get(s, 0.0) + float(value)
                continue
            m3 = pulls_re.match(series)
            if m3:
                s = int(m3.group(1))
                shard_access[s] = shard_access.get(s, 0.0) + float(value)
    return ps_split_decision(shard_rows, num_shards, hot_ratio=hot_ratio,
                             min_total_rows=min_total_rows,
                             max_shards=max_shards,
                             shard_access=shard_access,
                             access_ratio=access_ratio)


# ------------------------------------------------- serve replica autoscale

#: Replica-policy defaults (env-overridable through maybe_scale_serve):
#: a replica is "full" at SERVE_TARGET_QPS_PER_REPLICA, and p99 past the
#: budget means queueing — scale up even when the QPS math says there is
#: headroom (latency is the symptom the batch queue shows FIRST when the
#: forward or the PS pull saturates). Scale-down needs the fleet
#: comfortably under target (hysteresis) so a noisy minute can't flap
#: replicas — the serving twin of the straggler hold-down.
SERVE_TARGET_QPS_PER_REPLICA = 500.0
SERVE_P99_BUDGET_S = 0.050
SERVE_MIN_REPLICAS = 1
SERVE_MAX_REPLICAS = 64
SERVE_SCALE_DOWN_FRACTION = 0.4


def serve_scale_decision(replica_qps: Dict[str, float],
                         replica_p99: Dict[str, float],
                         target_qps: float = SERVE_TARGET_QPS_PER_REPLICA,
                         p99_budget_s: float = SERVE_P99_BUDGET_S,
                         min_replicas: int = SERVE_MIN_REPLICAS,
                         max_replicas: int = SERVE_MAX_REPLICAS,
                         scale_down_fraction: float =
                         SERVE_SCALE_DOWN_FRACTION,
                         router_offered_qps: Optional[float] = None,
                         router_replicas: Optional[int] = None,
                         router_p99_s: Optional[float] = None
                         ) -> Optional[int]:
    """Pure decision: observed per-replica QPS and p99 → target replica
    count, or None for "leave it alone". Same shape as
    :func:`ps_split_decision`: pure inputs → pure verdict, unit-testable
    without a live tier.

    - **capacity**: enough replicas that total QPS / replica ≤ target;
    - **latency**: any replica's p99 past the budget adds at least one
      replica even under the QPS target (queueing has started);
    - **hysteresis**: scale down only when total QPS would keep even the
      SHRUNK fleet under ``scale_down_fraction`` × target per replica and
      every p99 is under half the budget.

    ``router_*`` are the fleet router's door-side observations, and when
    present they are AUTHORITATIVE for what they measure: offered load
    (completed AND shed AND requests routed to replicas whose exporters
    this scrape cannot see — remote hosts, mid-crash replicas) and the
    true fleet size. Summing whichever replica gauges happened to get
    scraped UNDER-counts both: a 3-replica fleet at 60% each whose
    router answered the scrape but whose replicas didn't would otherwise
    read as one idle replica and scale to the floor."""
    replicas = max(len(replica_qps), int(router_replicas or 0))
    if replicas <= 0 or target_qps <= 0:
        return None
    total_qps = float(sum(replica_qps.values()))
    if router_offered_qps is not None:
        # The door sees every request; replicas see only what reached
        # them. max(): a stale router gauge must not hide replica load.
        total_qps = max(total_qps, float(router_offered_qps))
    worst_p99 = max(replica_p99.values(), default=0.0)
    if router_p99_s is not None:
        worst_p99 = max(worst_p99, float(router_p99_s))
    need_capacity = max(1, math.ceil(total_qps / target_qps))
    want = replicas
    if worst_p99 > p99_budget_s:
        want = max(need_capacity, replicas + 1)
    elif need_capacity > replicas:
        want = need_capacity
    elif (replicas > min_replicas
          and worst_p99 < 0.5 * p99_budget_s
          and total_qps < (scale_down_fraction * target_qps
                           * (replicas - 1))):
        want = max(need_capacity, min_replicas, replicas - 1)
    want = max(min_replicas, min(max_replicas, want))
    return want if want != replicas else None


def maybe_scale_serve(workdir: str,
                      target_qps: Optional[float] = None,
                      p99_budget_s: Optional[float] = None,
                      min_replicas: Optional[int] = None,
                      max_replicas: Optional[int] = None) -> Optional[int]:
    """Scrape every serving replica's rolling ``easydl_serve_qps_recent``
    / ``easydl_serve_p99_seconds_recent`` gauges (the PR-1 exporters under
    the job workdir) and run :func:`serve_scale_decision` over them.
    Returns the recommended replica count, or None.

    Recommendation only, like :func:`maybe_split_ps`: the operator loop
    (or a human reading the runbook) levels the replica set — the same
    CREATE/DELETE pod mechanics every other role uses. Thresholds default
    from ``EASYDL_SERVE_TARGET_QPS`` / ``EASYDL_SERVE_P99_BUDGET_S`` /
    ``EASYDL_SERVE_MIN_REPLICAS`` / ``EASYDL_SERVE_MAX_REPLICAS``;
    explicit args win."""
    import re as _re

    if target_qps is None:
        target_qps = knob_float("EASYDL_SERVE_TARGET_QPS",
                                SERVE_TARGET_QPS_PER_REPLICA)
    if p99_budget_s is None:
        p99_budget_s = knob_float("EASYDL_SERVE_P99_BUDGET_S",
                                  SERVE_P99_BUDGET_S)
    if min_replicas is None:
        min_replicas = knob_int("EASYDL_SERVE_MIN_REPLICAS",
                                SERVE_MIN_REPLICAS)
    if max_replicas is None:
        max_replicas = knob_int("EASYDL_SERVE_MAX_REPLICAS",
                                SERVE_MAX_REPLICAS)

    from easydl_tpu.obs.scrape import merge_snapshot

    try:
        snap = merge_snapshot(workdir=workdir)
    except Exception as e:
        count_swallowed("controller.serve_snapshot", e)
        return None
    qps_re = _re.compile(r'^easydl_serve_qps_recent\{.*replica="([^"]+)"')
    p99_re = _re.compile(
        r'^easydl_serve_p99_seconds_recent\{.*replica="([^"]+)"')
    # Fleet router gauges (easydl_tpu/serve/router.py): door-side offered
    # load + true rotation size. Summed / maxed across routers.
    r_qps_re = _re.compile(
        r'^easydl_serve_router_offered_qps_recent\{.*replica="([^"]+)"')
    r_live_re = _re.compile(
        r'^easydl_serve_router_live_replicas\{.*replica="([^"]+)"')
    r_p99_re = _re.compile(
        r'^easydl_serve_router_p99_seconds_recent\{.*replica="([^"]+)"')
    replica_qps: Dict[str, float] = {}
    replica_p99: Dict[str, float] = {}
    router_offered: Dict[str, float] = {}
    router_live: Dict[str, float] = {}
    router_p99: Dict[str, float] = {}
    for _component, svc in (snap.get("services") or {}).items():
        for series, value in (svc.get("metrics") or {}).items():
            for rx, sink in ((qps_re, replica_qps), (p99_re, replica_p99),
                             (r_qps_re, router_offered),
                             (r_live_re, router_live),
                             (r_p99_re, router_p99)):
                m = rx.match(series)
                if m:
                    sink[m.group(1)] = float(value)
                    break
    if not replica_qps and not router_offered:
        return None
    return serve_scale_decision(
        replica_qps, replica_p99, target_qps=target_qps,
        p99_budget_s=p99_budget_s, min_replicas=min_replicas,
        max_replicas=max_replicas,
        router_offered_qps=(sum(router_offered.values())
                            if router_offered else None),
        router_replicas=(int(max(router_live.values()))
                         if router_live else None),
        router_p99_s=(max(router_p99.values()) if router_p99 else None))
