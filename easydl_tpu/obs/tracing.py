"""Dependency-free distributed tracing: spans, context propagation, and a
per-process flight-recorder sink.

The metrics layer (PR 1) answers *how much* — p99 drain is 4 s — but not
*why this one*: which agent's quiesce, which PS pull retry, which dist-init
wait ate a particular reshape. This module is the span layer every process
records into:

- **Spans** carry ``trace_id``/``span_id``/``parent_id``, a name, wall-clock
  start/end, attributes, and events. Contexts propagate W3C-traceparent
  style (``00-<32hex trace>-<16hex span>-01``): through gRPC metadata
  (``easydl-trace``, injected/extracted in :mod:`easydl_tpu.utils.rpc`) and
  into worker subprocesses via the ``EASYDL_TRACE_CONTEXT`` environment
  variable (agent → ``trainer_main``/worker).
- **Sink**: one JSONL file per process, ``<workdir>/obs/spans-<proc>.jsonl``,
  size-bounded with one rotation (``.1``) so it acts as an always-on flight
  recorder — the newest ~2×``EASYDL_TRACE_MAX_BYTES`` of spans survive any
  crash for autopsy. ``scripts/trace_export.py`` merges every process' file
  (plus timelines and the master WAL) into one Perfetto-loadable trace.

Contract (same as :func:`easydl_tpu.elastic.timeline.emit`): **emission
never raises into the caller**, and with ``EASYDL_TRACE`` unset every hook
is one env-dict lookup — no files are created, no gRPC metadata is added.
Sampling is therefore default-off; drills and debugging sessions arm it
with ``EASYDL_TRACE=1``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from easydl_tpu.obs.errors import count_swallowed
from easydl_tpu.utils.env import knob_raw, knob_str

#: master switch for the whole layer (default off).
TRACE_ENV = "EASYDL_TRACE"
#: traceparent handed to worker subprocesses by the agent.
CTX_ENV = "EASYDL_TRACE_CONTEXT"
#: process name override for the span sink of a spawned worker.
PROC_ENV = "EASYDL_TRACE_PROC"
#: gRPC metadata key carrying the traceparent (both directions: client
#: request metadata, and the master's directive replies as trailing
#: metadata).
METADATA_KEY = "easydl-trace"
#: rotate the sink past this size (one ``.1`` generation is kept).
MAX_BYTES_ENV = "EASYDL_TRACE_MAX_BYTES"
_DEFAULT_MAX_BYTES = 8 << 20

_HEX = set("0123456789abcdef")


def enabled() -> bool:
    """One env lookup; the gate every hook point checks first."""
    v = knob_str(TRACE_ENV)
    return v not in ("", "0", "off", "false", "no", "disabled", "none")


@dataclass(frozen=True)
class SpanContext:
    trace_id: str  # 32 lowercase hex chars
    span_id: str   # 16 lowercase hex chars


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def inject(ctx: "SpanContext | Span | None" = None) -> Optional[str]:
    """Serialize a context (default: the current span's) as a traceparent
    string, or None when tracing is disabled / there is nothing to carry."""
    if not enabled():
        return None
    if isinstance(ctx, (Span, _NullSpan)):
        ctx = ctx.context
    if ctx is None:
        ctx = current_context()
    if ctx is None:
        return None
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def extract(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a traceparent; malformed/absent input → None, NEVER raises
    (a bad peer must cost a broken link, not a broken RPC)."""
    try:
        if not header or not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) < 3:
            return None
        trace_id, span_id = parts[1].lower(), parts[2].lower()
        if len(trace_id) != 32 or not set(trace_id) <= _HEX:
            return None
        if len(span_id) != 16 or not set(span_id) <= _HEX:
            return None
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return SpanContext(trace_id, span_id)
    except Exception as e:
        count_swallowed("obs.tracing.extract", e)
        return None


def from_env(environ: Optional[Dict[str, str]] = None) -> Optional[SpanContext]:
    """The subprocess half of propagation: the agent's EASYDL_TRACE_CONTEXT."""
    env = environ if environ is not None else os.environ
    return extract(knob_str(CTX_ENV, env=env))


# ------------------------------------------------------------------- sink
_lock = threading.RLock()
_state: Dict[str, Any] = {"proc": None, "path": None, "dir": None, "fd": None}
_tls = threading.local()


def configure(proc: str, workdir: Optional[str]) -> None:
    """Point this process' span sink at ``<workdir>/obs/spans-<proc>.jsonl``.

    Creates NO files (the sink opens lazily on the first enabled emit).
    Within one job workdir the first service to configure names the process
    (an in-process master + agent share one sink); configuring with a NEW
    workdir switches sinks — the chaos runner executes scenarios over fresh
    workdirs sequentially in one process."""
    if not workdir:
        return
    try:
        from easydl_tpu.obs.exporter import OBS_DIR

        d = os.path.join(workdir, OBS_DIR)
        with _lock:
            if _state["dir"] == d:
                return
            if _state["fd"] is not None:
                try:
                    _state["fd"].close()
                except OSError:
                    pass
            safe = "".join(c if (c.isalnum() or c in "-._") else "_"
                           for c in proc) or "proc"
            _state.update(proc=safe, dir=d,
                          path=os.path.join(d, f"spans-{safe}.jsonl"),
                          fd=None)
    except Exception as e:
        count_swallowed("obs.tracing.configure", e)


def sink_path() -> Optional[str]:
    return _state["path"]


def _max_bytes() -> int:
    try:
        return int(knob_raw(MAX_BYTES_ENV) or _DEFAULT_MAX_BYTES)
    except ValueError:
        return _DEFAULT_MAX_BYTES


def _write(rec: Dict[str, Any]) -> None:
    """Append one record; bounded + rotating; never raises."""
    try:
        path = _state["path"]
        if path is None or not enabled():
            return
        line = json.dumps(rec) + "\n"
        with _lock:
            fd = _state["fd"]
            if fd is None:
                os.makedirs(_state["dir"], exist_ok=True)
                fd = _state["fd"] = open(path, "a")
            fd.write(line)
            fd.flush()
            if fd.tell() > _max_bytes():
                # Flight-recorder rotation: current → .1 (dropping the
                # previous .1) — the newest window always survives.
                fd.close()
                _state["fd"] = None
                os.replace(path, path + ".1")
    except Exception as e:
        count_swallowed("obs.tracing.write_rotate", e)
        with _lock:
            _state["fd"] = None  # reopen on the next emit


# ------------------------------------------------------------------- spans
def _stack() -> List["Span"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional["Span"]:
    st = _stack()
    return st[-1] if st else None


def current_context() -> Optional[SpanContext]:
    s = current_span()
    return s.context if s is not None else None


@dataclass
class Span:
    """One in-flight span; ``end()`` (or the ``with`` block) writes it."""

    name: str
    context: SpanContext
    parent_id: Optional[str] = None
    t0: float = field(default_factory=time.time)
    attrs: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    _tid: int = 0
    _ended: bool = False

    def set_attr(self, key: str, value: Any) -> "Span":
        try:
            self.attrs[key] = value
        except Exception as e:
            count_swallowed("obs.tracing.span.set_attr", e)
        return self

    def add_event(self, name: str, **attrs: Any) -> "Span":
        try:
            ev: Dict[str, Any] = {"t": time.time(), "name": str(name)}
            if attrs:
                ev["attrs"] = attrs
            self.events.append(ev)
        except Exception as e:
            count_swallowed("obs.tracing.span.add_event", e)
        return self

    def end(self, **attrs: Any) -> None:
        try:
            if self._ended:
                return
            self._ended = True
            if attrs:
                self.attrs.update(attrs)
            st = _stack()
            if self in st:
                st.remove(self)
            rec: Dict[str, Any] = {
                "ph": "X",
                "name": self.name,
                "trace": self.context.trace_id,
                "span": self.context.span_id,
                "t": self.t0,
                "dur": max(time.time() - self.t0, 0.0),
                "pid": os.getpid(),
                "tid": self._tid,
            }
            if self.parent_id:
                rec["parent"] = self.parent_id
            if self.attrs:
                rec["attrs"] = self.attrs
            if self.events:
                rec["events"] = self.events
            _write(rec)
        except Exception as e:
            count_swallowed("obs.tracing.span.end", e)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.add_event("error", error=repr(exc))
        self.end()

    def __bool__(self) -> bool:
        return True


class _NullSpan:
    """No-op stand-in returned while tracing is disabled, so call sites
    never branch."""

    context = None
    attrs: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []

    def set_attr(self, key: str, value: Any) -> "_NullSpan":
        return self

    def add_event(self, name: str, **attrs: Any) -> "_NullSpan":
        return self

    def end(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()


def _tid() -> int:
    try:
        return threading.get_native_id()
    except Exception as e:
        count_swallowed("obs.tracing.tid", e)
        return 0


def start_span(name: str,
               parent: "SpanContext | Span | None" = None,
               detached: bool = False,
               **attrs: Any):
    """Open a span (child of ``parent``, else of the thread's current span,
    else a new root) and make it the thread's current span. Writes a ``B``
    (open) record immediately so an unfinished span — a hang, a crash — is
    visible to ``obs_scrape --spans`` and survives in the flight recorder.

    ``detached=True`` skips the thread-local current-span stack: REQUIRED
    for spans that outlive the opening call and may be ended on a DIFFERENT
    thread (the master's generation-switch span can be opened on a gRPC
    handler thread and closed by the tick loop) — ``end()`` pops only the
    ending thread's stack, so an attached cross-thread span would pin the
    opener thread's "current span" to a dead span forever."""
    if not enabled():
        return NULL_SPAN
    try:
        if isinstance(parent, (Span, _NullSpan)):
            parent = parent.context
        if parent is None:
            parent = current_context()
        if parent is None:
            ctx = SpanContext(_new_trace_id(), _new_span_id())
            parent_id = None
        else:
            ctx = SpanContext(parent.trace_id, _new_span_id())
            parent_id = parent.span_id
        span = Span(name=str(name), context=ctx, parent_id=parent_id,
                    attrs=dict(attrs), _tid=_tid())
        if not detached:
            _stack().append(span)
        rec: Dict[str, Any] = {
            "ph": "B",
            "name": span.name,
            "trace": ctx.trace_id,
            "span": ctx.span_id,
            "t": span.t0,
            "pid": os.getpid(),
            "tid": span._tid,
        }
        if parent_id:
            rec["parent"] = parent_id
        if attrs:
            rec["attrs"] = dict(attrs)
        _write(rec)
        return span
    except Exception as e:
        count_swallowed("obs.tracing.start_span", e)
        return NULL_SPAN


def record_span(name: str, t0: float, t1: float,
                parent: "SpanContext | Span | None" = None,
                **attrs: Any) -> Optional[SpanContext]:
    """Write a completed span retroactively (no open record): zero-overhead
    tracing for work that is already timed — a training step, a measured
    switch leg."""
    if not enabled():
        return None
    try:
        if isinstance(parent, (Span, _NullSpan)):
            parent = parent.context
        if parent is None:
            parent = current_context()
        ctx = SpanContext(
            parent.trace_id if parent else _new_trace_id(), _new_span_id())
        rec: Dict[str, Any] = {
            "ph": "X",
            "name": str(name),
            "trace": ctx.trace_id,
            "span": ctx.span_id,
            "t": float(t0),
            "dur": max(float(t1) - float(t0), 0.0),
            "pid": os.getpid(),
            "tid": _tid(),
        }
        if parent:
            rec["parent"] = parent.span_id
        if attrs:
            rec["attrs"] = attrs
        _write(rec)
        return ctx
    except Exception as e:
        count_swallowed("obs.tracing.record_span", e)
        return None


def instant(name: str, parent: "SpanContext | Span | None" = None,
            t: Optional[float] = None, **attrs: Any) -> None:
    """A zero-duration marker (chaos faults, timeline boundaries)."""
    if not enabled():
        return
    try:
        if isinstance(parent, (Span, _NullSpan)):
            parent = parent.context
        if parent is None:
            parent = current_context()
        rec: Dict[str, Any] = {
            "ph": "i",
            "name": str(name),
            "trace": parent.trace_id if parent else _new_trace_id(),
            "span": _new_span_id(),
            "t": float(t) if t is not None else time.time(),
            "pid": os.getpid(),
            "tid": _tid(),
        }
        if parent:
            rec["parent"] = parent.span_id
        if attrs:
            rec["attrs"] = attrs
        _write(rec)
    except Exception as e:
        count_swallowed("obs.tracing.instant", e)


def add_event(name: str, **attrs: Any) -> None:
    """Attach an event to the thread's current span; no-op without one.
    utils/retry.py stamps each retry attempt through this, so a PS pull
    that rode three UNAVAILABLEs shows them inside its span."""
    s = current_span()
    if s is not None:
        s.add_event(name, **attrs)


# --------------------------------------------------------------- gRPC glue
def start_rpc_server_span(service: str, method: str, grpc_context):
    """Open the per-handler server span: child of the caller's injected
    context when present, a fresh root otherwise (absent/malformed metadata
    must never fail the RPC)."""
    if not enabled():
        return NULL_SPAN
    parent = None
    try:
        md = grpc_context.invocation_metadata() if grpc_context is not None \
            else None
        for key, value in md or ():
            if key == METADATA_KEY:
                parent = extract(value)
                break
    except Exception as e:
        count_swallowed("obs.tracing.rpc_server_span", e)
        parent = None
    return start_span(f"rpc:{service}/{method}", parent=parent,
                      service=service, method=method)


def attach_reply_context(grpc_context,
                         ctx: "SpanContext | Span | None") -> None:
    """Server side of the reply direction: piggyback a context (the
    master's open generation-switch span) on the response's trailing
    metadata. Directives are RESPONSES to agent-initiated RPCs, so this is
    the only gRPC channel the master has back to its agents."""
    if ctx is None or not enabled():
        return
    try:
        header = inject(ctx)
        if header and grpc_context is not None \
                and hasattr(grpc_context, "set_trailing_metadata"):
            grpc_context.set_trailing_metadata(((METADATA_KEY, header),))
    except Exception as e:
        count_swallowed("obs.tracing.attach_reply_context", e)


def note_reply_metadata(metadata) -> None:
    """Client side: stash the reply's traceparent (or None) for the caller
    to collect via :func:`take_reply_context`. Thread-local — the agent's
    run loop issues the RPC and collects the context on the same thread."""
    header = None
    try:
        for key, value in metadata or ():
            if key == METADATA_KEY:
                header = value
                break
    except Exception as e:
        count_swallowed("obs.tracing.note_reply_metadata", e)
        header = None
    _tls.reply = header


def take_reply_context() -> Optional[SpanContext]:
    """The context the last traced RPC's reply carried (cleared on read)."""
    header = getattr(_tls, "reply", None)
    _tls.reply = None
    return extract(header)


# ----------------------------------------------------------- file reading
def span_files(workdir: str) -> List[str]:
    """Every process' span sink under ``<workdir>/obs/`` (rotated ``.1``
    generations included, oldest first per process)."""
    from easydl_tpu.obs.exporter import OBS_DIR

    out: List[str] = []
    d = os.path.join(workdir, OBS_DIR)
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if name.startswith("spans-") and name.endswith(".jsonl.1"):
            out.append(os.path.join(d, name))
    for name in names:
        if name.startswith("spans-") and name.endswith(".jsonl"):
            out.append(os.path.join(d, name))
    return out


def read_records(path: str) -> List[Dict[str, Any]]:
    """One file's records, torn tail lines skipped; each record is tagged
    with its source process (``proc``, from the filename)."""
    base = os.path.basename(path)
    proc = base[len("spans-"):].split(".jsonl")[0]
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    rec["proc"] = proc
                    out.append(rec)
    except OSError:
        pass
    return out


def read_all(workdir: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for path in span_files(workdir):
        out.extend(read_records(path))
    return out


def open_spans(workdir: str) -> List[Dict[str, Any]]:
    """Spans with an open (``B``) record and no matching end — what every
    process is doing *right now* (or was doing when it died): the
    poor-man's hung-drill debugger behind ``obs_scrape --spans``."""
    opens: Dict[str, Dict[str, Any]] = {}
    for rec in read_all(workdir):
        sid = str(rec.get("span", ""))
        if rec.get("ph") == "B":
            opens[sid] = rec
        elif rec.get("ph") == "X":
            opens.pop(sid, None)
    now = time.time()
    out = []
    for rec in opens.values():
        rec = dict(rec)
        rec["age_s"] = round(now - float(rec.get("t", now)), 3)
        out.append(rec)
    return sorted(out, key=lambda r: (str(r.get("proc")), -r["age_s"]))
