"""Per-host phase timeline for recovery/reshape decomposition.

The reference promises fast elastic recovery (README.md:25-35) without a
mechanism; our generation switch has seven distinct phases (quiesce consensus,
drain checkpoint, re-rendezvous, process spawn, runtime imports, distributed
init, restore, first-step compile) and optimizing the wrong one is easy —
round 2's compile cache bought ~10s of a ~60s stall because process start,
not recompile, dominated. Every worker/agent appends one JSON line per phase
boundary to ``timeline-<agent>.jsonl`` in the job workdir; the master's
``events.jsonl`` carries the plan/phase transitions. ``scripts/
measure_recovery.py`` folds both into the per-phase breakdown in
RECOVERY.json.

Records: ``{"t": <unix time>, "phase": str, "gen": int, ...}``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List

# In-process listeners: fn(path, record) called on every emit. One
# instrumentation point feeds both the JSONL decomposition AND live gauges —
# the agent bridges its phase boundaries into /metrics by registering here
# (easydl_tpu/elastic/agent.py), so the two views can never drift apart.
# Listeners fire only in the emitting process; a worker subprocess' emits
# reach other processes through the JSONL file, as before.
_listeners: List[Callable[[str, Dict[str, Any]], None]] = []
_listeners_lock = threading.Lock()


def add_listener(fn: Callable[[str, Dict[str, Any]], None]) -> None:
    with _listeners_lock:
        _listeners.append(fn)


def remove_listener(fn: Callable[[str, Dict[str, Any]], None]) -> None:
    with _listeners_lock:
        try:
            _listeners.remove(fn)
        except ValueError:
            pass


def emit(path: str | None, phase: str, generation: int, **data: Any) -> None:
    """Append one phase boundary; never raises (timing is best-effort and
    must not take down a worker)."""
    if not path:
        return
    rec = {"t": time.time(), "phase": phase, "gen": int(generation), **data}
    with _listeners_lock:
        listeners = list(_listeners)
    for fn in listeners:
        try:
            fn(path, rec)
        except Exception:
            pass  # same contract as the file write: never raises
    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def read(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                if line.strip():
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn concurrent append
    except OSError:
        pass
    return out


def read_all(workdir: str) -> List[Dict[str, Any]]:
    """All agents' timelines in one list (unsorted; callers filter by gen)."""
    out: List[Dict[str, Any]] = []
    try:
        names = os.listdir(workdir)
    except OSError:
        return out
    for name in names:
        if name.startswith("timeline-") and name.endswith(".jsonl"):
            for rec in read(os.path.join(workdir, name)):
                rec["source"] = name[len("timeline-"):-len(".jsonl")]
                out.append(rec)
    return out
