// Controller reconcile core — the native diff engine behind the operator.
//
// The reference's elastic-operator is a Go controller (SURVEY.md §2.1 item 1;
// .pre-commit-config.yaml:42-49) that "reconcile[s] Pods of the job against"
// a JobResource (docs/design/elastic-training-operator.md:97-98) and, for
// resource_updation entries, "launch[es] a new Pod ... to replace the Pod
// with the resource_updation.name" (:99-101). This C++ core implements that
// decision function: (desired plan, observed pods) -> pod operations. It is
// pure and level-triggered — the Python operator loop feeds it fresh state
// every pass and applies the returned ops, so a crash loses nothing.
//
// Wire format (line-based, '|'-separated — keeps the C ABI to two functions):
//   desired:  J|<job>            job name (pod-name prefix)
//             R|<role>|<replicas>|<resource_sig>
//             U|<pod_name>|<resource_sig>        resource_updation entry
//   observed: P|<name>|<role>|<phase>|<resource_sig>|<replaces>
//   ops out:  CREATE|<name>|<role>|<resource_sig>|<replaces>
//             DELETE|<name>|<reason>             reason: failed|replaced|scale_down
//
// Replace-then-retire: a replacement pod is CREATEd carrying `replaces`; the
// old pod is only DELETEd once its replacement reports Running. In-flight
// replacements don't count toward role replicas (the old pod still serves its
// slot), so scaling and replacement compose without double-counting.

#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <map>
#include <set>
#include <sstream>
#include <string>
#include <vector>

namespace {

struct Pod {
  std::string name, role, phase, sig, replaces;
  int index = -1;  // trailing -<n> of the name, -1 if unparsable
};

std::vector<std::string> split(const std::string& s, char sep) {
  std::vector<std::string> out;
  std::string cur;
  for (char c : s) {
    if (c == sep) {
      out.push_back(cur);
      cur.clear();
    } else {
      cur += c;
    }
  }
  out.push_back(cur);
  return out;
}

int trailing_index(const std::string& name) {
  size_t pos = name.rfind('-');
  if (pos == std::string::npos || pos + 1 >= name.size()) return -1;
  for (size_t i = pos + 1; i < name.size(); ++i) {
    if (!isdigit(name[i])) return -1;
  }
  return std::atoi(name.c_str() + pos + 1);
}

class Reconciler {
 public:
  std::string Run(const std::string& desired, const std::string& observed) {
    Parse(desired, observed);
    std::ostringstream ops;
    std::set<std::string> gone;  // pods DELETEd this pass

    // 1. Failed pods are retired; the scale rule below recreates the slot
    //    (reference: recover failed PS/workers, README.md:26-29).
    for (const auto& p : pods_) {
      if (p.phase == "Failed") {
        ops << "DELETE|" << p.name << "|failed\n";
        gone.insert(p.name);
      }
    }

    // Index live pods.
    std::map<std::string, const Pod*> by_name;
    std::map<std::string, const Pod*> replacement_of;  // old name -> new pod
    for (const auto& p : pods_) {
      if (gone.count(p.name)) continue;
      by_name[p.name] = &p;
    }
    for (const auto& p : pods_) {
      if (gone.count(p.name) || p.replaces.empty()) continue;
      if (by_name.count(p.replaces)) replacement_of[p.replaces] = &p;
    }

    // 2. resource_updation: replace-then-retire.
    for (const auto& u : updations_) {
      auto it = by_name.find(u.first);
      if (it == by_name.end()) continue;  // already retired
      const Pod* old = it->second;
      // Succeeded pods completed their work: resizing one is meaningless and
      // replacing it would re-run finished work (the completion loop).
      if (old->phase == "Terminating" || old->phase == "Succeeded") continue;
      auto rit = replacement_of.find(u.first);
      if (rit != replacement_of.end()) {
        if (rit->second->phase == "Running") {
          ops << "DELETE|" << old->name << "|replaced\n";
          gone.insert(old->name);
        }  // Pending replacement: wait.
      } else {
        std::string name = NextName(old->role);
        ops << "CREATE|" << name << "|" << old->role << "|" << u.second
            << "|" << old->name << "\n";
      }
    }

    // 3. Horizontal scaling per desired role. A role that has pods but is
    // absent from the plan means replicas 0 — omission must not orphan pods.
    // (The trainer role is operator-owned, never replica-levelled here.)
    for (const auto& p : pods_) {
      if (p.role != "trainer" && !roles_.count(p.role) &&
          !frozen_roles_.count(p.role)) {
        roles_[p.role] = {0, ""};
      }
    }
    for (const auto& r : roles_) {
      const std::string& role = r.first;
      int want = r.second.first;
      const std::string& sig = r.second.second;
      // Succeeded pods fill their slot permanently (k8s Job semantics): a
      // pod only exits 0 when its work is complete, so the slot is not
      // refilled and the pod is never scale_down'd. Identical in the
      // Python twin — pinned by the parity fuzzer.
      int done = 0;
      for (const auto& p : pods_) {
        if (p.role == role && !gone.count(p.name) && p.phase == "Succeeded") {
          ++done;
        }
      }
      int need = want - done;
      if (need < 0) need = 0;
      // Active = serving pods of the role: Pending/Running, not deleted this
      // pass, and not an in-flight replacement (its old pod holds the slot).
      // The exclusion requires the old pod to still be SERVING — once it is
      // Terminating/Failed, the replacement owns the slot (otherwise graceful
      // deletion would double-count the slot as empty and churn extra pods).
      std::vector<const Pod*> active;
      for (const auto& p : pods_) {
        if (p.role != role || gone.count(p.name)) continue;
        if (p.phase != "Pending" && p.phase != "Running") continue;
        if (!p.replaces.empty() && !gone.count(p.replaces)) {
          auto t = by_name.find(p.replaces);
          if (t != by_name.end() && (t->second->phase == "Pending" ||
                                     t->second->phase == "Running")) {
            continue;  // in-flight replacement
          }
        }
        active.push_back(&p);
      }
      int have = static_cast<int>(active.size());
      for (int i = have; i < need; ++i) {
        ops << "CREATE|" << NextName(role) << "|" << role << "|" << sig
            << "|\n";
      }
      if (have > need) {
        std::sort(active.begin(), active.end(),
                  [](const Pod* a, const Pod* b) { return a->index > b->index; });
        for (int i = 0; i < have - need; ++i) {
          ops << "DELETE|" << active[i]->name << "|scale_down\n";
          gone.insert(active[i]->name);
        }
      }
    }
    return ops.str();
  }

 private:
  void Parse(const std::string& desired, const std::string& observed) {
    for (const auto& line : split(desired, '\n')) {
      if (line.empty()) continue;
      auto f = split(line, '|');
      if (f[0] == "J" && f.size() >= 2) {
        job_ = f[1];
      } else if (f[0] == "R" && f.size() >= 4) {
        // Replicas must be all ASCII digits AND at most 7 of them (bounds
        // the value far below INT_MAX — atoi overflow is UB — and bounds
        // the levelling loop); a malformed count FREEZES the role for this
        // pass (no creates, no deletes) — merely skipping the line would
        // hand the role to the absent-role-means-replicas-0 fallback and
        // delete every healthy pod; atoi's silent 0 would do the same.
        // Identical in the Python twin — pinned by the fuzzer.
        bool valid = !f[2].empty() && f[2].size() <= 7;
        for (char c : f[2]) {
          if (c < '0' || c > '9') {
            valid = false;
            break;
          }
        }
        if (valid) {
          roles_[f[1]] = {std::atoi(f[2].c_str()), f[3]};
        } else {
          frozen_roles_.insert(f[1]);
        }
      } else if (f[0] == "U" && f.size() >= 3) {
        updations_.push_back({f[1], f[2]});
      }
    }
    for (const auto& line : split(observed, '\n')) {
      if (line.empty()) continue;
      auto f = split(line, '|');
      if (f[0] != "P" || f.size() < 6) continue;
      Pod p;
      p.name = f[1];
      p.role = f[2];
      p.phase = f[3];
      p.sig = f[4];
      p.replaces = f[5];
      p.index = trailing_index(p.name);
      int next = p.index + 1;
      if (next > next_index_[p.role]) next_index_[p.role] = next;
      pods_.push_back(std::move(p));
    }
  }

  // Fresh pod name: <job>-<role>-<n> with n past every observed index
  // (including Terminating/Failed pods, so names never collide).
  std::string NextName(const std::string& role) {
    int n = next_index_[role]++;
    return job_ + "-" + role + "-" + std::to_string(n);
  }

  std::string job_;
  std::set<std::string> frozen_roles_;  // malformed replicas: don't level
  std::map<std::string, std::pair<int, std::string>> roles_;
  std::vector<std::pair<std::string, std::string>> updations_;
  std::vector<Pod> pods_;
  std::map<std::string, int> next_index_;
};

}  // namespace

extern "C" {

// Returns a malloc'd ops string; caller frees with edr_free.
char* edr_reconcile(const char* desired, const char* observed) {
  Reconciler r;
  std::string out = r.Run(desired ? desired : "", observed ? observed : "");
  char* buf = static_cast<char*>(std::malloc(out.size() + 1));
  std::memcpy(buf, out.c_str(), out.size() + 1);
  return buf;
}

void edr_free(char* p) { std::free(p); }

}  // extern "C"
