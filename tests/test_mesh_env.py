"""Sanity: the test environment really presents >=8 CPU devices."""

import jax


def test_eight_cpu_devices(eight_devices):
    assert len(eight_devices) == 8
    assert all(d.platform == "cpu" for d in eight_devices)
    assert jax.default_backend() == "cpu"
