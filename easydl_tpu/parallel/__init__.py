"""The parallelism API surface: one import site for every axis the mesh
supports.

The framework scales a model by composing named mesh axes
(SURVEY.md §2.2; "How to Scale Your Model"'s recipe — pick a mesh,
annotate shardings, let GSPMD insert the collectives):

- ``dp``   — data parallelism (batch sharded; gradient psum over ICI)
- ``fsdp`` — fully-sharded data parallelism (params sharded on ``embed``;
  GSPMD inserts the all-gather/reduce-scatter pair)
- ``tp``   — tensor parallelism (``mlp``/``heads``/``vocab`` sharded)
- ``sp``   — sequence/context parallelism (ring attention over
  ``ppermute``, or Ulysses head-all-to-all) for long context
- ``ep``   — expert parallelism (MoE experts sharded; all-to-all
  dispatch/combine)
- ``pp``   — pipeline parallelism (layer stack stage-sharded; GPipe
  fill–drain inside one ``shard_map``)

The implementations live where they are used — mesh/sharding in
``easydl_tpu.core``, the schedule/kernel machinery in ``easydl_tpu.ops``
— and this package is the supported import path that composes them:
``MeshSpec(dp=2, fsdp=2, tp=2)`` + the rule table + the per-axis factory
functions below are everything a model needs to run on any mesh shape
(the multichip dryrun exercises each axis family exactly through these
names).
"""

from easydl_tpu.core.mesh import MeshSpec, build_mesh  # noqa: F401
from easydl_tpu.core.sharding import (  # noqa: F401
    DEFAULT_RULES,
    state_shardings,
)
from easydl_tpu.ops.moe import MoeMlp, top_k_routing  # noqa: F401
from easydl_tpu.ops.pipeline import (  # noqa: F401
    apply_pipeline_config,
    bubble_fraction,
    make_pipeline,
    pipeline_rules,
    pipeline_ticks,
)
from easydl_tpu.ops.sequence_parallel import (  # noqa: F401
    make_sp_attention,
    ring_attention,
    ulysses_attention,
)

__all__ = [
    "MeshSpec",
    "build_mesh",
    "DEFAULT_RULES",
    "state_shardings",
    "make_sp_attention",
    "ring_attention",
    "ulysses_attention",
    "make_pipeline",
    "pipeline_rules",
    "pipeline_ticks",
    "bubble_fraction",
    "apply_pipeline_config",
    "MoeMlp",
    "top_k_routing",
]
