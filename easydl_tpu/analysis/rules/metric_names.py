"""metric-name: the easydl_* naming conventions, checked at the source.

The discipline (PRs 1/9): the runtime registry (obs/registry.py) already
rejects names outside the Prometheus grammar at REGISTRATION time — but
only on paths the test run actually executes. This rule applies the same
contract, plus the repo's stricter conventions, to every registration
site statically, covering the branches the runtime lint never reaches:

* names are ``easydl_<component>_<metric>`` — lowercase
  ``[a-z0-9_]``, at least three segments, ``easydl_`` prefix (the fleet
  dashboard's namespace);
* counters end ``_total`` (rate() reads naturally, matches every
  existing counter);
* histograms end in a unit suffix (``_seconds``/``_bytes``/…) so the
  bucket scale is legible from the name;
* label names come from the shared vocabulary below — a new label is a
  cross-cutting schema decision, made once here, not ad hoc at a call
  site — and never the reserved ``le``/``quantile``/``__*``;
* a registration whose name is not statically checkable (a bare
  variable) is itself a finding: an f-string with a literal ``easydl_``
  prefix is as dynamic as the convention allows.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from easydl_tpu.analysis.core import (
    Finding,
    Rule,
    ScopedVisitor,
    dotted_name,
)

_REGISTER_METHODS = ("counter", "gauge", "histogram")

_NAME_RE = re.compile(r"^easydl(_[a-z0-9]+){2,}$")
_CHUNK_RE = re.compile(r"^[a-z0-9_]*$")
_LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: Unit suffixes a histogram name must end with — the bucket scale must
#: be legible from the name alone.
HISTOGRAM_UNITS = ("_seconds", "_bytes", "_examples", "_records", "_rows",
                   "_ids", "_ratio")

#: The shared label vocabulary. Adding a label here is the act of
#: declaring a new fleet-wide series dimension; every registration site
#: must draw from it.
KNOWN_LABELS = frozenset((
    "agent", "arm", "axis", "cell", "component", "fault", "generation",
    "has_plan", "job", "kind", "method", "op", "phase", "reason", "replica",
    "result", "role", "scenario", "service", "severity", "shard", "site",
    "slo", "source", "table", "target", "verb", "verdict",
))

_RESERVED_LABELS = frozenset(("le", "quantile"))

#: Every metric family the tree registers — the reference the
#: ``slo-metric-refs`` rule (analysis/rules/slo_refs.py) resolves SLO
#: series selectors against, and what tests/test_easylint.py keeps in
#: sync with the registration sites by AST scan. A name here and not in
#: the tree is stale; a registration not here is undeclared — both fail
#: the sync test. The ``easydl_rpc_{side}_*`` f-string family is listed
#: expanded (side ∈ client/server).
REGISTERED_METRICS = frozenset((
    "easydl_agent_generation",
    "easydl_agent_heartbeat_rate_per_s",
    "easydl_agent_heartbeats_total",
    "easydl_agent_master_outage_seconds",
    "easydl_agent_master_outages_total",
    "easydl_agent_outage_buffered_metrics",
    "easydl_agent_phase_events_total",
    "easydl_agent_phase_seconds",
    "easydl_agent_worker_loss",
    "easydl_agent_worker_samples_per_sec",
    "easydl_agent_worker_step",
    "easydl_agent_worker_step_time_seconds",
    "easydl_alert_active",
    "easydl_brain_metric_reports_total",
    "easydl_brain_plan_requests_total",
    "easydl_brain_plan_version",
    "easydl_brain_plan_workers",
    "easydl_brain_replans_total",
    "easydl_cell_fenced_pushes_total",
    "easydl_cell_promotion_seconds",
    "easydl_cell_replication_lag",
    "easydl_cell_ship_errors_total",
    "easydl_cell_ship_gaps_total",
    "easydl_cell_ship_torn_segments_total",
    "easydl_cell_ship_truncations_total",
    "easydl_cell_shipped_bytes_total",
    "easydl_cell_shipped_records_total",
    "easydl_cell_shipped_segments_total",
    "easydl_cell_shipped_snapshots_total",
    "easydl_cell_shipped_versions_total",
    "easydl_chaos_faults_injected_total",
    "easydl_chaos_scenarios_run_total",
    "easydl_controller_jobs",
    "easydl_controller_pod_ops_total",
    "easydl_controller_reconcile_seconds",
    "easydl_controller_reconcile_total",
    "easydl_feedback_bytes_total",
    "easydl_feedback_dropped_total",
    "easydl_feedback_events_total",
    "easydl_loop_checkpoints_total",
    "easydl_loop_lag_seconds",
    "easydl_loop_trained_events_total",
    "easydl_master_desired_workers",
    "easydl_master_directives_total",
    "easydl_master_failovers_total",
    "easydl_master_generation",
    "easydl_master_journal_writes_total",
    "easydl_master_membership_size",
    "easydl_master_phase_seconds",
    "easydl_master_plan_version",
    "easydl_master_reconciled_agents_total",
    "easydl_master_reshapes_total",
    "easydl_master_straggler_evictions_total",
    "easydl_master_train_loss",
    "easydl_master_train_samples_per_sec",
    "easydl_master_train_step",
    "easydl_ps_client_dedup_ratio",
    "easydl_ps_pull_bytes_total",
    "easydl_ps_pull_ids_total",
    "easydl_ps_push_bytes_total",
    "easydl_ps_push_fence_rejected_total",
    "easydl_ps_push_ids_total",
    "easydl_ps_push_rejected_total",
    "easydl_ps_push_stale_route_total",
    "easydl_ps_reshard_replayed_records_total",
    "easydl_ps_reshard_rows_migrated_total",
    "easydl_ps_shard_epoch",
    "easydl_ps_shm_client_fallbacks_total",
    "easydl_ps_shm_client_ids_total",
    "easydl_ps_shm_client_pulls_total",
    "easydl_ps_table_rows",
    "easydl_ps_tier_cold_hits_total",
    "easydl_ps_tier_cold_rows",
    "easydl_ps_tier_demotions_total",
    "easydl_ps_tier_hot_rows",
    "easydl_ps_tier_promotions_total",
    "easydl_ps_wal_appends_total",
    "easydl_ps_wal_bytes_total",
    "easydl_ps_wal_deduped_pushes_total",
    "easydl_ps_wal_replayed_records_total",
    "easydl_ps_wal_retired_segments_total",
    "easydl_retrieval_candidates_total",
    "easydl_retrieval_freshness_seconds",
    "easydl_retrieval_index_rows",
    "easydl_retrieval_index_updates_total",
    "easydl_retrieval_index_version",
    "easydl_retrieval_requests_total",
    "easydl_rollout_publishes_total",
    "easydl_rollout_quarantines_total",
    "easydl_rollout_rollbacks_total",
    "easydl_rpc_client_errors_total",
    "easydl_rpc_client_latency_seconds",
    "easydl_rpc_client_requests_total",
    "easydl_rpc_server_errors_total",
    "easydl_rpc_server_latency_seconds",
    "easydl_rpc_server_requests_total",
    "easydl_scrape_attempts_total",
    "easydl_scrape_failures_total",
    "easydl_serve_batch_examples",
    "easydl_serve_cache_bytes",
    "easydl_serve_cache_evictions_total",
    "easydl_serve_cache_hits_total",
    "easydl_serve_cache_invalidations_total",
    "easydl_serve_cache_misses_total",
    "easydl_serve_examples_total",
    "easydl_serve_model_version",
    "easydl_serve_p99_seconds_recent",
    "easydl_serve_qps_recent",
    "easydl_serve_queue_examples",
    "easydl_serve_request_latency_seconds",
    "easydl_serve_requests_total",
    "easydl_serve_router_ejections_total",
    "easydl_serve_router_hedges_total",
    "easydl_serve_router_known_replicas",
    "easydl_serve_router_live_replicas",
    "easydl_serve_router_offered_qps_recent",
    "easydl_serve_router_p99_seconds_recent",
    "easydl_serve_router_readmissions_total",
    "easydl_serve_router_request_latency_seconds",
    "easydl_serve_router_requests_total",
    "easydl_serve_router_reroutes_total",
    "easydl_serve_router_routed_total",
    "easydl_swallowed_errors_total",
    "easydl_timeline_listener_errors_total",
    "easydl_train_loss",
    "easydl_train_samples_per_sec",
    "easydl_train_step",
    "easydl_train_step_time_seconds",
    "easydl_train_steps_total",
    "easydl_worker_mesh_axis",
    "easydl_worker_mfu",
))


def _module_tuple_constants(tree: ast.Module):
    """Module-level ``NAME = ("a", "b")`` tuples — resolves the
    ``_RPC_LABELS`` indirection in utils/rpc.py."""
    out = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, (ast.Tuple, ast.List))
                and all(isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                        for e in stmt.value.elts)):
            out[stmt.targets[0].id] = tuple(
                e.value for e in stmt.value.elts)
    return out


class _Visitor(ScopedVisitor):
    def __init__(self, rule: str, path: str, tuple_consts):
        super().__init__(rule, path)
        self._tuples = tuple_consts

    # ------------------------------------------------------------- name
    def _check_name(self, node: ast.Call, kind: str) -> None:
        arg = node.args[0] if node.args else None
        name: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if not _NAME_RE.match(name):
                self.emit(node, f"bad-name:{name}",
                          f"metric name {name!r} breaks the "
                          "easydl_<component>_<metric> lowercase "
                          "convention")
                return
        elif isinstance(arg, ast.JoinedStr):
            chunks = [v.value for v in arg.values
                      if isinstance(v, ast.Constant)]
            first = arg.values[0]
            if not (isinstance(first, ast.Constant)
                    and str(first.value).startswith("easydl_")):
                self.emit(node, "dynamic-name-prefix",
                          "f-string metric name must start with a literal "
                          "easydl_<component> prefix")
                return
            if not all(_CHUNK_RE.match(str(c)) for c in chunks):
                self.emit(node, "bad-name-chunk",
                          "literal parts of an f-string metric name must "
                          "be lowercase [a-z0-9_]")
                return
            name = "".join(str(c) for c in chunks)  # suffix still checkable
        else:
            self.emit(node, "unverifiable-name",
                      "metric name is not statically checkable — use a "
                      "literal or an f-string with a literal easydl_ "
                      "prefix")
            return
        if kind == "counter" and not name.endswith("_total"):
            self.emit(node, f"counter-no-total:{name}",
                      f"counter {name!r} must end in _total")
        if kind == "histogram" and not name.endswith(HISTOGRAM_UNITS):
            self.emit(node, f"histogram-no-unit:{name}",
                      f"histogram {name!r} must end in a unit suffix "
                      f"{HISTOGRAM_UNITS}")

    # ----------------------------------------------------------- labels
    def _label_values(self, node: ast.Call):
        lab = node.args[2] if len(node.args) > 2 else None
        for kw in node.keywords:
            if kw.arg == "labelnames":
                lab = kw.value
        if lab is None:
            return ()
        if isinstance(lab, (ast.Tuple, ast.List)):
            vals = []
            for e in lab.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    vals.append(e.value)
                else:
                    return None  # dynamic element
            return tuple(vals)
        if isinstance(lab, ast.Name):
            return self._tuples.get(lab.id)
        return None

    def _check_labels(self, node: ast.Call) -> None:
        vals = self._label_values(node)
        if vals is None:
            self.emit(node, "unverifiable-labels",
                      "labelnames are not statically checkable — use a "
                      "literal tuple (or a module-level tuple constant)")
            return
        for v in vals:
            if (not _LABEL_RE.match(v) or v in _RESERVED_LABELS
                    or v.startswith("__")):
                self.emit(node, f"bad-label:{v}",
                          f"label {v!r} breaks the lowercase grammar or "
                          "shadows a reserved Prometheus label")
            elif v not in KNOWN_LABELS:
                self.emit(node, f"unknown-label:{v}",
                          f"label {v!r} is not in the shared vocabulary "
                          "(analysis/rules/metric_names.py KNOWN_LABELS) "
                          "— declare it there (a schema decision) or "
                          "reuse an existing label")

    def visit_Call(self, node: ast.Call) -> None:
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTER_METHODS):
            recv = (dotted_name(node.func.value) or "").lower()
            # skip unrelated .counter()/.gauge() on non-registry objects:
            # every registry receiver in-tree is reg/registry/get_registry()
            looks_registry = ("reg" in recv.rsplit(".", 1)[-1]
                              or isinstance(node.func.value, ast.Call))
            if looks_registry:
                self._check_name(node, node.func.attr)
                self._check_labels(node)
        self.generic_visit(node)


class MetricNameLint(Rule):
    name = "metric-name"
    invariant = ("Every metric registration site follows the "
                 "easydl_<component>_<metric> naming scheme, counter/_total"
                 " and histogram/unit suffixes, and the shared label "
                 "vocabulary — statically, including unexecuted paths.")

    def check(self, path: str, tree: ast.Module,
              source: str) -> List[Finding]:
        v = _Visitor(self.name, path, _module_tuple_constants(tree))
        v.visit(tree)
        return v.findings
