"""Brain — the resource-plan optimization service.

The reference names Brain as its third component: "An optimization service to
generate resources plans" (README.md:13) answering two query types from the
trainer — a startup plan from job features and periodic re-plans from runtime
performance (docs/design/elastic-training-operator.md:106-112). The TPU-native
rebuild consumes XLA step-time metrics and plans in *chips* over pod slices.
"""

from easydl_tpu.brain.mesh_policy import (
    MeshPolicyConfig,
    MeshShapePolicy,
    mesh_shape_decision,
)
from easydl_tpu.brain.policy import Autoscaler, AutoscalerConfig, startup_plan
from easydl_tpu.brain.service import BRAIN_SERVICE, Brain

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "MeshPolicyConfig",
    "MeshShapePolicy",
    "mesh_shape_decision",
    "startup_plan",
    "BRAIN_SERVICE",
    "Brain",
]
