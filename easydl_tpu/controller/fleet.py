"""TenantFleet: live actuation of the global chip arbiter over N
ElasticJob masters sharing one agent pool (ROADMAP item 5).

The operator-level integration (controller/operator.py ``chip_budget``)
levels POD replicas; this module is the in-process twin the multi-tenant
chaos drill runs — the same :class:`~easydl_tpu.brain.arbiter.
GlobalChipArbiter` decisions actuated over real :class:`Master`/
:class:`Agent` objects, with the property the drill asserts: **a
preempted chip always drains before it is killed.**

Actuation of one preemption (the only non-trivial move):

1. pick the donor job's victim agent — its current MEMBER, i.e. the host
   whose chip the arbiter is reclaiming (cloud semantics: you lose a
   specific VM, and your standby takes over);
2. deliver the preempt notice (:meth:`Agent.notify_preemption` — the very
   hook a GCE maintenance notice / SIGTERM lands on), which makes the
   victim's master run the PLANNED preempt drain: quiesce at a step
   boundary, checkpoint, reshape the survivors;
3. only after the worker provably exited (or the escalation timeout — a
   recorded failure, never a silent one) stop the agent and record the
   "kill" mark;
4. hand the freed chip to the receiver: a fresh agent registered to the
   winner's master (it joins as member or standby per that master's own
   rendezvous).

Free-pool grants skip 1-3. The fleet keeps the arbiter's full decision
log plus drill-relative allocation samples and per-move drain marks —
exactly the evidence shape ``sim/multijob.check_tenants`` judges and
``brain.arbiter.replay_decision_log`` byte-verifies offline.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from easydl_tpu.brain.arbiter import (
    ArbiterConfig,
    GlobalChipArbiter,
    JobClaim,
)
from easydl_tpu.obs.errors import count_swallowed
from easydl_tpu.utils.logging import get_logger

log = get_logger("controller", "fleet")


@dataclass
class TenantJob:
    """One ElasticJob's standing in the fleet."""

    name: str
    master: Any  # elastic.master.Master
    workdir: str
    priority: int = 0
    min_chips: int = 0
    max_chips: int = 1
    demand: int = 0
    #: agent_id -> live Agent (the job's chips)
    agents: Dict[str, Any] = field(default_factory=dict)
    spawned: int = 0
    #: [[t_rel, demand], ...] — the demand timeline the offline checks
    #: replay (scale-ups land here via TenantFleet.set_demand)
    demand_history: List[List[float]] = field(default_factory=list)


@dataclass
class _PendingDrain:
    """A preemption mid-flight: notice delivered, waiting for the drain."""

    donor: str
    agent_id: str
    to_job: str  # "" = reclaim to the free pool
    t_notice: float = 0.0
    deadline: float = 0.0


class TenantFleet:
    """Single-threaded control loop state machine: call :meth:`tick`
    periodically (the drill runs it on a 0.25s cadence). Not thread-safe
    by design — one ticker owns it, like the operator's reconcile loop."""

    def __init__(self, total_chips: int,
                 agent_factory: Callable[[str, Any, "TenantJob"], Any],
                 config: Optional[ArbiterConfig] = None,
                 drain_timeout_s: float = 30.0,
                 epoch: Optional[float] = None):
        #: agent_factory(agent_id, master, job) -> STARTED Agent
        self.total_chips = int(total_chips)
        self.agent_factory = agent_factory
        self.arbiter = GlobalChipArbiter(config)
        self.drain_timeout_s = drain_timeout_s
        self.jobs: Dict[str, TenantJob] = {}
        self._pending: List[_PendingDrain] = []
        #: evidence (drill-relative seconds against ``epoch``)
        self.epoch = time.monotonic() if epoch is None else epoch
        self.allocation_samples: List[Dict[str, Any]] = []
        self.moves: List[Dict[str, Any]] = []
        self.preempt_drains: List[Dict[str, Any]] = []

    # ------------------------------------------------------------- plumbing
    def _rel(self, t: float) -> float:
        return round(t - self.epoch, 6)

    def add_job(self, job: TenantJob) -> None:
        if job.name in self.jobs:
            raise ValueError(f"job {job.name!r} already in the fleet")
        job.demand_history = [[0.0, int(job.demand)]]
        self.jobs[job.name] = job

    def set_demand(self, name: str, chips: int) -> None:
        log.info("fleet: job %s demand -> %d", name, chips)
        job = self.jobs[name]
        job.demand = int(chips)
        job.demand_history.append(
            [self._rel(time.monotonic()), int(chips)])

    def allocations(self) -> Dict[str, int]:
        return {name: len(j.agents) for name, j in sorted(self.jobs.items())}

    def _spawn_agent(self, job: TenantJob) -> str:
        job.spawned += 1
        aid = f"{job.name}-a{job.spawned}"
        job.agents[aid] = self.agent_factory(aid, job.master, job)
        log.info("fleet: spawned agent %s for job %s (now %d chips)",
                 aid, job.name, len(job.agents))
        return aid

    def _victim_agent(self, job: TenantJob) -> Optional[str]:
        """The MEMBER first (the chip being reclaimed is its host — the
        drain path is the point); deterministic standby fallback when the
        job has no member (mid-reshape). Agents already mid-drain are
        excluded: two preemptions from one donor in a single decision
        (max_preemptions >= 2) must take two DIFFERENT hosts — re-picking
        the pending victim would queue a second drain for one agent,
        record a drain that never happened, and grant a phantom chip."""
        draining = {d.agent_id for d in self._pending if d.donor == job.name}
        try:
            members = list(job.master.status().get("members", []))
        except Exception as e:
            count_swallowed("fleet.victim_status", e)
            members = []
        for m in members:
            if m in job.agents and m not in draining:
                return m
        candidates = sorted(set(job.agents) - draining)
        return candidates[0] if candidates else None

    def _drained(self, job: TenantJob, aid: str) -> bool:
        """True once the victim's worker has provably exited AND its
        master no longer counts it a member — drain complete."""
        agent = job.agents.get(aid)
        if agent is None:
            return True
        if agent.worker_pid is not None:
            return False
        try:
            return aid not in job.master.status().get("members", [])
        except Exception as e:
            count_swallowed("fleet.drain_status", e)
            return False

    # ------------------------------------------------------------- the tick
    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Advance pending drains; when none are in flight, run one
        arbitration round and actuate it. Returns the decision made this
        tick (None while drains are pending or nothing changed)."""
        now = time.monotonic() if now is None else now
        self._advance_drains(now)
        if self._pending:
            # Chips mid-drain are owned by NOBODY the claims can see;
            # deciding now would let the free-pool math re-grant them.
            # Drains are seconds; arbitration is paced anyway.
            self._sample(now)
            return None
        decision = self.arbiter.decide(self._claims(), self.total_chips, now)
        for g in decision["grants"]:
            job = self.jobs[str(g["to"])]
            for _ in range(int(g["chips"])):
                self._spawn_agent(job)
            self.moves.append({"t": self._rel(now), "from": "",
                               "to": job.name, "chips": int(g["chips"])})
        for p in decision["preemptions"]:
            self._begin_drain(str(p["from"]), str(p["to"]), now)
        for r in decision.get("reclaims", []):
            # Overcommit shed — cannot arise under this fleet's
            # drain-then-grant ordering, handled for completeness.
            self._begin_drain(str(r["from"]), "", now)
        self._sample(now)
        return decision

    def _claims(self) -> List[JobClaim]:
        return [
            JobClaim(
                name=j.name, priority=j.priority, min_chips=j.min_chips,
                max_chips=j.max_chips, demand=j.demand,
                allocated=len(j.agents),
            )
            for j in self.jobs.values()
        ]

    def _begin_drain(self, donor: str, to_job: str, now: float) -> None:
        job = self.jobs[donor]
        aid = self._victim_agent(job)
        if aid is None:
            log.warning("fleet: preemption from %s found no agent", donor)
            return
        agent = job.agents[aid]
        agent.notify_preemption()
        self._pending.append(_PendingDrain(
            donor=donor, agent_id=aid, to_job=to_job, t_notice=now,
            deadline=now + self.drain_timeout_s,
        ))
        log.info("fleet: preempt notice -> %s/%s (chip destined for %s)",
                 donor, aid, to_job or "<free>")

    def _advance_drains(self, now: float) -> None:
        still: List[_PendingDrain] = []
        for d in self._pending:
            job = self.jobs[d.donor]
            drained = self._drained(job, d.agent_id)
            escalated = not drained and now >= d.deadline
            if not drained and not escalated:
                still.append(d)
                continue
            agent = job.agents.pop(d.agent_id, None)
            worker_alive = (agent is not None
                            and agent.worker_pid is not None)
            if agent is not None:
                agent.stop()  # the "kill": after the drain, by contract
            mark = {
                "job": d.donor, "agent": d.agent_id,
                "to_job": d.to_job,
                "t_notice": self._rel(d.t_notice),
                "t_stop": self._rel(now),
                "worker_alive_at_stop": bool(worker_alive),
                "escalated": bool(escalated),
            }
            self.preempt_drains.append(mark)
            self.moves.append({"t": self._rel(now), "from": d.donor,
                               "to": d.to_job, "chips": 1})
            log.info("fleet: drain of %s/%s complete (escalated=%s); "
                     "chip -> %s", d.donor, d.agent_id, escalated,
                     d.to_job or "<free>")
            if d.to_job:
                self._spawn_agent(self.jobs[d.to_job])
        self._pending = still

    def _sample(self, now: float) -> None:
        alloc = self.allocations()
        if (self.allocation_samples
                and self.allocation_samples[-1]["allocations"] == alloc
                and now - self.epoch
                - self.allocation_samples[-1]["t"] < 1.0):
            return  # bound growth: only changes + a 1 Hz heartbeat
        self.allocation_samples.append(
            {"t": self._rel(now), "allocations": alloc})

    # ------------------------------------------------------------- teardown
    def stop(self) -> None:
        for j in self.jobs.values():
            for agent in j.agents.values():
                try:
                    agent.stop()
                except Exception:
                    log.exception("fleet: agent stop failed")
            j.agents.clear()

    # --------------------------------------------------------- evidence doc
    def evidence(self) -> Dict[str, Any]:
        """The check-ready document: profile + decision log + samples +
        moves + drain marks (``sim/multijob.check_tenants`` judges it; the
        decision log byte-replays through the pure arbiter)."""
        return {
            "profile": {
                "total_chips": self.total_chips,
                "config": self.arbiter.config.to_dict(),
                "jobs": [
                    {"name": j.name, "priority": j.priority,
                     "min_chips": j.min_chips, "max_chips": j.max_chips,
                     "demand": [list(d) for d in j.demand_history]}
                    for j in sorted(self.jobs.values(),
                                    key=lambda j: j.name)
                ],
            },
            "decision_log": list(self.arbiter.log),
            "moves": list(self.moves),
            "allocation_samples": list(self.allocation_samples),
            "preempt_drains": list(self.preempt_drains),
            "final_allocations": self.allocations(),
        }


def run_fleet_loop(fleet: TenantFleet, stop: threading.Event,
                   interval_s: float = 0.25) -> threading.Thread:
    """Background ticker (the drill's control loop)."""
    def loop():
        while not stop.is_set():
            try:
                fleet.tick()
            except Exception:
                log.exception("fleet tick failed")
            stop.wait(interval_s)

    t = threading.Thread(target=loop, daemon=True, name="tenant-fleet")
    t.start()
    return t
