"""The continuous trainer: tail feedback spools, train, checkpoint
cursors atomically with the model, resume exactly-once.

The WAL/replay discipline applied to *input data*: the spool is the log,
the trainer's cursor is the replay boundary, and the joint checkpoint —
sparse tier snapshot (``client.save``) + dense arrays + spool cursors,
committed by ONE atomic pointer rename — is the cut. A SIGKILLed trainer
resumes by restoring all three halves of that cut (``client.restore``
rolls the PS tables back to the snapshot; the dense arrays and cursors
come from the pointer), then re-tails the spool from the cursor: every
event between the cut and the crash re-trains exactly once on top of
exactly the state it originally trained on, and nothing after the cut is
double-applied or dropped. The chaos drill proves it the strongest way
the repo knows: final table digests (optimizer rows included) and dense
digests bit-identical to a fault-free reference that consumed the same
stream once.

Training math lives in module functions (:func:`event_grads`,
:func:`dense_update`) shared VERBATIM by the live trainer and the
drill's reference replay — the two sides cannot drift.

Also runnable as a process (the drill's SIGKILL target)::

    python -m easydl_tpu.loop.continuous --workdir W --spool S \
        --shards 2 --table loop_emb --publish-dir W/models
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from easydl_tpu.loop.feedback import FeedbackBatcher, FeedbackEvent
from easydl_tpu.loop import publish as model_publish
from easydl_tpu.utils.logging import get_logger

log = get_logger("loop", "continuous")

_POINTER = "latest.json"


_metrics_cache: Optional[tuple] = None


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from easydl_tpu.obs import get_registry

        reg = get_registry()
        _metrics_cache = (
            reg.gauge(
                "easydl_loop_lag_seconds",
                "Freshness lag of the most recent trained batch: serve-"
                "time event emission → trained into the live tier. THE "
                "loop SLO signal (BENCH_LOOP.json gates its p99).",
                ("replica",)),
            reg.counter(
                "easydl_loop_trained_events_total",
                "Feedback events trained into the model.", ("replica",)),
            reg.counter(
                "easydl_loop_checkpoints_total",
                "Joint cursor+dense+sparse checkpoints committed.",
                ("replica",)),
        )
    return _metrics_cache


# ------------------------------------------------------------ training math
def event_grads(ev: FeedbackEvent, dim: int):
    """Deterministic sparse gradient for one feedback event: one f32 row
    per (row, field) id, a pure function of the event's bytes — the live
    trainer and the drill's fault-free reference compute the identical
    update from the identical spool record."""
    flat = np.ascontiguousarray(ev.ids.reshape(-1), np.int64)
    fields = ev.ids.shape[1] if ev.ids.ndim == 2 else 1
    labels = np.asarray(ev.labels, np.float32)
    lab = np.repeat(labels - np.float32(0.5), fields)
    base = ((flat % 1009).astype(np.float32) / np.float32(1009.0)
            - np.float32(0.5))
    col = ((np.arange(dim, dtype=np.float32) + np.float32(1.0))
           / np.float32(dim))
    g = (lab + base)[:, None] * col[None, :]
    return flat, np.ascontiguousarray(g, np.float32)


def fresh_dense(dim: int) -> Dict[str, np.ndarray]:
    return {"w": np.zeros(dim, np.float32), "b": np.zeros((), np.float32)}


def dense_update(dense: Dict[str, np.ndarray], ev: FeedbackEvent,
                 lr: float) -> None:
    """Deterministic in-place dense step for one event (sequential f32
    accumulation: a double-trained event provably moves the digest)."""
    labels = np.asarray(ev.labels, np.float32)
    err = np.float32(labels.mean(dtype=np.float32) - np.float32(0.5))
    feat = ((ev.ids.reshape(-1)[: len(dense["w"])] % 257)
            .astype(np.float32) / np.float32(257.0))
    if len(feat) < len(dense["w"]):
        feat = np.pad(feat, (0, len(dense["w"]) - len(feat)))
    dense["w"] += np.float32(lr) * err * feat
    dense["b"] += np.float32(lr) * err


def dense_digest(dense: Dict[str, np.ndarray]) -> str:
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for k in sorted(dense):
        h.update(k.encode())
        h.update(np.ascontiguousarray(dense[k], "<f4").tobytes())
    return h.hexdigest()


# --------------------------------------------------------------- the trainer
class ContinuousTrainer:
    """Tail spools → train → jointly checkpoint → publish.

    ``client`` is any PS client (ShardedPsClient against live pods, or
    LocalPsClient for the in-process reference/bench). The joint
    checkpoint commit order is the whole correctness story:

    1. ``client.save(ps_ckpt_dir, step)`` — the sparse half (every
       shard's ``.done`` markers make a torn save invisible);
    2. dense arrays → ``dense-<step>.npz`` (tmp + rename);
    3. the POINTER (``latest.json``: step, npz name, spool cursors,
       accounting) — tmp + fsync + rename: THIS is the commit;
    4. only then ``mark_consumed()`` — the spool writer may now retire
       segments, because the durable cursor covers them.

    A crash between any two steps resumes from the previous pointer; a
    pointer always names a sparse step and an npz that exist."""

    def __init__(self, client, table_spec, spool_dirs: List[str],
                 state_dir: str, ps_ckpt_dir: str,
                 publish_dir: Optional[str] = None,
                 batch_events: int = 8, ckpt_every_batches: int = 10,
                 publish_every_ckpts: int = 2, dense_dim: int = 8,
                 lr: float = 0.05, name: str = "loop-trainer",
                 label_horizon_s: Optional[float] = None):
        self.client = client
        self.table = table_spec
        self.state_dir = state_dir
        self.ps_ckpt_dir = ps_ckpt_dir
        self.publish_dir = publish_dir
        self.batch_events = int(batch_events)
        self.ckpt_every = int(ckpt_every_batches)
        self.publish_every = int(publish_every_ckpts)
        self.lr = float(lr)
        self.name = name
        os.makedirs(state_dir, exist_ok=True)
        self.batcher = FeedbackBatcher(spool_dirs,
                                       label_horizon_s=label_horizon_s)
        self.dense = fresh_dense(int(dense_dim))
        self.step = 0                 # committed checkpoint step (batches)
        self.batches = 0              # batches trained this lineage
        self.events_trained = 0       # events trained since last restore
        self.ckpts = 0
        self.published: List[int] = []
        client.create_table(table_spec)

    # ------------------------------------------------------------- restore
    def restore(self) -> Dict[str, Any]:
        """Resume from the last committed joint checkpoint (no-op on a
        fresh state dir). Returns evidence for the drill verdict."""
        pointer = os.path.join(self.state_dir, _POINTER)
        try:
            with open(pointer) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {"restored": False}
        with np.load(os.path.join(self.state_dir, doc["npz"])) as z:
            self.dense = {k: np.array(z[k]) for k in z.files}
        self.step = int(doc["step"])
        self.batches = self.step
        self.batcher.restore_state(doc.get("cursors", {}))
        if self.step > 0:
            # Roll the sparse tier back to the snapshot the cursor names:
            # events after it re-train on exactly the state they first
            # trained on — the exactly-once half the cursor alone can't
            # give (the tier kept the crashed run's extra pushes).
            self.client.restore(self.ps_ckpt_dir, self.step)
        cursors = doc.get("cursors", {})
        evidence = {
            "restored": True,
            "restored_step": self.step,
            "restored_cursor_events": {
                d: int((c or {}).get("events", 0))
                for d, c in cursors.items()},
            "published": list(doc.get("published", [])),
        }
        self.published = list(doc.get("published", []))
        log.info("continuous trainer resumed at step %d (cursors: %s)",
                 self.step, cursors)
        return evidence

    # ------------------------------------------------------------ training
    def train_batch(self, events: List[FeedbackEvent]) -> None:
        m = _metrics()
        now = time.time()
        for ev in events:
            flat, g = event_grads(ev, self.table.dim)
            self.client.push(self.table.name, flat, g, scale=self.lr)
            dense_update(self.dense, ev, self.lr)
        self.events_trained += len(events)
        self.batches += 1
        lag = max(0.0, now - min(ev.t for ev in events))
        m[0].set(lag, replica=self.name)
        m[1].inc(len(events), replica=self.name)

    def checkpoint(self) -> None:
        step = self.batches
        self.client.save(self.ps_ckpt_dir, step)
        npz = f"dense-{step:010d}.npz"
        tmp = os.path.join(self.state_dir, npz + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **self.dense)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.state_dir, npz))
        doc = {
            "step": step,
            "npz": npz,
            "cursors": self.batcher.state(),
            "events_trained": self.events_trained,
            "published": list(self.published),
            "dense_digest": dense_digest(self.dense),
        }
        pointer = os.path.join(self.state_dir, _POINTER)
        tmp = pointer + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, pointer)        # <- the commit
        self.step = step
        self.ckpts += 1
        self.batcher.mark_consumed()    # retirement only past the commit
        self._prune_checkpoints()
        _metrics()[2].inc(replica=self.name)
        if self.publish_dir and self.ckpts % self.publish_every == 0:
            v = model_publish.publish_version(
                self.publish_dir, self.dense,
                meta={"step": step, "events": self.events_trained,
                      "trainer": self.name})
            self.published.append(v)

    def _prune_checkpoints(self, keep: int = 3) -> None:
        """A continuous trainer never terminates: without retention the
        per-checkpoint dense npz files and sparse step dirs would grow
        without bound. Keep the newest ``keep`` of each, and NEVER
        anything at/above the committed pointer step backwards — only
        strictly older state the pointer can no longer name."""
        import glob as _glob
        import re as _re
        import shutil as _shutil

        npzs = sorted(_glob.glob(os.path.join(self.state_dir,
                                              "dense-*.npz")))
        for p in npzs[:-keep]:
            m = _re.search(r"dense-(\d+)\.npz$", p)
            if m and int(m.group(1)) < self.step:
                try:
                    os.remove(p)
                except OSError:
                    pass
        steps = sorted(_glob.glob(os.path.join(self.ps_ckpt_dir,
                                               "step_*")))
        for d in steps[:-keep]:
            m = _re.search(r"step_(\d+)$", d)
            if m and int(m.group(1)) < self.step:
                _shutil.rmtree(d, ignore_errors=True)

    def run(self, stop_check: Callable[[], bool],
            batch_timeout_s: float = 2.0) -> Dict[str, Any]:
        """Tail-train until ``stop_check()`` AND the spools are drained;
        exhausted spools block-with-timeout, they never terminate the
        loop. Ends with a final joint checkpoint."""
        while True:
            batch = self.batcher.next_batch(
                self.batch_events, timeout_s=batch_timeout_s,
                allow_partial=stop_check())
            if batch:
                self.train_batch(batch)
                if self.batches % self.ckpt_every == 0:
                    self.checkpoint()
                continue
            if stop_check():
                break
        if self.batches > self.step:
            self.checkpoint()
        return {
            "step": self.step,
            "events_trained": self.events_trained,
            "published": list(self.published),
            "dense_digest": dense_digest(self.dense),
            "batcher": dict(self.batcher.stats),
        }


# --------------------------------------------------------- reference replay
def reference_replay(spool_dirs: List[str], table_spec, num_shards: int,
                     batch_events: int, dense_dim: int, lr: float,
                     ckpt_every_batches: int = 10**9):
    """Fault-free in-process replay of the same spool stream, exactly
    once, through the SAME math — the drill's digest oracle. Returns the
    (LocalPsClient, trainer) pair after consuming everything readable."""
    from easydl_tpu.ps.client import LocalPsClient

    client = LocalPsClient(num_shards=num_shards, coalesce=False)
    import tempfile

    tmp = tempfile.mkdtemp(prefix="loop-ref-")
    trainer = ContinuousTrainer(
        client, table_spec, spool_dirs,
        state_dir=os.path.join(tmp, "state"),
        ps_ckpt_dir=os.path.join(tmp, "ps-ckpt"),
        publish_dir=None, batch_events=batch_events,
        ckpt_every_batches=ckpt_every_batches, dense_dim=dense_dim,
        lr=lr, name="loop-reference", label_horizon_s=3600.0)
    while True:
        batch = trainer.batcher.next_batch(batch_events, timeout_s=0.0,
                                           allow_partial=True)
        if not batch:
            break
        trainer.train_batch(batch)
    return client, trainer


# ------------------------------------------------------------------ process
def main(argv: Optional[List[str]] = None) -> int:
    """The SIGKILL-able process shape of the trainer (the chaos drill's
    target): connects to the registry-backed PS tier, restores the joint
    checkpoint if one exists, and tail-trains until ``--stop-file``
    appears and the spools drain."""
    ap = argparse.ArgumentParser(description="continuous feedback trainer")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--spool", action="append", required=True,
                    help="feedback spool dir (repeatable)")
    ap.add_argument("--table", default="loop_emb")
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--optimizer", default="adagrad")
    ap.add_argument("--shards", type=int, required=True)
    ap.add_argument("--batch-events", type=int, default=8)
    ap.add_argument("--ckpt-every", type=int, default=10)
    ap.add_argument("--publish-every", type=int, default=2)
    ap.add_argument("--publish-dir", default=None)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--stop-file", required=True)
    ap.add_argument("--status-file", required=True)
    ap.add_argument("--name", default="loop-trainer")
    args = ap.parse_args(argv)

    from easydl_tpu.ps.client import ShardedPsClient
    from easydl_tpu.ps.table import TableSpec

    def status(doc: Dict[str, Any]) -> None:
        with open(args.status_file, "a") as f:
            f.write(json.dumps(dict(doc, pid=os.getpid(),
                                    t=time.time())) + "\n")

    spec = TableSpec(name=args.table, dim=args.dim,
                     optimizer=args.optimizer, seed=11, lr=0.05)
    from easydl_tpu.obs import get_registry, start_exporter
    exporter = start_exporter(component=args.name, registry=get_registry(),
                              workdir=args.workdir)
    client = ShardedPsClient.from_registry(
        args.workdir, args.shards, timeout=5.0,
        drain_retry_s=120.0, transient_retry_s=60.0)
    try:
        trainer = ContinuousTrainer(
            client, spec, list(args.spool),
            state_dir=os.path.join(args.workdir, "loop-state"),
            ps_ckpt_dir=os.path.join(args.workdir, "loop-ps-ckpt"),
            publish_dir=args.publish_dir,
            batch_events=args.batch_events,
            ckpt_every_batches=args.ckpt_every,
            publish_every_ckpts=args.publish_every,
            dense_dim=args.dim, lr=args.lr, name=args.name)
        evidence = trainer.restore()
        status(dict(evidence, phase="started"))
        summary = trainer.run(
            stop_check=lambda: os.path.exists(args.stop_file))
        status(dict(summary, phase="done"))
    finally:
        client.close()
        # clean exits deregister: only a KILLED trainer leaves its
        # discovery doc behind for the fleet_scrape_health SLO to see.
        if exporter is not None:
            exporter.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
