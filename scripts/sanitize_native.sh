#!/usr/bin/env bash
# TSan/ASan gate for the C++ cores (SURVEY.md §5.2 — the reference had no
# sanitizers, CI was lint-only). Builds each core with the sanitizer runtime
# plus a stress driver that hammers the concurrent paths, and fails on any
# report. Run locally or in CI: scripts/sanitize_native.sh [tsan|asan|all]
set -euo pipefail
cd "$(dirname "$0")/.."
mode="${1:-all}"
build() {  # $1 sanitizer flag, $2 tag
  local flag="$1" tag="$2" out
  out="$(mktemp -d)"
  # -lrt: shm_open/shm_unlink (the zero-copy pull mirror) live in librt
  # on this image's glibc. The stress driver hammers concurrent pushes
  # against shm gathers, so the seqlock protocol itself is under the
  # sanitizer here.
  g++ -O1 -g -std=c++17 -fsanitize="$flag" -fno-omit-frame-pointer -Wall \
    -o "$out/eds_stress" \
    easydl_tpu/ps/native/embedding_store_stress.cc -lpthread -lrt
  "$out/eds_stress"
  echo "embedding store: $tag clean"
  g++ -O1 -g -std=c++17 -fsanitize="$flag" -fno-omit-frame-pointer -Wall \
    -o "$out/edr_stress" \
    easydl_tpu/controller/native/reconciler_stress.cc -lpthread
  "$out/edr_stress"
  echo "reconciler core: $tag clean"
  g++ -O1 -g -std=c++17 -fsanitize="$flag" -fno-omit-frame-pointer -Wall \
    -o "$out/edb_stress" \
    easydl_tpu/brain/native/brain_stress.cc -lpthread
  "$out/edb_stress"
  echo "brain core: $tag clean"
  rm -rf "$out"
}
[[ "$mode" == "tsan" || "$mode" == "all" ]] && build thread tsan
[[ "$mode" == "asan" || "$mode" == "all" ]] && build address,undefined asan+ubsan
echo "sanitizers OK"
