"""Pure multiwindow burn-rate alerting — the detection half of the Brain.

The stack exports ~60 metric series and survives every drill in the
chaos catalog, but nothing turned those series into "page a human, and
here's the runbook section". This module is the decision core that does:
:func:`alert_decision` maps (SLO specs, a short window of fleet metric
snapshots, the prior alert state, now) → the canonical alert document,
in the multiwindow burn-rate shape of Google's SRE Workbook ch. 5 — an
alert fires only when BOTH a long and a short window burn through the
objective's error budget (the long window rejects blips, the short
window makes the page stop quickly once the burn stops), and it clears
once the long window is clean again.

Like every policy in ``brain/`` (easylint rule 5 ``PURE_PATHS``), the
function is PURE: no clock, no RNG, no I/O — every input it consumes is
in its argument list, the stateful :class:`AlertPolicy` wrapper logs the
FULL inputs next to each verdict, and :func:`replay_decision_log`
re-derives every live decision offline and byte-compares
(:func:`decision_bytes`) — the chaos drills' detection evidence is
accepted only when that replay is identical.

Three objective shapes cover the shipped SLOs (``slos/*.yaml``, loaded
and validated by :mod:`easydl_tpu.obs.slo`):

- ``ratio`` — bad-event / total-event counter deltas over each window,
  divided by the error ``budget`` (the allowed bad fraction): the
  classic burn rate. No traffic → no burn (a silent fleet is not an
  outage; dead exporters have their own SLO).
- ``bound`` — a gauge compared against a threshold; the "burn" is the
  fraction of snapshots in the window that breach. ``ignore_zero``
  exempts exact zeros (``easydl_worker_mfu`` is 0 when the model
  publishes no FLOP hint — idle instrumentation, not an outage).
- ``increase`` — a counter that should not move at all (failovers,
  quarantines, ejections): any delta beyond ``max_increase`` in both
  windows fires; the alert clears ``long_s`` after the last increment.

Series selectors are canonical sample keys — ``name`` (every labelset of
the family, counters summed / gauges max-ed) or ``name{k="v"}`` (only
labelsets containing those pairs), matching the sorted-label
serialization both :meth:`MetricsRegistry.samples` and
``obs.scrape.parse_text`` emit. NaN samples are treated as absent —
scrape text can carry them and arithmetic must not.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Mapping, Sequence, Tuple

__all__ = [
    "alert_decision",
    "decision_bytes",
    "match_series",
    "parse_selector",
    "AlertPolicy",
    "replay_decision_log",
]

#: severities an SLO may declare; "page" wakes a human, "ticket" waits
#: for business hours — the fault-free negative control is stated over
#: pages only.
SEVERITIES = ("page", "ticket")


def parse_selector(selector: str) -> Tuple[str, Dict[str, str]]:
    """``name{k="v",k2="v2"}`` → (name, {k: v}); bare names select the
    whole family. Tolerates only the canonical serialization the
    registry and the scraper emit — selectors come from validated SLO
    specs, not from the wire."""
    sel = selector.strip()
    if "{" not in sel:
        return sel, {}
    name, _, inner = sel.partition("{")
    labels: Dict[str, str] = {}
    inner = inner.rstrip("}")
    if inner:
        for pair in inner.split(","):
            k, _, v = pair.partition("=")
            labels[k.strip()] = v.strip().strip('"')
    return name, labels


def match_series(selector: str, samples: Mapping[str, float]) -> Dict[str, float]:
    """Every sample key the selector covers → value. NaN values are
    dropped here so no downstream arithmetic ever sees one."""
    name, want = parse_selector(selector)
    out: Dict[str, float] = {}
    for key, value in samples.items():
        v = float(value)
        if v != v:  # NaN — scrape text can carry it; arithmetic must not
            continue
        base, _, inner = key.partition("{")
        if base != name:
            continue
        if want:
            have: Dict[str, str] = {}
            for pair in inner.rstrip("}").split(","):
                k, _, val = pair.partition("=")
                have[k] = val.strip('"')
            if any(have.get(k) != v2 for k, v2 in want.items()):
                continue
        out[key] = v
    return out


def _window(history: Sequence[Mapping[str, Any]], now: float,
            span_s: float) -> List[Mapping[str, Any]]:
    lo = now - float(span_s)
    return [h for h in history if lo <= float(h.get("t", 0.0)) <= now]


def _delta(selector: str, rounds: Sequence[Mapping[str, Any]]) -> float:
    """Summed per-series counter increase across a window. A series
    absent at the window start counts from 0 (fresh registries start
    there); a series that vanishes (its pod died) contributes nothing —
    the monotone clamp keeps a shrinking additive merge from reading as
    negative traffic."""
    if not rounds:
        return 0.0
    end = match_series(selector, rounds[-1].get("s") or {})
    start_samples = match_series(selector, rounds[0].get("s") or {})
    total = 0.0
    for key, v_end in end.items():
        total += max(0.0, v_end - start_samples.get(key, 0.0))
    return total


def _breach_fraction(objective: Mapping[str, Any],
                     rounds: Sequence[Mapping[str, Any]]) -> float:
    """bound objectives: fraction of window snapshots where any covered
    series breaches. Snapshots where the series is absent count as
    healthy — absence is the scrape-health SLO's job."""
    if not rounds:
        return 0.0
    op = str(objective.get("op", "gt"))
    bound = float(objective.get("bound", 0.0))
    ignore_zero = bool(objective.get("ignore_zero", False))
    breached = 0
    for h in rounds:
        values = match_series(str(objective.get("series", "")),
                              h.get("s") or {})
        hit = False
        for v in values.values():
            if ignore_zero and v == 0.0:
                continue
            if (v > bound) if op == "gt" else (v < bound):
                hit = True
                break
        breached += 1 if hit else 0
    return breached / len(rounds)


def _burn(objective: Mapping[str, Any],
          rounds: Sequence[Mapping[str, Any]]) -> float:
    kind = str(objective.get("type", ""))
    if kind == "ratio":
        total = _delta(str(objective.get("total", "")), rounds)
        if total <= 0.0:
            return 0.0
        bad = _delta(str(objective.get("bad", "")), rounds)
        budget = max(1e-9, float(objective.get("budget", 1.0)))
        return (bad / total) / budget
    if kind == "bound":
        return _breach_fraction(objective, rounds)
    if kind == "increase":
        inc = _delta(str(objective.get("series", "")), rounds)
        return 1.0 if inc > float(objective.get("max_increase", 0.0)) else 0.0
    return 0.0


def alert_decision(specs: Sequence[Mapping[str, Any]],
                   history: Sequence[Mapping[str, Any]],
                   prior: Mapping[str, Mapping[str, Any]],
                   now: float) -> Dict[str, Any]:
    """One evaluation round → the canonical alert document.

    ``history`` is the evaluator's snapshot window, oldest first:
    ``[{"t": wall_s, "s": {sample_key: value}}, ...]``; ``prior`` the
    previous round's ``{slo: {"active", "since"}}`` state. Returns::

        {"now": r6, "alerts": {slo: {"active", "severity", "since",
                                     "burn_long", "burn_short"}},
         "firing": [slo...], "pages": [slo...],
         "transitions": [{"slo", "to"}]}

    Fire requires BOTH windows over threshold; once active, the alert
    holds while the LONG window still burns (the short window going
    quiet alone must not flap the page) and clears when it stops. The
    function never mutates its inputs."""
    now = round(float(now), 6)
    hist = sorted((dict(h) for h in history),
                  key=lambda h: float(h.get("t", 0.0)))
    alerts: Dict[str, Any] = {}
    transitions: List[Dict[str, str]] = []
    for spec in specs:
        name = str(spec.get("name", ""))
        objective = dict(spec.get("objective") or {})
        windows = dict(spec.get("windows") or {})
        long_s = float(windows.get("long_s", 6.0))
        short_s = float(windows.get("short_s", 1.5))
        threshold = float(spec.get("burn_threshold", 1.0))
        burn_long = _burn(objective, _window(hist, now, long_s))
        burn_short = _burn(objective, _window(hist, now, short_s))
        was = dict(prior.get(name) or {})
        was_active = bool(was.get("active", False))
        if was_active:
            active = burn_long >= threshold
        else:
            active = burn_long >= threshold and burn_short >= threshold
        since = float(was.get("since", now)) if was_active and active else now
        alerts[name] = {
            "active": active,
            "severity": str(spec.get("severity", "ticket")),
            "since": round(since, 6),
            "burn_long": round(burn_long, 6),
            "burn_short": round(burn_short, 6),
        }
        if active != was_active:
            transitions.append({"slo": name,
                                "to": "firing" if active else "clear"})
    firing = sorted(n for n, a in alerts.items() if a["active"])
    return {
        "now": now,
        "alerts": {n: alerts[n] for n in sorted(alerts)},
        "firing": firing,
        "pages": [n for n in firing if alerts[n]["severity"] == "page"],
        "transitions": transitions,
    }


def decision_bytes(decision: Mapping[str, Any]) -> bytes:
    """Canonical serialization — the byte identity the offline replay
    gate (chaos verdicts, slo_report --smoke) is stated over."""
    return json.dumps(decision, sort_keys=True,
                      separators=(",", ":")).encode()


class AlertPolicy:
    """Stateful wrapper owning the active/since bookkeeping — shared
    verbatim between the live :class:`~easydl_tpu.obs.alerts.AlertEvaluator`
    and the fleet-scale simulator, so the two can never drift. Every
    entry point takes ``now`` (virtual-clock-pure)."""

    def __init__(self, specs: Sequence[Mapping[str, Any]]):
        #: canonical spec documents (plain JSON data) — logged with every
        #: decision so a record replays with no side channel
        self.specs: List[Dict[str, Any]] = [
            json.loads(json.dumps(dict(s), sort_keys=True)) for s in specs]
        #: slo -> {"active", "since"} carried between rounds
        self.state: Dict[str, Dict[str, Any]] = {}
        #: decision records ({"inputs": ..., "verdict": ...}) in order —
        #: what the ledger persists and the replay re-derives
        self.log: List[Dict[str, Any]] = []

    def evaluate(self, history: Sequence[Mapping[str, Any]],
                 now: float) -> Dict[str, Any]:
        """Evaluate once; appends the full (inputs, verdict) record to
        :attr:`log`. The inputs snapshot (including the prior state) is
        taken BEFORE the state advances — replaying it through
        :func:`alert_decision` must reproduce the verdict bytes."""
        now = round(float(now), 6)
        hist = [{"t": float(h.get("t", 0.0)), "s": dict(h.get("s") or {})}
                for h in history]
        inputs = {
            "specs": self.specs,
            "history": hist,
            "prior": {k: dict(v) for k, v in sorted(self.state.items())},
            "now": now,
        }
        decision = alert_decision(self.specs, hist, self.state, now)
        self.state = {
            name: {"active": a["active"], "since": a["since"]}
            for name, a in decision["alerts"].items()
        }
        self.log.append({"inputs": inputs, "verdict": decision})
        return decision


def replay_decision_log(records: Sequence[Mapping[str, Any]]
                        ) -> Dict[str, Any]:
    """Re-derive every logged verdict from its own recorded inputs
    through the pure function and byte-compare — the offline half of
    every drill's ``detected_and_cleared`` gate. Returns::

        {"decisions": N, "identical": bool, "mismatches": [...]}
    """
    mismatches: List[Dict[str, Any]] = []
    for i, rec in enumerate(records):
        inputs = dict(rec.get("inputs") or {})
        want = rec.get("verdict")
        got = alert_decision(
            list(inputs.get("specs") or []),
            list(inputs.get("history") or []),
            dict(inputs.get("prior") or {}),
            float(inputs.get("now", 0.0)),
        )
        if want is None or decision_bytes(got) != decision_bytes(want):
            mismatches.append({
                "index": i, "recorded": want, "replayed": got,
            })
    return {
        "decisions": len(records),
        "identical": not mismatches and len(records) > 0,
        "mismatches": mismatches[:5],
    }
