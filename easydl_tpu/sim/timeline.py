"""Signal timelines for the control-plane simulator: the recorded (or
synthesized) per-agent step streams + fault markers a simulation replays.

A timeline is a plain JSON-serializable document — the committed fixture
format — with three parts:

- ``agents``: per agent, the ordered list of ``[step_time_s,
  samples_per_sec, world_size]`` samples its worker produced. This is the
  *signal* stream: the simulator's worker model replays these durations one
  step at a time, so the control plane under test sees exactly the step
  times a real (or imagined) fleet produced.
- ``faults``: control-plane inputs at relative timestamps —
  ``straggler`` (synthetic slowdown windows; ``inject`` false when the
  slowdown is already baked into recorded durations and the marker only
  anchors invariant budgets), ``preempt_notice``, ``kill`` (the VM dies:
  worker SIGKILL + agent silence), ``agent_down``.
- ``meta``: job facts the worker model needs (``total_steps``,
  ``ckpt_interval``) plus provenance.

``load_workdir`` turns any kept chaos/job workdir into a timeline: the
``metrics-<agent>.jsonl`` streams (PR 1) become the signal streams, and the
workdir's ``chaos-plan.json`` — when present — becomes the fault markers,
re-anchored from wall clock to the recording's own t axis. That is the
"incident replay" path: scripts/policy_replay.py feeds the result through
the REAL Autoscaler/Rendezvous/StragglerDetector in milliseconds.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Mapping, Optional

from easydl_tpu.utils.logging import get_logger

log = get_logger("sim", "timeline")

#: fault kinds a timeline may carry (superset-checked at load)
FAULT_KINDS = ("straggler", "preempt_notice", "kill", "agent_down")


def _round(x: float, nd: int = 6) -> float:
    return round(float(x), nd)


def make_timeline(name: str, agents: Mapping[str, List[List[float]]],
                  faults: Optional[List[Dict[str, Any]]] = None,
                  meta: Optional[Dict[str, Any]] = None,
                  source: str = "synthetic") -> Dict[str, Any]:
    """Assemble + validate a timeline document."""
    doc = {
        "name": str(name),
        "source": str(source),
        "agents": {
            str(a): [[_round(s[0]), _round(s[1]), int(s[2])]
                     for s in stream]
            for a, stream in sorted(agents.items())
        },
        "faults": sorted(
            (dict(f) for f in (faults or [])),
            key=lambda f: (float(f["t"]), str(f.get("agent", ""))),
        ),
        "meta": dict(meta or {}),
    }
    for f in doc["faults"]:
        if f.get("kind") not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {f.get('kind')!r} "
                             f"(known: {FAULT_KINDS})")
        f["t"] = _round(f["t"])
    return doc


def save_fixture(timeline: Mapping[str, Any], path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        # Compact rows, but one line per top-level key stays greppable:
        # sort_keys + fixed separators also make re-recording the same
        # workdir byte-stable.
        json.dump(timeline, f, sort_keys=True, separators=(",", ":"))
        f.write("\n")
    os.replace(tmp, path)


def load_fixture(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    return make_timeline(
        doc.get("name", os.path.basename(path)),
        doc.get("agents", {}), doc.get("faults", []),
        doc.get("meta", {}), doc.get("source", path),
    )


# ------------------------------------------------------------- recordings
def load_workdir(workdir: str, name: Optional[str] = None) -> Dict[str, Any]:
    """Build a timeline from a kept job/chaos workdir.

    Signal streams come from ``metrics-<agent>.jsonl``; records are sorted
    by wall time and deduped by (generation, step) — a killed worker's torn
    tail lines are skipped, matching the chaos invariant readers. Faults
    come from ``chaos-plan.json`` when the drill kept one AND stamped t0;
    ``straggler`` events are marked ``inject: false`` (the slowdown is
    already in the recorded durations — re-applying it would double-count).
    All timestamps are re-anchored so t=0 is the earliest step record."""
    streams: Dict[str, List[List[float]]] = {}
    times: Dict[str, List[float]] = {}
    t_base: Optional[float] = None
    for fn in sorted(os.listdir(workdir)):
        if not (fn.startswith("metrics-") and fn.endswith(".jsonl")):
            continue
        agent = fn[len("metrics-"):-len(".jsonl")]
        recs: List[Dict[str, Any]] = []
        with open(os.path.join(workdir, fn)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    continue  # torn tail from a killed worker
        recs.sort(key=lambda r: float(r.get("t", 0.0)))
        seen = set()
        stream: List[List[float]] = []
        ts: List[float] = []
        for r in recs:
            try:
                key = (int(r.get("generation", 0)), int(r["step"]))
                dt = float(r["step_time_s"])
            except (KeyError, TypeError, ValueError):
                continue
            if key in seen or dt <= 0:
                continue
            seen.add(key)
            stream.append([dt, float(r.get("samples_per_sec", 0.0)),
                           int(r.get("world_size", 1))])
            ts.append(float(r.get("t", 0.0)))
        if stream:
            streams[agent] = stream
            times[agent] = ts
            first = ts[0]
            t_base = first if t_base is None else min(t_base, first)
    if not streams:
        raise ValueError(f"no usable metrics-*.jsonl streams in {workdir}")

    faults = _faults_from_chaos_plan(workdir, t_base or 0.0)
    meta: Dict[str, Any] = {
        "recorded_from": os.path.basename(os.path.abspath(workdir)),
        "total_steps": _total_steps_from_job(workdir, streams),
        "ckpt_interval": _ckpt_interval_from_job(workdir),
    }
    return make_timeline(
        name or (os.path.basename(os.path.abspath(workdir)) or "recorded"),
        streams, faults, meta, source=os.path.abspath(workdir),
    )


def _faults_from_chaos_plan(workdir: str, t_base: float
                            ) -> List[Dict[str, Any]]:
    path = os.path.join(workdir, "chaos-plan.json")
    try:
        with open(path) as f:
            plan = json.load(f)
    except (OSError, ValueError):
        return []
    t0 = plan.get("t0")
    if t0 is None:
        return []
    out: List[Dict[str, Any]] = []
    for ev in plan.get("events", []):
        kind = str(ev.get("kind", ""))
        target = dict(ev.get("target", {}))
        params = dict(ev.get("params", {}))
        rel = float(t0) + float(ev.get("start_s", 0.0)) - t_base
        if kind == "straggler":
            out.append({
                "t": rel, "kind": "straggler",
                "agent": str(target.get("agent", "")),
                "end_t": float(t0) + float(ev.get("end_s", 0.0)) - t_base,
                "params": params,
                # recorded: the sleep already shows in the durations
                "inject": False,
            })
        elif kind == "preempt_notice":
            out.append({"t": rel, "kind": "preempt_notice",
                        "agent": str(target.get("agent", ""))})
        elif kind == "worker_kill":
            out.append({"t": rel, "kind": "kill",
                        "agent": str(target.get("agent", "")),
                        "params": params})
        elif kind == "agent_stop":
            out.append({"t": rel, "kind": "agent_down",
                        "agent": str(target.get("agent", ""))})
        # other kinds (rpc_*, heartbeat_suppress, ps_*, master_crash) have
        # no control-plane-simulator equivalent yet; they are dropped.
    return out


def _total_steps_from_job(workdir: str,
                          streams: Mapping[str, List[List[float]]]) -> int:
    try:
        with open(os.path.join(workdir, "job.json")) as f:
            return int(json.load(f).get("total_steps", 0))
    except (OSError, ValueError):
        return max(len(s) for s in streams.values())


def _ckpt_interval_from_job(workdir: str) -> int:
    try:
        with open(os.path.join(workdir, "job.json")) as f:
            return int(json.load(f).get("ckpt_interval", 100))
    except (OSError, ValueError):
        return 100


# ------------------------------------------------------------- synthetic
def _lcg_noise(seed: int):
    """Tiny deterministic noise source (no global RNG, no wall clock):
    yields floats in [0, 1)."""
    state = (seed * 6364136223846793005 + 1442695040888963407) & (2**64 - 1)
    while True:
        state = (state * 6364136223846793005 + 1442695040888963407) \
            & (2**64 - 1)
        yield (state >> 11) / float(2**53)


def synthetic_straggler(name: str = "synthetic_straggler",
                        n_agents: int = 3, base_dt: float = 0.05,
                        noise: float = 0.1, straggle_factor: float = 10.0,
                        straggle_at: float = 12.0,
                        straggle_agent: str = "a0",
                        total_steps: int = 2000, duration_s: float = 90.0,
                        seed: int = 7) -> Dict[str, Any]:
    """N agents stepping at ``base_dt`` (±noise); one turns ``factor``×
    slower at ``straggle_at`` and stays slow. The straggler fault is
    ``inject: true`` — the simulator applies the slowdown, so the SAME
    base stream serves the tuned policy and the mis-tuned negative
    control."""
    rng = _lcg_noise(seed)
    agents: Dict[str, List[List[float]]] = {}
    steps = int(duration_s / base_dt) + 8
    for i in range(n_agents):
        stream = []
        for _ in range(steps):
            dt = base_dt * (1.0 + noise * (2.0 * next(rng) - 1.0))
            stream.append([dt, 32.0 / dt, 1])
        agents[f"a{i}"] = stream
    faults = [{
        "t": straggle_at, "kind": "straggler", "agent": straggle_agent,
        "end_t": duration_s, "inject": True,
        "params": {"factor": straggle_factor},
    }]
    return make_timeline(
        name, agents, faults,
        meta={"total_steps": total_steps, "ckpt_interval": 200,
              "duration_s": duration_s},
    )


def synthetic_autoscale(name: str = "synthetic_autoscale",
                        n_agents: int = 4, total_steps: int = 1500,
                        duration_s: float = 150.0) -> Dict[str, Any]:
    """Scale-up ramp for the real Autoscaler: per-world (dt, rate) profile
    with efficiency 1.0 → 0.94 → 0.78, so a correctly-damped policy climbs
    1→2→4 workers and then HOLDS (the 4→8 step would land under the
    efficiency floor)."""
    agents = {f"a{i}": [[0.05, 640.0, 1]] * 4 for i in range(n_agents)}
    return make_timeline(
        name, agents, [],
        meta={
            "total_steps": total_steps, "ckpt_interval": 200,
            "duration_s": duration_s,
            # world size → [step_time_s, global samples_per_sec]
            "world_profile": {
                "1": [0.05, 640.0],
                "2": [0.0533, 1200.0],   # eff 0.9375 ≥ floor: keep going
                "3": [0.052, 1700.0],
                "4": [0.064, 2000.0],    # eff 0.78 < floor: hold here
            },
        },
    )


def synthetic_mesh_autoscale(name: str = "synthetic_mesh_autoscale",
                             n_agents: int = 33, base_dt: float = 0.1,
                             preempt_at: float = 6.0, grace_s: float = 4.0,
                             total_steps: int = 100_000,
                             duration_s: float = 150.0) -> Dict[str, Any]:
    """ISSUE 12's offline acceptance scenario: a preemption mid-run, then
    an autoscale ramp 8 -> 16 -> 32 workers, over a per-(world, shape)
    performance surface where the BEST factorization changes with scale —
    pure DP wins at 8 chips, but at 32 the 3D ``dp=8,fsdp=2,tp=2`` cell is
    ~17% faster than ``dp=32`` (gradient all-reduce over 32 ways saturates
    the slow axis; sharding the model trades it for cheap ICI traffic —
    the shape the paper's TPU-native premise exists for). A correct
    mesh-shape policy must probe its way there; the static-pod oracle is
    the best cell at the final world, and the convergence invariant allows
    <5% loss against it. The pinned negative control replays the SAME
    surface with the policy nailed to a pathological shape and must be
    caught.

    One preempted member (``a0``: notice, then the VM dies) exercises the
    decided-shape-survives-a-reshape path; 33 agents = 32 survivors, so
    every ramp stage has a full membership to form.
    """
    agents = {f"a{i:02d}": [[base_dt, 1600.0, 1]] * 4
              for i in range(n_agents)}
    faults = [
        {"t": preempt_at, "kind": "preempt_notice", "agent": "a00"},
        {"t": preempt_at + grace_s, "kind": "kill", "agent": "a00",
         "params": {"vm_dies": True}},
    ]
    return make_timeline(
        name, agents, faults,
        meta={
            "total_steps": total_steps, "ckpt_interval": 100,
            "duration_s": duration_s,
            # world -> shape key -> [step_time_s, global samples_per_sec].
            # Scaling efficiency vs the converged 8-world cell (200/chip)
            # stays above the autoscaler's 0.8 floor at every stage.
            "shape_profile": {
                "8": {
                    "dp=8": [0.1, 1600.0],
                    "dp=4,fsdp=2": [0.104, 1540.0],
                    "dp=4,tp=2": [0.12, 1330.0],
                    "dp=2,fsdp=2,tp=2": [0.128, 1250.0],
                },
                "16": {
                    "dp=16": [0.11, 2900.0],
                    "dp=8,fsdp=2": [0.104, 3080.0],
                    "dp=8,tp=2": [0.12, 2660.0],
                    "dp=4,fsdp=2,tp=2": [0.116, 2760.0],
                },
                "32": {
                    "dp=32": [0.116, 5450.0],
                    "dp=16,fsdp=2": [0.12, 5330.0],
                    "dp=16,tp=2": [0.13, 4920.0],
                    "dp=8,fsdp=2,tp=2": [0.1, 6400.0],
                },
            },
        },
    )


def synthetic_preempt(name: str = "synthetic_preempt",
                      n_agents: int = 2, base_dt: float = 0.05,
                      notice_at: float = 10.0, grace_s: float = 8.0,
                      target_agent: str = "a0", total_steps: int = 1500,
                      duration_s: float = 120.0,
                      seed: int = 11) -> Dict[str, Any]:
    """A preemption notice to one member at ``notice_at``, the VM SIGKILL
    ``grace_s`` later — the race the proactive-drain invariant judges."""
    rng = _lcg_noise(seed)
    agents: Dict[str, List[List[float]]] = {}
    steps = int(duration_s / base_dt) + 8
    for i in range(n_agents):
        stream = []
        for _ in range(steps):
            dt = base_dt * (1.0 + 0.05 * (2.0 * next(rng) - 1.0))
            stream.append([dt, 32.0 / dt, 1])
        agents[f"a{i}"] = stream
    faults = [
        {"t": notice_at, "kind": "preempt_notice", "agent": target_agent},
        {"t": notice_at + grace_s, "kind": "kill", "agent": target_agent,
         "params": {"vm_dies": True}},
    ]
    return make_timeline(
        name, agents, faults,
        meta={"total_steps": total_steps, "ckpt_interval": 200,
              "duration_s": duration_s},
    )
