"""Run chaos drills against the simulated-distributed runtime and write one
``CHAOS_r*_<scenario>.json`` verdict per scenario.

The executable half of the chaos subsystem (docs/design/chaos.md): each
scenario launches a real job (gRPC master + agents + jax.distributed worker
subprocesses on the forced CPU mesh, PS pods where the scenario needs them),
injects its seed-deterministic fault schedule, and asserts the recovery
invariants. Exit code is non-zero when any scenario's invariants fail — this
is a gate, not a report.

Usage::

    python scripts/chaos_run.py                       # every scenario
    python scripts/chaos_run.py --scenario worker_kill
    python scripts/chaos_run.py --scenario master_crash   # failover drill
    python scripts/chaos_run.py --scenario rpc_burst --seed 99
    python scripts/chaos_run.py --list

Must run where jax can use a CPU platform; spawns its own subprocess with
the forced-CPU env (like measure_recovery.py) if the current backend is not.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from easydl_tpu.utils.env import knob_raw  # noqa: E402


def next_round(out_dir: str) -> int:
    rounds = [0]
    for path in glob.glob(os.path.join(out_dir, "CHAOS_r*.json")):
        m = re.match(r"CHAOS_r(\d+)", os.path.basename(path))
        if m:
            rounds.append(int(m.group(1)))
    return max(rounds) + 1


def main() -> None:
    ap = argparse.ArgumentParser(description="easydl_tpu chaos drills")
    ap.add_argument("--scenario", action="append", default=None,
                    help="scenario name (repeatable; default: all)")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the scenario's default seed")
    ap.add_argument("--out-dir", default=REPO,
                    help="where CHAOS_r*.json verdicts land")
    ap.add_argument("--round", type=int, default=None,
                    help="verdict round number (default: auto-increment)")
    ap.add_argument("--keep-workdir", action="store_true",
                    help="keep each scenario's job workdir for autopsy")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    args = ap.parse_args()

    if knob_raw("EASYDL_CHAOS_CHILD") != "1" and not args.list:
        import jax

        if jax.default_backend() != "cpu":
            # Same self-bootstrap as measure_recovery: the drills need a
            # multi-device CPU platform, not the TPU tunnel.
            import subprocess

            from easydl_tpu.utils.env import cpu_subprocess_env

            env = cpu_subprocess_env(8)
            env["EASYDL_CHAOS_CHILD"] = "1"
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            # No wall-clock cap here: each scenario bounds itself (steady +
            # done timeouts); an outer timeout would SIGKILL the child
            # mid-scenario and lose the in-flight verdict on a slow box.
            raise SystemExit(subprocess.run(
                [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                env=env, cwd=REPO,
            ).returncode)

    from easydl_tpu.chaos.harness import SCENARIOS, run_scenario

    if args.list:
        for name, builder in SCENARIOS.items():
            sc = builder()
            print(f"{name:24s} seed={sc.chaos.seed:<4d} "
                  f"tier={sc.tier:7s} {sc.chaos.notes}")
        return

    names = args.scenario or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        raise SystemExit(f"unknown scenario(s) {unknown}; "
                         f"known: {sorted(SCENARIOS)}")
    os.makedirs(args.out_dir, exist_ok=True)
    rnd = args.round if args.round is not None else next_round(args.out_dir)
    failed = []
    for name in names:
        t0 = time.monotonic()
        print(f"=== chaos scenario {name} (round {rnd}) ===", flush=True)
        verdict = run_scenario(name, seed=args.seed,
                               keep_workdir=args.keep_workdir)
        out = os.path.join(args.out_dir, f"CHAOS_r{rnd:02d}_{name}.json")
        with open(out, "w") as f:
            json.dump(verdict, f, indent=2)
            f.write("\n")
        status = "PASS" if verdict["passed"] else "FAIL"
        print(f"{status} {name} in {time.monotonic() - t0:.1f}s -> {out}",
              flush=True)
        for check, doc in verdict["invariants"]["checks"].items():
            print(f"  [{'ok' if doc['ok'] else 'VIOLATED'}] {check}")
        if not verdict["passed"]:
            failed.append(name)
    if failed:
        raise SystemExit(f"chaos scenarios FAILED: {failed}")


if __name__ == "__main__":
    main()
