"""The evaluator pod's entrypoint — the reference's third pod role, live
under the operator (docs/design/elastic-training-operator.md:43-44,79-85:
side evaluation alongside training, replicas 1).

Launched by the operator when the JobResource carries an ``evaluator`` role
(Brain adds one whenever the ElasticJob defines the role). Like the worker
pods it derives everything from the shared workdir: waits for the trainer's
``job.json``, builds the same model bundle, then follows the training run's
checkpoint directory with :class:`~easydl_tpu.core.evaluator.Evaluator` —
never joining the training collective, so worker membership can change or
crash freely without touching evaluation.

Each evaluated checkpoint appends one JSON line to ``<workdir>/eval.jsonl``
(override with ``--out``). Exit: when the job's DONE marker exists and the
final committed checkpoint has been evaluated, the process exits 0 — the
pod ends Succeeded on its own rather than waiting for the operator's
terminal GC to kill it.

``python -m easydl_tpu.elastic.evaluator_main --workdir <shared dir>``
"""

from __future__ import annotations

import argparse
import json
import os
import time
from types import SimpleNamespace


def main() -> None:
    ap = argparse.ArgumentParser(description="easydl_tpu evaluator pod")
    ap.add_argument("--workdir", required=True, help="shared job workdir")
    ap.add_argument("--poll-interval", type=float, default=1.0)
    ap.add_argument("--batches-per-eval", type=int, default=4)
    ap.add_argument("--out", default="",
                    help="eval metrics JSONL (default <workdir>/eval.jsonl)")
    ap.add_argument("--config-timeout", type=float, default=300.0,
                    help="max wait for the trainer to write job.json")
    args = ap.parse_args()

    workdir = args.workdir
    out_path = args.out or os.path.join(workdir, "eval.jsonl")
    cfg_path = os.path.join(workdir, "job.json")
    done_path = os.path.join(workdir, "DONE")

    # The operator may start this pod before the trainer has written the
    # worker config (pods launch in parallel off the same JobResource).
    deadline = time.monotonic() + args.config_timeout
    while not os.path.exists(cfg_path):
        if time.monotonic() > deadline:
            raise SystemExit(f"no {cfg_path} after {args.config_timeout}s — "
                             "is the trainer pod running?")
        time.sleep(0.5)
    with open(cfg_path) as f:
        cfg = json.load(f)

    model_kwargs = dict(cfg.get("model_kwargs", {}))
    if model_kwargs.get("embedding") == "ps":
        # The PS-backed sparse tower lives on the PS tier; a side evaluator
        # would need its own PS read path. Not supported yet — fail loudly
        # instead of evaluating a model with missing parameters.
        raise SystemExit("evaluator does not support embedding='ps' jobs")

    import jax  # noqa: F401  (backend init order matters)

    from easydl_tpu.utils.env import pin_cpu_platform_if_requested

    pin_cpu_platform_if_requested()

    import optax

    from easydl_tpu.core import MeshSpec, Trainer, TrainConfig, build_mesh
    from easydl_tpu.core.checkpoint import CheckpointManager
    from easydl_tpu.core.evaluator import Evaluator
    from easydl_tpu.models import get_model
    from easydl_tpu.utils.logging import get_logger

    log = get_logger("elastic", "evaluator")

    bundle = get_model(cfg["model"], **model_kwargs)
    global_batch = int(cfg.get("global_batch", 32))
    # The evaluator's own (usually single-host) mesh: reshard-on-restore
    # absorbs any mismatch with the training mesh.
    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adam(float(cfg.get("lr", 1e-3))),
        config=TrainConfig(global_batch=global_batch,
                           seed=int(cfg.get("seed", 0))),
        mesh=build_mesh(MeshSpec(dp=jax.device_count())),
    )
    ckpt = CheckpointManager(os.path.join(workdir, "ckpt"), async_save=False)

    val_fraction = float(cfg.get("val_fraction", 0.0))
    if cfg.get("data_dir"):
        from easydl_tpu.models.run import file_data

        ns = SimpleNamespace(data_dir=cfg["data_dir"], batch=global_batch,
                             seq_len=int(cfg.get("seq_len", 0)),
                             val_fraction=val_fraction)
        # a real holdout when the job carved one; otherwise a different
        # shuffle order than training (seed_offset=1)
        data = iter(file_data(ns, bundle, seed_offset=1,
                              split="val" if val_fraction else "train"))
    else:
        data = iter(bundle.make_data(global_batch, seed=1))

    def append_result(result) -> None:
        with open(out_path, "a") as f:
            f.write(json.dumps(result) + "\n")

    ev = Evaluator(trainer, ckpt, data, eval_fn=bundle.eval_fn,
                   batches_per_eval=args.batches_per_eval,
                   on_result=append_result)
    log.info("following %s/ckpt (results -> %s)", workdir, out_path)
    while True:
        # DONE is checked BEFORE polling: it is written only after the final
        # save commits, so "DONE was already visible AND the poll found
        # nothing new" proves the final checkpoint is evaluated. (Checking
        # after could race a commit that lands between poll and check,
        # skipping the last eval.)
        done_before = os.path.exists(done_path)
        evaluated = ev.poll_once()
        if evaluated is None:
            if done_before:
                log.info("job done; %d checkpoints evaluated",
                         len(ev.results))
                return
            time.sleep(args.poll_interval)


if __name__ == "__main__":
    main()
