"""The offline control-plane simulator (easydl_tpu/sim/): policy replays
through the REAL Rendezvous / StragglerDetector / Autoscaler on a virtual
clock — deterministic, subprocess-free, milliseconds per multi-minute
scenario. ISSUE 8 acceptance: committed recorded timelines replay
byte-identically, and the invariant checks catch a deliberately mis-tuned
policy (negative control)."""

import json
import os

import pytest

from easydl_tpu.brain.policy import AutoscalerConfig
from easydl_tpu.brain.straggler import StragglerConfig, StragglerDetector
from easydl_tpu.sim import (
    SimPolicy, load_fixture, load_workdir, save_fixture, simulate,
    synthetic_autoscale, synthetic_preempt, synthetic_straggler,
)

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "sim")


# ------------------------------------------------------------ detector unit
def test_detector_flags_skewed_member_and_damps():
    # recent_window=1: this test pins the raw streak/damping mechanics;
    # burst immunity has its own test below
    det = StragglerDetector(StragglerConfig(
        ratio=4.0, consecutive=3, min_samples=4, holddown_s=30.0,
        recent_window=1))
    # healthy baseline on two agents
    for step in range(8):
        det.observe("a0", 0.01, step, now=step * 0.3)
        det.observe("a1", 0.01, step, now=step * 0.3)
    assert det.suspects(now=3.0) == []
    # a0 turns 50x slower; three consecutive skewed samples flag it
    for i, step in enumerate(range(8, 12)):
        det.observe("a0", 0.5, step, now=3.0 + i * 0.3)
        det.observe("a1", 0.01, step, now=3.0 + i * 0.3)
    assert det.suspects(now=5.0) == ["a0"]
    cand = det.evict_candidate(["a0", "a1"], ["a0", "a1", "a2"], 1, now=5.0)
    assert cand == "a0"
    det.note_eviction("a0", now=5.0)
    # hold-down: no candidate inside the window even if skew reappears
    for i, step in enumerate(range(20, 26)):
        det.observe("a1", 0.01, step, now=6.0 + i * 0.1)
    assert det.evict_candidate(["a1"], ["a0", "a1"], 1, now=10.0) is None
    # evicted agent's window was forgotten (fresh evidence on relapse)
    assert "a0" not in det.status()["agents"]


def test_detector_dedupes_stalled_step_reports():
    det = StragglerDetector(StragglerConfig(min_samples=4, consecutive=3,
                                            allow_self_skew=True))
    for step in range(6):
        det.observe("a0", 0.01, step, now=step * 0.3)
    for _ in range(10):  # the same slow step re-reported must not streak
        det.observe("a0", 0.5, 6, now=3.0)
    assert det.status()["agents"]["a0"]["streak"] <= 1


def test_detector_windowed_median_ignores_isolated_bursts():
    """An async-checkpoint burst (a couple of slow steps) must not streak:
    each skew observation is the median of the recent window, which at
    most half-poisoned stays fast. A persistent straggler saturates the
    window and still fires."""
    det = StragglerDetector(StragglerConfig(
        ratio=4.0, consecutive=3, min_samples=4, recent_window=5,
        allow_self_skew=True))
    step = 0
    for _ in range(10):
        det.observe("a0", 0.01, step, now=step * 0.3); step += 1
    # repeated 2-sample bursts 20x the median, separated by fast steps
    for _ in range(6):
        for dt in (0.2, 0.2, 0.01, 0.01, 0.01):
            det.observe("a0", dt, step, now=step * 0.3); step += 1
    assert det.suspects(now=step * 0.3) == []
    # persistent slowness saturates the window within ~recent+consecutive
    # samples — while the baseline is still fast (suspicion is judged per
    # observation, exactly when the live tick loop would actuate it)
    fired_at = None
    for k in range(9):
        det.observe("a0", 0.2, step, now=step * 0.3); step += 1
        if det.suspects(now=step * 0.3) == ["a0"] and fired_at is None:
            fired_at = k
    assert fired_at is not None and fired_at <= 7


def test_detector_ignores_global_slowdown_with_peers():
    det = StragglerDetector(StragglerConfig(
        ratio=4.0, consecutive=3, min_samples=4, min_peer_agents=2))
    for step in range(6):
        for a in ("a0", "a1", "a2"):
            det.observe(a, 0.01, step, now=step * 0.3)
    # EVERY rank slows 10x (input stall): fleet median moves too slowly
    # to matter within one window, but no agent should streak — they all
    # sit at the same (slow) pace relative to each other after the
    # baseline catches up.
    for step in range(6, 30):
        for a in ("a0", "a1", "a2"):
            det.observe(a, 0.1, step, now=step * 0.3)
    assert det.suspects(now=10.0) == []


def test_detector_refuses_eviction_below_min_workers():
    det = StragglerDetector(StragglerConfig(
        ratio=4.0, consecutive=2, min_samples=3, allow_self_skew=True))
    for step in range(5):
        det.observe("a0", 0.01, step, now=step * 0.1)
    for step in range(5, 9):
        det.observe("a0", 0.9, step, now=step * 0.1)
    assert det.suspects(now=1.0) == ["a0"]
    # no replacement available: evicting would kill the job
    assert det.evict_candidate(["a0"], ["a0"], 1, now=1.0) is None
    # a standby appears: now the eviction is viable
    assert det.evict_candidate(["a0"], ["a0", "a1"], 1, now=1.0) == "a0"


def test_detector_generation_change_restarts_the_window():
    """Review finding: an unplanned reshape rolls members back to the
    last checkpoint — re-executed step numbers must be FRESH evidence at
    the new generation, not deduped against the pre-crash high-water
    mark, and the pre-reshape pace must not linger as the reference."""
    det = StragglerDetector(StragglerConfig(
        ratio=4.0, consecutive=2, min_samples=3, recent_window=1,
        allow_self_skew=True))
    for step in range(10):
        det.observe("a0", 0.01, step, now=step * 0.3, generation=1)
    # rollback: generation 2 re-executes steps 5.. — samples must land
    for i, step in enumerate(range(5, 14)):
        det.observe("a0", 0.01, step, now=3.0 + i * 0.3, generation=2)
    st = det.status()["agents"]["a0"]
    assert st["last_step"] == 13 and st["n"] > 0


def test_detector_prunes_departed_members_from_the_reference():
    """Review finding: an ex-member's frozen window must not anchor the
    fleet reference. After a membership change plus a legitimate
    fleet-wide pace change, the survivor is judged against CURRENT
    members only — no false eviction."""
    det = StragglerDetector(StragglerConfig(
        ratio=4.0, consecutive=2, min_samples=4, recent_window=1))
    for step in range(6):
        for a in ("a0", "a1", "a2"):
            det.observe(a, 0.01, step, now=step * 0.3)
    # a1/a2 leave membership; the surviving world legitimately slows 5x.
    # The decision path runs every master tick (0.2s) between samples
    # (0.3s+), pruning the departed agents' frozen windows before any
    # streak can mature against them — mirror that cadence here.
    for step in range(6, 20):
        det.observe("a0", 0.05, step, now=step * 0.3)
        assert det.evict_candidate(["a0"], ["a0", "a3"], 1,
                                   now=step * 0.3) is None
    assert set(det.status()["agents"]) == {"a0"}


# --------------------------------------------------------- synthetic drills
def test_sim_straggler_evicted_and_holddown_quiet():
    r = simulate(
        synthetic_straggler(n_agents=3, total_steps=1200, duration_s=90.0),
        SimPolicy(desired_workers=2,
                  straggler=StragglerConfig(ratio=4.0, consecutive=3,
                                            holddown_s=20.0)),
        {"straggler_evicted": "a0", "evict_budget_s": 20.0,
         "holddown_quiet": True, "max_reshapes": 2, "max_evictions": 1,
         "final_workers": 2},
    )
    assert r["passed"], json.dumps(r["invariants"], indent=2)
    assert [e["agent"] for e in r["evictions"]] == ["a0"]
    assert "a0" not in r["final"]["members"]
    reasons = [x["reason"] for x in r["reshapes"]]
    assert "straggler" in reasons


def test_sim_mis_tuned_policy_is_caught():
    """ISSUE 8 acceptance (negative control): a hair-trigger, undamped
    detector over a noisy fleet must ping-pong — and the invariants must
    say so instead of passing."""
    r = simulate(
        synthetic_straggler(n_agents=3, total_steps=1200, duration_s=90.0,
                            noise=0.35),
        SimPolicy(desired_workers=2,
                  straggler=StragglerConfig(ratio=1.02, consecutive=1,
                                            min_samples=2, holddown_s=0.5,
                                            recent_window=1)),
        {"max_reshapes": 2, "holddown_quiet": True, "max_evictions": 1},
    )
    assert not r["passed"]
    checks = r["invariants"]["checks"]
    assert not checks["no_directive_ping_pong"]["ok"]
    assert not checks["eviction_churn_bounded"]["ok"]
    assert len(r["evictions"]) > 5  # it really flapped


def test_sim_proactive_drain_wins_the_preemption_race():
    r = simulate(
        synthetic_preempt(grace_s=8.0), SimPolicy(),
        {"proactive_drain": True, "max_steps_lost": 0, "target_step": 1500,
         "final_workers": 1, "max_reshapes": 1},
    )
    assert r["passed"], json.dumps(r["invariants"], indent=2)
    race = r["invariants"]["checks"]["proactive_drain_before_kill"]
    assert race["races"][0]["won"] and race["races"][0]["margin_s"] > 0
    assert [x["reason"] for x in r["reshapes"]] == ["preemption"]


def test_sim_reactive_recovery_fails_the_race():
    """Negative control: a grace window too short for any drain — the
    kill lands on a live worker and the invariant must fail."""
    r = simulate(
        synthetic_preempt(grace_s=0.05), SimPolicy(),
        {"proactive_drain": True},
    )
    assert not r["passed"]
    race = r["invariants"]["checks"]["proactive_drain_before_kill"]
    assert race["races"][0]["worker_alive_at_kill"]


def test_sim_autoscaler_ramp_through_real_decide_path():
    """The real Autoscaler (forced-python twin) climbs the efficiency
    profile 1→2→4 and HOLDS when the next doubling would land under the
    efficiency floor."""
    r = simulate(
        synthetic_autoscale(),
        SimPolicy(autoscaler=AutoscalerConfig(max_workers=8, cooldown_s=3.0,
                                              min_samples=5)),
        {"min_scale_ups": 2, "final_desired_workers": 4, "final_workers": 4,
         "max_reshapes": 3, "target_step": 1500},
    )
    assert r["passed"], json.dumps(r["invariants"], indent=2)
    ups = [(s["from_workers"], s["to_workers"]) for s in r["scale_decisions"]]
    assert ups == [(1, 2), (2, 4)]


def test_sim_verdict_byte_identical_across_runs():
    def run():
        return json.dumps(
            simulate(synthetic_straggler(), SimPolicy(desired_workers=2),
                     {"straggler_evicted": "a0"}),
            sort_keys=True)
    assert run() == run()


# ---------------------------------------------------- recorded workdir path
def test_load_workdir_builds_timeline_with_faults(tmp_path):
    for agent, dts in (("a0", [0.01, 0.02, 0.3]), ("a1", [0.011, 0.012])):
        with open(tmp_path / f"metrics-{agent}.jsonl", "w") as f:
            for i, dt in enumerate(dts):
                f.write(json.dumps({
                    "step": i + 1, "loss": 1.0, "step_time_s": dt,
                    "samples_per_sec": 32 / dt, "world_size": 1,
                    "generation": 1, "t": 100.0 + i,
                }) + "\n")
            f.write('{"torn')  # killed-worker tail must be skipped
    with open(tmp_path / "chaos-plan.json", "w") as f:
        json.dump({"t0": 101.5, "events": [
            {"kind": "straggler", "start_s": 0.5, "end_s": 60.0,
             "target": {"agent": "a0"}, "params": {"sleep_s": 0.25}},
            {"kind": "preempt_notice", "start_s": 1.0,
             "target": {"agent": "a0"}},
            {"kind": "worker_kill", "start_s": 3.0,
             "target": {"agent": "a0"}, "params": {}},
        ]}, f)
    with open(tmp_path / "job.json", "w") as f:
        json.dump({"total_steps": 500, "ckpt_interval": 50}, f)
    tl = load_workdir(str(tmp_path), name="rec")
    assert set(tl["agents"]) == {"a0", "a1"}
    assert len(tl["agents"]["a0"]) == 3
    kinds = [f["kind"] for f in tl["faults"]]
    assert kinds == ["straggler", "preempt_notice", "kill"]
    # recorded straggler windows must NOT be re-injected (the slowdown is
    # already in the recorded durations)
    strag = next(f for f in tl["faults"] if f["kind"] == "straggler")
    assert strag["inject"] is False
    # re-anchored: t0+0.5 relative to the first record at wall 100.0
    assert strag["t"] == pytest.approx(2.0)
    assert tl["meta"]["total_steps"] == 500
    # round-trip through the fixture format
    save_fixture(tl, str(tmp_path / "fix.json"))
    assert load_fixture(str(tmp_path / "fix.json"))["agents"] == tl["agents"]


@pytest.mark.parametrize("fixture,invariant", [
    ("straggler_mitigation.json", "straggler_evicted"),
    ("preempt_race.json", "proactive_drain_before_kill"),
])
def test_committed_fixture_replays_deterministically(fixture, invariant):
    """ISSUE 8 acceptance: the committed recorded timelines replay through
    the real policy stack, their invariants hold, and two runs produce
    byte-identical verdicts — entirely in tier-1, no subprocesses."""
    path = os.path.join(FIXTURE_DIR, fixture)
    tl = load_fixture(path)
    # the drills' member+standby worlds have ONE reporting member: skew is
    # judged against the member's own baseline (same policy
    # scripts/policy_replay.py applies to recorded timelines)
    def drill_policy():
        return SimPolicy(
            straggler=StragglerConfig(ratio=8.0, consecutive=6,
                                      min_samples=6, holddown_s=10.0,
                                      allow_self_skew=True))

    def expect_for(timeline):
        kinds = {f["kind"] for f in timeline["faults"]}
        exp = {"max_reshapes": 2}
        if "straggler" in kinds:
            exp.update({"straggler_evicted": "a0", "evict_budget_s": 30.0,
                        "holddown_quiet": True, "max_evictions": 1})
        if "kill" in kinds and "preempt_notice" in kinds:
            exp["proactive_drain"] = True
        return exp

    r1 = simulate(tl, drill_policy(), expect_for(tl))
    r2 = simulate(load_fixture(path), drill_policy(), expect_for(tl))
    assert r1["passed"], json.dumps(r1["invariants"], indent=2)
    assert invariant in r1["invariants"]["checks"]
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)


# ------------------------------------------------------- mesh-shape mode
def _policy_replay_module():
    """Import scripts/policy_replay.py so the tier-1 tests validate the
    EXACT policy + expectations the chaos_smoke replay gate runs — a
    local copy could silently drift from the gate."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(FIXTURE_DIR)),
                        "..", "scripts", "policy_replay.py")
    spec = importlib.util.spec_from_file_location(
        "policy_replay_under_test", os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_PR = _policy_replay_module()


def _mesh_sim_policy(pinned=""):
    return _PR._mesh_policy(pinned=pinned)


_MESH_EXPECT = _PR._MESH_EXPECT


def test_sim_mesh_autoscale_converges_within_5pct_of_oracle():
    """ISSUE 12 acceptance (offline): a preemption + an 8->32 autoscale
    ramp over a shape-dependent performance surface — the REAL
    MeshShapePolicy probes factorizations through the real
    request_mesh_reshape path and converges on a shape within 5%
    simulated throughput of the static-pod oracle (here: ON it), and the
    committed fixture replays byte-identically."""
    from easydl_tpu.sim import synthetic_mesh_autoscale

    path = os.path.join(FIXTURE_DIR, "mesh_autoscale.json")
    tl = load_fixture(path)
    # the committed fixture IS the synthetic generator's output
    assert tl["agents"] == synthetic_mesh_autoscale()["agents"]
    r1 = simulate(tl, _mesh_sim_policy(), dict(_MESH_EXPECT))
    assert r1["passed"], json.dumps(r1["invariants"], indent=2)
    conv = r1["invariants"]["checks"]["mesh_shape_converged"]
    assert conv["final_shape"] == "dp=8,fsdp=2,tp=2"
    assert conv["throughput_loss"] <= 0.05
    # every probe/adoption went through a PLANNED mesh-shape reshape
    assert any(e["reason"] == "mesh-shape" for e in r1["reshapes"])
    assert all(e["planned"] for e in r1["reshapes"]
               if e["reason"] == "mesh-shape")
    # the decision inputs ride the mesh log (WAL forensics contract)
    probe_logs = [e for e in r1["mesh"]["log"]
                  if (e["inputs"] or {}).get("reason") == "probe"]
    assert probe_logs and all("candidates" in (e["inputs"] or {})
                              for e in probe_logs)
    r2 = simulate(load_fixture(path), _mesh_sim_policy(),
                  dict(_MESH_EXPECT))
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)


def test_sim_mesh_pinned_pathological_shape_is_caught():
    """Negative control: the policy nailed to a valid-but-pathological
    factorization for the final world must FAIL the convergence
    invariant (vacuous passes refused) — and the pin must actually BIND
    (the final shape IS the pinned one, not a fallback)."""
    from easydl_tpu.sim import synthetic_mesh_autoscale

    res = simulate(synthetic_mesh_autoscale(),
                   _mesh_sim_policy(pinned="dp=16,tp=2"),
                   dict(_MESH_EXPECT, max_reshapes=6))
    assert not res["passed"]
    conv = res["invariants"]["checks"]["mesh_shape_converged"]
    assert conv["ok"] is False
    assert conv["final_shape"] == "dp=16,tp=2"
    assert conv["throughput_loss"] > 0.05
    # everything else about the run stayed healthy: ONLY the mesh check
    # caught the mis-pin
    others = {k: v["ok"] for k, v in res["invariants"]["checks"].items()
              if k != "mesh_shape_converged"}
    assert all(others.values()), others


def test_sim_mesh_convergence_check_refuses_vacuous_pass():
    """A mesh_converged expectation against a timeline with no
    shape_profile (or a run that never decided a shape) must FAIL, not
    pass by absence of evidence."""
    res = simulate(synthetic_straggler(), SimPolicy(desired_workers=2),
                   {"mesh_converged": {"tolerance": 0.05}})
    check = res["invariants"]["checks"]["mesh_shape_converged"]
    assert check["ok"] is False and "vacuous" in check["reason"]


# ----------------------------------------------------- multi-tenant mode
_TENANT_EXPECT = _PR._TENANT_EXPECT


def test_sim_tenant_contention_preempts_paced_and_converges():
    """ISSUE 15 acceptance (offline): the 3-job contention shape — a
    high-priority scale-up over an exhausted supply is satisfied by
    PACED preemption (one chip per decision, hold-down between moves),
    floors hold throughout, no chip ping-pongs, the fleet converges on
    the water-fill target, and the decision log byte-replays through
    the pure arbiter. Byte-identical across runs."""
    from easydl_tpu.sim import simulate_tenants, synthetic_tenant_contention

    r1 = simulate_tenants(synthetic_tenant_contention(), None,
                          dict(_TENANT_EXPECT))
    assert r1["passed"], json.dumps(r1["invariants"], indent=2)
    preempts = [m for m in r1["moves"] if m["from"]]
    assert len(preempts) == 2
    assert [p["from"] for p in preempts] == ["lo", "mid"]  # poorest first
    holddown = r1["config"]["holddown_s"]
    assert preempts[1]["t"] - preempts[0]["t"] >= holddown  # paced
    assert r1["final_allocations"] == {"hi": 3, "lo": 1, "mid": 1}
    r2 = simulate_tenants(synthetic_tenant_contention(), None,
                          dict(_TENANT_EXPECT))
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)


def test_sim_tenant_starvation_negative_is_caught():
    """The starvation-prone claims-set (min_chips=0 under a saturating
    high-priority demand) must FAIL the no-starvation check — and ONLY
    it: the arbiter honored priorities exactly as configured."""
    from easydl_tpu.sim import simulate_tenants, synthetic_tenant_starvation

    res = simulate_tenants(
        synthetic_tenant_starvation(), None,
        {"priorities_honored": True, "no_starvation": True,
         "no_thrash": True})
    assert not res["passed"]
    checks = res["invariants"]["checks"]
    assert checks["tenant_no_starvation"]["ok"] is False
    assert checks["tenant_no_starvation"]["starved"][0]["job"] == "lo"
    others = {k: v["ok"] for k, v in checks.items()
              if k != "tenant_no_starvation"}
    assert all(others.values()), others


def test_sim_tenant_checks_refuse_vacuous_passes():
    """Empty evidence never passes: no samples fails no_starvation, no
    decisions fails priorities_honored and the replay identity."""
    from easydl_tpu.sim.multijob import check_tenants

    verdict = check_tenants(
        {"allocation_samples": [], "moves": [], "decision_log": []},
        {"priorities_honored": True, "no_starvation": True},
        {"jobs": [], "config": {"holddown_s": 10.0}})
    checks = verdict["checks"]
    assert checks["tenant_no_starvation"]["ok"] is False
    assert checks["tenant_priorities_honored"]["ok"] is False
    assert checks["tenant_replay_identical"]["ok"] is False


def test_committed_tenant_fixture_replays_deterministically():
    """The committed tenant fixture rides the same replay gate as every
    other sim fixture: the policy_replay dispatch picks the tenant
    engine + expectations, the invariants hold, and two replays are
    byte-identical."""
    from easydl_tpu.sim import simulate_tenants

    path = os.path.join(FIXTURE_DIR, "tenant_contention.json")
    tl = load_fixture(path)
    pol, expect = _PR._policy_and_expect_for(tl)
    assert pol is None and expect == _TENANT_EXPECT
    r1 = simulate_tenants(tl, pol, expect)
    r2 = simulate_tenants(load_fixture(path), pol, expect)
    assert r1["passed"], json.dumps(r1["invariants"], indent=2)
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)
