"""Per-shard push write-ahead log: the durability half of zero-loss rescue.

A PS shard crash used to fall back to the last sparse snapshot, silently
discarding every push applied since it. The WAL closes that gap: every
applied push is appended — in the exact order the store applied it — to a
size-rotated segment file under the shard's WAL directory, and rescue
(ps/__main__.py) replays surviving segments on top of the restored
snapshot, reproducing the pre-crash table **bit-identically** (replay goes
through the same vectorized store math as the original apply).

Layout::

    <workdir>/ps-wal/shard-<i>/            the shard's WAL root
        epoch-<e>/                         one dir per shard incarnation
            seg-00000001.wal ...           size-rotated record segments
            REPLAYED.json                  written by the rescuer: bytes of
                                           each segment it consumed, so a
                                           zombie's late appends are never
                                           replayed by a LATER rescue

Record framing (little-endian): ``u32 payload_len | u32 crc32(payload) |
payload``. The payload leads with a kind byte — ``0`` = push (table,
scale, ids, grads: the exact decoded arguments the store applied),
``1`` = create_table (the spec JSON, so replay can recreate a table born
after the last snapshot). Readers validate every record's checksum and
stop at the first bad/short frame — a torn tail from a SIGKILL truncates,
it never poisons the replay.

Durability contract: records are ``write()``-en to the OS before the push
is acked (process-crash safe — a SIGKILLed shard loses nothing it acked),
while ``fsync`` runs on a background cadence (``EASYDL_PS_WAL_SYNC_S``),
bounding host-crash loss to one sync interval. This mirrors the PR-5
AsyncPusher discipline: the hot path pays one buffered append, the
expensive barrier runs behind it, and errors surface on the next append
rather than vanishing. Segments are retired atomically when a snapshot
commits (ps/server.py ``save``): once the rows are durably in the
checkpoint lineage a rescue restores from, the log that produced them is
dead weight.

Knobs: ``EASYDL_PS_WAL`` (default on for pod-served shards),
``EASYDL_PS_WAL_SEGMENT_BYTES`` (rotation threshold, default 32 MiB),
``EASYDL_PS_WAL_SYNC_S`` (fsync cadence, default 0.2s; 0 = fsync every
append, negative = never fsync).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from easydl_tpu.utils.logging import get_logger
from easydl_tpu.utils.env import knob_float, knob_int

log = get_logger("ps", "wal")

ENV_WAL = "EASYDL_PS_WAL"
ENV_SEGMENT_BYTES = "EASYDL_PS_WAL_SEGMENT_BYTES"
ENV_SYNC_S = "EASYDL_PS_WAL_SYNC_S"

DEFAULT_SEGMENT_BYTES = 32 << 20
DEFAULT_SYNC_S = 0.2

REC_PUSH = 0
REC_CREATE = 1

_HEADER = struct.Struct("<II")  # payload_len, crc32(payload)
_PUSH_HEAD = struct.Struct("<BHdII")  # kind, table_len, scale, n_ids, dim

REPLAYED_MARKER = "REPLAYED.json"


class WalError(RuntimeError):
    """The WAL could not be appended — durability is broken, so the push
    that triggered it must FAIL (a silent fallback to no-WAL would turn
    the zero-loss promise into a lie)."""


# ------------------------------------------------------------------ encoding
def encode_push_parts(table: str, ids: np.ndarray, grads: np.ndarray,
                      scale: float) -> List[bytes]:
    """Payload for one applied push as scatter-gather parts: the exact
    arguments the store saw (raw-ids wire form — little-endian int64
    bytes, float32 grads). Parts, not one buffer: a push on the wire is a
    few MB, and the hot-path append (:meth:`PsWal.append`) checksums the
    parts incrementally and hands them to ``os.writev`` — zero joins, zero
    full-payload copies. ``ids``/``grads`` decoded off the wire are
    already little-endian contiguous, so the casts below are no-ops
    there."""
    tb = table.encode()
    ids = np.ascontiguousarray(ids, "<i8")
    grads = np.ascontiguousarray(grads, "<f4")
    return [
        _PUSH_HEAD.pack(REC_PUSH, len(tb), float(scale), len(ids),
                        grads.shape[1] if grads.ndim == 2 else 0),
        tb,
        ids.tobytes(),
        grads.tobytes(),
    ]


def encode_push(table: str, ids: np.ndarray, grads: np.ndarray,
                scale: float) -> bytes:
    return b"".join(encode_push_parts(table, ids, grads, scale))


def decode_push(payload: bytes) -> Tuple[str, np.ndarray, np.ndarray, float]:
    kind, tlen, scale, n, dim = _PUSH_HEAD.unpack_from(payload, 0)
    if kind != REC_PUSH:
        raise ValueError(f"not a push record (kind={kind})")
    off = _PUSH_HEAD.size
    table = payload[off:off + tlen].decode()
    off += tlen
    ids = np.frombuffer(payload, "<i8", count=n, offset=off)
    off += 8 * n
    grads = np.frombuffer(payload, "<f4", count=n * dim,
                          offset=off).reshape(n, dim)
    return table, ids, grads, scale


def encode_create(spec_json: str) -> bytes:
    return bytes((REC_CREATE,)) + spec_json.encode()


def decode_create(payload: bytes) -> str:
    return payload[1:].decode()


def record_kind(payload: bytes) -> int:
    return payload[0] if payload else -1


def frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def push_digest(payload) -> bytes:
    """Identity of one applied push, for replay-vs-retry dedupe: a client
    that never saw the ack of a push the dead shard DID apply (and WAL)
    will retry it verbatim against the rescuer — the rescuer recognises
    the payload bytes and acks without applying twice. The digest is over
    the payload only (the stamped epoch is NOT part of it: the retry
    carries the successor's epoch). Accepts the joined payload or its
    scatter-gather parts — both digest identically."""
    h = hashlib.blake2b(digest_size=16)
    for part in ([payload] if isinstance(payload, bytes) else payload):
        h.update(part)
    return h.digest()


# ------------------------------------------------------------------- reading
def read_segment(path: str, limit: Optional[int] = None
                 ) -> Tuple[List[bytes], int, bool]:
    """Parse one segment: ``(payloads, bytes_consumed, clean)``.

    Stops at the first short or checksum-failing frame — everything from
    there on is treated as a torn tail and excluded (``clean`` False).
    ``limit`` caps the bytes considered (a rescuer's recorded replay
    offset: appends a zombie made after that rescue must stay invisible
    to later rescues — they were re-acked by the successor)."""
    payloads: List[bytes] = []
    consumed = 0
    clean = True
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return payloads, 0, False
    if limit is not None:
        data = data[:limit]
    off = 0
    while off + _HEADER.size <= len(data):
        length, crc = _HEADER.unpack_from(data, off)
        start = off + _HEADER.size
        end = start + length
        if end > len(data):
            clean = False  # torn tail: killed mid-append
            break
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            clean = False  # corrupt record: stop, never apply past it
            break
        payloads.append(payload)
        consumed = end
        off = end
    if off + _HEADER.size > len(data) and off != len(data):
        clean = False  # trailing partial header
    return payloads, consumed, clean


def _segments(d: str) -> List[str]:
    try:
        return sorted(
            n for n in os.listdir(d)
            if n.startswith("seg-") and n.endswith(".wal")
        )
    except OSError:
        return []


def epoch_dirs(root: str) -> List[Tuple[int, str]]:
    """``(epoch, path)`` of every incarnation dir under a shard WAL root,
    epoch-sorted."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for n in names:
        if n.startswith("epoch-"):
            try:
                out.append((int(n[len("epoch-"):]), os.path.join(root, n)))
            except ValueError:
                continue
    out.sort()
    return out


def read_replay_caps(epoch_dir: str) -> Dict[str, int]:
    """Parse an incarnation dir's ``REPLAYED.json`` consumed-offset caps
    (empty when absent/unreadable). The one reader of the marker format —
    replay and the chaos zombie-fence check both go through here, so the
    schema lives in exactly one place."""
    try:
        with open(os.path.join(epoch_dir, REPLAYED_MARKER)) as f:
            return {str(k): int(v)
                    for k, v in json.load(f).get("segments", {}).items()}
    except (OSError, ValueError):
        return {}


def iter_replay(root: str, before_epoch: int,
                start: Optional[Tuple[int, str]] = None
                ) -> Iterator[Tuple[int, str, List[bytes], int, bool]]:
    """Yield ``(epoch, segment_path, payloads, consumed, clean)`` for every
    segment of every incarnation older than ``before_epoch``, in apply
    order (epoch, then segment name). Honors a prior rescuer's
    ``REPLAYED.json`` offsets as hard caps.

    ``start`` is the restored snapshot's cut boundary ``(epoch,
    first_live_segment)`` (ps/server.py writes it into every step dir):
    records the snapshot already contains must not replay on top of it.
    Epochs older than the snapshot writer's are skipped whole — any
    record of theirs was replayed (or handed off) into the writer's state
    before it could take a snapshot — and within the writer's epoch only
    segments at or past the cut replay. Without a boundary every
    surviving segment replays, which is the pre-cut-marker contract where
    correctness leaned on retirement alone."""
    for epoch, d in epoch_dirs(root):
        if before_epoch and epoch >= before_epoch:
            continue
        if start is not None and epoch < start[0]:
            continue
        caps = read_replay_caps(d)
        for name in _segments(d):
            if start is not None and epoch == start[0] and name < start[1]:
                continue
            path = os.path.join(d, name)
            payloads, consumed, clean = read_segment(path, caps.get(name))
            yield epoch, path, payloads, consumed, clean


def write_replay_marker(epoch_dir: str, consumed: Dict[str, int]) -> None:
    """Record how far a rescue consumed each segment of a predecessor
    incarnation, so a zombie predecessor's post-rescue appends (acked by
    the SUCCESSOR when the client retried them) are never replayed by a
    later rescue. Merges over an existing marker: a cap, once written,
    never grows."""
    path = os.path.join(epoch_dir, REPLAYED_MARKER)
    merged = dict(consumed)
    try:
        with open(path) as f:
            for k, v in json.load(f).get("segments", {}).items():
                merged[str(k)] = min(int(v), merged.get(str(k), int(v)))
    except (OSError, ValueError):
        pass
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"segments": merged}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ------------------------------------------------------------------- writing
class PsWal:
    """The append side: one open segment, size-rotated, background-fsynced.

    NOT thread-safe by itself — the shard serializes appends (and the
    append→store-apply pair) under its WAL ordering lock, which is what
    guarantees file order == apply order == replay order."""

    def __init__(self, epoch_dir: str,
                 segment_bytes: Optional[int] = None,
                 sync_s: Optional[float] = None):
        self.dir = epoch_dir
        os.makedirs(epoch_dir, exist_ok=True)
        self.segment_bytes = int(
            knob_int(ENV_SEGMENT_BYTES, DEFAULT_SEGMENT_BYTES)
            if segment_bytes is None else segment_bytes)
        self.sync_s = float(
            knob_float(ENV_SYNC_S, DEFAULT_SYNC_S)
            if sync_s is None else sync_s)
        existing = _segments(epoch_dir)
        self._next_index = (int(existing[-1][4:-4]) + 1) if existing else 1
        self._fd: Optional[int] = None
        self._size = 0
        self._path = ""
        self._dirty = False
        self._broken: Optional[Exception] = None
        # Guards fd close/reassign against the background syncer: without
        # it, cut() closing the segment between the syncer's fd check and
        # its fsync raises EBADF (or fsyncs an unrelated reused fd) and
        # permanently bricks the log via _broken.
        self._fdmu = threading.Lock()
        self._open_segment()
        self._stop = threading.Event()
        self._syncer: Optional[threading.Thread] = None
        if self.sync_s > 0:
            self._syncer = threading.Thread(
                target=self._sync_loop, name="ps-wal-sync", daemon=True)
            self._syncer.start()

    # ------------------------------------------------------------ internals
    def _open_segment(self) -> None:
        self._path = os.path.join(
            self.dir, f"seg-{self._next_index:08d}.wal")
        self._next_index += 1
        self._fd = os.open(self._path,
                           os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        self._size = 0

    def _sync_loop(self) -> None:
        while not self._stop.wait(self.sync_s):
            try:
                self.sync()
            except OSError as e:  # surfaces on the next append
                self._broken = e

    # ----------------------------------------------------------------- api
    @property
    def path(self) -> str:
        return self._path

    def append(self, payload) -> int:
        """Frame + write one record; returns the framed byte count. Caller
        holds the shard's WAL ordering lock. Raises :class:`WalError` if
        the log is unappendable (the push must then fail — see class
        docstring).

        Accepts the payload either joined or as scatter-gather parts
        (:func:`encode_push_parts`): the parts form checksums incrementally
        and lands via one ``os.writev`` — no joined-buffer copy, which is
        most of a multi-MB append's cost on the push hot path."""
        if self._broken is not None:
            raise WalError(f"ps wal {self.dir} broken: {self._broken}")
        # Rotate BEFORE the write, not after: the frame just appended is
        # then always wholly inside the OPEN segment, which is what makes
        # :meth:`rollback` a plain ftruncate when the store apply it was
        # logged for fails.
        if self._size >= self.segment_bytes:
            self.cut()
        parts = [payload] if isinstance(payload, bytes) else list(payload)
        length = sum(len(p) for p in parts)
        crc = 0
        for p in parts:
            crc = zlib.crc32(p, crc)
        total = _HEADER.size + length
        try:
            written = os.writev(self._fd,
                                [_HEADER.pack(length, crc)] + parts)
            if written < total:  # partial writev: finish the frame plainly
                rest = (_HEADER.pack(length, crc)
                        + b"".join(parts))[written:]
                while rest:
                    rest = rest[os.write(self._fd, rest):]
            if self.sync_s == 0:
                os.fsync(self._fd)
        except OSError as e:
            self._broken = e
            raise WalError(f"ps wal append to {self._path} failed: {e}")
        self._size += total
        self._dirty = True
        return total

    def rollback(self, n_bytes: int) -> None:
        """Truncate the last ``n_bytes`` (one just-appended frame) off the
        open segment: the store apply it logged never happened, and leaving
        the record would make a rescue replay an update the acked history
        does not contain. Only valid immediately after the append, under
        the same ordering lock (append rotates first, so the frame is
        always in the open segment). A failed truncate marks the log
        broken — subsequent pushes then fail loudly rather than diverge."""
        with self._fdmu:
            if self._fd is None:
                return
            self._size = max(0, self._size - n_bytes)
            try:
                os.ftruncate(self._fd, self._size)
            except OSError as e:
                self._broken = e

    def sync(self) -> None:
        with self._fdmu:
            if self._dirty and self._fd is not None:
                self._dirty = False
                os.fsync(self._fd)

    def cut(self) -> List[str]:
        """Close the open segment and start a fresh one; returns the paths
        of every COMPLETED segment (candidates for retirement once a
        snapshot covering them commits). Caller holds the ordering lock,
        so the cut is an exact partition of the record stream."""
        with self._fdmu:
            if self._fd is not None:
                try:
                    os.fsync(self._fd)
                except OSError:
                    pass
                os.close(self._fd)
            done = self._path
            self._open_segment()
            self._dirty = False
        older = [os.path.join(self.dir, n) for n in _segments(self.dir)]
        return [p for p in older if p != self._path and p <= done]

    def close(self) -> None:
        self._stop.set()
        if self._syncer is not None:
            # A still-running syncer (join timeout) is why the fd close
            # below must also happen under _fdmu.
            self._syncer.join(timeout=2.0)
        try:
            self.sync()
        except OSError:
            pass
        with self._fdmu:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


def retire_segments(paths, root: Optional[str] = None,
                    before_epoch: int = 0) -> int:
    """Delete retired segment files (and, when ``root``/``before_epoch``
    name them, whole predecessor incarnation dirs) after a snapshot
    commit. Every record in them is durably inside the snapshot a rescue
    would restore, so losing them loses nothing. Returns files removed."""
    removed = 0
    for p in paths:
        try:
            os.remove(p)
            removed += 1
        except OSError:
            pass
    if root and before_epoch:
        import shutil

        for epoch, d in epoch_dirs(root):
            if epoch < before_epoch:
                shutil.rmtree(d, ignore_errors=True)
    return removed
