// Sanitizer stress driver for the Brain decision core (brain_core.cc).
//
// The core is stateless by design — the service layer owns all state — so
// the property under test is exactly that: N threads hammering edb_startup
// and edb_decide with randomized, adversarial wire inputs must produce no
// data races (TSan), no leaks/overflows (ASan), and no UB (UBSan). Built
// and run by scripts/sanitize_native.sh next to the other cores'
// stress drivers (SURVEY.md §5.2).

#include "brain_core.cc"  // NOLINT(build/include)

#include <cassert>
#include <cstdio>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

std::string random_features(std::mt19937* rng) {
  static const char* kFam[] = {"mlp", "gpt", "deepfm", "", "junk",
                               "widedeep"};
  std::uniform_int_distribution<int> fam(0, 5), chips(0, 8), b(0, 1);
  std::uniform_int_distribution<int64_t> params(0, 6000000000LL);
  std::string s = "F|";
  s += kFam[fam(*rng)];
  s += "|" + std::to_string(params(*rng));
  s += "|" + std::to_string(b(*rng));
  s += "|" + std::to_string(b(*rng));
  s += "|v5e|" + std::to_string(chips(*rng)) + "\n";
  return s;
}

std::string random_state(std::mt19937* rng) {
  std::uniform_int_distribution<int> sz(1, 32), n(0, 10), b(0, 1);
  std::uniform_real_distribution<double> v(0.0, 100.0);
  std::string s = "C|1|32|2|10.0|0.8|0.6|0.35|2\n";
  s += "T|" + std::to_string(v(*rng)) + "|0.0|" + std::to_string(sz(*rng)) +
       "\n";
  s += "B|" + std::to_string(v(*rng)) + "\n";
  if (b(*rng)) s += "X|" + std::to_string(sz(*rng)) + "\n";
  if (b(*rng))
    s += "K|" + std::to_string(sz(*rng)) + "|" + std::to_string(sz(*rng)) +
         "\n";
  for (int i = n(*rng); i > 0; --i) {
    s += "S|" + std::to_string(sz(*rng)) + "|";
    int k = n(*rng);
    for (int j = 0; j < k; ++j) {
      if (j) s += ",";
      s += std::to_string(v(*rng));
    }
    s += "\n";
  }
  // Occasionally feed garbage: truncated lines, empty fields, non-numerics.
  if (b(*rng)) s += "S|x|,,\nT|\n|||\nQ|?\n";
  return s;
}

void worker(unsigned seed) {
  std::mt19937 rng(seed);
  for (int i = 0; i < 2000; ++i) {
    char* a = edb_startup(random_features(&rng).c_str());
    assert(a != nullptr && a[0] == 'P');
    edb_free(a);
    char* d = edb_decide(random_state(&rng).c_str());
    assert(d != nullptr && d[0] == 'D');
    edb_free(d);
  }
  // Null + empty inputs must be safe too.
  char* e = edb_decide(nullptr);
  edb_free(e);
  e = edb_startup("");
  edb_free(e);
}

}  // namespace

int main() {
  std::vector<std::thread> threads;
  for (unsigned i = 0; i < 8; ++i) threads.emplace_back(worker, 1000u + i);
  for (auto& t : threads) t.join();
  std::printf("brain core stress: OK\n");
  return 0;
}
