"""Shared stdlib HTTP client for the Kubernetes API server.

Both halves of the operator's k8s surface ride this one client: the pod
backend (kube_pod_api.py) and the custom-resource watch (kube_cr_source.py).
The reference routes all control flow through the API server
(/root/reference/docs/design/elastic-training-operator.md:16-18,53-55), so
this client speaks exactly the two protocols that requires: plain JSON
request/response for CRUD, and the chunked line-delimited JSON stream the
WATCH verb returns.

stdlib-only on purpose: the image carries no ``kubernetes`` client package,
and the surface we need (GET/POST/PUT/DELETE plus a streaming GET) is small.
In-cluster auth (service-account token + CA + namespace) is picked up from
the conventional mount path when ``base_url`` is empty; tests point
``base_url`` at a local fake API server over plain HTTP.
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, Optional

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(f"k8s API {code}: {message}")
        self.code = code


class KubeClient:
    """Minimal k8s API-server client: JSON CRUD + watch streaming."""

    def __init__(
        self,
        base_url: str = "",
        namespace: str = "",
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        timeout: float = 10.0,
    ):
        if not base_url:
            # In-cluster defaults (the conventional env + SA mount).
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise ValueError(
                    "base_url not given and KUBERNETES_SERVICE_HOST unset "
                    "(not running in a cluster?)"
                )
            base_url = f"https://{host}:{port}"
            if token is None:
                try:
                    with open(f"{SA_DIR}/token") as f:
                        token = f.read().strip()
                except OSError:
                    token = None
            if ca_file is None:
                ca_file = f"{SA_DIR}/ca.crt"
            if not namespace:
                try:
                    with open(f"{SA_DIR}/namespace") as f:
                        namespace = f.read().strip()
                except OSError:
                    pass
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace or "default"
        self._token = token
        self._timeout = timeout
        self._ctx: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            self._ctx = ssl.create_default_context(
                cafile=ca_file if ca_file else None
            )

    def _make_request(self, method: str, path: str,
                      body: Optional[Dict[str, Any]],
                      content_type: str = "application/json",
                      ) -> urllib.request.Request:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method
        )
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        return req

    def request(self, method: str, path: str,
                body: Optional[Dict[str, Any]] = None,
                content_type: str = "application/json") -> Dict[str, Any]:
        req = self._make_request(method, path, body, content_type)
        try:
            with urllib.request.urlopen(
                req, timeout=self._timeout, context=self._ctx
            ) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise KubeApiError(e.code, f"{method} {path}: {detail}") from e
        return json.loads(payload) if payload else {}

    def stream(self, path: str,
               read_timeout: float = 90.0) -> Iterator[Dict[str, Any]]:
        """GET ``path`` and yield one parsed JSON object per line as the
        server writes them — the k8s WATCH wire format. The iterator ends
        when the server closes the stream (watch timeoutSeconds elapsed);
        callers re-watch from their last resourceVersion."""
        req = self._make_request("GET", path, None)
        try:
            resp = urllib.request.urlopen(
                req, timeout=read_timeout, context=self._ctx
            )
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise KubeApiError(e.code, f"WATCH {path}: {detail}") from e
        try:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue  # torn line at stream teardown
        finally:
            resp.close()
